"""JAX runtime telemetry bridge: compiles, memory, and the run journal.

The two signals that actually dominate TPU cost are invisible to wall-clock
instrumentation: an XLA recompile on a supposedly-warm path (tens of
seconds cold on a chip) and HBM pressure creeping toward an OOM.  This
module surfaces both:

* **compile counting** — a ``jax.monitoring`` listener counts every jaxpr
  trace (``compile.traces`` — each implies a compile-path dispatch, even
  when the persistent cache then satisfies the backend compile), every
  real backend compile (``compile.backend``) and every persistent-cache
  hit (``compile.cache_hits``), attributed to the ACTIVE TRACE ROOT
  (``fit.GaussianProcessRegression``, ``serve.batch``, ...) so "what
  recompiled in production" has a per-entry-point answer — the batcher's
  trace-counting guard (``serve/batcher.py``) feeds the same counters;
* **memory gauges** — ``device.memory_stats()`` sampled at phase
  boundaries into ``memory.bytes_in_use`` / ``memory.peak_bytes_in_use``
  (host peak RSS as the CPU-backend fallback, so the signal exists on
  every harness);
* **run journal** — every fit is stamped with a ``run_journal`` dict
  (span tree, lane, mesh, quarantine events, compile counts, memory
  peaks), persisted next to the checkpoints when a checkpoint directory
  (or ``GP_RUN_JOURNAL_DIR``) is configured.

All keys are registered in :mod:`spark_gp_tpu.obs.names`.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Dict, List, Optional

from spark_gp_tpu.obs import trace as _trace

# the event names jax 0.4.x emits (jax/_src/dispatch.py, compiler.py)
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_UNTRACED = "untraced"


class RuntimeTelemetry:
    """Process-global counters/gauges fed by the runtime hooks.

    Thread-safe; listeners are registered once (jax.monitoring offers no
    deregistration, so installation is idempotent and the callbacks stay
    O(dict op) forever)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        # counter key -> {entry point -> count}; entry = active trace root
        self.per_entry: Dict[str, Dict[str, float]] = {}
        self.gauges: Dict[str, float] = {}
        self._installed = False
        # host-RSS fallback throttle: getrusage costs ~10-50us a call and
        # fires on every phase boundary — cache it for a short interval so
        # tiny fits (many boundaries per 100ms) don't pay it repeatedly
        self._rss_at = 0.0
        self._rss = None

    # -- emission ----------------------------------------------------------
    def inc(self, key: str, entry: Optional[str] = None, n: float = 1.0) -> None:
        if entry is None:
            entry = _trace.current_root_name() or _UNTRACED
        with self._lock:
            self.counters[key] = self.counters.get(key, 0.0) + n
            by = self.per_entry.setdefault(key, {})
            by[entry] = by.get(entry, 0.0) + n

    # -- jax.monitoring hooks ----------------------------------------------
    def install(self) -> None:
        with self._lock:
            if self._installed:
                return
            # flip first even though registration may fail below: retrying
            # (and re-warning) on every subsequent fit would be spam, and
            # a half-registered listener pair must not be re-registered
            self._installed = True
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_listener(self._on_event)
            monitoring.register_event_duration_secs_listener(self._on_duration)
        except Exception:  # noqa: BLE001 — telemetry must never fail a fit
            # (the listener API is jax-internal-adjacent and may move);
            # one loud warning, then compile telemetry stays dark
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "jax.monitoring listener registration failed — compile "
                "telemetry disabled for this process", exc_info=True,
            )

    def _on_event(self, event: str, **kwargs) -> None:
        if event == _CACHE_HIT_EVENT:
            self.inc("compile.cache_hits")

    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        if event == _TRACE_EVENT:
            self.inc("compile.traces")
            _trace.add_event("compile.trace", duration_s=float(duration))
        elif event == _BACKEND_EVENT:
            self.inc("compile.backend")
            _trace.add_event("compile.backend", duration_s=float(duration))

    # -- memory ------------------------------------------------------------
    def sample_memory(self) -> Dict[str, float]:
        """One sample of device HBM (host RSS as the CPU fallback).

        Returns the RAW sample — a :class:`FitCapture` computes ITS
        fit's peak from the samples taken within the fit, so one big
        fit's high-water mark never bleeds into a later fit's journal.
        Only the process-global exposition gauges apply max-retention to
        ``*peak*`` keys (a scrape between fits should still see the
        high-water mark).  The underlying sources are what they are:
        device ``peak_bytes_in_use`` and host ``ru_maxrss`` are
        process-lifetime peaks at the source."""
        sample: Dict[str, float] = {}
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            if stats:
                if "bytes_in_use" in stats:
                    sample["memory.bytes_in_use"] = float(stats["bytes_in_use"])
                if "peak_bytes_in_use" in stats:
                    sample["memory.peak_bytes_in_use"] = float(
                        stats["peak_bytes_in_use"]
                    )
        except Exception:  # noqa: BLE001 — telemetry must never fail a fit
            pass
        try:
            now = time.monotonic()
            if self._rss is None or now - self._rss_at > 0.02:
                import resource

                # ru_maxrss is KiB on Linux — the only harness platform
                self._rss = float(
                    resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
                )
                self._rss_at = now
            sample["memory.host_peak_rss_bytes"] = self._rss
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            for key, value in sample.items():
                if "peak" in key:
                    value = max(value, self.gauges.get(key, 0.0))
                self.gauges[key] = value
        return sample

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "per_entry": {k: dict(v) for k, v in self.per_entry.items()},
                "gauges": dict(self.gauges),
            }


#: THE process singleton every hook feeds
telemetry = RuntimeTelemetry()


def build_info() -> Dict[str, object]:
    """The ``gp_build_info`` identity: package + jax/jaxlib versions,
    backend, precision lane and process count — the labels that answer
    "what exactly produced this page/journal/bundle" without ssh.
    Collected lazily and failure-tolerant (a broken backend must not
    break a scrape)."""
    info: Dict[str, object] = {}
    try:
        import spark_gp_tpu

        info["version"] = getattr(spark_gp_tpu, "__version__", "unknown")
    except Exception:  # noqa: BLE001 — identity is best-effort
        info["version"] = "unknown"
    try:
        import jax
        import jaxlib

        info["jax"] = jax.__version__
        info["jaxlib"] = jaxlib.__version__
        info["backend"] = jax.default_backend()
        info["process_count"] = int(jax.process_count())
    except Exception:  # noqa: BLE001 — no backend, still an identity
        info.setdefault("backend", "unknown")
        info.setdefault("process_count", 1)
    try:
        from spark_gp_tpu.ops.precision import active_lane

        info["precision_lane"] = active_lane()
    except Exception:  # noqa: BLE001
        info["precision_lane"] = "unknown"
    return info


# -- cross-process trace stitching ------------------------------------------

_trace_token: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "gp_obs_trace_token", default=None
)


def active_trace_token() -> Optional[str]:
    """The stitched trace id of the enclosing fit (None outside one):
    minted on process 0 and propagated over the coordination KV plane
    (``parallel/coord.stitch_trace_token``), so every host's journal and
    incident bundle carries the SAME id for one distributed fit."""
    return _trace_token.get()


@contextlib.contextmanager
def trace_token_scope(token: Optional[str]):
    """Bind the stitched trace id for the span of one fit."""
    ctx_token = _trace_token.set(token)
    try:
        yield token
    finally:
        _trace_token.reset(ctx_token)


# -- per-fit capture --------------------------------------------------------

_active_capture: contextvars.ContextVar[Optional["FitCapture"]] = (
    contextvars.ContextVar("gp_obs_fit_capture", default=None)
)


class FitCapture:
    """Deltas + samples bracketing one fit (the run journal's inputs).

    Compile deltas are process-global counter differences: two fits
    racing in separate threads may cross-attribute each other's compiles
    in the TOTALS, while the per-entry table stays exact (attribution
    follows the trace root of the compiling thread)."""

    _COMPILE_KEYS = ("compile.traces", "compile.backend", "compile.cache_hits")

    def __init__(self, name: str):
        self.name = name
        snap = telemetry.snapshot()
        self._base = {k: snap["counters"].get(k, 0.0) for k in self._COMPILE_KEYS}
        self._base_entry = {
            k: dict(snap["per_entry"].get(k, {})) for k in self._COMPILE_KEYS
        }
        self.memory_samples: List[dict] = []
        self.compiles: Dict[str, float] = {}
        self.compiles_by_entry: Dict[str, Dict[str, float]] = {}
        # entry -> {flops_per_execution, bytes_per_execution, executions}
        # fed by obs/cost.observe_call while this capture is active
        self.xla_costs: Dict[str, Dict[str, float]] = {}
        self._finished = False

    def add_memory_sample(self, tag: str) -> None:
        sample = telemetry.sample_memory()
        if sample:
            self.memory_samples.append({"phase": tag, **sample})

    def note_xla_cost(self, entry: str, cost: Dict[str, float],
                      weight: float = 1.0) -> None:
        # DISTINCT compiled programs can share one trace-root entry (a
        # degraded fit re-executes on another rung; host + device paths
        # in one fit): keep one row per (entry, per-execution cost) —
        # suffixing "#2", "#3" — so flops_total sums the programs that
        # actually ran instead of multiplying one program's cost by every
        # other program's executions
        key = entry
        suffix = 2
        while True:
            row = self.xla_costs.get(key)
            if row is None or (
                row["flops_per_execution"] == cost["flops"]
                and row["bytes_per_execution"] == cost["bytes"]
            ):
                break
            key = f"{entry}#{suffix}"
            suffix += 1
        row = self.xla_costs.setdefault(key, {
            "flops_per_execution": cost["flops"],
            "bytes_per_execution": cost["bytes"],
            "executions": 0.0,
        })
        if cost.get("peak_bytes"):
            # the compiler's memory_analysis peak (obs/cost.py), the
            # per-entry actual the journal's plan table compares against
            row["peak_bytes_per_execution"] = cost["peak_bytes"]
        row["executions"] += weight

    def finish(self) -> None:
        if self._finished:
            return  # a failure-path bundle may have finished us already
        self._finished = True
        self.add_memory_sample("end")
        snap = telemetry.snapshot()
        self.compiles = {
            k: snap["counters"].get(k, 0.0) - self._base[k]
            for k in self._COMPILE_KEYS
        }
        self.compiles_by_entry = {}
        for key in self._COMPILE_KEYS:
            now = snap["per_entry"].get(key, {})
            base = self._base_entry[key]
            delta = {
                entry: n - base.get(entry, 0.0)
                for entry, n in now.items()
                if n - base.get(entry, 0.0) > 0
            }
            if delta:
                self.compiles_by_entry[key] = delta

    @property
    def peak_memory(self) -> Dict[str, float]:
        peaks: Dict[str, float] = {}
        for sample in self.memory_samples:
            for key, value in sample.items():
                if key == "phase":
                    continue
                peaks[key] = max(peaks.get(key, 0.0), value)
        return peaks


@contextlib.contextmanager
def fit_capture(name: str):
    """Activate compile attribution + phase-boundary memory sampling for
    one fit; yields the :class:`FitCapture` (None when tracing is off)."""
    if not _trace.tracing_enabled():
        yield None
        return
    telemetry.install()
    cap = FitCapture(name)
    token = _active_capture.set(cap)
    cap.add_memory_sample("start")
    try:
        yield cap
    finally:
        _active_capture.reset(token)
        cap.finish()


def on_phase_boundary(instr_name: str, phase_name: str) -> None:
    """Called by ``Instrumentation.phase`` on every phase exit: samples
    memory into the active capture.  A cheap contextvar read when no
    capture is active — the serve hot path never pays for it."""
    cap = _active_capture.get()
    if cap is not None:
        cap.add_memory_sample(phase_name)


def note_xla_cost(entry: str, cost: Dict[str, float],
                  weight: float = 1.0) -> None:
    """Relay one cost-metered execution into the active fit capture (the
    run journal's per-fit MFU table); dropped outside a capture — the
    process-wide totals live in the telemetry counters regardless."""
    cap = _active_capture.get()
    if cap is not None:
        cap.note_xla_cost(entry, cost, weight)


# -- run journal ------------------------------------------------------------

JOURNAL_FORMAT = "spark_gp_tpu.run_journal/v1"

#: monotone integer bumped when journal KEYS change meaning or new
#: required keys appear.  History: 1 (implicit — pre-stamp journals,
#: through PR 12), 2 (explicit stamp + expert_quality).  ``gpctl show``
#: validates journal documents against :data:`JOURNAL_REQUIRED_KEYS`
#: exactly the way it validates incident bundles (exit 1 on malformed).
JOURNAL_SCHEMA_VERSION = 2

#: keys every schema-valid journal carries — the journal's twin of
#: ``obs/recorder.BUNDLE_REQUIRED_KEYS`` (tests + gpctl validation read
#: this, so the contract lives in one place).  ``schema_version`` itself
#: is NOT required: pre-stamp journals on disk are legacy v1 and must
#: keep loading without complaint.
JOURNAL_REQUIRED_KEYS = (
    "format", "name", "created_unix", "pid", "build_info", "precision_lane",
    "timings", "metrics", "degradations", "quarantine", "compiles",
    "memory", "spans",
)

#: keys that arrived AFTER the first journals shipped (``pid`` /
#: ``build_info`` with the forensics plane, ``degradations`` with the
#: fallback ladder) — a pre-stamp legacy document must not fail
#: validation for predating them
_JOURNAL_V2_ONLY_KEYS = frozenset(("pid", "build_info", "degradations"))


def validate_journal(journal: dict) -> List[str]:
    """Schema check shared by tests and ``tools/gpctl`` — returns the
    list of problems (empty = valid).  A ``schema_version`` NEWER than
    this build's is a problem (the document may carry semantics this
    reader cannot interpret); an absent stamp is legacy v1 and fine."""
    problems = []
    if journal.get("format") != JOURNAL_FORMAT:
        problems.append(f"format is {journal.get('format')!r}")
    legacy = "schema_version" not in journal
    for key in JOURNAL_REQUIRED_KEYS:
        if key not in journal and not (legacy and key in _JOURNAL_V2_ONLY_KEYS):
            problems.append(f"missing required key {key!r}")
    version = journal.get("schema_version")
    if version is not None:
        if not isinstance(version, int):
            problems.append(f"schema_version is {version!r}, not an int")
        elif version > JOURNAL_SCHEMA_VERSION:
            problems.append(
                f"schema_version {version} is newer than this build's "
                f"{JOURNAL_SCHEMA_VERSION}"
            )
    for key in ("timings", "metrics", "quarantine", "memory", "compiles"):
        if key in journal and not isinstance(journal[key], dict):
            problems.append(f"{key} is not an object")
    for key in ("spans", "degradations"):
        if key in journal and not isinstance(journal[key], list):
            problems.append(f"{key} is not a list")
    return problems

#: per-fit artifacts that accumulate in a long-lived checkpoint/journal
#: directory (journals are stamped unique per fit; host-optimizer
#: checkpoints are per-tag; incident bundles per failure) — the
#: retention GC's prune targets
_RETENTION_PATTERNS = ("run_journal_*.json", "lbfgs_state_*",
                       "incident_*.json")


def artifact_retention() -> Optional[int]:
    """The opt-in retention budget: ``GP_ARTIFACT_RETENTION=K`` keeps the
    newest K files per artifact class; unset/invalid/K<1 disables the GC
    (retention stays the operator's, exactly as before)."""
    raw = os.environ.get("GP_ARTIFACT_RETENTION", "").strip()
    if not raw:
        return None
    try:
        keep = int(raw)
    except ValueError:
        return None
    return keep if keep >= 1 else None


def prune_artifacts(
    directory: str,
    keep: Optional[int] = None,
    protect: Optional[str] = None,
) -> int:
    """Prune old run journals and host-optimizer checkpoint files in
    ``directory``, keeping the newest ``keep`` of EACH pattern by mtime.
    ``protect`` names the artifact the caller JUST wrote: mtime has
    filesystem-tick granularity, so a same-tick neighbor could otherwise
    win the tiebreak and the GC would delete the very file it was invoked
    for.  Returns the number of files removed; every failure is
    best-effort-ignored — GC is housekeeping, never a fit or serve
    failure.  NOTE the checkpoint-file leg: with several concurrent fits
    sharing one directory and a small K, one fit's live ``lbfgs_state_*``
    can be another's "old" file — the knob is opt-in for precisely that
    reason."""
    keep = artifact_retention() if keep is None else int(keep)
    if keep is None or keep < 1:
        return 0
    import glob

    def _mtime(path: str) -> float:
        try:
            return os.path.getmtime(path)
        except OSError:
            return float("inf")  # racing writer: treat as newest, skip

    protect = None if protect is None else os.path.abspath(protect)
    removed = 0
    for pattern in _RETENTION_PATTERNS:
        paths = sorted(
            glob.glob(os.path.join(directory, pattern)),
            key=lambda p: (
                os.path.abspath(p) == protect,  # the fresh write is newest
                _mtime(p),
                p,
            ),
            reverse=True,
        )
        for path in paths[keep:]:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


def _xla_cost_summary(capture: Optional[FitCapture],
                      timings: Dict[str, float]) -> Optional[dict]:
    """The journal's measured-cost block: per-entry flops/bytes tables
    from the capture plus the measured optimize-phase MFU against the
    running chip's nominal peak (``obs/cost.mfu_against_peak``).  None
    when cost metering was off for the fit."""
    if capture is None or not capture.xla_costs:
        return None
    entries = {}
    flops_total = 0.0
    for entry, row in capture.xla_costs.items():
        total = row["flops_per_execution"] * row["executions"]
        entries[entry] = {**row, "flops_total": total}
        flops_total += total
    from spark_gp_tpu.obs import cost as obs_cost

    opt_s = timings.get("optimize_hypers")
    return {
        "entries": entries,
        "flops_total": flops_total,
        "optimize_seconds": opt_s,
        "measured_mfu_optimize": obs_cost.mfu_against_peak(
            flops_total, opt_s or 0.0
        ),
    }


def _memory_plan_rows(instr, capture: Optional[FitCapture]) -> List[dict]:
    """The journal's ``memory_plan`` block: every plan decision stamped
    on the fit (``resilience/memplan.stamp_decision``), annotated with
    the ACTUALS known at journal time — the measured device peak of the
    fit (like-for-like only: the host-RSS fallback is a process-lifetime
    proxy, not a dispatch peak) and the compiler's own per-entry
    ``memory_analysis`` peak when cost metering ran.  An actual above
    the margined prediction counts ``plan.margin_breach``: the exact
    alert a wrong cost model should raise BEFORE it becomes an OOM."""
    rows = [dict(r) for r in (getattr(instr, "memory_plan", []) or [])]
    if not rows:
        return rows
    actual = None
    compiled = None
    if capture is not None:
        actual = capture.peak_memory.get("memory.peak_bytes_in_use")
        peaks = [
            row.get("peak_bytes_per_execution")
            for row in capture.xla_costs.values()
            if row.get("peak_bytes_per_execution")
        ]
        compiled = max(peaks) if peaks else None
    for row in rows:
        row["actual_peak_bytes"] = actual
        row["compiled_peak_bytes"] = compiled
        predicted = row.get("predicted_bytes")
        # breach compares LIKE-FOR-LIKE: the compiler's per-program peak
        # when metering ran (the prediction's own granularity), else the
        # whole-fit device peak (conservative — it includes every
        # resident buffer across every phase, documented as such).  A
        # fits=False row never breaches: the plan already priced the
        # overrun, the alert would page on the expected outcome.
        measured = compiled if compiled is not None else actual
        breach = bool(
            predicted and row.get("fits")
            and measured is not None and measured > predicted
        )
        row["margin_breach"] = breach
        if breach:
            telemetry.inc("plan.margin_breach", entry=row.get("entry"))
    return rows


def write_run_journal(
    instr,
    root,
    capture: Optional[FitCapture],
    mesh=None,
    journal_dir: Optional[str] = None,
    trace_token: Optional[str] = None,
) -> dict:
    """Assemble (and optionally persist) one fit's run journal.

    ``root`` is the fit's closed root span; the journal's ``spans`` is the
    reassembled tree for its trace.  Persisted as
    ``run_journal_<name>-<unix_ms>-p<pid>-t<trace_id>.json`` (tmp +
    atomic rename, the checkpoint writers' convention) into
    ``journal_dir`` when given — callers pass the checkpoint directory,
    falling back to ``GP_RUN_JOURNAL_DIR``.  The unique tag keeps
    concurrent or repeated fits of one estimator family from clobbering
    each other's journal.  Retention: by default a long-lived dir is the
    operator's to manage (journals are small); ``GP_ARTIFACT_RETENTION=K``
    opts into :func:`prune_artifacts` after each persist — keep the
    newest K journals and host checkpoints.  Schema:
    docs/OBSERVABILITY.md."""
    from spark_gp_tpu.ops.iterative import active_solver_lane
    from spark_gp_tpu.ops.precision import active_lane

    spans = _trace.spans_of_root(root) if getattr(root, "trace_id", 0) else []
    quarantine_events = [
        {**event, "span": s.name}
        for s in spans
        for event in s.events
        if event["name"].startswith(
            ("experts.", "fit.retry", "breaker.", "fallback.")
        )
    ]
    if trace_token is None:
        trace_token = active_trace_token()
    journal = {
        "format": JOURNAL_FORMAT,
        "schema_version": JOURNAL_SCHEMA_VERSION,
        "name": getattr(instr, "name", "gp"),
        "created_unix": time.time(),
        # the STITCHED trace id: one value across every host's journal
        # (and any incident bundle) of one distributed fit — the key
        # tools/gpctl merges on.  None only for direct writer calls
        # outside a fit scope.
        "trace_id": trace_token,
        "pid": os.getpid(),
        "build_info": build_info(),
        "precision_lane": active_lane(),
        # the engaged solver (exact/iterative, auto resolved against the
        # fitted stack) is the metrics-level ``solver_lane`` stamp; this
        # top-level key is the AMBIENT knob for journals written outside
        # a fit (and the gpctl one-liner's quick read)
        "solver_lane": getattr(instr, "metrics", {}).get(
            "solver_lane", active_solver_lane()
        ),
        "mesh": (
            None if mesh is None
            else {"axes": {str(k): int(v) for k, v in dict(mesh.shape).items()}}
        ),
        "timings": dict(getattr(instr, "timings", {})),
        "metrics": dict(getattr(instr, "metrics", {})),
        # the degradation ladder's transition history (resilience/
        # fallback.py): which classified failures re-executed this fit at
        # which rung — the journal-side twin of the saved model's
        # provenance_json degradations
        "degradations": list(getattr(instr, "degradations", [])),
        # the memory planner's decisions (resilience/memplan.py) with
        # predicted-vs-actual peaks — the provenance that makes a wrong
        # prediction a debuggable artifact instead of a mystery crash
        "memory_plan": _memory_plan_rows(instr, capture),
        # fit-time per-expert quality telemetry (models/common.
        # _emit_expert_quality): per-expert NLL at theta*, settled jitter
        # level, effective BCM weight — the statistical health plane's
        # fit-side evidence (``gpctl quality`` renders it); None when the
        # probe was skipped or disabled
        "expert_quality": getattr(instr, "expert_quality", None),
        "quarantine": {
            "experts_quarantined": getattr(instr, "metrics", {}).get(
                "experts_quarantined", 0.0
            ),
            "experts_jittered": getattr(instr, "metrics", {}).get(
                "experts_jittered", 0.0
            ),
            "fit_retries": getattr(instr, "metrics", {}).get("fit_retries", 0.0),
            "events": quarantine_events,
        },
        "compiles": dict(capture.compiles) if capture is not None else {},
        "compiles_by_entry": (
            dict(capture.compiles_by_entry) if capture is not None else {}
        ),
        "memory": {
            "samples": list(capture.memory_samples),
            "peak": capture.peak_memory,
        } if capture is not None else {"samples": [], "peak": {}},
        "span_count": len(spans),
        "spans": _trace.span_tree(spans),
        # measured flops/bytes + optimize-phase MFU (obs/cost.py); None
        # when GP_XLA_COST was off for this fit
        "xla_cost": _xla_cost_summary(
            capture, dict(getattr(instr, "timings", {}))
        ),
        "path": None,
    }
    if journal_dir is None:
        journal_dir = os.environ.get("GP_RUN_JOURNAL_DIR", "").strip() or None
    if journal_dir is not None:
        try:
            os.makedirs(journal_dir, exist_ok=True)
            # ms timestamp + pid disambiguate across processes, the trace
            # id within one (two fits can share a millisecond)
            tag = (
                f"{int(journal['created_unix'] * 1000):d}"
                f"-p{os.getpid()}-t{getattr(root, 'trace_id', 0)}"
            )
            path = os.path.join(
                journal_dir, f"run_journal_{journal['name']}-{tag}.json"
            )
            from spark_gp_tpu.utils.checkpoint import _fsync_replace

            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(journal, fh, default=str)
            _fsync_replace(tmp, path)
            journal["path"] = path
            # opt-in (GP_ARTIFACT_RETENTION); the fresh journal is
            # protected against same-mtime-tick tiebreaks
            prune_artifacts(journal_dir, protect=path)
        except OSError as exc:
            # the journal is telemetry, never a fit failure — but say so
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "run journal not persisted to %r: %s", journal_dir, exc
            )
    return journal
