"""Statistical health plane: online calibration, drift, per-expert quality.

The systems observability layers (obs/trace, obs/runtime, obs/recorder)
watch latency, memory, compiles and failures — none of them can tell an
operator whether the *distributions* a GP serves are honest.  The
product-of-experts aggregation is known to turn overconfident as the
expert count grows (Healing Products of GP experts, arxiv 2102.07106;
expert selection, arxiv 2102.01496), and an overconfident σ ships silent
damage: downstream consumers trust intervals that do not cover.  This
module makes miscalibration and input drift first-class, alertable,
chaos-provable observables:

* :class:`QualityMonitor` — a bounded-memory streaming calibration
  monitor over ``(μ, σ², y)`` triples: standardized-residual z statistics
  (mean/variance), a fixed-bin PIT histogram, nominal-coverage counters
  for the 50/90/99% central intervals, and a rolling predictive NLL.
  Statistics accumulate both process-lifetime totals and fixed-size
  windows; a **multi-window verdict engine** flips the monitor to
  ``alert`` only after ``breach_windows`` CONSECUTIVE breached windows
  (one noisy window never pages), and a clean window recovers it;
* :class:`DriftMonitor` — scores incoming covariate rows against the
  fit-time :func:`summarize_covariates` summary (per-dim moments + an
  active-set-centroid distance sketch stamped into the saved model's
  ``provenance_json``), with the same multi-window verdict semantics;
* :class:`PendingRing` — the bounded ``request_id -> (μ, σ²)`` join
  buffer behind the serve ``observe`` verb: delayed ground-truth labels
  arrive minutes after the predictions they grade, so the server parks
  each answered request's distribution (keyed by the client's
  ``request_id``) until the label shows up.  Joins are idempotent — a
  re-sent observation of an already-joined id is a counted no-op, never
  a double count — and eviction is strictly oldest-first;
* :class:`ServeQualityPlane` — the per-model composition the server
  owns: monitors + pending ring + metric emission (``quality.*`` /
  ``drift.*`` families, ``obs/names.py``) + the one-line verdict the
  ``health`` verb and the canary guard consume.

Everything here is plain numpy on the host — no device work, no jit —
and every per-observation step is O(1) against fixed-size state, so the
monitor can run always-on in production (bench's ``observability.quality``
section prices it; ``test_bench_contract`` holds it under 2% of the
serve path).  Chaos proof: ``chaos.miscalibrate`` (σ-scaling) and
``chaos.drift_inputs`` (covariate shift) must each trip their alert
within a bounded number of observations while a clean seeded twin never
does (``tools/soak.py``, ``tests/test_quality_obs.py``).
"""

from __future__ import annotations

import math
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

import numpy as np

#: two-sided standard-normal bounds of the nominal central intervals the
#: coverage counters track: P(|z| <= bound) = level
COVERAGE_LEVELS: Dict[str, float] = {
    "50": 0.6744897501960817,
    "90": 1.6448536269514722,
    "99": 2.5758293035489004,
}

#: fixed PIT histogram bin count (uniform [0, 1] bins)
PIT_BINS = 20

_LOG_2PI = math.log(2.0 * math.pi)

#: schema marker of the covariate summary stamped into provenance_json
COVARIATE_SUMMARY_VERSION = 1


class ObserveError(RuntimeError):
    """Base of the ``observe`` verb's classified failures (``code`` is a
    wire code from :mod:`spark_gp_tpu.serve.codes`)."""

    code = "observe.unknown_request"


class UnknownRequestError(ObserveError):
    """The observed ``request_id`` has no pending prediction: it was
    never served with a ``request_id``, its entry aged out of the
    bounded pending ring, or the label went to the wrong replica."""

    code = "observe.unknown_request"

    def __init__(self, request_id: str) -> None:
        super().__init__(
            f"no pending prediction for request_id {request_id!r} "
            "(never served here, or evicted from the pending ring)"
        )


class QualityDisabledError(ObserveError):
    """``observe`` reached a server whose quality plane is disabled."""

    code = "observe.disabled"

    def __init__(self) -> None:
        super().__init__(
            "the statistical quality plane is disabled on this server "
            "(GP_SERVE_QUALITY=0 or --quality 0)"
        )


_erf = np.vectorize(math.erf, otypes=[np.float64])


def _phi(z: np.ndarray) -> np.ndarray:
    """Standard-normal CDF, vectorized (the PIT transform)."""
    return 0.5 * (1.0 + _erf(np.asarray(z) / math.sqrt(2.0)))


# --------------------------------------------------------------------------
# streaming calibration monitor
# --------------------------------------------------------------------------


class _WindowAccumulator:
    """One fixed-size window's running sums (reset on close)."""

    __slots__ = ("n", "z_sum", "z2_sum", "nll_sum", "cov", "pit")

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.n = 0
        self.z_sum = 0.0
        self.z2_sum = 0.0
        self.nll_sum = 0.0
        self.cov = {level: 0 for level in COVERAGE_LEVELS}
        self.pit = np.zeros(PIT_BINS, dtype=np.int64)


class QualityMonitor:
    """Streaming calibration statistics with a multi-window verdict.

    ``observe(mean, var, y)`` folds a batch of graded predictions in;
    every ``window`` observations one window closes and is judged against
    four independent breach tests (each sized so a WELL-SPECIFIED model
    breaches with negligible probability — the thresholds are k-sigma
    bounds under the null, not tuning knobs):

    * **coverage** — for each nominal level p in 50/90/99%, the window's
      empirical coverage must sit within ``coverage_sigmas`` binomial
      standard errors of p;
    * **z-variance** — the window mean of z² must sit within
      ``zvar_sigmas * sqrt(2/window)`` of 1 (the χ² null) — THE
      overconfidence signal: a model whose σ is 2× too small shows
      mean z² ≈ 4;
    * **z-mean** — |window mean of z| must stay under
      ``zmean_sigmas / sqrt(window)`` (systematic bias);
    * **PIT uniformity** — the window's PIT histogram χ² statistic must
      stay under ``pit_chi2_bar`` (df = bins - 1 = 19; the default 60 is
      far past the 1e-4 tail).

    A window failing any test is *breached*; ``breach_windows``
    consecutive breached windows flip the monitor to **alert** (the
    sustained-breach semantics — one weird burst of labels never pages),
    and one clean window recovers it.  All state is O(bins + history):
    bounded memory by construction.

    Thread-safe: the serve reader threads and the batcher feed one
    instance concurrently.
    """

    def __init__(
        self,
        window: int = 128,
        breach_windows: int = 2,
        history: int = 16,
        coverage_sigmas: float = 4.0,
        zvar_sigmas: float = 6.0,
        zmean_sigmas: float = 5.0,
        pit_chi2_bar: float = 60.0,
        min_sigma: float = 1e-12,
    ) -> None:
        if window < 8:
            raise ValueError("window must be >= 8 observations")
        if breach_windows < 1:
            raise ValueError("breach_windows must be >= 1")
        self.window = int(window)
        self.breach_windows = int(breach_windows)
        self.coverage_sigmas = float(coverage_sigmas)
        self.zvar_sigmas = float(zvar_sigmas)
        self.zmean_sigmas = float(zmean_sigmas)
        self.pit_chi2_bar = float(pit_chi2_bar)
        self.min_sigma = float(min_sigma)
        self._lock = threading.Lock()
        # lifetime totals
        self.n = 0
        self._z_sum = 0.0
        self._z2_sum = 0.0
        self._nll_sum = 0.0
        self._cov = {level: 0 for level in COVERAGE_LEVELS}
        self._pit = np.zeros(PIT_BINS, dtype=np.int64)
        # windowing
        self._win = _WindowAccumulator()
        self._recent: deque = deque(maxlen=max(history, breach_windows))
        self._consecutive_breaches = 0
        self.windows_closed = 0
        self.alert = False
        self.alert_reasons: List[str] = []

    # -- feeding -----------------------------------------------------------
    def observe(self, mean, var, y) -> List[dict]:
        """Fold a batch of graded predictions in; returns the verdicts of
        any windows this batch closed (empty for most calls)."""
        mean = np.asarray(mean, dtype=np.float64).reshape(-1)
        var = np.asarray(var, dtype=np.float64).reshape(-1)
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        if not (mean.shape == var.shape == y.shape):
            raise ValueError(
                f"mean/var/y must align; got {mean.shape}/{var.shape}/{y.shape}"
            )
        sigma = np.sqrt(np.maximum(var, self.min_sigma**2))
        z = (y - mean) / sigma
        pit = _phi(z)
        nll = 0.5 * (_LOG_2PI + 2.0 * np.log(sigma) + z * z)
        bins = np.minimum((pit * PIT_BINS).astype(np.int64), PIT_BINS - 1)
        closed: List[dict] = []
        with self._lock:
            for i in range(z.shape[0]):
                zi = float(z[i])
                self.n += 1
                self._z_sum += zi
                self._z2_sum += zi * zi
                self._nll_sum += float(nll[i])
                self._pit[bins[i]] += 1
                win = self._win
                win.n += 1
                win.z_sum += zi
                win.z2_sum += zi * zi
                win.nll_sum += float(nll[i])
                win.pit[bins[i]] += 1
                abs_z = abs(zi)
                for level, bound in COVERAGE_LEVELS.items():
                    if abs_z <= bound:
                        self._cov[level] += 1
                        win.cov[level] += 1
                if win.n >= self.window:
                    closed.append(self._close_window_locked())
        return closed

    # -- verdicts ----------------------------------------------------------
    def _close_window_locked(self) -> dict:
        win = self._win
        w = float(win.n)
        reasons: List[str] = []
        for level in COVERAGE_LEVELS:
            p = float(level) / 100.0
            emp = win.cov[level] / w
            sigma_b = math.sqrt(p * (1.0 - p) / w)
            if abs(emp - p) > self.coverage_sigmas * sigma_b:
                reasons.append(
                    f"coverage_{level}: {emp:.3f} vs nominal {p:.3f}"
                )
        z_mean = win.z_sum / w
        z2_mean = win.z2_sum / w
        if abs(z2_mean - 1.0) > self.zvar_sigmas * math.sqrt(2.0 / w):
            reasons.append(f"z_variance: mean z^2 = {z2_mean:.3f}")
        if abs(z_mean) > self.zmean_sigmas / math.sqrt(w):
            reasons.append(f"z_mean: {z_mean:.3f}")
        expected = w / PIT_BINS
        chi2 = float(np.sum((win.pit - expected) ** 2) / expected)
        if chi2 > self.pit_chi2_bar:
            reasons.append(f"pit_chi2: {chi2:.1f}")
        verdict = {
            "n": win.n,
            "z_mean": z_mean,
            "z_std": math.sqrt(max(z2_mean - z_mean * z_mean, 0.0)),
            "nll_mean": win.nll_sum / w,
            "coverage": {
                level: win.cov[level] / w for level in COVERAGE_LEVELS
            },
            "pit_chi2": chi2,
            "breached": bool(reasons),
            "reasons": reasons,
        }
        self.windows_closed += 1
        self._recent.append(verdict)
        if reasons:
            self._consecutive_breaches += 1
        else:
            self._consecutive_breaches = 0
        was_alert = self.alert
        self.alert = self._consecutive_breaches >= self.breach_windows
        if self.alert:
            self.alert_reasons = reasons
        elif was_alert:
            self.alert_reasons = []
        verdict["alert"] = self.alert
        verdict["alert_changed"] = self.alert != was_alert
        win.reset()
        return verdict

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            n = self.n
            if n == 0:
                totals = {
                    "z_mean": None, "z_std": None, "nll_mean": None,
                    "coverage": {level: None for level in COVERAGE_LEVELS},
                    "pit": [0] * PIT_BINS,
                }
            else:
                z_mean = self._z_sum / n
                z2 = self._z2_sum / n
                totals = {
                    "z_mean": z_mean,
                    "z_std": math.sqrt(max(z2 - z_mean * z_mean, 0.0)),
                    "nll_mean": self._nll_sum / n,
                    "coverage": {
                        level: self._cov[level] / n
                        for level in COVERAGE_LEVELS
                    },
                    "pit": [int(c) for c in self._pit],
                }
            return {
                "observations": n,
                "window": self.window,
                "windows_closed": self.windows_closed,
                "consecutive_breaches": self._consecutive_breaches,
                "alert": self.alert,
                "alert_reasons": list(self.alert_reasons),
                "recent_windows": [dict(v) for v in self._recent],
                **totals,
            }


# --------------------------------------------------------------------------
# covariate summary + drift monitor
# --------------------------------------------------------------------------


def summarize_covariates(
    x,
    active=None,
    sample: int = 4096,
    seed: int = 0,
) -> Optional[dict]:
    """Compact, JSON-serializable summary of the training covariates —
    what serve needs to score incoming rows for input drift, stamped
    into the saved model's ``provenance_json``:

    * per-dim moments (mean/std/min/max over the training rows);
    * an **active-set distance sketch** — quantiles (q50/q90/q99/max) of
      the standardized euclidean distance from (a bounded sample of)
      training rows to the active set's centroid, in per-dim-std units —
      the scale-free "how far from the data mass" yardstick the drift
      scorer compares serve traffic against.

    Returns None for degenerate inputs (no rows / no finite variance).
    """
    x = np.asarray(x, dtype=np.float64)
    if x.ndim != 2 or x.shape[0] < 2:
        return None
    finite = np.all(np.isfinite(x), axis=1)
    x = x[finite]
    if x.shape[0] < 2:
        return None
    mean = x.mean(axis=0)
    std = x.std(axis=0)
    if not np.all(np.isfinite(std)):
        return None
    std_safe = np.where(std > 0.0, std, 1.0)
    if active is not None:
        centroid = np.asarray(active, dtype=np.float64).mean(axis=0)
    else:
        centroid = mean
    if x.shape[0] > sample:
        rng = np.random.default_rng(seed)
        rows = x[rng.choice(x.shape[0], size=sample, replace=False)]
    else:
        rows = x
    zc = (centroid - mean) / std_safe
    zr = (rows - mean) / std_safe
    dist = np.sqrt(np.mean((zr - zc) ** 2, axis=1))
    q50, q90, q99 = np.quantile(dist, (0.5, 0.9, 0.99))
    return {
        "version": COVARIATE_SUMMARY_VERSION,
        "n": int(x.shape[0]),
        "dims": int(x.shape[1]),
        "mean": [float(v) for v in mean],
        "std": [float(v) for v in std],
        "min": [float(v) for v in x.min(axis=0)],
        "max": [float(v) for v in x.max(axis=0)],
        "active_centroid": [float(v) for v in centroid],
        "active_dist": {
            "q50": float(q50),
            "q90": float(q90),
            "q99": float(q99),
            "max": float(dist.max()),
        },
    }


class DriftMonitor:
    """Scores serve-time covariate rows against a fit-time summary.

    Two scale-free breach tests per window (effect sizes, not p-values —
    with thousands of rows a p-value trips on shifts too small to
    matter):

    * **mean shift** — the window's per-dim mean must stay within
      ``shift_bar`` training standard deviations of the training mean
      (the largest dim decides);
    * **out-of-mass fraction** — the fraction of rows whose standardized
      active-centroid distance exceeds the training q99 must stay under
      ``oor_frac_bar`` (a healthy window sits near 1%).

    Same multi-window verdict semantics as :class:`QualityMonitor`.

    The per-dispatch cost is BOUNDED: a batch larger than
    ``max_rows_per_batch`` (default 16) is stride-sampled down to it
    before scoring — drift is a question about means and tail
    fractions, so a uniform subsample answers it while keeping the
    serve hot path's worst case O(16·p) regardless of batch size.
    Windows count SCORED rows.
    """

    def __init__(
        self,
        summary: dict,
        window: int = 64,
        breach_windows: int = 2,
        history: int = 16,
        shift_bar: float = 0.5,
        oor_frac_bar: float = 0.3,
        max_rows_per_batch: Optional[int] = 16,
    ) -> None:
        if window < 8:
            raise ValueError("window must be >= 8 rows")
        self.summary = summary
        self.window = int(window)
        self.breach_windows = int(breach_windows)
        self.shift_bar = float(shift_bar)
        self.oor_frac_bar = float(oor_frac_bar)
        self.max_rows_per_batch = (
            None if max_rows_per_batch is None else int(max_rows_per_batch)
        )
        self._mean = np.asarray(summary["mean"], dtype=np.float64)
        std = np.asarray(summary["std"], dtype=np.float64)
        self._std = np.where(std > 0.0, std, 1.0)
        self._inv_std = 1.0 / self._std
        self._centroid_z = (
            np.asarray(summary["active_centroid"], dtype=np.float64)
            - self._mean
        ) * self._inv_std
        # fused standardization: (x - mean)/std - centroid_z
        #                       = x * _scale - _offset  (two ops, not three)
        self._scale = self._inv_std
        self._offset = self._mean * self._inv_std + self._centroid_z
        self._dist_q99 = float(summary["active_dist"]["q99"])
        # squared threshold: the hot path compares mean squared distance
        # without paying a sqrt per batch
        self._dist_q99_sq = self._dist_q99 * self._dist_q99
        self._lock = threading.Lock()
        self.rows = 0
        self._win_n = 0
        self._win_sum = np.zeros_like(self._mean)
        self._win_oor = 0
        self._recent: deque = deque(maxlen=max(history, breach_windows))
        self._consecutive_breaches = 0
        self.windows_closed = 0
        self.alert = False
        self.alert_reasons: List[str] = []
        self.last_shift = 0.0
        self.last_oor_frac = 0.0

    def score_rows(self, x) -> List[dict]:
        """Fold a batch of serve rows in (stride-sampled down to
        ``max_rows_per_batch``); returns closed-window verdicts."""
        x = np.asarray(x)
        if x.ndim != 2 or x.shape[1] != self._mean.shape[0]:
            return []
        cap = self.max_rows_per_batch
        if cap is not None and x.shape[0] > cap:
            # sample BEFORE the f64 conversion: only the scored rows pay
            x = x[:: -(-x.shape[0] // cap)][:cap]
        x = np.asarray(x, dtype=np.float64)
        diff = x * self._scale - self._offset
        diff *= diff
        oor_flags = diff.mean(axis=1) > self._dist_q99_sq
        closed: List[dict] = []
        n = x.shape[0]
        with self._lock:
            self.rows += n
            # fill windows chunk by chunk: one oversized batch must close
            # as many FULL windows as it spans, not collapse into one
            start = 0
            while start < n:
                take = min(n - start, self.window - self._win_n)
                seg = slice(start, start + take)
                self._win_n += take
                self._win_sum += x[seg].sum(axis=0)
                self._win_oor += int(oor_flags[seg].sum())
                start += take
                if self._win_n >= self.window:
                    closed.append(self._close_window_locked())
        return closed

    def _close_window_locked(self) -> dict:
        w = float(self._win_n)
        win_mean = self._win_sum / w
        shift = np.abs(win_mean - self._mean) / self._std
        max_shift = float(shift.max())
        oor_frac = self._win_oor / w
        reasons: List[str] = []
        if max_shift > self.shift_bar:
            dim = int(np.argmax(shift))
            reasons.append(
                f"mean_shift: dim {dim} moved {max_shift:.2f} train-std"
            )
        if oor_frac > self.oor_frac_bar:
            reasons.append(
                f"out_of_mass: {oor_frac:.2f} of rows past the train q99 "
                "distance"
            )
        verdict = {
            "rows": self._win_n,
            "max_shift_std": max_shift,
            "oor_frac": oor_frac,
            "breached": bool(reasons),
            "reasons": reasons,
        }
        self.windows_closed += 1
        self.last_shift = max_shift
        self.last_oor_frac = oor_frac
        self._recent.append(verdict)
        if reasons:
            self._consecutive_breaches += 1
        else:
            self._consecutive_breaches = 0
        was_alert = self.alert
        self.alert = self._consecutive_breaches >= self.breach_windows
        if self.alert:
            self.alert_reasons = reasons
        elif was_alert:
            self.alert_reasons = []
        verdict["alert"] = self.alert
        verdict["alert_changed"] = self.alert != was_alert
        self._win_n = 0
        self._win_sum = np.zeros_like(self._mean)
        self._win_oor = 0
        return verdict

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rows": self.rows,
                "window": self.window,
                "windows_closed": self.windows_closed,
                "consecutive_breaches": self._consecutive_breaches,
                "alert": self.alert,
                "alert_reasons": list(self.alert_reasons),
                "last_max_shift_std": self.last_shift,
                "last_oor_frac": self.last_oor_frac,
                "train_dist_q99": self._dist_q99,
            }


# --------------------------------------------------------------------------
# pending-prediction ring (the observe join buffer)
# --------------------------------------------------------------------------


class PendingRing:
    """Bounded ``request_id -> (μ, σ²)`` buffer with idempotent joins.

    ``put`` parks one answered request's predictive distribution;
    ``join`` pops it for grading.  Capacity is strictly enforced
    (oldest-first eviction, counted) so a client that never sends labels
    cannot grow server memory.  A bounded ring of RECENTLY JOINED ids
    distinguishes a duplicate observation (idempotent no-op — the
    fleet-client retry pattern re-sends) from a genuinely unknown id
    (:class:`UnknownRequestError`)."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._pending: "OrderedDict[str, tuple]" = OrderedDict()
        self._joined: "OrderedDict[str, None]" = OrderedDict()
        self.evicted = 0

    def put(self, request_id: str, mean, var) -> None:
        with self._lock:
            # a re-served id (hedged duplicate dispatch, client resend)
            # overwrites: one logical request, one pending entry
            self._pending[request_id] = (mean, var)
            self._pending.move_to_end(request_id)
            while len(self._pending) > self.capacity:
                self._pending.popitem(last=False)
                self.evicted += 1

    def join(self, request_id: str, expect_rows: Optional[int] = None):
        """``(mean, var)`` for the id, popping it; ``None`` for an
        already-joined id (the idempotent duplicate); raises
        :class:`UnknownRequestError` otherwise.  A non-None
        ``expect_rows`` that disagrees with the parked prediction raises
        ``ValueError`` WITHOUT consuming the entry — the client's
        corrected retry must still find a pending prediction, not an
        idempotent-duplicate no-op that silently loses the labels."""
        with self._lock:
            entry = self._pending.get(request_id)
            if entry is not None:
                if (
                    expect_rows is not None
                    and entry[0].shape[0] != int(expect_rows)
                ):
                    raise ValueError(
                        f"observation for {request_id!r} has "
                        f"{int(expect_rows)} label(s) but the prediction "
                        f"served {entry[0].shape[0]} row(s)"
                    )
                del self._pending[request_id]
                self._joined[request_id] = None
                while len(self._joined) > self.capacity:
                    self._joined.popitem(last=False)
                return entry
            if request_id in self._joined:
                return None
        raise UnknownRequestError(request_id)

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)


# --------------------------------------------------------------------------
# the serve-side plane (per-model composition + metric emission)
# --------------------------------------------------------------------------


#: live drift monitors kept per model NAME: stable + canary candidate +
#: headroom for a rollback/re-register racing in
_DRIFT_VERSIONS = 4


class _ModelQuality:
    """One served model name's quality state."""

    __slots__ = ("monitor", "drifts", "pending")

    def __init__(self, monitor, pending) -> None:
        self.monitor = monitor
        self.pending = pending
        # version -> Optional[DriftMonitor]: per VERSION, not one slot —
        # a canary rollout alternates stable/candidate dispatches of the
        # same name, and a single last-seen-version slot would rebuild
        # the monitor on every alternation, resetting the drift window
        # before it could ever close (drift alerting silently dead
        # exactly while a canary is active)
        self.drifts: "OrderedDict[object, Optional[DriftMonitor]]" = (
            OrderedDict()
        )

    def drift_for(self, version) -> Optional[DriftMonitor]:
        return self.drifts.get(version)

    def live_drifts(self) -> List[DriftMonitor]:
        return [d for d in self.drifts.values() if d is not None]


def quality_enabled_default() -> bool:
    """The plane's default gate: on unless ``GP_SERVE_QUALITY`` disables
    it (read at server construction, the lifecycle knobs' convention)."""
    import os

    return os.environ.get("GP_SERVE_QUALITY", "").strip().lower() not in (
        "0", "off", "false",
    )


class ServeQualityPlane:
    """Every served model's quality state, plus metric emission.

    The server calls three things: :meth:`note_predictions` on the
    batcher thread after each successful dispatch (park the answered
    requests' distributions, score the batch rows for drift),
    :meth:`observe` from the reader threads when delayed labels arrive
    (join + grade + verdict), and :meth:`snapshot` / :meth:`alert_reason`
    from the health verb and the canary guard.  All metric keys are
    registered in ``obs/names.py``; alert flips are span events so they
    land in the flight recorder next to the systems-health history.

    The batcher thread is the serving bottleneck (one dispatch loop,
    GIL-contended against every submitting client), so
    :meth:`note_predictions` does NO statistics there: it appends the
    batch to a bounded lock-free feed (a plain deque — GIL-atomic
    append, and deliberately NO wakeup notify: waking the drainer per
    dispatch forces a GIL handoff convoy on exactly the thread being
    protected) and a background drainer polls every ``DRAIN_INTERVAL_S``
    and does the pending-ring puts and drift scoring in one sweep.  A
    full feed drops the batch (counted) — telemetry must never apply
    backpressure to serving.  :meth:`observe` flushes the feed (with an
    explicit wake) before declaring a request_id unknown, so the
    label-after-reply race resolves correctly."""

    #: bound on batches parked for the drainer; beyond it batches are
    #: dropped (telemetry loss, never serve latency)
    FEED_CAPACITY = 512
    #: drainer poll cadence — the monitor's verdict latency floor, far
    #: under any real label delay
    DRAIN_INTERVAL_S = 0.05

    def __init__(
        self,
        metrics,
        window: int = 128,
        drift_window: int = 64,
        breach_windows: int = 2,
        pending_capacity: int = 4096,
    ) -> None:
        self.metrics = metrics
        self.window = int(window)
        self.drift_window = int(drift_window)
        self.breach_windows = int(breach_windows)
        self.pending_capacity = int(pending_capacity)
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelQuality] = {}
        self._feed: deque = deque()
        self._wake = threading.Event()
        self._busy = False
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._closed = False
        self.dropped_batches = 0

    def _state_for(self, name: str, entry=None) -> _ModelQuality:
        with self._lock:
            state = self._models.get(name)
            if state is None:
                state = self._models[name] = _ModelQuality(
                    QualityMonitor(
                        window=self.window,
                        breach_windows=self.breach_windows,
                    ),
                    PendingRing(self.pending_capacity),
                )
        if entry is not None and entry.version not in state.drifts:
            with self._lock:
                if entry.version not in state.drifts:
                    # bind a drift scorer to THIS version's fit-time
                    # covariate summary — a hot swap onto a retrained
                    # model must score against the new model's training
                    # mass, not the old.  Copy-on-write: readers
                    # (drainer scoring, health snapshots) iterate
                    # whatever dict object they grabbed, lock-free.
                    summary = getattr(entry.model, "covariate_summary", None)
                    drifts = OrderedDict(state.drifts)
                    drifts[entry.version] = (
                        None if not summary
                        else DriftMonitor(
                            summary,
                            window=self.drift_window,
                            breach_windows=self.breach_windows,
                        )
                    )
                    while len(drifts) > _DRIFT_VERSIONS:
                        drifts.popitem(last=False)
                    state.drifts = drifts
        return state

    # -- batcher-thread feed ------------------------------------------------
    def note_predictions(self, name, entry, group, rows, mean, var, x) -> None:
        """Hand one successful dispatch to the drainer thread: collect
        the ``(request_id, offset, rows)`` triples (the only per-request
        work) and enqueue the batch by reference — the batcher pays a
        short python loop plus one bounded-queue put.  ``mean``/``var``/
        ``x`` are the executor's own write-once-per-dispatch buffers, so
        handing references across threads is safe."""
        ids = []
        offset = 0
        for req, t in zip(group, rows):
            if req.request_id is not None and getattr(
                req, "observable", True
            ):
                ids.append((req.request_id, offset, t))
            offset += t
        if len(self._feed) >= self.FEED_CAPACITY:
            # racy overshoot by a few entries is fine; the bound holds
            self.dropped_batches += 1
            return
        self._feed.append((name, entry, ids, mean, var, x))
        worker = self._worker
        if worker is None or not worker.is_alive():
            self._ensure_worker()

    def _ensure_worker(self) -> None:
        with self._worker_lock:
            if self._closed or (
                self._worker is not None and self._worker.is_alive()
            ):
                return
            self._worker = threading.Thread(
                target=self._drain_loop, name="gp-serve-quality", daemon=True
            )
            self._worker.start()

    def _drain_loop(self) -> None:
        while not self._closed:
            self._wake.wait(self.DRAIN_INTERVAL_S)
            self._wake.clear()
            self._busy = True
            try:
                while True:
                    try:
                        item = self._feed.popleft()
                    except IndexError:
                        break
                    try:
                        self._process(*item)
                    except Exception:  # noqa: BLE001 — telemetry must never die
                        import logging

                        logging.getLogger("spark_gp_tpu").warning(
                            "quality drainer failed on a batch", exc_info=True
                        )
            finally:
                self._busy = False

    def _process(self, name, entry, ids, mean, var, x) -> None:
        """One dispatched batch's quality work (drainer thread): park the
        id-carrying requests' distributions, score the rows for drift."""
        state = self._state_for(name, entry)
        if ids and var is not None:
            # ONE vectorized f64 conversion; each parked entry COPIES its
            # slice — a view would pin the whole dispatch's buffers alive
            # for as long as one 1-row entry stays pending (a 4096-deep
            # ring of 1-row views into 1024-row batches retains ~1000x
            # the useful bytes)
            mean64 = np.asarray(mean, dtype=np.float64)
            var64 = np.asarray(var, dtype=np.float64)
            for request_id, offset, t in ids:
                state.pending.put(
                    request_id,
                    mean64[offset : offset + t].copy(),
                    var64[offset : offset + t].copy(),
                )
            self.metrics.set_gauge(
                f"quality.pending_depth.{name}", float(state.pending.depth())
            )
        drift = None if entry is None else state.drift_for(entry.version)
        if drift is not None:
            for verdict in drift.score_rows(x):
                self._emit_drift_window(name, state, verdict)

    def flush(self, timeout_s: float = 2.0) -> bool:
        """Wait until every parked batch has been processed (bounded).
        The observe path calls this before declaring an id unknown, so
        a label arriving right behind its reply cannot lose the race
        against the drainer.  Wakes the drainer explicitly — the one
        place an immediate drain is worth a GIL handoff."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self._feed and not self._busy:
                return True
            if not self._closed and (
                self._worker is None or not self._worker.is_alive()
            ):
                self._ensure_worker()  # a died worker must not wedge this
            self._wake.set()
            time.sleep(0.002)
        return False

    def close(self) -> None:
        """Stop the drainer (server stop/drain); idempotent.  Batches
        still parked are dropped — shutdown telemetry loss, never a
        shutdown hang."""
        with self._worker_lock:
            self._closed = True
            worker = self._worker
        self._wake.set()
        if worker is not None:
            worker.join(timeout=2.0)

    # -- label joins ----------------------------------------------------------
    def observe(self, name: str, request_id: str, y, entry=None) -> dict:
        """Join delayed labels to the parked prediction and grade it.

        ``y`` is the ground-truth vector for the request's rows (scalar
        accepted for 1-row requests).  Idempotent per ``request_id``:
        the duplicate of an already-joined observation is a counted
        no-op.  Raises :class:`UnknownRequestError` when no prediction
        is pending."""
        state = self._state_for(name, entry)
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        # length is checked INSIDE the join (against the parked entry,
        # without consuming it): a mismatched observation must leave the
        # prediction pending so the client's corrected retry still grades
        expect = int(y.shape[0])
        try:
            joined = state.pending.join(str(request_id), expect_rows=expect)
        except UnknownRequestError:
            # the prediction's batch may still sit in the drainer feed
            # (a label arriving right behind its reply): flush once and
            # retry before declaring the id unknown
            self.flush()
            try:
                joined = state.pending.join(
                    str(request_id), expect_rows=expect
                )
            except UnknownRequestError:
                self.metrics.inc("quality.observe.unknown_request")
                raise
        if joined is None:
            self.metrics.inc("quality.observe.duplicate")
            return {
                "model": name, "request_id": str(request_id),
                "joined": 0, "duplicate": True,
            }
        mean, var = joined
        self.metrics.inc("quality.observations", float(y.shape[0]))
        for verdict in state.monitor.observe(mean, var, y):
            self._emit_quality_window(name, state, verdict)
        self._set_quality_gauges(name, state)
        return {
            "model": name, "request_id": str(request_id),
            "joined": int(y.shape[0]), "duplicate": False,
            "alert": state.monitor.alert,
        }

    # -- metric emission -----------------------------------------------------
    def _set_quality_gauges(self, name: str, state: _ModelQuality) -> None:
        snap = state.monitor.snapshot()
        if snap["observations"] == 0:
            return
        self.metrics.set_gauge(f"quality.z_mean.{name}", snap["z_mean"])
        self.metrics.set_gauge(f"quality.z_std.{name}", snap["z_std"])
        self.metrics.set_gauge(f"quality.nll_mean.{name}", snap["nll_mean"])
        for level, value in snap["coverage"].items():
            if value is not None:
                # concatenation (not an f-string) keeps the linter from
                # wildcarding BOTH parts; the concrete keys match the
                # registered quality.coverage_<level>.* patterns
                self.metrics.set_gauge(
                    "quality.coverage_" + level + "." + name, value
                )

    def _emit_quality_window(self, name, state, verdict: dict) -> None:
        from spark_gp_tpu.obs import trace as obs_trace

        self.metrics.inc("quality.windows")
        if verdict["alert_changed"]:
            self.metrics.set_gauge(
                f"quality.alert.{name}", 1.0 if verdict["alert"] else 0.0
            )
            if verdict["alert"]:
                self.metrics.inc("quality.alerts")
                obs_trace.add_event(
                    "quality.alert", model=name,
                    reasons="; ".join(verdict["reasons"]),
                )
            else:
                obs_trace.add_event("quality.recovered", model=name)

    def _emit_drift_window(self, name, state, verdict: dict) -> None:
        from spark_gp_tpu.obs import trace as obs_trace

        self.metrics.inc("drift.windows")
        self.metrics.set_gauge(
            f"drift.score.{name}", verdict["max_shift_std"]
        )
        if verdict["alert_changed"]:
            self.metrics.set_gauge(
                f"drift.alert.{name}", 1.0 if verdict["alert"] else 0.0
            )
            if verdict["alert"]:
                self.metrics.inc("drift.alerts")
                obs_trace.add_event(
                    "drift.alert", model=name,
                    reasons="; ".join(verdict["reasons"]),
                )
            else:
                obs_trace.add_event("drift.recovered", model=name)

    # -- verdict consumers -----------------------------------------------------
    def alert_reason(self, name: str) -> Optional[str]:
        """One-line active-alert description for ``name`` (the canary
        guard's input), or None when healthy/unknown."""
        with self._lock:
            state = self._models.get(name)
        if state is None:
            return None
        if state.monitor.alert:
            return "miscalibration: " + "; ".join(state.monitor.alert_reasons)
        for drift in state.live_drifts():
            if drift.alert:
                return "input drift: " + "; ".join(drift.alert_reasons)
        return None

    def alerting_models(self) -> List[str]:
        with self._lock:
            names = list(self._models)
        return sorted(n for n in names if self.alert_reason(n) is not None)

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._models.items())
        models = {}
        for name, state in items:
            # surface ONE drift snapshot per name: an alerting monitor
            # wins (the health payload must show the problem), else the
            # most recently bound version's
            drifts = state.live_drifts()
            drift = next(
                (d for d in drifts if d.alert),
                drifts[-1] if drifts else None,
            )
            models[name] = {
                "calibration": state.monitor.snapshot(),
                "drift": (
                    None if drift is None else drift.snapshot()
                ),
                "pending": {
                    "depth": state.pending.depth(),
                    "capacity": state.pending.capacity,
                    "evicted": state.pending.evicted,
                },
            }
        return {
            "enabled": True,
            "window": self.window,
            "breach_windows": self.breach_windows,
            "dropped_batches": self.dropped_batches,
            "alerting": self.alerting_models(),
            "models": models,
        }
