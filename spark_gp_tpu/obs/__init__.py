"""Unified observability layer: span tracing, metric exposition, runtime
telemetry.

Three modules, one system (docs/OBSERVABILITY.md):

* :mod:`spark_gp_tpu.obs.trace` — context-var span tracer.  Nested,
  attributed spans with a process-global ring buffer; a fit or a serve
  request renders as one tree.  ``Instrumentation.phase`` and the serve
  batch path emit into it automatically.  Exports: JSONL and
  Chrome/Perfetto ``trace_event``.
* :mod:`spark_gp_tpu.obs.expo` — OpenMetrics/Prometheus text exposition
  of any :class:`~spark_gp_tpu.serve.metrics.ServingMetrics` /
  :class:`~spark_gp_tpu.utils.instrumentation.Instrumentation` instance,
  plus a minimal plain-text TCP scrape listener.
* :mod:`spark_gp_tpu.obs.runtime` — the JAX runtime bridge:
  ``jax.monitoring`` compile/retrace counting per entry point,
  ``device.memory_stats()`` gauges sampled on phase boundaries, and the
  per-fit ``run_journal`` artifact.
* :mod:`spark_gp_tpu.obs.recorder` — the flight recorder (bounded event
  ring fed by span events, failures and the serve metric watchlist) and
  the incident bundles dumped on terminal classified failures.
* :mod:`spark_gp_tpu.obs.cost` — XLA ``cost_analysis`` attribution:
  measured flops/bytes per compiled entry point, and the measured
  optimize-phase MFU stamped into run journals (``GP_XLA_COST=1``).

Every metric key any of this emits is registered in
:mod:`spark_gp_tpu.obs.names` — the one catalog
``tools/check_metric_names.py`` lints the package against.
"""

from spark_gp_tpu.obs.trace import (  # noqa: F401
    add_event,
    current_span,
    set_tracing,
    span,
    tracing_enabled,
)
from spark_gp_tpu.obs.expo import render_openmetrics  # noqa: F401
from spark_gp_tpu.obs.recorder import (  # noqa: F401
    RECORDER,
    dump_incident,
    recording_enabled,
    set_recording,
)
from spark_gp_tpu.obs.runtime import (  # noqa: F401
    build_info,
    telemetry,
    write_run_journal,
)
