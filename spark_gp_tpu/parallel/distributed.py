"""Multi-host orchestration: the DCN-side counterpart of the in-chip mesh.

The reference's distributed backend is Spark's driver<->executor RPC with
``treeAggregate``/``broadcast`` (SURVEY.md §2.4).  On TPU the communication
splits into two planes:

* **ICI** (inter-chip interconnect) carries every algorithmic collective —
  the (NLL, grad) psum of the BCM objective and the (U1, u2) psum of the
  PPA statistics (likelihood.py / ppa.py shard_map programs).  Nothing in
  this module touches ICI: XLA inserts those collectives from the sharding
  annotations.
* **DCN** (data-center network) only carries process coordination and
  per-host data feeding — this module.  There is no point-to-point traffic
  anywhere in the algorithm (SURVEY.md §2.4), so the DCN layer is exactly
  three things: runtime initialization, a global mesh over every host's
  chips, and assembling globally-sharded expert stacks from process-local
  rows.

Single-process environments (one chip, CPU tests, the 8-device simulated
mesh) pass through unchanged: ``initialize()`` is a no-op,
``global_expert_mesh()`` sees only local devices, and
``distribute_global_experts`` degrades to :func:`mesh.shard_experts`.

Typical multi-host launch (same program on every host, e.g. via the TPU VM
runtime or mpirun over DCN):

    from spark_gp_tpu.parallel import distributed as dist

    dist.initialize()                       # env-driven coordinator discovery
    mesh = dist.global_expert_mesh()        # 1-D mesh over ALL hosts' chips
    data = dist.distribute_global_experts(  # per-host rows -> global [E,s,p]
        x_local, y_local, expert_size, mesh
    )
    model = (GaussianProcessRegression()... .setMesh(mesh)).fit_distributed(...)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS, expert_mesh, shard_experts


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the JAX distributed runtime (DCN coordination plane).

    A no-op when the runtime is already initialized or when running
    single-process with no coordinator configured — so library code can call
    it unconditionally.  On managed TPU pods all three arguments come from
    the environment and may be omitted (``jax.distributed.initialize()``
    autodetects); on hand-rolled clusters pass them explicitly.
    """
    import jax

    if jax.distributed.is_initialized():
        return
    if coordinator_address is None and num_processes is None:
        import os

        auto = (
            "COORDINATOR_ADDRESS" in os.environ
            or "JAX_COORDINATOR_ADDRESS" in os.environ
            or os.environ.get("TPU_WORKER_HOSTNAMES")
        )
        if not auto:
            return  # single-process: nothing to coordinate
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def num_processes() -> int:
    import jax

    return jax.process_count()


def global_expert_mesh():
    """1-D ``experts`` mesh over every chip of every host.

    ``jax.devices()`` is global after :func:`initialize`; the expert axis
    spans hosts so the psum collectives ride ICI within a slice and DCN only
    between slices (XLA picks the hierarchical reduction)."""
    return expert_mesh()


def distribute_global_experts(
    x_local: np.ndarray,
    y_local: np.ndarray,
    dataset_size_for_expert: int,
    mesh=None,
) -> ExpertData:
    """Assemble a globally-sharded expert stack from process-local rows.

    Each host contributes its own ``[n_local, p]`` rows (e.g. its shard of a
    distributed file set — the counterpart of HDFS partitions feeding Spark
    executors, GaussianProcessCommons.scala:20-24).  Rows are grouped into
    experts host-locally (round-robin is an arbitrary-but-balanced
    assignment — grouping locally just picks a different arbitrary balanced
    assignment and saves the all-to-all resharding), then the per-host
    ``[E_local, s, ...]`` stacks are stitched into one global array whose
    expert axis is sharded across all hosts' devices.

    Single-process: equivalent to ``shard_experts(group_for_experts(...))``.
    """
    import jax

    if mesh is None:
        mesh = global_expert_mesh()

    if jax.process_count() == 1:
        return shard_experts(
            group_for_experts(x_local, y_local, dataset_size_for_expert), mesh
        )

    from jax.sharding import NamedSharding, PartitionSpec as P

    local = group_for_experts(x_local, y_local, dataset_size_for_expert)
    # Every process must contribute the same expert count for a dense global
    # axis: pad to the max across hosts (masked experts contribute nothing).
    from jax.experimental import multihost_utils

    dims = np.asarray([local.num_experts, local.expert_size], dtype=np.int64)
    gathered = multihost_utils.process_allgather(dims, tiled=False)
    e_max, s_max = (int(v) for v in np.max(gathered.reshape(-1, 2), axis=0))
    if local.expert_size != s_max or local.num_experts != e_max:
        local = _pad_stack(local, e_max, s_max)

    def stitch(a):
        spec = P(EXPERT_AXIS, *([None] * (a.ndim - 1)))
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(a), mesh, spec
        )

    return ExpertData(
        x=stitch(local.x), y=stitch(local.y), mask=stitch(local.mask)
    )


def _pad_stack(data: ExpertData, e_target: int, s_target: int) -> ExpertData:
    """Pad an expert stack to [e_target, s_target, ...] with masked entries."""
    x = np.asarray(data.x)
    y = np.asarray(data.y)
    mask = np.asarray(data.mask)
    e, s = x.shape[0], x.shape[1]
    if s_target > s:
        # benign feature padding: repeat each expert's first point
        x_pad = np.repeat(x[:, :1], s_target - s, axis=1)
        x = np.concatenate([x, x_pad], axis=1)
        y = np.pad(y, ((0, 0), (0, s_target - s)))
        mask = np.pad(mask, ((0, 0), (0, s_target - s)))
    if e_target > e:
        x = np.concatenate(
            [x, np.repeat(x[:1], e_target - e, axis=0)], axis=0
        )
        y = np.pad(y, ((0, e_target - e), (0, 0)))
        mask = np.pad(mask, ((0, e_target - e), (0, 0)))
    import jax.numpy as jnp

    return ExpertData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.asarray(mask))
