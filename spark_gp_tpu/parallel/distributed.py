"""Multi-host orchestration: the DCN-side counterpart of the in-chip mesh.

The reference's distributed backend is Spark's driver<->executor RPC with
``treeAggregate``/``broadcast`` (SURVEY.md §2.4).  On TPU the communication
splits into two planes:

* **ICI** (inter-chip interconnect) carries every algorithmic collective —
  the (NLL, grad) psum of the BCM objective and the (U1, u2) psum of the
  PPA statistics (likelihood.py / ppa.py shard_map programs).  Nothing in
  this module touches ICI: XLA inserts those collectives from the sharding
  annotations.
* **DCN** (data-center network) only carries process coordination and
  per-host data feeding — this module.  There is no point-to-point traffic
  anywhere in the algorithm (SURVEY.md §2.4), so the DCN layer is exactly
  three things: runtime initialization, a global mesh over every host's
  chips, and assembling globally-sharded expert stacks from process-local
  rows.

Single-process environments (one chip, CPU tests, the 8-device simulated
mesh) pass through unchanged: ``initialize()`` is a no-op,
``global_expert_mesh()`` sees only local devices, and
``distribute_global_experts`` degrades to :func:`mesh.shard_experts`.

The theta-invariant gram cache (kernels/base.py precompute plane) needs
nothing from this module: ``fit_distributed`` builds it from the sharded
stack it is handed (one jitted vmapped ``prepare`` — GSPMD shards the
cache like the stack), the shard_map fit programs take it as one more
``P(EXPERT_AXIS)`` operand, and in DCN-fallback mode the stack is
host-local so the cache simply rides each host's local objective
programs across every KV-allreduced evaluation.

Typical multi-host launch (same program on every host, e.g. via the TPU VM
runtime or mpirun over DCN):

    from spark_gp_tpu.parallel import distributed as dist

    dist.initialize()                       # env-driven coordinator discovery
    mesh = dist.global_expert_mesh()        # 1-D mesh over ALL hosts' chips
    data = dist.distribute_global_experts(  # per-host rows -> global [E,s,p]
        x_local, y_local, expert_size, mesh
    )
    model = (GaussianProcessRegression()... .setMesh(mesh)).fit_distributed(...)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS, expert_mesh, shard_experts


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the JAX distributed runtime (DCN coordination plane).

    A no-op when the runtime is already initialized or when running
    single-process with no coordinator configured — so library code can call
    it unconditionally.  On managed TPU pods all three arguments come from
    the environment and may be omitted (``jax.distributed.initialize()``
    autodetects); on hand-rolled clusters pass them explicitly.

    ``jax.distributed.initialize`` hard-fails once the XLA backend exists
    (it must run before ``jax.devices()``/any computation).  If the backend
    is already up, joining a coordination plane is impossible — this
    function then degrades to single-process with a ``RuntimeWarning``
    rather than crashing callers that invoke it defensively in
    environments (e.g. a single-host TPU site) where coordinator env vars
    happen to be set.
    """
    from spark_gp_tpu.parallel import coord
    from spark_gp_tpu.utils.platform import backends_already_initialized

    if coord.runtime_initialized():
        return
    auto = coordinator_address is None and num_processes is None
    multi_host = False
    if auto:
        import os

        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        detected = (
            "COORDINATOR_ADDRESS" in os.environ
            or "JAX_COORDINATOR_ADDRESS" in os.environ
            or hostnames
        )
        # A genuinely multi-host cluster must not silently degrade: each host
        # training on 1/P of the data would be wrong results with no error.
        # An explicit coordinator address is deliberate cluster config (a
        # single-host TPU site sets only TPU_WORKER_HOSTNAMES=localhost).
        multi_host = (
            "COORDINATOR_ADDRESS" in os.environ
            or "JAX_COORDINATOR_ADDRESS" in os.environ
            or len([h for h in hostnames.split(",") if h.strip()]) > 1
        )
        if not detected:
            return  # single-process: nothing to coordinate
    if backends_already_initialized():
        late_msg = (
            "distributed.initialize() called after the XLA backend was "
            "initialized; multi-process coordination is unavailable (it must "
            "run before jax.devices()/device_put/any computation)."
        )
        if not auto or multi_host:
            # Explicit coordinator args or a detected multi-host pod:
            # silently training 1/num_processes of the data per host would
            # be a correctness bug — fail loudly.
            raise RuntimeError(late_msg)
        _degraded_to_single_process("backend_already_initialized")
        import warnings

        warnings.warn(
            late_msg + " Continuing single-process.",
            RuntimeWarning,
            stacklevel=2,
        )
        return
    try:
        coord.initialize_runtime(
            coordinator_address, num_processes, process_id
        )
    except (RuntimeError, ValueError) as exc:
        # RuntimeError: the backend raced us up; ValueError: env vars present
        # but incomplete (e.g. TPU_WORKER_HOSTNAMES with no coordinator
        # address on a single-host TPU site).
        if not auto or multi_host:
            raise  # real cluster: surface the failure, don't train 1/P-wrong
        _degraded_to_single_process(type(exc).__name__)
        import warnings

        warnings.warn(
            f"jax.distributed.initialize() failed during env-driven "
            f"autodetection; continuing single-process: {exc}",
            RuntimeWarning,
            stacklevel=2,
        )


def _degraded_to_single_process(reason: str) -> None:
    """A warning that scrolls by is how pod misconfiguration ships: the
    silent-degrade branches ALSO count ``coord.degraded`` (OpenMetrics /
    run journals) and stamp a span event, so a fleet dashboard sees every
    process that quietly fell back to 1/P of the job."""
    from spark_gp_tpu.obs import trace as obs_trace
    from spark_gp_tpu.obs.runtime import telemetry

    telemetry.inc("coord.degraded")
    obs_trace.add_event("coord.degraded", reason=reason)


def num_processes() -> int:
    import jax

    return jax.process_count()


def global_expert_mesh():
    """1-D ``experts`` mesh over every chip of every host.

    ``jax.devices()`` is global after :func:`initialize`; the expert axis
    spans hosts so the psum collectives ride ICI within a slice and DCN only
    between slices (XLA picks the hierarchical reduction).

    On backends whose runtime cannot execute one program across processes
    (``coord.dcn_required()`` — this jax's CPU backend), a cross-host mesh
    would make every fit program hang or crash; the mesh then covers the
    LOCAL devices only and the cross-host sums ride the KV store instead
    (the DCN-fallback fit mode, ``parallel/coord.py``)."""
    import jax

    from spark_gp_tpu.parallel import coord

    if coord.dcn_required():
        return expert_mesh(jax.local_devices())
    return expert_mesh()


def distribute_global_experts(
    x_local: np.ndarray,
    y_local: np.ndarray,
    dataset_size_for_expert: int,
    mesh=None,
) -> ExpertData:
    """Assemble a globally-sharded expert stack from process-local rows.

    Each host contributes its own ``[n_local, p]`` rows (e.g. its shard of a
    distributed file set — the counterpart of HDFS partitions feeding Spark
    executors, GaussianProcessCommons.scala:20-24).  Rows are grouped into
    experts host-locally (round-robin is an arbitrary-but-balanced
    assignment — grouping locally just picks a different arbitrary balanced
    assignment and saves the all-to-all resharding), then the per-host
    ``[E_local, s, ...]`` stacks are stitched into one global array whose
    expert axis is sharded across all hosts' devices.

    Single-process: equivalent to ``shard_experts(group_for_experts(...))``.

    Multi-process, two modes (``parallel/coord.py``):

    * **global-array** (TPU pods): the per-host dims exchange rides
      ``coord.kv_allgather`` (deadline-guarded, names a dead host instead
      of hanging; falls back to ``process_allgather`` when the KV client
      is unavailable) and the stitch itself is entered through a guarded
      barrier.
    * **DCN fallback** (backends with no cross-process execution): the
      local rows become a LOCAL expert stack on the local mesh; the fit's
      cross-host sums ride the KV store.
    """
    import jax

    from spark_gp_tpu.parallel import coord

    if mesh is None:
        mesh = global_expert_mesh()

    if jax.process_count() == 1:
        return shard_experts(
            group_for_experts(x_local, y_local, dataset_size_for_expert), mesh
        )

    if coord.dcn_required():
        # DCN-fallback: host-local stack, host-local mesh; dims need no
        # exchange (each host's objective terms are summed over the KV
        # store, so per-host expert counts may differ freely).  Creating
        # the context here also starts the heartbeat monitor.
        coord.dcn_context()
        if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
            mesh = expert_mesh(jax.local_devices())
        return shard_experts(
            group_for_experts(x_local, y_local, dataset_size_for_expert), mesh
        )

    from jax.sharding import NamedSharding, PartitionSpec as P

    local = group_for_experts(x_local, y_local, dataset_size_for_expert)
    # Every process must contribute the same expert count for a dense global
    # axis: pad to the max across hosts (masked experts contribute nothing).
    dims = np.asarray([local.num_experts, local.expert_size], dtype=np.int64)
    gathered = _exchange_dims(dims)
    e_max, s_max = (int(v) for v in np.max(gathered.reshape(-1, 2), axis=0))
    # The stitched global expert axis (e_max * num_processes) must divide
    # evenly over the mesh actually used for P(EXPERT_AXIS) sharding: round
    # e_max up to a multiple of the mesh's per-process device count (NOT
    # jax.local_device_count() — the mesh may span a device subset).
    per_proc = max(1, mesh.devices.size // jax.process_count())
    e_max = -(-e_max // per_proc) * per_proc
    if local.expert_size != s_max or local.num_experts != e_max:
        local = _pad_stack(local, e_max, s_max)

    # ONE guarded rendezvous covers all three stitches (each barrier is a
    # cluster round-trip; the three native calls share the fate the first
    # barrier already established)
    coord.guard_collective("stitch")

    def stitch(a):
        spec = P(EXPERT_AXIS, *([None] * (a.ndim - 1)))
        return coord.host_local_to_global(
            np.asarray(a), mesh, spec, guarded=False
        )

    return ExpertData(
        x=stitch(local.x), y=stitch(local.y), mask=stitch(local.mask)
    )


_DIMS_ROUND = 0


def _exchange_dims(dims: np.ndarray) -> np.ndarray:
    """``[P, 2]`` per-host (num_experts, expert_size): through the KV store
    when the coordination service is up — deadline-guarded, and the only
    path the CPU backend can take at all (its ``process_allgather`` runs a
    jitted collective the runtime refuses cross-process) — else the legacy
    raw collective."""
    from spark_gp_tpu.parallel import coord

    client = coord.coord_client()
    if client is not None:
        # every process runs the same program, so its k-th dims exchange is
        # every peer's k-th too — the lockstep counter IS the shared nonce
        # (stale keys from exchange k-1 can never satisfy exchange k).  No
        # extra guard barrier: kv_allgather is itself a deadline-guarded
        # rendezvous with the chaos hooks applied.
        global _DIMS_ROUND
        round_id, _DIMS_ROUND = _DIMS_ROUND, _DIMS_ROUND + 1
        payloads = coord.kv_allgather(
            f"dims/{round_id}", dims.tobytes(), client=client
        )
        if round_id >= 2:
            # same r-2 GC as DcnContext.allgather_bytes: completing round
            # r means every peer published round r, i.e. finished reading
            # all earlier rounds — our r-2 key is provably drained
            client.delete(f"ag/dims/{round_id - 2}/{client.process_id}")
        return np.stack([
            np.frombuffer(p, dtype=dims.dtype) for p in payloads
        ])
    from jax.experimental import multihost_utils  # collective-guard-ok

    gathered = multihost_utils.process_allgather(dims, tiled=False)  # collective-guard-ok
    return gathered.reshape(-1, 2)


def replicated_valid_indices(data: ExpertData, mesh) -> np.ndarray:
    """Global flat indices of the real (unpadded) rows of a sharded stack,
    identical on every host.

    The validity mask is tiny (N floats) so resharding it to replicated is
    cheap; every process then sees the same index set and can make
    deterministic seeded draws without any further coordination.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    mask = np.asarray(jax.jit(lambda a: a, out_shardings=rep)(data.mask))
    return np.flatnonzero(mask.reshape(-1) > 0)


def sample_active_from_stack(
    data: ExpertData, m: int, seed: int, mesh
) -> np.ndarray:
    """Uniform active-set selection straight off a (possibly multi-host)
    sharded expert stack, returned replicated on every host.

    The multi-host counterpart of RandomActiveSetProvider / the reference's
    ``takeSample`` (ActiveSetProvider.scala:48-56): no host ever sees the
    global rows.  Every process draws the *same* m flat indices from the
    shared seed (via :func:`replicated_valid_indices`), then the [m, p] row
    gather runs as one XLA program with a replicated output — the cross-host
    traffic is the m selected rows, not the dataset.

    In DCN-fallback mode the replicated gather cannot run (no cross-process
    programs); the draw rides ``coord.sample_active_dcn`` instead — same
    uniform semantics, the m selected rows travel over the KV store.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_gp_tpu.parallel import coord

    ctx = coord.dcn_context()
    if ctx is not None:
        return coord.sample_active_dcn(ctx, data, m, seed)

    if mesh is None:
        # single-host stack (the degradation ladder's last sharded-fit
        # rung re-runs the distributed body over a host-fetched local
        # stack, resilience/fallback.py): the rows are all here — draw the
        # same uniform valid-row sample directly
        xf = np.asarray(data.x).reshape(-1, data.x.shape[-1])
        maskf = np.asarray(data.mask).reshape(-1)
        valid = np.flatnonzero(maskf > 0)
        m = min(m, valid.size)
        rng = np.random.default_rng(seed)
        return xf[np.sort(rng.choice(valid, size=m, replace=False))]

    rep = NamedSharding(mesh, P())
    valid = replicated_valid_indices(data, mesh)
    # clamp like RandomActiveSetProvider so fit_distributed keeps fit()'s
    # single-process behavior for m > N
    m = min(m, valid.size)
    rng = np.random.default_rng(seed)
    sel = np.sort(rng.choice(valid, size=m, replace=False))

    p = data.x.shape[-1]
    gather = jax.jit(
        lambda x, i: x.reshape(-1, p)[i], out_shardings=rep
    )
    return np.asarray(gather(data.x, jnp.asarray(sel)))


def _pad_stack(data: ExpertData, e_target: int, s_target: int) -> ExpertData:
    """Pad an expert stack to [e_target, s_target, ...] with masked entries."""
    x = np.asarray(data.x)
    y = np.asarray(data.y)
    mask = np.asarray(data.mask)
    e, s = x.shape[0], x.shape[1]
    if s_target > s:
        # benign feature padding: repeat each expert's first point
        x_pad = np.repeat(x[:, :1], s_target - s, axis=1)
        x = np.concatenate([x, x_pad], axis=1)
        y = np.pad(y, ((0, 0), (0, s_target - s)))
        mask = np.pad(mask, ((0, 0), (0, s_target - s)))
    if e_target > e:
        x = np.concatenate(
            [x, np.repeat(x[:1], e_target - e, axis=0)], axis=0
        )
        y = np.pad(y, ((0, e_target - e), (0, 0)))
        mask = np.pad(mask, ((0, e_target - e), (0, 0)))
    import jax.numpy as jnp

    return ExpertData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.asarray(mask))
