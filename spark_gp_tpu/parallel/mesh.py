"""Device meshes and sharding helpers for the expert axis.

The reference's parallelism (SURVEY.md §2.3) is exactly one strategy — data
parallelism over experts with all-reduce — plus broadcast.  Mapping:

* Spark executors          -> devices of a 1-D ``jax.sharding.Mesh``
* RDD of experts           -> ``[E, ...]`` arrays sharded on ``EXPERT_AXIS``
* ``treeAggregate``        -> ``jax.lax.psum`` over ICI inside ``shard_map``
* ``broadcast(activeSet)`` -> replicated sharding (every chip holds the m
  active points and the m x m factors)

``aggregationDepth`` (declared but never forwarded in the reference,
GaussianProcessParams.scala:9) has no analogue: the all-reduce tree shape is
XLA's problem.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EXPERT_AXIS = "experts"


def expert_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name ``experts``."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (EXPERT_AXIS,))


def sharded_cache_operand(cache):
    """Optional expert-sharded operand plumbing for shard_map programs —
    THE home of the convention every sharded fit uses to carry the
    theta-invariant gram cache (kernels/base.py precompute plane).

    Returns ``(extra_specs, extra_args, unpack)``:

    * ``extra_specs`` — append to the program's ``in_specs`` (one
      ``P(EXPERT_AXIS)`` entry acting as a pytree PREFIX over the whole
      cache subtree, so composite caches shard every leaf on the expert
      axis), empty when there is no cache;
    * ``extra_args`` — append to the call's positional arguments;
    * ``unpack(maybe_cache)`` — recover the cache (or ``None``) from the
      body's trailing ``*maybe_cache`` varargs.

    One helper instead of eight hand-rolled copies: changing how the
    cache operand is sharded or validated happens here, nowhere else.
    """
    if cache is None:
        return (), (), (lambda maybe_cache: None)
    return (P(EXPERT_AXIS),), (cache,), (lambda maybe_cache: maybe_cache[0])


def sharded_weights_operand(weights):
    """The per-expert aggregation-weight twin of
    :func:`sharded_cache_operand` (``models/aggregation.py``): a ``[E]``
    weight vector shards on the expert axis exactly like the stack, so
    each device's local weighted partial sum psums to the global
    ``sum_e w_e NLL_e``.  Same ``(extra_specs, extra_args, unpack)``
    contract; ``None`` (every clean fit) contributes nothing to the
    program signature."""
    if weights is None:
        return (), (), (lambda maybe_w: None)
    return (P(EXPERT_AXIS),), (weights,), (lambda maybe_w: maybe_w[0])


def shard_experts(data, mesh: Mesh):
    """Place an :class:`ExpertData`-like pytree with leading expert axes onto
    the mesh, sharded on the leading axis, padding E to a device multiple."""
    from spark_gp_tpu.parallel.experts import ExpertData

    n_dev = mesh.devices.size
    data = data.pad_experts(n_dev)

    def put(a):
        spec = P(EXPERT_AXIS, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return ExpertData(x=put(data.x), y=put(data.y), mask=put(data.mask))


def replicated(a, mesh: Mesh):
    """Replicate an array on every device of the mesh (the ``broadcast``)."""
    return jax.device_put(a, NamedSharding(mesh, P()))


def mesh_shape(mesh):
    """JSON-able ``[[axis, size], ...]`` topology of a mesh (or ``None``)
    — the form the elastic-resume checkpoint stamp records so a resumed
    fit can tell "same mesh" from "re-sharded" (``parallel/coord.py``)."""
    if mesh is None:
        return None
    return [
        [str(name), int(size)]
        for name, size in zip(mesh.axis_names, mesh.devices.shape)
    ]
