"""Distribution layer: expert grouping, device meshes, sharded reductions.

TPU-native replacement for the reference's entire Spark runtime usage
(SURVEY.md §2.4): the ``groupByKey`` shuffle becomes a pad+reshape, RDD
partitions become a sharded leading array axis, ``treeAggregate`` becomes
``psum`` over ICI, and ``broadcast`` becomes replicated sharding.
"""

from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS, expert_mesh, shard_experts

__all__ = [
    "ExpertData",
    "group_for_experts",
    "EXPERT_AXIS",
    "expert_mesh",
    "shard_experts",
    # parallel.coord (imported lazily by consumers: the hardened DCN
    # coordination layer — deadline-guarded barriers, liveness,
    # coordinated checkpoints, the KV-store fit fallback)
]
