"""Hardened DCN coordination: every cross-host interaction routes through here.

The fit's algorithmic collectives (the BCM (NLL, grad) psum, the (U1, u2)
psum) ride ICI inside compiled programs — XLA's problem.  Everything else a
multi-host fit needs — agreeing on stack dims, electing a checkpoint writer,
noticing that a peer died — is *process coordination over DCN*, and before
this module it went through raw ``jax.experimental.multihost_utils`` calls
with no timeout, no liveness and no diagnosis: one slow, preempted or dead
host turned ``fit_distributed`` into an indefinite hang.

This module is the one place allowed to touch the ``jax.distributed``
runtime and ``multihost_utils`` (``tools/check_collective_guards.py`` lints
the rest of the package).  It provides:

* a **KV-store client** over the jax coordination service
  (:class:`KVStoreClient`) plus an in-process fake with injectable clock
  (:class:`InProcessCoordClient`) so every protocol here is tier-1-testable
  without real processes;
* **deadline-guarded barriers** and :func:`kv_allgather` /
  ``DcnContext.allreduce_arrays`` that raise
  :class:`CoordinationTimeoutError` *naming the missing process ids*
  instead of hanging;
* a **heartbeat/liveness registry** (:class:`HeartbeatMonitor`): each
  process stamps ``heartbeat/<pid>``; stragglers and dead hosts become
  span events and ``coord.*`` metrics; an EXPLICIT dead verdict handed
  to a gather aborts the wait early, while the passive monitor's own
  flags stay advisory (heartbeats are rightly quiet during long local
  compute — the deadline is the arbiter).  On the real runtime the monitor is
  driven from the main thread's coordination waits (``maybe_poll``),
  never a background thread: this jaxlib's KV client segfaults when
  called concurrently with jit compilation, so only the in-process fake
  client uses the threaded ``start()`` mode;
* **coordinated checkpointing** (:class:`CoordinatedLbfgsCheckpointer`,
  :class:`CoordinatedDeviceCheckpointer`): processes agree on the save
  step via barrier, process 0 writes (PR 2's atomic tmp+fsync+rename+
  sha256 writers, unchanged), every other process verifies the payload
  digest through the KV store — a divergent host is an error, not a
  silently different checkpoint;
* **elastic-resume metadata**: checkpoints carry ``(process_count,
  mesh_shape, expert_assignment)`` so a P-process fit can resume on P'
  processes — the iterate is replicated, only the expert stack re-shards
  — with :class:`~spark_gp_tpu.utils.checkpoint.ElasticResumeError` (a
  hard error, never silent wrong results) when the payload is
  incompatible;
* the **DCN-fallback fit mode** (:class:`DcnContext`): on backends whose
  runtime cannot execute one program across processes (this container's
  CPU backend: "Multiprocess computations aren't implemented"), the fit
  degrades to the reference's actual architecture — each host computes
  its local experts' contributions with local compiled programs and the
  small aggregates (the per-evaluation (NLL, grad), the (U1, u2)
  statistics, the sampled active rows) are summed deterministically over
  the KV store, Spark's ``treeAggregate`` over the driver network
  reborn on the jax coordination service.  TPU pods keep the native
  global-array path.

Timeout defaults (seconds, env-overridable): ``GP_COORD_TIMEOUT_S`` (120)
for barriers/gathers, ``GP_COORD_HEARTBEAT_S`` (5) for the stamp interval;
a peer is a *straggler* past 3 intervals without a fresh stamp and *dead*
past 10 (``GP_COORD_DEAD_AFTER_S`` overrides the latter).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "CoordinationTimeoutError",
    "DcnContext",
    "HeartbeatMonitor",
    "LivenessLedger",
    "InProcessCoordClient",
    "InProcessCoordStore",
    "KVStoreClient",
    "CoordinatedLbfgsCheckpointer",
    "CoordinatedDeviceCheckpointer",
    "barrier",
    "coord_client",
    "dcn_context",
    "elastic_meta",
    "kv_allgather",
    "liveness_snapshot",
    "install_preemption_watcher",
    "make_flag_handler",
    "preemption_requested",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_timeout_s() -> float:
    """The one deadline every guarded coordination step defaults to."""
    return _env_float("GP_COORD_TIMEOUT_S", 120.0)


def heartbeat_interval_s() -> float:
    return _env_float("GP_COORD_HEARTBEAT_S", 5.0)


class CoordinationTimeoutError(RuntimeError):
    """A cross-host coordination step blew its deadline.

    Carries the operation name, the deadline, and — the part a 3am pager
    actually needs — ``missing``: the process ids that never showed up.
    """

    def __init__(self, op: str, timeout_s: float, missing: Sequence[int],
                 detail: str = "") -> None:
        self.op = op
        self.timeout_s = float(timeout_s)
        self.missing = tuple(int(p) for p in missing)
        who = (
            f"missing process id(s) {list(self.missing)}"
            if self.missing else "missing process set unknown"
        )
        super().__init__(
            f"coordination step {op!r} timed out after {timeout_s:.1f}s: "
            f"{who}"
            + (f" ({detail})" if detail else "")
        )


def _bump(key: str, n: float = 1.0) -> None:
    """coord.* metrics ride the process-global runtime telemetry (the same
    sink as the compile counters), so they land in OpenMetrics pages and
    run journals without any new plumbing."""
    from spark_gp_tpu.obs.runtime import telemetry

    telemetry.inc(key, n=n)  # metric-name-ok (concrete key from the caller)


def _event(name: str, **attrs) -> None:
    from spark_gp_tpu.obs import trace as obs_trace

    obs_trace.add_event(name, **attrs)


# --------------------------------------------------------------------------
# clients
# --------------------------------------------------------------------------


class AgentErrorSignal(RuntimeError):
    """The native coordination agent reported an error state (not a plain
    deadline): a peer died and the runtime noticed.  Carries the error
    text so the caller can name the dead task(s) WITHOUT issuing further
    native calls on the (now unsafe) agent."""

    def __init__(self, message: str) -> None:
        self.message = message
        super().__init__(message)


def _tasks_named_in_error(message: str) -> List[int]:
    """Process ids the coordination service's own error text implicates
    (``.../task:1``) — the diagnosis source that needs NO further native
    call on an already-errored agent."""
    import re

    return sorted({int(m) for m in re.findall(r"task[:_](\d+)", message)})


def _gc_own_attendance(client, history: List[str], new_key: str) -> None:
    """Attendance-key GC shared by both clients' ``barrier``: record our
    own new stamp and delete the one from TWO barriers ago — any peer has
    passed barrier k-1 before we can enter barrier k (barriers are
    strictly sequential per process), so nobody can still be reading the
    k-2 stamp.  Without this a long coordinated fit leaks one attendance
    key per process per barrier into the coordination service forever."""
    history.append(new_key)
    if len(history) > 2:
        client.delete(history.pop(0))


class KVStoreClient:
    """The live coordination service of ``jax.distributed`` behind the one
    interface every protocol in this module is written against:

    ``set/get/dir_get`` move small ``bytes`` payloads; ``barrier`` is the
    native distributed barrier.  All waits are chunked (<= 0.5 s slices)
    so a deadline or a death verdict from the heartbeat monitor can abort
    a wait early instead of sleeping out the full native timeout.
    """

    _CHUNK_S = 0.5

    def __init__(self, client, process_id: int, num_processes: int) -> None:
        self._client = client
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.clock = time.monotonic
        self.sleep = time.sleep
        # one native call in flight per process: the heartbeat thread and
        # the fit thread share this client, and the native stub's
        # thread-safety is not a documented contract we want to lean on.
        # get() holds the lock only per <=0.5 s slice, so a blocked fit
        # gather never starves the heartbeat for longer than that.
        self._lock = threading.Lock()
        self._att_history: List[str] = []

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._client.key_value_set_bytes(
                key, bytes(value), allow_overwrite=True
            )

    def get(self, key: str, timeout_s: float) -> Optional[bytes]:
        """The value, or ``None`` on deadline (callers own the diagnosis).

        Raises :class:`AgentErrorSignal` when the exception is NOT a plain
        deadline expiry — the coordination agent has entered the error
        state (a peer died and the runtime noticed first).  Callers must
        then diagnose from the error text alone: further native calls on
        an errored agent (``key_value_dir_get`` in particular) segfault
        this jaxlib."""
        deadline = self.clock() + max(0.0, timeout_s)
        while True:
            remaining = deadline - self.clock()
            if remaining <= 0.0:
                return None
            slice_ms = max(1, int(min(remaining, self._CHUNK_S) * 1000))
            try:
                with self._lock:
                    return self._client.blocking_key_value_get_bytes(
                        key, slice_ms
                    )
            except Exception as exc:  # noqa: BLE001 — timeout or agent error
                msg = str(exc)
                if "DEADLINE" in msg.upper() or "NOT_FOUND" in msg.upper():
                    continue  # the slice expired; keep waiting
                raise AgentErrorSignal(msg) from exc

    def dir_get(self, prefix: str) -> Dict[str, bytes]:
        try:
            with self._lock:
                return dict(self._client.key_value_dir_get_bytes(prefix))
        except Exception:  # noqa: BLE001 — an empty directory may raise
            return {}

    def delete(self, key: str) -> None:
        try:
            with self._lock:
                self._client.key_value_delete(key)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    def barrier(self, name: str, timeout_s: float) -> None:
        """Native barrier, attendance-stamped: each process marks
        ``barrier_att/{name}/<pid>`` *before* waiting, so a timeout can
        name exactly who never arrived.

        Failure diagnosis is careful about WHICH failure: when the native
        error already names the broken peer(s) ("task is set to ERROR ...
        task:1"), those ids are parsed out and NO further KV call is made
        — once the agent is in the error state this jaxlib segfaults on
        ``key_value_dir_get`` (and the runtime's own fatal-error poll is
        about to terminate the process anyway).  Only a plain deadline
        expiry — agent healthy, peers merely late — reads the attendance
        keys back."""
        att = f"barrier_att/{name}/{self.process_id}"
        self.set(att, b"1")
        _gc_own_attendance(self, self._att_history, att)
        try:
            with self._lock:
                self._client.wait_at_barrier(
                    name, max(1, int(timeout_s * 1000))
                )
        except Exception as exc:  # noqa: BLE001 — timeout / peer error
            msg = str(exc)
            missing = [
                t for t in _tasks_named_in_error(msg)
                if t != self.process_id
            ]
            if not missing and "DEADLINE" in msg.upper():
                arrived = {
                    int(k.rsplit("/", 1)[-1])
                    for k in self.dir_get(f"barrier_att/{name}/")
                }
                missing = sorted(set(range(self.num_processes)) - arrived)
            raise CoordinationTimeoutError(
                f"barrier/{name}", timeout_s, missing, detail=msg[:200]
            ) from exc


class InProcessCoordStore:
    """The shared half of :class:`InProcessCoordClient`: one of these per
    simulated cluster, handed to every logical process's client."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.kv: Dict[str, bytes] = {}


class InProcessCoordClient:
    """Fake KV client: N logical processes inside one OS process.

    The tier-1 proof harness for every protocol in this module — barriers,
    allgathers, heartbeats, coordinated checkpoints, elastic resume — with
    no subprocesses and, via the injectable ``clock``/``sleep`` pair, no
    real waiting in timeout tests (a fake clock that advances on ``sleep``
    resolves a 120 s deadline instantly).
    """

    _POLL_S = 0.002

    def __init__(
        self,
        store: InProcessCoordStore,
        process_id: int,
        num_processes: int,
        clock: Callable[[], float] = time.monotonic,
        sleep: Optional[Callable[[float], None]] = None,
    ) -> None:
        self._store = store
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.clock = clock
        self.sleep = sleep if sleep is not None else time.sleep
        self._att_history: List[str] = []

    def set(self, key: str, value: bytes) -> None:
        with self._store.cond:
            self._store.kv[key] = bytes(value)
            self._store.cond.notify_all()

    def get(self, key: str, timeout_s: float) -> Optional[bytes]:
        deadline = self.clock() + max(0.0, timeout_s)
        while True:
            with self._store.lock:
                if key in self._store.kv:
                    return self._store.kv[key]
            if self.clock() >= deadline:
                return None
            self.sleep(self._POLL_S)

    def dir_get(self, prefix: str) -> Dict[str, bytes]:
        with self._store.lock:
            return {
                k: v for k, v in self._store.kv.items()
                if k.startswith(prefix)
            }

    def delete(self, key: str) -> None:
        with self._store.lock:
            self._store.kv.pop(key, None)

    def barrier(self, name: str, timeout_s: float) -> None:
        att = f"barrier_att/{name}/{self.process_id}"
        self.set(att, b"1")
        _gc_own_attendance(self, self._att_history, att)
        deadline = self.clock() + max(0.0, timeout_s)
        prefix = f"barrier_att/{name}/"
        while True:
            arrived = {
                int(k.rsplit("/", 1)[-1]) for k in self.dir_get(prefix)
            }
            if len(arrived) >= self.num_processes:
                return
            if self.clock() >= deadline:
                missing = sorted(set(range(self.num_processes)) - arrived)
                raise CoordinationTimeoutError(
                    f"barrier/{name}", timeout_s, missing
                )
            self.sleep(self._POLL_S)


_CLIENT_SINGLETON: Optional[KVStoreClient] = None
_CLIENT_LOCK = threading.Lock()


def coord_client() -> Optional[KVStoreClient]:
    """The live KV client, or ``None`` when the jax distributed runtime
    (and with it the coordination service) is not up.  ONE cached
    instance per process: the client carries the serialize-native-calls
    lock and the attendance-GC history, both of which only work if every
    caller shares them (a fresh instance per call would void the lock's
    one-call-in-flight guarantee and leak every attendance key)."""
    global _CLIENT_SINGLETON
    if _CLIENT_SINGLETON is not None:
        return _CLIENT_SINGLETON
    import jax

    try:
        if not jax.distributed.is_initialized():  # collective-guard-ok
            return None
        from jax._src.distributed import global_state  # collective-guard-ok

        raw = global_state.client
    except Exception:  # noqa: BLE001 — runtime layouts move across versions
        return None
    if raw is None:
        return None
    with _CLIENT_LOCK:
        if _CLIENT_SINGLETON is None:
            _CLIENT_SINGLETON = KVStoreClient(
                raw, jax.process_index(), jax.process_count()
            )
    return _CLIENT_SINGLETON


# --------------------------------------------------------------------------
# runtime ownership: the only jax.distributed touchpoints in the package
# --------------------------------------------------------------------------


def runtime_initialized() -> bool:
    import jax

    return bool(jax.distributed.is_initialized())  # collective-guard-ok


def initialize_runtime(coordinator_address, num_processes, process_id) -> None:
    import jax

    jax.distributed.initialize(  # collective-guard-ok
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_local_to_global(array: np.ndarray, mesh, spec, *,
                         name: str = "stitch",
                         timeout_s: Optional[float] = None,
                         guarded: bool = True):
    """Deadline-guarded ``host_local_array_to_global_array``: a barrier
    with a timeout runs FIRST, so a dead or wedged peer surfaces as a
    :class:`CoordinationTimeoutError` naming it — before this process
    enters a native call it could never be interrupted out of.
    ``guarded=False`` skips the barrier for a call the caller has ALREADY
    guarded (e.g. the y/mask stitches right after x's — one rendezvous
    covers the batch; each barrier is a cluster round-trip)."""
    from jax.experimental import multihost_utils  # collective-guard-ok

    if guarded:
        guard_collective(name, timeout_s=timeout_s)
    return multihost_utils.host_local_array_to_global_array(  # collective-guard-ok
        np.asarray(array), mesh, spec
    )


_COLLECTIVE_SEQ_LOCK = threading.Lock()
_COLLECTIVE_SEQ_N = 0


def _next_collective_seq() -> int:
    """PROCESS-global barrier sequence: peers must land on the same
    barrier id for their k-th guarded collective regardless of which
    thread runs the fit (a thread-local counter would restart at 0 when
    a host's second fit runs on a fresh worker thread while its peer
    reuses the original — a healthy cluster stalling to a spurious
    timeout)."""
    global _COLLECTIVE_SEQ_N
    with _COLLECTIVE_SEQ_LOCK:
        seq = _COLLECTIVE_SEQ_N
        _COLLECTIVE_SEQ_N += 1
        return seq


def guard_collective(name: str, *, timeout_s: Optional[float] = None,
                     client: Optional[object] = None) -> None:
    """The no-hang pre-flight of every blocking cross-host step: apply any
    chaos straggler delay, die if this process is the staged dead host,
    then rendezvous at a deadline-guarded barrier.  Single-process (or no
    KV client): a no-op."""
    from spark_gp_tpu.resilience import chaos

    chaos.apply_straggler_delay(name)
    chaos.maybe_die_before_collective(name)
    cl = client if client is not None else coord_client()
    if cl is None or cl.num_processes <= 1:
        return
    seq = _next_collective_seq()
    try:
        cl.barrier(
            f"collective/{name}/{seq}",
            default_timeout_s() if timeout_s is None else timeout_s,
        )
    except CoordinationTimeoutError:
        _bump("coord.barrier_timeouts")
        _event("coord.barrier_timeout", op=name)
        raise


def barrier(name: str, timeout_s: Optional[float] = None,
            client: Optional[object] = None) -> None:
    """Deadline-guarded named barrier (module-level convenience)."""
    cl = client if client is not None else coord_client()
    if cl is None or cl.num_processes <= 1:
        return
    try:
        cl.barrier(
            name, default_timeout_s() if timeout_s is None else timeout_s
        )
    except CoordinationTimeoutError:
        _bump("coord.barrier_timeouts")
        _event("coord.barrier_timeout", op=name)
        raise


# --------------------------------------------------------------------------
# allgather / allreduce over the KV store
# --------------------------------------------------------------------------


def _pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{f"a{i}": np.asarray(a) for i, a in enumerate(arrays)})
    return buf.getvalue()


def _unpack_arrays(payload: bytes) -> List[np.ndarray]:
    with np.load(io.BytesIO(payload)) as npz:
        return [npz[f"a{i}"] for i in range(len(npz.files))]


def kv_allgather(
    name: str,
    payload: bytes,
    *,
    client: object,
    timeout_s: Optional[float] = None,
    dead_pids: Optional[Callable[[], Sequence[int]]] = None,
    monitor: Optional["HeartbeatMonitor"] = None,
    trust=None,
) -> List[bytes]:
    """Gather one small ``bytes`` payload per process, ordered by pid.

    Every process publishes ``ag/<name>/<pid>`` then reads every peer's key
    under one deadline.  On expiry — or as soon as ``dead_pids`` (the
    heartbeat monitor's verdict) implicates a peer we are still waiting on
    — raises :class:`CoordinationTimeoutError` naming the missing ids.
    ``name`` must be unique per round (callers sequence it).  A ``monitor``
    is DRIVEN from this wait loop (``maybe_poll``): heartbeat stamping and
    verdicts ride the coordination plane's own thread, because this
    jaxlib's KV client cannot be called from a second thread while the
    first compiles.  The monitor's verdicts are ADVISORY here (metrics,
    span events, health surfaces) — only an explicit ``dead_pids``
    callable aborts a wait before its deadline, because passive heartbeats
    go quiet during any long LOCAL computation and must not fail a healthy
    slow peer early (the deadline is the arbiter).

    This is also the attestation choke point (``resilience/integrity.py``):
    every payload is sealed with a content digest + publisher pid + the
    round-qualified name before publishing, and verified on read, so a
    corrupted payload raises :class:`~spark_gp_tpu.resilience.integrity.
    AttestationError` naming its PUBLISHER on every reader identically —
    instead of surfacing later as a mysteriously wrong sum.  A ``trust``
    ledger (the DCN context's) takes the definitive verdict.
    ``GP_INTEGRITY=0`` publishes raw bytes, bit-for-bit the old wire.
    """
    from spark_gp_tpu.resilience import chaos, integrity

    # chaos choke point: gathers are the DCN plane's collectives, so the
    # staged straggler delay / dead-host exit applies here exactly as
    # guard_collective applies it to global-array stitches
    chaos.apply_straggler_delay(name)
    chaos.maybe_die_before_collective(name)
    cl = client
    verify = integrity.enabled()
    payload = integrity.seal(name, cl.process_id, payload) if verify else payload
    # corruption lands AFTER sealing, right before the wire — exactly
    # where a flaky NIC/DMA fault would
    payload = chaos.maybe_corrupt_published(name, cl.process_id, payload)
    timeout = default_timeout_s() if timeout_s is None else timeout_s
    if monitor is not None:
        monitor.maybe_poll()

    def _fail(missing: Sequence[int], detail: str = "") -> None:
        _bump("coord.barrier_timeouts")
        _event("coord.barrier_timeout", op=f"allgather/{name}")
        raise CoordinationTimeoutError(
            f"allgather/{name}", timeout, missing, detail=detail
        )

    prefix = f"ag/{name}/"
    cl.set(f"{prefix}{cl.process_id}", payload)
    out: List[Optional[bytes]] = [None] * cl.num_processes
    deadline = cl.clock() + timeout
    for pid in range(cl.num_processes):
        while out[pid] is None:
            remaining = deadline - cl.clock()
            if remaining <= 0.0:
                break
            try:
                got = cl.get(f"{prefix}{pid}", min(remaining, 0.5))
            except AgentErrorSignal as exc:
                # the runtime noticed a death first: name the task(s) from
                # ITS error text — the agent is no longer safe to query
                named = [
                    t for t in _tasks_named_in_error(exc.message)
                    if t != cl.process_id
                ]
                _fail(named or [pid], detail=exc.message[:200])
            if got is not None:
                out[pid] = got
                break
            if monitor is not None:
                monitor.maybe_poll()
            if dead_pids is not None:
                dead = set(int(p) for p in dead_pids())
                if pid in dead:
                    break
        if out[pid] is None:
            # plain deadline expiry: the agent is healthy (an errored one
            # raised AgentErrorSignal above), so reading the round's keys
            # back for an exact attendance list is safe
            present = {
                int(k[len(prefix):]) for k in cl.dir_get(prefix)
            }
            missing = sorted(set(range(cl.num_processes)) - present)
            _fail(missing or [pid])
    results: List[bytes] = []
    for pid, blob in enumerate(out):
        if blob is None:
            continue
        try:
            results.append(integrity.unseal(name, pid, blob, verify=verify))
        except integrity.AttestationError as exc:
            _bump("integrity.attestation_failures")
            _event(
                "integrity.corrupt_payload", op=name, pid=pid, code=exc.code
            )
            if trust is not None:
                trust.record_disagreement(
                    pid, definitive=True, reason=exc.code
                )
            raise
    return results


# --------------------------------------------------------------------------
# heartbeat / liveness
# --------------------------------------------------------------------------


class LivenessLedger:
    """Identity-agnostic straggler/dead escalation — the one state machine
    behind every heartbeat surface: the process :class:`HeartbeatMonitor`
    (integer pids over ``heartbeat/``) and the serve fleet's replica
    membership (string replica ids over ``fleet/<name>/heartbeat/``,
    ``serve/fleet.py``) both drive this ledger so "straggler past 3
    intervals, dead past 10, recovered on a fresh stamp" means exactly
    the same thing at both scales.

    ``observe`` takes one sweep's view — the current clock, the stamp
    counters read back from the KV plane, and the expected identity set —
    and updates the flags; the callbacks fire OUTSIDE the lock (they emit
    metrics and span events, which may take other locks).  A peer is
    considered *seen* when its stamp counter CHANGES, not when a key
    merely exists: a dead process's last stamp stays in the store forever.
    """

    def __init__(
        self,
        straggler_after_s: float,
        dead_after_s: float,
        on_straggler: Optional[Callable[[object, float], None]] = None,
        on_dead: Optional[Callable[[object, float], None]] = None,
        on_recover: Optional[Callable[[object], None]] = None,
    ) -> None:
        self.straggler_after_s = float(straggler_after_s)
        self.dead_after_s = float(dead_after_s)
        self._on_straggler = on_straggler
        self._on_dead = on_dead
        self._on_recover = on_recover
        self._last_seen: Dict[object, Tuple[int, float]] = {}  # id -> (n, at)
        self._flagged: Dict[object, str] = {}  # id -> "straggler" | "dead"
        self._lock = threading.Lock()

    def observe(self, now: float, stamps: Dict[object, int],
                expected: Sequence[object] = (),
                skip: Sequence[object] = ()) -> None:
        skip_set = set(skip)
        recovered: List[object] = []
        escalated: List[Tuple[object, str, float]] = []
        with self._lock:
            # seed every expected identity at first sight: a peer that dies
            # before its first stamp would otherwise never enter the
            # escalation scan and read as healthy forever
            for ident in expected:
                self._last_seen.setdefault(ident, (-1, now))
            for ident, n in stamps.items():
                prev = self._last_seen.get(ident)
                if prev is None or prev[0] != n:
                    self._last_seen[ident] = (int(n), now)
                    if ident in self._flagged:
                        del self._flagged[ident]
                        recovered.append(ident)
            for ident, (_, at) in self._last_seen.items():
                if ident in skip_set:
                    continue
                age = now - at
                state = self._flagged.get(ident)
                if age > self.dead_after_s and state != "dead":
                    self._flagged[ident] = "dead"
                    escalated.append((ident, "dead", age))
                elif (
                    self.dead_after_s >= age > self.straggler_after_s
                    and state is None
                ):
                    self._flagged[ident] = "straggler"
                    escalated.append((ident, "straggler", age))
        for ident in recovered:
            if self._on_recover is not None:
                self._on_recover(ident)
        for ident, state, age in escalated:
            callback = self._on_dead if state == "dead" else self._on_straggler
            if callback is not None:
                callback(ident, age)

    def _flagged_as(self, state: str) -> List[object]:
        with self._lock:
            return [i for i, s in self._flagged.items() if s == state]

    def dead(self) -> List[object]:
        return self._flagged_as("dead")

    def stragglers(self) -> List[object]:
        return self._flagged_as("straggler")

    def last_seen(self) -> Dict[object, Tuple[int, float]]:
        with self._lock:
            return dict(self._last_seen)

    def forget(self, ident: object) -> None:
        """Drop one identity entirely (a deregistered fleet member must
        not keep reading as dead after it politely left)."""
        with self._lock:
            self._last_seen.pop(ident, None)
            self._flagged.pop(ident, None)


class HeartbeatMonitor:
    """Liveness over the KV store: stamp ``heartbeat/<pid>`` every
    ``interval_s``, watch every peer's stamp age, and escalate —
    *straggler* past ``straggler_after_s`` (span event +
    ``coord.stragglers``), *dead* past ``dead_after_s`` (span event +
    ``coord.dead_hosts``).  Verdicts are ADVISORY for in-flight waits —
    passive heartbeats go quiet during long local compute, so only an
    explicit ``dead_pids`` source aborts a gather before its deadline.
    ``poll_once`` is the deterministic unit the tests
    drive; :meth:`start` runs it on a daemon thread.
    """

    def __init__(
        self,
        client,
        interval_s: Optional[float] = None,
        straggler_after_s: Optional[float] = None,
        dead_after_s: Optional[float] = None,
    ) -> None:
        self.client = client
        self.interval_s = (
            heartbeat_interval_s() if interval_s is None else float(interval_s)
        )
        self.straggler_after_s = (
            3.0 * self.interval_s if straggler_after_s is None
            else float(straggler_after_s)
        )
        self.dead_after_s = (
            _env_float("GP_COORD_DEAD_AFTER_S", 10.0 * self.interval_s)
            if dead_after_s is None else float(dead_after_s)
        )
        # the shared escalation state machine (LivenessLedger): the serve
        # fleet's replica membership drives the same one, so process- and
        # replica-level verdicts share identical semantics
        self._ledger = LivenessLedger(
            self.straggler_after_s,
            self.dead_after_s,
            on_straggler=lambda pid, age: (
                _bump("coord.stragglers"),
                _event("coord.straggler", pid=pid, stamp_age_s=age),
            ),
            on_dead=lambda pid, age: (
                _bump("coord.dead_hosts"),
                _event("coord.dead_host", pid=pid, stamp_age_s=age),
            ),
            on_recover=lambda pid: _event("coord.recovered", pid=pid),
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._beats = 0
        self._last_poll: Optional[float] = None

    # -- the deterministic unit --------------------------------------------
    def poll_once(self) -> None:
        from spark_gp_tpu.resilience import chaos

        cl = self.client
        now = cl.clock()
        if not chaos.heartbeats_suppressed():
            self._beats += 1
            cl.set(
                f"heartbeat/{cl.process_id}",
                json.dumps({"n": self._beats, "t": now}).encode(),
            )
            _bump("coord.heartbeats")
        parsed: Dict[object, int] = {}
        for key, raw in cl.dir_get("heartbeat/").items():
            try:
                parsed[int(key.rsplit("/", 1)[-1])] = int(
                    json.loads(raw.decode())["n"]
                )
            except (ValueError, KeyError):
                continue
        self._ledger.observe(
            now, parsed,
            expected=range(cl.num_processes), skip=(cl.process_id,),
        )

    def maybe_poll(self) -> None:
        """Rate-limited :meth:`poll_once` for the PASSIVE (main-thread)
        drive mode: coordination waits call this each loop turn; the poll
        actually runs at most once per interval.  Exceptions are swallowed
        — liveness accounting must never fail a fit."""
        now = self.client.clock()
        if (
            self._last_poll is not None
            and now - self._last_poll < self.interval_s
        ):
            return
        self._last_poll = now
        try:
            self.poll_once()
        except Exception:  # noqa: BLE001
            pass

    def dead_pids(self) -> List[int]:
        return sorted(self._ledger.dead())

    def stragglers(self) -> List[int]:
        return sorted(self._ledger.stragglers())

    def snapshot(self) -> dict:
        return {
            "process_id": self.client.process_id,
            "process_count": self.client.num_processes,
            "interval_s": self.interval_s,
            "stragglers": sorted(self._ledger.stragglers()),
            "dead": sorted(self._ledger.dead()),
            "last_seen": {
                str(p): {"n": n, "at": at}
                for p, (n, at) in self._ledger.last_seen().items()
            },
        }

    # -- thread plumbing ---------------------------------------------------
    def start(self) -> "HeartbeatMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="gp-coord-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — liveness must never crash a fit
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# --------------------------------------------------------------------------
# the DCN-fallback fit context
# --------------------------------------------------------------------------


class DcnContext:
    """One process's handle on a DCN-coordinated fit.

    Holds the KV client, this process's id / the cluster size, the
    heartbeat monitor, and a per-namespace round counter so every
    process's k-th ``allreduce``/``allgather`` call lands on the same
    keys (the fit is deterministic lockstep: same data layout decisions,
    same retry decisions — every branch that could diverge is driven by
    globally-reduced values).
    """

    def __init__(self, client, monitor: Optional[HeartbeatMonitor] = None,
                 timeout_s: Optional[float] = None) -> None:
        self.client = client
        self.process_id = int(client.process_id)
        self.num_processes = int(client.num_processes)
        self.monitor = monitor
        self.timeout_s = timeout_s
        self._rounds: Dict[str, int] = {}
        self._lock = threading.Lock()
        from spark_gp_tpu.resilience import integrity

        # the numerical trust plane's per-host ledger + the armed
        # duplicate-dispatch spec (integrity.stage_spot_check): verdicts
        # about PEERS accumulate here across the whole fit
        self.trust = integrity.make_trust_ledger()
        self.dup_check = None

    def _round(self, name: str) -> int:
        with self._lock:
            r = self._rounds.get(name, 0)
            self._rounds[name] = r + 1
            return r

    def allgather_bytes(self, name: str, payload: bytes) -> List[bytes]:
        """Per-process payloads, pid-ordered (one KV round-trip); the
        round counter sequences repeated gathers under one name.  The
        monitor rides along for stamping/verdicts only — a passive
        heartbeat verdict must not abort a wait early (a peer is rightly
        quiet during long local compute); the deadline is the arbiter."""
        r = self._round(name)
        out = kv_allgather(
            f"{name}/{r}", payload, client=self.client,
            timeout_s=self.timeout_s, monitor=self.monitor,
            trust=self.trust,
        )
        if r >= 2:
            # GC this process's OWN round r-2 key: a DCN fit does one
            # gather per objective evaluation, and without cleanup the
            # coordination service accumulates every round's payload for
            # the process's lifetime.  Drained by construction: our
            # round-r gather only completes once every peer has PUBLISHED
            # round r, i.e. finished reading every earlier round's keys
            # (rounds are strictly sequential per process); r-2 keeps one
            # extra round of slack on top of that proof.
            self.client.delete(f"ag/{name}/{r - 2}/{self.process_id}")
        return out

    def allgather_arrays(
        self, name: str, *arrays: np.ndarray
    ) -> List[List[np.ndarray]]:
        """Per-process array tuples, pid-ordered (one KV round-trip).

        The magnitude-attestation choke point: a contribution carrying a
        finite value past ``GP_INTEGRITY_MAX_ABS`` is attributed to its
        publisher and refused on every host identically, BEFORE any sum
        folds it in.  Non-finite values deliberately pass — the vag
        round exchanges them on purpose (synchronized per-expert
        recovery)."""
        from spark_gp_tpu.resilience import chaos, integrity

        # chaos choke point for the wrong-COMPUTE fault: the scale kind
        # corrupts this host's values before they are packed and sealed
        # (internally consistent bytes — only value-level checks catch it)
        arrays = chaos.maybe_corrupt_arrays(
            name, self.process_id, [np.asarray(a) for a in arrays]
        )
        parts = [
            _unpack_arrays(p)
            for p in self.allgather_bytes(name, _pack_arrays(arrays))
        ]
        if integrity.enabled():
            for pid, contribution in enumerate(parts):
                if integrity.bounds_violation(contribution):
                    _bump("integrity.bounds_violations")
                    _event(
                        "integrity.bounds_violation", op=name, pid=pid
                    )
                    self.trust.record_disagreement(
                        pid, definitive=True, reason="bounds"
                    )
                    raise integrity.AttestationError(
                        f"collective {name!r}: pid {pid} published a "
                        "finite contribution beyond the magnitude "
                        f"attestation bar ({integrity.max_abs_bound():.1e})"
                        " — corrupted compute attributed at the gather",
                        pid=pid, code="bounds",
                    )
        return parts

    def allreduce_arrays(self, name: str, *arrays) -> List[np.ndarray]:
        """Deterministic global sums: every process receives the per-host
        contributions pid-ordered and reduces them in that fixed order, so
        the f64 result is bit-identical on every host — the property the
        lockstep L-BFGS trajectories (and the checkpoint digest
        cross-check) stand on."""
        parts = self.allgather_arrays(name, *[np.asarray(a) for a in arrays])
        out = []
        for i in range(len(arrays)):
            acc = np.zeros_like(np.asarray(parts[0][i], dtype=np.float64))
            for contribution in parts:
                acc = acc + np.asarray(contribution[i], dtype=np.float64)
            out.append(acc)
        return out

    def wrap_value_and_grad(self, value_and_grad):
        """The DCN analogue of the objective's cross-host psum: local
        (value, grad) in, globally-summed (value, grad) out.

        The local preemption flag rides the same round (one extra
        scalar): when ANY host has been SIGTERMed, every host learns it
        at the next evaluation and stops together with
        :class:`PreemptedError` — the peers of a preempted host must not
        burn the full coordination deadline to then read an opaque
        "missing process" timeout.  The latest coordinated checkpoint is
        complete on disk either way."""

        def reduced(theta):
            value, grad = value_and_grad(theta)
            # non-finite locals are exchanged like any other value
            # (skipping a round would desynchronize the lockstep
            # counters); the sum propagates the non-finite result to
            # every host identically, so recovery stays synchronized
            s_value, s_grad, s_preempt = self.allreduce_arrays(
                "vag",
                np.asarray([float(np.asarray(value))], dtype=np.float64),
                np.asarray(grad, dtype=np.float64),
                np.asarray(
                    [1.0 if preemption_requested() else 0.0],
                    dtype=np.float64,
                ),
            )
            if float(s_preempt[0]) > 0.0:
                note_preemption_observed()
                consume_preemption()  # acted on: no re-delivery at the
                #                       watch-scope exit, no poisoning of
                #                       the next fit
                raise PreemptedError(
                    "preemption signalled on at least one host: all "
                    f"{self.num_processes} processes stop at this "
                    "evaluation; the last coordinated checkpoint is "
                    "complete — resume after rescheduling"
                )
            # duplicate-dispatch spot check (integrity plane): the
            # decision is a pure hash of the vag round index, so every
            # host takes the audit branch together in lockstep
            if self.dup_check is not None:
                from spark_gp_tpu.resilience import integrity

                k = self._rounds.get("vag", 1) - 1
                if integrity.should_spot_check(k):
                    integrity.run_spot_check(self, theta, k)
            return float(s_value[0]), s_grad

        return reduced


_DCN_FORCED = threading.local()  # .ctx per thread: tests run one logical
#                                  "host" per thread, each with its own ctx


def set_dcn_context_for_testing(ctx: Optional[DcnContext]):
    """Install a fake DCN context for THIS THREAD (tests simulate logical
    processes with one thread + :class:`InProcessCoordClient` each);
    ``None`` restores autodetection."""
    _DCN_FORCED.ctx = ctx


def _forced_ctx() -> Optional[DcnContext]:
    return getattr(_DCN_FORCED, "ctx", None)


def dcn_required() -> bool:
    """True when the runtime spans processes but the backend cannot run one
    program across them (the CPU backend of this jax: 'Multiprocess
    computations aren't implemented') — global-array mode would hang or
    crash, so cross-host math must ride the KV store instead."""
    import jax

    if jax.process_count() <= 1:
        return False
    forced = os.environ.get("GP_DCN_MODE", "").strip().lower()
    if forced in ("1", "on", "true"):
        return True
    if forced in ("0", "off", "false"):
        return False
    return jax.default_backend() == "cpu"


_DCN_SINGLETON: Optional[DcnContext] = None
_BARE_SINGLETON: Optional[DcnContext] = None
_DCN_LOCK = threading.Lock()


def checkpoint_coordination_context() -> Optional[DcnContext]:
    """The context coordinated CHECKPOINTS should use: the DCN fit context
    when the fallback mode applies, else one CACHED bare context over the
    live KV client (global-array pods).  Cached, not per-call: the bare
    context's round counters sequence the ``ckpt_resume`` broadcast, and
    a fresh context per fit would restart them at 0 — a resuming peer
    could then read the PREVIOUS fit's broadcast payload."""
    ctx = dcn_context()
    if ctx is not None:
        return ctx
    global _BARE_SINGLETON
    with _DCN_LOCK:
        if _BARE_SINGLETON is None:
            client = coord_client()
            if client is None or client.num_processes <= 1:
                return None
            _BARE_SINGLETON = DcnContext(client)
    return _BARE_SINGLETON


def dcn_context() -> Optional[DcnContext]:
    """The process's DCN fit context, or ``None`` when native global-array
    coordination applies (single process, or a backend with real
    cross-process execution).  Created once per process; creation starts
    the heartbeat monitor."""
    forced = _forced_ctx()
    if forced is not None:
        return forced
    if not dcn_required():
        return None
    global _DCN_SINGLETON
    with _DCN_LOCK:
        if _DCN_SINGLETON is None:
            client = coord_client()
            if client is None:
                # dcn_required() is True: this process IS part of a
                # multi-process cluster whose backend cannot run
                # cross-process programs, and without the KV client there
                # is no channel to sum the objective over.  Returning
                # None here would make every host silently fit 1/P of
                # the data — the wrong-results bug initialize() exists
                # to prevent — so fail loudly instead.
                _bump("coord.degraded")
                raise RuntimeError(
                    "DCN-fallback coordination required "
                    f"({'jax.process_count()'}>1 on a backend without "
                    "cross-process execution) but the jax coordination "
                    "service KV client is unavailable — cannot sum the "
                    "objective across hosts; fitting would silently use "
                    "1/P of the data"
                )
            # passive monitor: driven from kv_allgather wait loops, NOT a
            # background thread — concurrent native KV calls while the fit
            # thread compiles segfault this jaxlib
            monitor = HeartbeatMonitor(client)
            _DCN_SINGLETON = DcnContext(client, monitor=monitor)
    return _DCN_SINGLETON


def dcn_fallback_available(current_ctx=None) -> bool:
    """Whether the degradation ladder's ``dcn_fallback`` rung can engage
    for a failing sharded fit (``resilience/fallback.py``): a
    multi-process cluster whose KV-store coordination channel is reachable
    and which is NOT already coordinating over it (``current_ctx`` is the
    fit's bound DCN context, if any).  Single-process runtimes — every
    CPU test harness — answer False and the ladder falls straight to its
    ``single_host`` rung."""
    if current_ctx is not None or _forced_ctx() is not None:
        return False
    import jax

    try:
        if jax.process_count() <= 1:
            return False
    except RuntimeError:
        return False
    if not dcn_required():
        # on backends with real cross-process execution dcn_context()
        # answers None — the rung would re-run the identical sharded path
        # while stamping provenance with a fallback that never engaged
        return False
    return coord_client() is not None


def liveness_snapshot() -> Optional[dict]:
    """Coordination liveness for health surfaces (the serve CLI's
    ``health`` verb): ``None`` single-process, else the heartbeat
    monitor's view plus the process topology."""
    ctx = _forced_ctx() or _DCN_SINGLETON
    if ctx is not None and ctx.monitor is not None:
        return ctx.monitor.snapshot()
    client = coord_client()
    if client is None or client.num_processes <= 1:
        return None
    return {
        "process_id": client.process_id,
        "process_count": client.num_processes,
        "stragglers": [],
        "dead": [],
        "note": "no heartbeat monitor active (no DCN fit ran)",
    }


# --------------------------------------------------------------------------
# DCN active-set sampling (the takeSample analogue over the KV store)
# --------------------------------------------------------------------------


def sample_active_dcn(ctx: DcnContext, data, m: int, seed: int) -> np.ndarray:
    """Uniform global active-set draw when no global array exists: publish
    local valid-row counts, draw the same m global indices from the shared
    seed on every host, gather exactly the selected rows.  Cross-host
    traffic is the m chosen rows — the reference's ``takeSample``
    (ActiveSetProvider.scala:48-56) over the coordination service."""
    x = np.asarray(data.x)
    mask = np.asarray(data.mask)
    p = x.shape[-1]
    flat_x = x.reshape(-1, p)
    valid = np.flatnonzero(mask.reshape(-1) > 0)
    counts = [
        int(part[0][0])
        for part in ctx.allgather_arrays(
            "active_counts", np.asarray([valid.size], dtype=np.int64)
        )
    ]
    total = int(sum(counts))
    m = min(int(m), total)
    rng = np.random.default_rng(seed)
    sel = np.sort(rng.choice(total, size=m, replace=False))
    offset = int(sum(counts[: ctx.process_id]))
    mine = sel[(sel >= offset) & (sel < offset + counts[ctx.process_id])]
    rows = flat_x[valid[mine - offset]]
    parts = ctx.allgather_arrays("active_rows", np.asarray(rows))
    # pid-ordered concatenation == global sorted-index order (offsets are
    # pid-ordered), so every host assembles the identical [m, p] set
    return np.concatenate(
        [np.asarray(part[0]).reshape(-1, p) for part in parts], axis=0
    )


# --------------------------------------------------------------------------
# cross-process trace stitching
# --------------------------------------------------------------------------


def stitch_trace_token(ctx=None) -> str:
    """ONE trace id per (possibly multi-host) fit: every process mints a
    local candidate and, when a coordination context spans processes,
    adopts process 0's over the KV plane — so all hosts' run journals and
    incident bundles of one distributed fit share a single stitched
    ``trace_id`` (``obs/runtime.write_run_journal`` / ``obs/recorder``).

    Deliberately best-effort: a coordination failure HERE falls back to
    the local token instead of failing the fit before it starts — the
    fit's own guarded collectives will surface the real, named error.
    Plain per-host ``fit()`` calls pass ``ctx=None`` and never rendezvous
    (the PR 5 independent-fits invariant).
    """
    import uuid

    local = f"t-{uuid.uuid4().hex[:16]}"
    if ctx is None or getattr(ctx, "num_processes", 1) <= 1:
        return local
    try:
        parts = ctx.allgather_bytes("trace_id", local.encode("ascii"))
        return parts[0].decode("ascii")
    except Exception:  # hygiene-ok: telemetry stitch only — the fit's own
        # collectives re-raise the genuine coordination failure, named
        import logging

        logging.getLogger("spark_gp_tpu").warning(
            "trace-id stitch failed; journals keep per-host trace ids",
            exc_info=True,
        )
        return local


# --------------------------------------------------------------------------
# elastic-resume metadata
# --------------------------------------------------------------------------


def elastic_meta(mesh=None, num_experts: Optional[int] = None,
                 expert_size: Optional[int] = None,
                 process_count: Optional[int] = None) -> dict:
    """The ``(process_count, mesh_shape, expert_assignment)`` stamp every
    coordinated checkpoint carries (``utils/checkpoint.py`` understands
    the ``"elastic"`` meta key): a P-process fit may resume on P'
    processes — the iterate is replicated and the expert stack re-shards
    — but a payload whose *identity* (kernel, data, shapes) differs is an
    :class:`~spark_gp_tpu.utils.checkpoint.ElasticResumeError`, never a
    silent restart."""
    import jax

    from spark_gp_tpu.parallel.mesh import mesh_shape

    return {
        "process_count": (
            jax.process_count() if process_count is None else int(process_count)
        ),
        "mesh_shape": mesh_shape(mesh),
        "expert_assignment": {
            "num_experts": None if num_experts is None else int(num_experts),
            "expert_size": None if expert_size is None else int(expert_size),
        },
    }


# --------------------------------------------------------------------------
# coordinated checkpointing
# --------------------------------------------------------------------------


class _CoordinatedWriter:
    """Shared protocol of both coordinated checkpointers.

    Save step k: process 0 runs the inner atomic writer, then every
    process contributes the digest of the payload *it would have
    written* (plus its preemption flag) to one deadline-guarded
    all-gather — identical lockstep states produce identical digests, so
    a divergent host surfaces as a checkpoint error ON EVERY HOST
    instead of a silently forked training run, and a host that never
    arrives is named by the deadline guard (see :meth:`_coordinate`).

    The "era" (the context's per-tag construction counter) namespaces
    each fit's coordination state: a refit — or an in-fit resilience
    retry — constructs a fresh checkpointer whose save counter restarts
    at 1, and without the era its barrier ids and digest keys would
    collide with the previous fit's still-present KV entries (reused
    barrier ids error; a stale digest would cross-check the wrong run).
    The counter lives on the context (one per logical host), so it
    advances in lockstep cluster-wide."""

    def __init__(self, ctx: Optional[DcnContext], tag: str,
                 timeout_s: Optional[float] = None) -> None:
        self.ctx = ctx
        era = 0 if ctx is None else ctx._round(f"ckpt_era/{tag}")
        self.tag = f"{tag}/e{era}"
        self.timeout_s = timeout_s
        self.saves = 0

    def _coordinate(self, write_fn, digest: str) -> None:
        """One symmetric gather per save carries everything the boundary
        needs: ``<digest>|<preempt_flag>`` from every host.

        * the gather IS the rendezvous — a host that never arrives is
          named by the deadline guard (no separate barrier round-trip);
        * process 0 writes BEFORE publishing, so a peer receiving the
          payload knows the file on disk is the complete step;
        * digests are compared all-to-all — EVERY host (the writer
          included) sees a forked trajectory as
          ``CheckpointMismatchError`` naming the divergent pids;
        * the preemption flag rides free: SIGTERM landing between the
          last objective evaluation and this save stops every host HERE,
          together, after the save completed cluster-wide — not just the
          signalled host, with its peers burning the full deadline into
          an opaque missing-process timeout."""
        self.saves += 1
        ctx = self.ctx
        if ctx is None or ctx.num_processes <= 1:
            write_fn()
            _bump("coord.checkpoints")
            return
        step = self.saves
        if ctx.process_id == 0:
            write_fn()
        preempt = "1" if preemption_requested() else "0"
        payloads = ctx.allgather_bytes(
            f"ckpt/{self.tag}", f"{digest}|{preempt}".encode()
        )
        entries = [p.decode().split("|", 1) for p in payloads]
        divergent = sorted(
            pid for pid, (d, _) in enumerate(entries) if d != digest
        )
        if divergent:
            from spark_gp_tpu.utils.checkpoint import CheckpointMismatchError

            raise CheckpointMismatchError(
                f"coordinated checkpoint {self.tag!r} step {step}: state "
                f"digests diverge across hosts (process(es) {divergent} "
                f"differ from process {ctx.process_id}) — the lockstep "
                "trajectories have forked"
            )
        _bump("coord.checkpoints")
        _event("coord.checkpoint", tag=self.tag, step=step)
        if any(flag == "1" for _, flag in entries):
            note_preemption_observed()
            consume_preemption()
            raise PreemptedError(
                "preemption signalled on at least one host: the "
                f"coordinated checkpoint (step {step}) just completed on "
                "every process — resume after rescheduling"
            )


class CoordinatedLbfgsCheckpointer(_CoordinatedWriter):
    """Multi-host shell of PR 2's :class:`LbfgsCheckpointer` callback:
    same per-iteration cadence, same atomic payload — but only process 0
    touches the disk, and every peer cross-checks the payload digest
    through the KV store.  Carries the elastic stamp."""

    def __init__(self, inner, ctx: Optional[DcnContext],
                 timeout_s: Optional[float] = None) -> None:
        super().__init__(ctx, tag=os.path.basename(inner.path),
                         timeout_s=timeout_s)
        self.inner = inner

    @property
    def path(self):
        return self.inner.path

    @property
    def iteration(self):
        return self.inner.iteration

    @iteration.setter
    def iteration(self, value):
        self.inner.iteration = value

    def __call__(self, theta) -> None:
        from spark_gp_tpu.resilience import chaos
        from spark_gp_tpu.utils.checkpoint import _raise_if_preempted

        payload = self.inner.build_payload(theta)
        write = lambda: self.inner.write_payload(payload)  # noqa: E731
        self._coordinate(write, payload["checksum"])
        # tick AFTER the coordinated round (the run_segmented convention):
        # "kill after N save boundaries" leaves N cluster-complete saves
        chaos.tick_kill_counter()
        _raise_if_preempted()


class CoordinatedDeviceCheckpointer(_CoordinatedWriter):
    """Multi-host shell of :class:`DeviceOptimizerCheckpointer`: barrier on
    the segment boundary, process 0 writes the npz, peers verify the npz
    digest through the KV store.

    ``load`` broadcasts: only process 0 is guaranteed to hold the file
    (it is the elected writer, and after rescheduling the peers may sit
    on fresh machines), so process 0 loads + validates locally (elastic
    checks included) and ships the state's leaves over the KV store;
    every process then resumes from the identical segment — without
    this, peers would fresh-init at ``n_iter=0`` while process 0 resumes
    at k, and the segment barriers would desynchronize immediately."""

    def __init__(self, inner, ctx: Optional[DcnContext],
                 timeout_s: Optional[float] = None) -> None:
        super().__init__(ctx, tag=os.path.basename(inner.path),
                         timeout_s=timeout_s)
        self.inner = inner

    @property
    def path(self):
        return self.inner.path

    def save(self, state, meta: dict) -> None:
        arrays = self.inner.build_arrays(state, meta)
        from spark_gp_tpu.utils.checkpoint import _npz_digest

        digest = _npz_digest(arrays)
        write = lambda: self.inner.write_arrays(arrays)  # noqa: E731
        self._coordinate(write, digest)

    def load(self, template_state, meta: dict):
        import jax

        ctx = self.ctx
        if ctx is None or ctx.num_processes <= 1:
            return self.inner.load(template_state, meta)
        state = None
        if ctx.process_id == 0:
            state = self.inner.load(template_state, meta)
        blob = b""
        if state is not None:
            leaves = [
                np.asarray(v) for v in jax.tree.leaves(jax.device_get(state))
            ]
            blob = _pack_arrays(leaves)
        parts = ctx.allgather_bytes(f"ckpt_load/{self.tag}", blob)
        if not parts[0]:
            return None  # process 0 had nothing resumable
        leaves = _unpack_arrays(parts[0])
        _, treedef = jax.tree.flatten(template_state)
        return jax.tree.unflatten(treedef, leaves)


# --------------------------------------------------------------------------
# preemption watcher
# --------------------------------------------------------------------------

_PREEMPTION = threading.Event()
_PREEMPTION_OBSERVED = threading.Event()
_WATCHER_INSTALLED = False


def install_preemption_watcher() -> bool:
    """SIGTERM -> one final coordinated save, then stop: the cluster
    analogue of PR 2's :class:`PreemptingCheckpointer` semantics.

    The handler does NOTHING but set a flag — metrics and span events
    acquire locks the interrupted main thread may already hold, which in
    a signal handler is a self-deadlock (the one outcome worse than no
    final save).  The segmented fit loop
    (``utils/checkpoint.run_segmented``) and the host checkpointer check
    :func:`preemption_requested` at their next save boundary, record the
    observation (``coord.preemptions`` + span event, safely outside the
    handler), persist, and raise :class:`PreemptedError` instead of
    burning the remaining eviction grace period on doomed iterations.

    This PERMANENT installation is the opt-in for long-lived training
    drivers; production fit paths use the scoped :func:`preemption_watch`
    instead (installed only while a checkpointed optimize loop runs, the
    previous disposition restored — and an unconsumed SIGTERM
    re-delivered — on exit, so SIGTERM keeps its default kill semantics
    outside fits).  Idempotent; returns False off the main thread
    (signal handlers cannot install there)."""
    global _WATCHER_INSTALLED
    if _WATCHER_INSTALLED:
        return True
    import signal

    if threading.current_thread() is not threading.main_thread():
        return False

    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _sigterm_flag_handler(prev))
    _WATCHER_INSTALLED = True
    return True


def make_flag_handler(flag: threading.Event, prev=None):
    """THE flag-only signal-handler factory every installer shares (both
    preemption watchers here, and the serve drain path in
    ``serve/lifecycle.py``): set the flag — nothing else (metrics/span
    emission acquire locks the interrupted thread may hold: a
    self-deadlock inside a signal handler) — then chain any real
    previous handler."""
    import signal

    def _on_signal(signum, frame):
        flag.set()
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    return _on_signal


def _sigterm_flag_handler(prev):
    return make_flag_handler(_PREEMPTION, prev)


_WATCH_DEPTH = 0
_WATCH_PREV = None


def preemption_watch():
    """Scoped SIGTERM watcher for checkpointed optimize loops — the
    production wiring (``models/common._optimize_hypers``,
    ``utils/checkpoint.run_segmented``).

    Unlike the permanent :func:`install_preemption_watcher`, the handler
    is installed only WHILE a save boundary exists to act on the flag and
    the previous disposition is restored on exit — so a process that once
    ran a checkpointed fit does not ignore SIGTERM for the rest of its
    life.  A SIGTERM that arrived during the scope but was never consumed
    at a save boundary (the fit finished first) is RE-DELIVERED after the
    handler is restored: the orchestrator asked this process to stop, and
    finishing the fit does not cancel that.  Re-entrant (depth-counted);
    a no-op off the main thread."""
    import contextlib

    @contextlib.contextmanager
    def _watch():
        global _WATCH_DEPTH, _WATCH_PREV
        import signal

        on_main = threading.current_thread() is threading.main_thread()
        installed = False
        if on_main and not _WATCHER_INSTALLED:
            if _WATCH_DEPTH == 0:
                _WATCH_PREV = signal.getsignal(signal.SIGTERM)
                signal.signal(
                    signal.SIGTERM, _sigterm_flag_handler(_WATCH_PREV)
                )
            _WATCH_DEPTH += 1
            installed = True
        try:
            yield
        finally:
            if installed:
                _WATCH_DEPTH -= 1
                if _WATCH_DEPTH == 0:
                    signal.signal(signal.SIGTERM, _WATCH_PREV)
                    _WATCH_PREV = None
                    if _PREEMPTION.is_set():
                        # deferred delivery under the RESTORED disposition
                        _PREEMPTION.clear()
                        os.kill(os.getpid(), signal.SIGTERM)

    return _watch()


def preemption_requested() -> bool:
    from spark_gp_tpu.resilience import chaos

    return _PREEMPTION.is_set() or chaos.preemption_staged()


def consume_preemption() -> None:
    """Clear the watcher flag once a save boundary has acted on it (the
    fit stops with PreemptedError) — a consumed preemption must not
    poison the process's NEXT checkpointed fit."""
    _PREEMPTION.clear()


def note_preemption_observed() -> None:
    """Record the preemption in telemetry ONCE, from ordinary (non-signal)
    context — called by the save boundary that acts on the flag."""
    if _PREEMPTION_OBSERVED.is_set():
        return
    _PREEMPTION_OBSERVED.set()
    _bump("coord.preemptions")
    _event("coord.preempted", signal="SIGTERM")


def clear_preemption_for_testing() -> None:
    _PREEMPTION.clear()
    _PREEMPTION_OBSERVED.clear()


class PreemptedError(RuntimeError):
    """The fit stopped at a save boundary because preemption was signalled
    (SIGTERM watcher): the checkpoint on disk is complete and current —
    resume after rescheduling continues exactly there."""
