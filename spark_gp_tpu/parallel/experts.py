"""Round-robin expert grouping as a pad + reshape.

The reference shuffles points into E = round(N / datasetSizeForExpert) experts
with a ``zipWithIndex -> key = index % E -> groupByKey`` Spark shuffle
(GaussianProcessCommons.scala:26-31) — a process-boundary data movement.  On
TPU the same assignment is a *layout transform*: point ``i`` belongs to expert
``i % E``, so sorting indices by ``(i % E, i // E)`` and padding the ragged
tail yields a dense ``[E, s, p]`` stack whose leading axis shards across
chips.  No communication happens at all until the likelihood reduction.

Per-expert sizes in the reference differ by at most one (mod split); the pad
mask makes every expert exactly ``ceil(N/E)`` wide and the masked Gram
embedding (``ops.linalg.masked_kernel_matrix``) keeps padding out of every
logdet / quadratic form / cross-kernel sum.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class ExpertData:
    """Dense expert stack.

    ``x``: ``[E, s, p]`` features, padded with copies of the expert's first
    point (benign values — masked out of every reduction).
    ``y``: ``[E, s]`` labels, zero-padded.
    ``mask``: ``[E, s]`` 1.0 for real points, 0.0 for padding.
    """

    x: jax.Array
    y: jax.Array
    mask: jax.Array

    @property
    def num_experts(self) -> int:
        return self.x.shape[0]

    @property
    def expert_size(self) -> int:
        return self.x.shape[1]

    def with_experts_masked(self, drop, benign_row=None) -> "ExpertData":
        """Stack with the ``drop``-flagged experts made inert (the
        quarantine primitive, ``resilience/quarantine.py``).

        Mask and labels zeroed — the masked Gram embedding
        (``ops.linalg.masked_kernel_matrix``) then turns each dropped
        expert into an identity block contributing exactly 0 to every
        reduction — and features replaced by ``benign_row`` (default:
        the first kept expert's first point), because a fully-masked
        expert still flows through ``kernel.gram`` and ``0 * NaN`` would
        re-poison the sum.  Shapes are unchanged, so compiled
        executables and sharding are reused."""
        drop = np.asarray(drop, dtype=bool)
        if not drop.any():
            return self
        drop_dev = jnp.asarray(drop)
        keep = jnp.asarray(~drop, dtype=self.mask.dtype)
        if benign_row is None:
            benign_row = self.x[int(np.argmax(~drop)), :1]  # [1, p]
        x = jnp.where(drop_dev[:, None, None], benign_row[None], self.x)
        # zero by SELECTION, never by multiplication: a dropped expert's
        # labels may be NaN/inf (the fault being quarantined), and
        # IEEE 0 * NaN = NaN would re-poison the very sum this masks
        y = jnp.where(drop_dev[:, None], jnp.zeros((), self.y.dtype), self.y)
        return ExpertData(
            x=x,
            y=y,
            mask=self.mask * keep[:, None],
        )

    def pad_experts(self, multiple: int) -> "ExpertData":
        """Pad the expert axis up to a multiple (for even sharding across
        devices).  Padded experts are fully masked and contribute nothing."""
        e = self.x.shape[0]
        target = math.ceil(e / multiple) * multiple
        if target == e:
            return self
        pad = target - e
        x = jnp.concatenate([self.x, jnp.tile(self.x[:1], (pad, 1, 1))], axis=0)
        y = jnp.concatenate([self.y, jnp.zeros_like(self.y[:1]).repeat(pad, 0)], axis=0)
        mask = jnp.concatenate(
            [self.mask, jnp.zeros_like(self.mask[:1]).repeat(pad, 0)], axis=0
        )
        return ExpertData(x=x, y=y, mask=mask)


def num_experts_for(n_points: int, dataset_size_for_expert: int) -> int:
    """E = round(N / s), at least 1 — GaussianProcessCommons.scala:27 uses
    ``Math.round`` (half-up)."""
    return max(1, int(math.floor(n_points / dataset_size_for_expert + 0.5)))


def group_for_experts(
    x: np.ndarray,
    y: np.ndarray,
    dataset_size_for_expert: int,
    dtype=None,
) -> ExpertData:
    """Group ``(x [N,p], y [N])`` into the ``[E, s, ...]`` expert stack.

    Host-side numpy (this is data layout, not compute): gather indices in
    round-robin order — expert ``e`` receives points ``e, e+E, e+2E, ...`` —
    then pad each expert to the common width ``s = ceil(N/E)``.
    """
    x = np.asarray(x)
    y = np.asarray(y)
    n = x.shape[0]
    e = num_experts_for(n, dataset_size_for_expert)
    s = math.ceil(n / e)

    # Expert j, slot t holds point j + t*e (when < n) — one vectorized
    # gather, no per-expert Python loop.  Padded slots gather the expert's
    # first point (benign values, masked out of every reduction).
    point = np.arange(e)[:, None] + np.arange(s)[None, :] * e  # [e, s]
    valid = point < n
    gather = np.where(valid, point, np.arange(e)[:, None])

    xg = x[gather]  # [e, s, p]
    yg = np.where(valid, y[gather], 0.0).astype(y.dtype)
    mask = valid.astype(x.dtype)

    if dtype is not None:
        xg = xg.astype(dtype)
        yg = yg.astype(dtype)
        mask = mask.astype(dtype)
    return ExpertData(x=jnp.asarray(xg), y=jnp.asarray(yg), mask=jnp.asarray(mask))


def ungroup(values: np.ndarray, n_points: int) -> np.ndarray:
    """Invert the round-robin grouping: ``[E, s] -> [N]`` in original point
    order.  Expert ``j`` slot ``t`` holds point ``j + t*E``; padded slots are
    dropped."""
    values = np.asarray(values)
    e, s = values.shape
    point = np.arange(e)[:, None] + np.arange(s)[None, :] * e  # [e, s]
    valid = point < n_points
    out = np.zeros(n_points, dtype=values.dtype)
    out[point[valid]] = values[valid]
    return out
