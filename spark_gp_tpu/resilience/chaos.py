"""Deterministic fault injection: the proof harness for the resilience layer.

Every fault here is seed- or count-driven — no wall-clock races, no
randomized kill timers — so the chaos tests (``pytest -m chaos``) are
ordinary fast deterministic tier-1 tests, not flaky integration theater.

Fault classes:

* :func:`poison_expert` — corrupt the raw rows round-robin-assigned to
  one expert (NaN / inf / huge values), the data-fault that used to turn
  the whole BCM objective to ``inf``;
* :func:`failing_cholesky` — make the host Cholesky raise for the first
  N calls, driving the adaptive jitter ladder and the
  ``NotPositiveDefiniteException`` path;
* :class:`PreemptingCheckpointer` — hard-kills the process
  (``os._exit``, the SIGKILL analogue: no cleanup, no atexit) right
  after the k-th checkpoint save — a deterministic preemption for
  kill-and-resume tests;
* :class:`FlakyPredictor` — a predict path that fails and/or stalls on
  schedule, for circuit-breaker and poisoned-batch isolation tests;
* :class:`HangingPredictor` — a predict path that BLOCKS until released
  (the wedged-device fault), for the serve hang-watchdog proof;
* :func:`oom_after_calls` / :func:`failing_compile` — execution-
  environment faults for the degradation ladder
  (``resilience/fallback.py``): a staged device ``RESOURCE_EXHAUSTED``
  (count-, op- and dispatch-size-scoped) or XLA compile failure raised as
  a GENUINE ``XlaRuntimeError`` at the dispatch choke points
  (:func:`maybe_injected_failure`), so every ladder rung is provable on
  CPU.  Env channel: ``GP_CHAOS_OOM_AFTER_CALLS`` (+ ``GP_CHAOS_OOM_OP``,
  ``GP_CHAOS_OOM_ROWS_ABOVE``) and ``GP_CHAOS_FAILING_COMPILE`` (+
  ``GP_CHAOS_COMPILE_OP``);
* :func:`memory_limit_bytes` — the SHRUNKEN-RUNTIME fault for the
  predictive memory planner (``resilience/memplan.py``): stages a device
  memory budget of ``n`` bytes.  The planner reads it as its budget
  (``memplan.memory_budget_bytes``), and the dispatch choke points model
  the allocator against it — a dispatch whose modeled byte cost exceeds
  the limit raises a genuine ``RESOURCE_EXHAUSTED``, exactly what a real
  runtime with that much HBM would do.  Planning ON pre-sizes every
  dispatch under the limit (zero OOM); ``GP_MEMPLAN=0`` restores the
  reactive crash-then-degrade behavior — both branches provable on CPU.
  Env channel: ``GP_CHAOS_MEMORY_LIMIT_BYTES``;
* :func:`miscalibrate` / :func:`drift_inputs` — statistical-quality
  faults for the health plane (``obs/quality.py``): scale every served
  σ (an overconfident model) or shift every admitted request's features
  (upstream covariate drift), so the calibration and drift alerts are
  provable on CPU with seeded determinism.  Env channels:
  ``GP_CHAOS_MISCALIBRATE``, ``GP_CHAOS_DRIFT_INPUTS``;
* **multi-host faults** (consumed by ``parallel/coord.py``'s guarded
  collectives and coordinated checkpointers):
  :class:`StragglerHost` — inject a fixed delay before a named
  collective (the slow-host fault the deadline guards must survive);
  :class:`DeadHost` — stop heartbeating and die (or raise) before the
  next collective (the preempted-host fault the guards must NAME within
  the deadline instead of hanging on);
  :func:`kill_process_after` — ``os._exit(137)`` after N checkpoint-save
  / segment boundaries.  All three are env-drivable
  (``GP_CHAOS_STRAGGLER_S`` [+ ``GP_CHAOS_STRAGGLER_OP``],
  ``GP_CHAOS_DEAD_HOST``, ``GP_CHAOS_KILL_AFTER_ITERS``) so subprocess
  tests can stage real multi-process failures without patching code in
  the child;
* **silent-data-corruption faults** (consumed by the integrity plane,
  ``resilience/integrity.py``): :func:`corrupt_host` makes ONE logical
  process publish wrong bytes/values at the DCN collective choke points
  — ``bitflip`` flips a payload bit after sealing (transport/memory
  corruption the attestation digest must catch), ``stuck`` republishes
  the previous round's sealed payload (the stale-replay fault the
  attestation's round-bound name must catch), ``scale`` multiplies the
  published numerical values (the wrong-COMPUTE fault only bounds
  attestation or duplicate-dispatch recomputation can catch);
  :func:`corrupt_device` corrupts one device's redundantly-computed
  diagonal panel inside the sharded Cholesky (the tripwire's fault);
  :func:`corrupt_replica` swaps a serve replica's predictor for one
  returning silently scaled answers (the wrong-answer fault the
  router's shadow verification must catch — the replica stays alive
  and heartbeating, which is the whole point).  Env channel:
  ``GP_CHAOS_CORRUPT_PID`` (+ ``GP_CHAOS_CORRUPT_KIND``,
  ``GP_CHAOS_CORRUPT_OP``, ``GP_CHAOS_CORRUPT_SCALE``,
  ``GP_CHAOS_CORRUPT_DEVICE``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Optional

import numpy as np


def poison_expert(
    x: np.ndarray,
    y: np.ndarray,
    expert: int,
    num_experts: int,
    kind: str = "nan",
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Corrupt every row that round-robin grouping assigns to ``expert``.

    ``parallel/experts.py``: expert ``j`` receives points ``j, j+E,
    j+2E, ...`` — so poisoning those rows poisons exactly one expert of
    the fitted stack.  ``kind``: ``"nan"`` (a NaN feature per row),
    ``"inf"`` (an infinite label), ``"huge"`` (1e300-scale features: the
    finite-but-catastrophic conditioning fault), ``"dup"`` (every row
    identical: an exactly singular expert Gram — the fault class the
    adaptive jitter ladder repairs without quarantine).  Returns
    corrupted copies; the inputs are untouched.
    """
    if not 0 <= expert < num_experts:
        raise ValueError(f"expert {expert} out of range [0, {num_experts})")
    x = np.array(x, dtype=np.float64, copy=True)
    y = np.array(y, dtype=np.float64, copy=True)
    rows = np.arange(expert, x.shape[0], num_experts)
    rng = np.random.default_rng(seed)
    cols = rng.integers(0, x.shape[1], size=rows.shape[0])
    if kind == "nan":
        x[rows, cols] = np.nan
    elif kind == "inf":
        y[rows] = np.inf
    elif kind == "huge":
        x[rows] *= 1e300
    elif kind == "dup":
        x[rows] = x[rows[0]]
        y[rows] = y[rows[0]]
    else:
        raise ValueError(f"unknown poison kind {kind!r}")
    return x, y


@contextlib.contextmanager
def failing_cholesky(times: int = 1):
    """Patch ``np.linalg.cholesky`` to raise ``LinAlgError`` for the first
    ``times`` calls (then behave normally).  Yields a one-element list
    holding the injected-failure count, so tests can assert the fault
    actually fired.  Drives the host jitter ladder
    (``ops.linalg.psd_safe_cholesky_np``) and, with a large ``times``,
    the ladder-exhausted ``NotPositiveDefiniteException`` path.
    """
    original = np.linalg.cholesky
    fired = [0]

    def chaotic(a, *args, **kwargs):
        if fired[0] < times:
            fired[0] += 1
            raise np.linalg.LinAlgError("chaos: injected Cholesky failure")
        return original(a, *args, **kwargs)

    np.linalg.cholesky = chaotic
    try:
        yield fired
    finally:
        np.linalg.cholesky = original


#: conventional exit status of a SIGKILLed process (128 + 9) — what a
#: cluster preemption looks like to the supervisor
PREEMPTION_EXIT_CODE = 137


class SimulatedPreemption(BaseException):
    """In-process preemption marker (``BaseException``: ordinary
    ``except Exception`` recovery code must not swallow a kill)."""


class PreemptingCheckpointer:
    """Device-checkpointer wrapper that dies right after the k-th save.

    Two kill modes: ``exit_process=True`` calls ``os._exit`` — no
    exception unwinding, no atexit, no buffered-file flushing, the
    closest in-process analogue of a SIGKILL preemption (subprocess
    tests); the default raises :class:`SimulatedPreemption`, which aborts
    the fit mid-segment without tearing down the interpreter — the fast
    deterministic variant for tier-1.  Because the wrapped saver's write
    is atomic (tmp + fsync + ``os.replace`` + checksum), the checkpoint
    on disk is the complete k-th state either way, and a restarted fit
    resumes from exactly there.
    """

    def __init__(self, inner, kill_after_saves: int,
                 exit_process: bool = False,
                 exit_code: int = PREEMPTION_EXIT_CODE) -> None:
        if kill_after_saves < 1:
            raise ValueError("kill_after_saves must be >= 1")
        self.inner = inner
        self.kill_after_saves = int(kill_after_saves)
        self.exit_process = bool(exit_process)
        self.exit_code = int(exit_code)
        self.saves = 0

    def save(self, state, meta: dict) -> None:
        self.inner.save(state, meta)
        self.saves += 1
        if self.saves >= self.kill_after_saves:
            if self.exit_process:
                os._exit(self.exit_code)
            raise SimulatedPreemption(
                f"preempted after checkpoint save #{self.saves}"
            )

    def load(self, template_state, meta: dict):
        return self.inner.load(template_state, meta)

    @property
    def path(self):
        return self.inner.path


class FlakyPredictor:
    """Predict path that fails / stalls on a deterministic schedule.

    Duck-types enough of :class:`~spark_gp_tpu.serve.batcher.
    BucketedPredictor` for the serving stack (everything else delegates
    to the wrapped predictor).  ``fail_first`` predicts raise
    ``exc_type``; with ``fail_forever`` every call raises; ``latency_s``
    sleeps before answering (slow-predict fault).
    """

    def __init__(
        self,
        inner,
        fail_first: int = 0,
        fail_forever: bool = False,
        latency_s: float = 0.0,
        exc_type: type = RuntimeError,
    ) -> None:
        self._inner = inner
        self.fail_first = int(fail_first)
        self.fail_forever = bool(fail_forever)
        self.latency_s = float(latency_s)
        self.exc_type = exc_type
        self.calls = 0

    def predict(self, x, *args, **kwargs):
        self.calls += 1
        if self.latency_s:
            time.sleep(self.latency_s)
        if self.fail_forever or self.calls <= self.fail_first:
            raise self.exc_type(
                f"chaos: injected predict failure (call {self.calls})"
            )
        return self._inner.predict(x, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class HangingPredictor:
    """Predict path that BLOCKS — the wedged-device fault the serve hang
    watchdog (``serve/lifecycle.py``) exists for, as distinct from
    :class:`FlakyPredictor`'s raising/slow faults.

    The first ``hang_first`` predicts (every one with ``hang_forever``)
    park on an internal event until :meth:`release` — deterministic, no
    wall-clock races: the test picks the hang deadline, trips the
    watchdog, then releases so the wedged thread unwinds instead of
    leaking blocked for the rest of the suite.  ``max_block_s`` is the
    leak backstop if a test forgets.  Duck-types
    :class:`~spark_gp_tpu.serve.batcher.BucketedPredictor` like
    FlakyPredictor does.
    """

    def __init__(
        self,
        inner,
        hang_first: int = 0,
        hang_forever: bool = False,
        max_block_s: float = 60.0,
    ) -> None:
        self._inner = inner
        self.hang_first = int(hang_first)
        self.hang_forever = bool(hang_forever)
        self.max_block_s = float(max_block_s)
        self._release = threading.Event()
        self.calls = 0
        self.hung = 0

    def predict(self, x, *args, **kwargs):
        self.calls += 1
        if self.hang_forever or self.calls <= self.hang_first:
            self.hung += 1
            self._release.wait(self.max_block_s)
        return self._inner.predict(x, *args, **kwargs)

    def release(self) -> None:
        """Unblock every parked (and future would-hang) predict."""
        self._release.set()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def hang_model(server, name: str, version: Optional[int] = None, **hang_kw):
    """Swap a registered model's predictor for a :class:`HangingPredictor`
    (the watchdog-proof analogue of :func:`break_model`).  Returns the
    wrapper — call ``release()`` in teardown."""
    entry = server.registry.get(name, version)
    hanging = HangingPredictor(entry.predictor, **hang_kw)
    entry.predictor = hanging
    return hanging


# --------------------------------------------------------------------------
# fleet replica faults (serve/fleet.py + serve/router.py consume these)
# --------------------------------------------------------------------------


def kill_replica(replica) -> None:
    """SIGKILL analogue for a fleet replica
    (:class:`~spark_gp_tpu.serve.fleet.LocalReplica`): the transport goes
    unreachable, heartbeats stop (the membership ledger must reach a
    dead verdict), and queued/in-flight futures are failed fast — the
    router's failover must re-route every affected request within its
    deadline with zero lost answers."""
    replica.kill()


def hang_replica(replica, name: Optional[str] = None, **hang_kw):
    """Wedge one fleet replica: its model predictor BLOCKS
    (:class:`HangingPredictor`) and — a wedged process stamps nothing —
    its heartbeats stop, so the membership ledger must evict it by
    verdict while the rest of the fleet keeps serving (the router hedges
    around the straggler in the meantime).  Returns the wrapper — call
    ``release()`` in teardown so the parked batcher thread unwinds."""
    replica.alive = False  # heartbeats stop with the wedge
    target = name if name is not None else replica.server.registry.names()[0]
    return hang_model(replica.server, target, **hang_kw)


# --------------------------------------------------------------------------
# multi-host faults (parallel/coord.py consumes these at its choke points)
# --------------------------------------------------------------------------

#: in-process staged faults; the env vars below are the subprocess channel
_mp_state = {
    "straggler_s": None,      # float | None
    "straggler_op": None,     # substring filter | None
    "dead_host": False,       # True -> die before the next collective
    "dead_exit": True,        # os._exit vs SimulatedPreemption
    "no_heartbeat": False,    # True -> suppress heartbeat stamps
    "kill_after": None,       # int | None remaining save/segment ticks
    "preempt": False,         # True -> coord.preemption_requested()
    "oom_after": None,        # int | None: matching calls allowed before OOM
    "oom_op": None,           # substring filter | None
    "oom_rows_above": None,   # int | None: only dispatches above this size
    "oom_calls": 0,           # matching calls observed so far
    "oom_fired": None,        # one-element list: injected-OOM count
    "compile_fail": None,     # int | None: remaining injected compile failures
    "compile_op": None,       # substring filter | None
    "compile_fired": None,    # one-element list: injected-failure count
    "memory_limit": None,     # float | None: staged device memory budget
    "memory_fired": None,     # one-element list: budget-OOM count
    "sigma_scale": None,      # float | None: served-σ miscalibration factor
    "input_shift": None,      # float | None: additive covariate shift
    "corrupt_pid": None,      # int | None: the corrupted logical process
    "corrupt_kind": None,     # "bitflip" | "stuck" | "scale" | None
    "corrupt_op": None,       # substring filter | None
    "corrupt_scale": None,    # float | None: the scale fault's factor
    "corrupt_fired": None,    # one-element list: corruption count
    "corrupt_prev": None,     # {(pid, base_op): last sealed blob} (stuck)
    "corrupt_device": None,   # int | None: device index for panel faults
}


def staged_memory_limit() -> Optional[float]:
    """The staged chaos memory budget in bytes, or None: the in-process
    stage (:func:`memory_limit_bytes`) wins, else the subprocess channel
    ``GP_CHAOS_MEMORY_LIMIT_BYTES``.  Read by the memory planner as its
    budget AND by the choke-point allocator model below — one number, so
    the plan and the 'runtime' can never disagree about the ceiling."""
    staged = _mp_state["memory_limit"]
    if staged is not None:
        return float(staged)
    return _env_chaos_float("GP_CHAOS_MEMORY_LIMIT_BYTES")


def _xla_runtime_error(message: str) -> BaseException:
    """A GENUINE ``XlaRuntimeError`` when the runtime exposes its
    constructor (it does on every harness jaxlib), so the classifier and
    every ``except`` clause see exactly what a real device failure looks
    like; a plain RuntimeError with the same canonical message otherwise
    (the classifier matches by message markers too)."""
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        return XlaRuntimeError(message)
    except Exception:  # hygiene-ok: jaxlib layout drift; message still classifies
        return RuntimeError(message)


def maybe_injected_failure(
    op: str, rows: Optional[int] = None, nbytes: Optional[float] = None,
) -> None:
    """The execution-failure trigger point: the device-fit dispatchers
    (each family's ``_fit_device``), the chunked PPA predict and the
    device magic solve call this before dispatching, so a staged fault
    surfaces exactly where the real runtime would raise.  Three faults:

    * **OOM** (:func:`oom_after_calls` / ``GP_CHAOS_OOM_AFTER_CALLS``):
      after ``n`` matching calls, every further matching call raises a
      genuine ``XlaRuntimeError("RESOURCE_EXHAUSTED: ...")``.  ``op``
      substring and ``rows_above`` filters scope the fault — e.g.
      ``op="one_dispatch"`` fails the one-dispatch fit while the
      segmented rung's smaller dispatches run clean, and
      ``rows_above=512`` models an allocator ceiling the predict ladder
      can get under by halving its chunk;
    * **compile failure** (:func:`failing_compile` /
      ``GP_CHAOS_FAILING_COMPILE``): the next ``times`` matching calls
      raise a compilation-shaped ``XlaRuntimeError``;
    * **memory budget** (:func:`memory_limit_bytes` /
      ``GP_CHAOS_MEMORY_LIMIT_BYTES``): a dispatch whose modeled byte
      cost ``nbytes`` (the planner's RAW model of the config about to
      run — ``resilience/memplan.py``) exceeds the staged limit raises
      ``RESOURCE_EXHAUSTED``, modeling an allocator with that ceiling.
      Callers that pass no ``nbytes`` are outside the modeled-allocator
      scope and never trip this fault.
    """
    # -- injected memory-budget OOM (memplan's shrunken runtime) -----------
    if nbytes is not None:
        limit = staged_memory_limit()
        if limit is not None and float(nbytes) > limit:
            fired = _mp_state["memory_fired"]
            if fired is not None:
                fired[0] += 1
            raise _xla_runtime_error(
                f"RESOURCE_EXHAUSTED: chaos: attempting to allocate "
                f"{int(nbytes)} bytes over the {int(limit)}-byte staged "
                f"device budget at {op!r}"
            )
    # -- injected OOM ------------------------------------------------------
    allow = _mp_state["oom_after"]
    op_filter = _mp_state["oom_op"]
    rows_above = _mp_state["oom_rows_above"]
    if allow is None:
        env = os.environ.get("GP_CHAOS_OOM_AFTER_CALLS", "").strip()
        if env:
            try:
                allow = int(env)
            except ValueError:
                allow = None
            op_filter = os.environ.get("GP_CHAOS_OOM_OP", "") or None
            raw_rows = os.environ.get("GP_CHAOS_OOM_ROWS_ABOVE", "").strip()
            rows_above = int(raw_rows) if raw_rows.isdigit() else None
    if allow is not None and (not op_filter or op_filter in op):
        if rows_above is None or (rows is not None and rows > rows_above):
            _mp_state["oom_calls"] += 1
            if _mp_state["oom_calls"] > allow:
                fired = _mp_state["oom_fired"]
                if fired is not None:
                    fired[0] += 1
                raise _xla_runtime_error(
                    f"RESOURCE_EXHAUSTED: chaos: injected device OOM at "
                    f"{op!r} (call {_mp_state['oom_calls']})"
                )
    # -- injected compile failure -----------------------------------------
    remaining = _mp_state["compile_fail"]
    c_filter = _mp_state["compile_op"]
    if remaining is None:
        env = os.environ.get("GP_CHAOS_FAILING_COMPILE", "").strip()
        if env:
            try:
                remaining = int(env)
            except ValueError:
                remaining = None
            if remaining is not None:
                _mp_state["compile_fail"] = remaining
            c_filter = os.environ.get("GP_CHAOS_COMPILE_OP", "") or None
            _mp_state["compile_op"] = c_filter
    if remaining and (not c_filter or c_filter in op):
        _mp_state["compile_fail"] = remaining - 1
        fired = _mp_state["compile_fired"]
        if fired is not None:
            fired[0] += 1
        raise _xla_runtime_error(
            f"INTERNAL: during compilation: chaos: injected XLA "
            f"compilation failure at {op!r}"
        )


@contextlib.contextmanager
def oom_after_calls(
    n: int, op: Optional[str] = None, rows_above: Optional[int] = None
):
    """Stage an injected device OOM: the first ``n`` matching dispatches
    succeed, every later one raises ``RESOURCE_EXHAUSTED`` (see
    :func:`maybe_injected_failure` for the ``op`` / ``rows_above``
    scoping).  Yields a one-element list counting injections, so tests
    can assert the fault actually fired.  Subprocesses stage it with
    ``GP_CHAOS_OOM_AFTER_CALLS`` (+ ``GP_CHAOS_OOM_OP`` /
    ``GP_CHAOS_OOM_ROWS_ABOVE``)."""
    if int(n) < 0:
        raise ValueError("n must be >= 0")
    prev = {
        k: _mp_state[k]
        for k in ("oom_after", "oom_op", "oom_rows_above", "oom_calls",
                  "oom_fired")
    }
    fired = [0]
    _mp_state.update(
        oom_after=int(n), oom_op=op,
        oom_rows_above=None if rows_above is None else int(rows_above),
        oom_calls=0, oom_fired=fired,
    )
    try:
        yield fired
    finally:
        _mp_state.update(prev)


@contextlib.contextmanager
def memory_limit_bytes(n: float):
    """Stage a shrunken device memory budget of ``n`` bytes: the memory
    planner (``resilience/memplan.py``) reads it as its budget, and any
    choke-point dispatch whose modeled byte cost exceeds it raises a
    genuine ``RESOURCE_EXHAUSTED`` — so planner pre-sizing and admission
    are provable on CPU with no real allocator involved.  Yields the
    one-element injected-OOM counter (0 under a working plan — that IS
    the acceptance assertion).  Subprocess channel:
    ``GP_CHAOS_MEMORY_LIMIT_BYTES``."""
    if float(n) <= 0:
        raise ValueError("memory limit must be > 0 bytes")
    prev = (_mp_state["memory_limit"], _mp_state["memory_fired"])
    fired = [0]
    _mp_state.update(memory_limit=float(n), memory_fired=fired)
    try:
        yield fired
    finally:
        _mp_state["memory_limit"], _mp_state["memory_fired"] = prev


# --------------------------------------------------------------------------
# statistical-quality faults (obs/quality.py consumes these on the serve path)
# --------------------------------------------------------------------------


def sigma_scale() -> Optional[float]:
    """The staged served-σ miscalibration factor, or None: the in-process
    stage (:func:`miscalibrate`) wins, else ``GP_CHAOS_MISCALIBRATE``.
    Consulted by the serve executor AFTER a successful predict — the
    served variance is scaled by ``scale**2``, modeling a model whose σ
    is ``scale``× wrong (``scale < 1`` = overconfident, the
    product-of-experts failure mode the quality monitor exists for)."""
    staged = _mp_state["sigma_scale"]
    if staged is not None:
        return float(staged)
    return _env_chaos_float("GP_CHAOS_MISCALIBRATE")


def input_shift() -> Optional[float]:
    """The staged additive covariate shift, or None: the in-process stage
    (:func:`drift_inputs`) wins, else ``GP_CHAOS_DRIFT_INPUTS``.
    Consulted by the serve submit path — every admitted request's
    features are shifted by this constant, modeling upstream feature
    drift the fit never saw (the drift monitor must alarm; predictions
    legitimately move)."""
    staged = _mp_state["input_shift"]
    if staged is not None:
        return float(staged)
    return _env_chaos_float("GP_CHAOS_DRIFT_INPUTS")


@contextlib.contextmanager
def miscalibrate(scale: float):
    """Stage a served-σ miscalibration: every serve answer's variance is
    scaled by ``scale**2`` (``scale=0.5`` = the classic 2× σ-shrink
    overconfidence).  The quality monitor (``obs/quality.py``) must trip
    ``quality.alert.*`` within a bounded number of graded observations —
    the acceptance proof in ``tools/soak.py`` and
    ``tests/test_quality_obs.py``.  Subprocess channel:
    ``GP_CHAOS_MISCALIBRATE``."""
    if float(scale) <= 0:
        raise ValueError("sigma scale must be > 0")
    prev = _mp_state["sigma_scale"]
    _mp_state["sigma_scale"] = float(scale)
    try:
        yield
    finally:
        _mp_state["sigma_scale"] = prev


@contextlib.contextmanager
def drift_inputs(shift: float):
    """Stage an additive covariate shift on every admitted serve request:
    the drift monitor must raise ``drift.alert.*`` within a bounded
    number of rows while a clean run never does.  Subprocess channel:
    ``GP_CHAOS_DRIFT_INPUTS``."""
    prev = _mp_state["input_shift"]
    _mp_state["input_shift"] = float(shift)
    try:
        yield
    finally:
        _mp_state["input_shift"] = prev


@contextlib.contextmanager
def failing_compile(times: int = 1, op: Optional[str] = None):
    """Stage injected XLA compilation failures for the next ``times``
    matching dispatches (then clean).  Yields the injected-failure
    counter list.  Subprocess channel: ``GP_CHAOS_FAILING_COMPILE`` (+
    ``GP_CHAOS_COMPILE_OP``)."""
    if int(times) < 1:
        raise ValueError("times must be >= 1")
    prev = {k: _mp_state[k] for k in ("compile_fail", "compile_op",
                                      "compile_fired")}
    fired = [0]
    _mp_state.update(
        compile_fail=int(times), compile_op=op, compile_fired=fired
    )
    try:
        yield fired
    finally:
        _mp_state.update(prev)


def _env_chaos_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def apply_straggler_delay(op: str) -> float:
    """Sleep the staged straggler delay before the named collective (when
    the op filter matches); returns the seconds actually slept.  Driven by
    :class:`StragglerHost` in-process or ``GP_CHAOS_STRAGGLER_S`` (+
    optional ``GP_CHAOS_STRAGGLER_OP`` substring filter) in a subprocess.
    """
    delay = _mp_state["straggler_s"]
    op_filter = _mp_state["straggler_op"]
    if delay is None:
        delay = _env_chaos_float("GP_CHAOS_STRAGGLER_S")
        op_filter = os.environ.get("GP_CHAOS_STRAGGLER_OP", "") or None
    if not delay or (op_filter and op_filter not in op):
        return 0.0
    time.sleep(delay)
    return delay


def maybe_die_before_collective(op: str) -> None:
    """The DeadHost trigger point: guarded collectives call this first, so
    a staged dead host exits (or raises) BEFORE entering a native call its
    peers would otherwise block on forever."""
    if _mp_state["dead_host"] or os.environ.get("GP_CHAOS_DEAD_HOST", "") == "1":
        if _mp_state["dead_host"] and not _mp_state["dead_exit"]:
            raise SimulatedPreemption(
                f"chaos: DeadHost died before collective {op!r}"
            )
        os._exit(PREEMPTION_EXIT_CODE)


def heartbeats_suppressed() -> bool:
    return (
        _mp_state["no_heartbeat"]
        or _mp_state["dead_host"]
        or os.environ.get("GP_CHAOS_NO_HEARTBEAT", "") == "1"
        or os.environ.get("GP_CHAOS_DEAD_HOST", "") == "1"
    )


def preemption_staged() -> bool:
    """In-process analogue of a delivered SIGTERM (tests stage it with
    :func:`stage_preemption` instead of signalling themselves)."""
    return bool(_mp_state["preempt"])


def stage_preemption(on: bool = True) -> None:
    _mp_state["preempt"] = bool(on)


@contextlib.contextmanager
def StragglerHost(delay_s: float, op: Optional[str] = None):
    """Make THIS process a straggler: every guarded collective (optionally
    only those whose name contains ``op``) is entered ``delay_s`` late —
    the deterministic slow-host fault for liveness/deadline tests."""
    prev = (_mp_state["straggler_s"], _mp_state["straggler_op"])
    _mp_state["straggler_s"], _mp_state["straggler_op"] = float(delay_s), op
    try:
        yield
    finally:
        _mp_state["straggler_s"], _mp_state["straggler_op"] = prev


@contextlib.contextmanager
def DeadHost(exit_process: bool = False):
    """Make THIS process die before its next guarded collective and stop
    heartbeating immediately.  ``exit_process=True`` uses ``os._exit(137)``
    (subprocess tests); the default raises :class:`SimulatedPreemption`
    at the collective — the tier-1-safe variant."""
    prev = (_mp_state["dead_host"], _mp_state["dead_exit"])
    _mp_state["dead_host"], _mp_state["dead_exit"] = True, bool(exit_process)
    try:
        yield
    finally:
        _mp_state["dead_host"], _mp_state["dead_exit"] = prev


def kill_process_after(n_iters: int) -> None:
    """Stage a hard ``os._exit(137)`` after ``n_iters`` more checkpoint
    save / segment boundaries (``tick_kill_counter`` is called at each by
    ``utils/checkpoint.py``) — the deterministic mid-fit preemption for
    kill-and-resume tests.  Subprocesses stage it with
    ``GP_CHAOS_KILL_AFTER_ITERS=<n>`` instead."""
    if int(n_iters) < 1:
        raise ValueError("n_iters must be >= 1")
    _mp_state["kill_after"] = int(n_iters)


def tick_kill_counter() -> None:
    remaining = _mp_state["kill_after"]
    if remaining is None:
        raw = os.environ.get("GP_CHAOS_KILL_AFTER_ITERS", "").strip()
        if not raw:
            return
        try:
            remaining = int(raw)
        except ValueError:
            return
        _mp_state["kill_after"] = remaining
    remaining -= 1
    _mp_state["kill_after"] = remaining
    if remaining <= 0:
        os._exit(PREEMPTION_EXIT_CODE)


# --------------------------------------------------------------------------
# silent-data-corruption faults (resilience/integrity.py's proof harness)
# --------------------------------------------------------------------------


def _corrupt_staged(op: str, pid) -> Optional[tuple]:
    """``(kind, scale, fired)`` when the staged/env corruption targets
    this (op, pid), else None.  ``pid`` scoping matters because the DCN
    tests run every logical host as a thread of ONE process — the fault
    must corrupt exactly one pid's publishes."""
    cpid = _mp_state["corrupt_pid"]
    kind = _mp_state["corrupt_kind"]
    op_filter = _mp_state["corrupt_op"]
    scale = _mp_state["corrupt_scale"]
    fired = _mp_state["corrupt_fired"]
    if cpid is None:
        env_pid = _env_chaos_float("GP_CHAOS_CORRUPT_PID")
        if env_pid is None:
            return None
        cpid = int(env_pid)
        kind = os.environ.get("GP_CHAOS_CORRUPT_KIND", "").strip() or "bitflip"
        op_filter = os.environ.get("GP_CHAOS_CORRUPT_OP", "").strip() or None
        scale = _env_chaos_float("GP_CHAOS_CORRUPT_SCALE")
    if int(pid) != int(cpid):
        return None
    if op_filter and op_filter not in op:
        return None
    return kind, float(scale if scale else 1e3), fired


def maybe_corrupt_published(op: str, pid, blob: bytes) -> bytes:
    """The byte-level corruption choke point: ``coord.kv_allgather``
    passes every payload through here AFTER sealing, right before the KV
    publish — corruption lands between attestation and the wire, exactly
    where a flaky NIC/DMA fault would.  ``bitflip`` flips one bit of the
    payload; ``stuck`` republishes this (pid, op)'s previous round's
    blob (the first matching round publishes honestly to have something
    to replay).  The ``scale`` kind is a value fault and fires at
    :func:`maybe_corrupt_arrays` instead."""
    staged = _corrupt_staged(op, pid)
    if staged is None:
        return blob
    kind, _, fired = staged
    if kind == "bitflip" and blob:
        if fired is not None:
            fired[0] += 1
        return blob[:-1] + bytes([blob[-1] ^ 0x01])
    if kind == "stuck":
        prev_map = _mp_state["corrupt_prev"]
        if prev_map is None:
            prev_map = _mp_state["corrupt_prev"] = {}
        key = (int(pid), op.split("/")[0])
        prev = prev_map.get(key)
        prev_map[key] = blob
        if prev is not None and prev != blob:
            if fired is not None:
                fired[0] += 1
            return prev
    return blob


def maybe_corrupt_arrays(op: str, pid, arrays):
    """The value-level corruption choke point: ``DcnContext`` array
    gathers pass their local contribution through here before packing —
    the ``scale`` kind multiplies every float array by the staged factor,
    modeling a host whose COMPUTE is silently wrong (its published bytes
    are internally consistent, so only magnitude attestation or a
    duplicate-dispatch recompute can catch it)."""
    staged = _corrupt_staged(op, pid)
    if staged is None or staged[0] != "scale":
        return arrays
    _, scale, fired = staged
    out = []
    changed = False
    for a in arrays:
        a = np.asarray(a)
        if a.size and np.issubdtype(a.dtype, np.floating):
            out.append((a * scale).astype(a.dtype))
            changed = True
        else:
            out.append(a)
    if changed and fired is not None:
        fired[0] += 1
    return out


@contextlib.contextmanager
def corrupt_host(
    pid: int, kind: str = "bitflip", op: Optional[str] = None,
    scale: float = 1e3,
):
    """Make logical process ``pid`` publish corrupted collective payloads
    (``kind`` ∈ bitflip | stuck | scale, optionally scoped to collectives
    whose name contains ``op``).  Yields a one-element fired-count list.
    Subprocesses stage the same fault with ``GP_CHAOS_CORRUPT_PID`` (+
    ``GP_CHAOS_CORRUPT_KIND`` / ``GP_CHAOS_CORRUPT_OP`` /
    ``GP_CHAOS_CORRUPT_SCALE``)."""
    if kind not in ("bitflip", "stuck", "scale"):
        raise ValueError(f"unknown corruption kind {kind!r}")
    keys = (
        "corrupt_pid", "corrupt_kind", "corrupt_op", "corrupt_scale",
        "corrupt_fired", "corrupt_prev",
    )
    prev = {k: _mp_state[k] for k in keys}
    fired = [0]
    _mp_state.update(
        corrupt_pid=int(pid), corrupt_kind=kind, corrupt_op=op,
        corrupt_scale=float(scale), corrupt_fired=fired, corrupt_prev={},
    )
    try:
        yield fired
    finally:
        _mp_state.update(prev)


def staged_device_corruption() -> Optional[tuple]:
    """``(device_index, scale)`` when a sharded-solve device fault is
    staged (:func:`corrupt_device` / ``GP_CHAOS_CORRUPT_DEVICE``), else
    None — read by ``ops/dist_linalg`` when binding a solve's chaos
    operand."""
    dev = _mp_state["corrupt_device"]
    scale = _mp_state["corrupt_scale"]
    if dev is None:
        env_dev = _env_chaos_float("GP_CHAOS_CORRUPT_DEVICE")
        if env_dev is None:
            return None
        dev = int(env_dev)
        scale = _env_chaos_float("GP_CHAOS_CORRUPT_SCALE")
    return int(dev), float(scale if scale else 1e3)


@contextlib.contextmanager
def corrupt_device(index: int, scale: float = 1e3):
    """Corrupt ONE device's redundantly-computed diagonal panel copies
    inside the sharded blocked Cholesky — the cross-device divergence the
    integrity plane's sampled panel tripwire exists to catch."""
    prev = (_mp_state["corrupt_device"], _mp_state["corrupt_scale"])
    _mp_state["corrupt_device"] = int(index)
    _mp_state["corrupt_scale"] = float(scale)
    try:
        yield
    finally:
        _mp_state["corrupt_device"], _mp_state["corrupt_scale"] = prev


class CorruptingPredictor:
    """Predict path that silently returns WRONG answers (means scaled by
    ``factor``) — the SDC serve fault, as distinct from
    :class:`FlakyPredictor` (raises) and :class:`HangingPredictor`
    (blocks): nothing here errors, stalls, or stops heartbeating, so
    only answer verification can notice.  Duck-types
    :class:`~spark_gp_tpu.serve.batcher.BucketedPredictor`."""

    def __init__(self, inner, factor: float = 1e3) -> None:
        self._inner = inner
        self.factor = float(factor)
        self.calls = 0

    def predict(self, x, *args, **kwargs):
        self.calls += 1
        mean, var = self._inner.predict(x, *args, **kwargs)
        return np.asarray(mean) * self.factor, var

    def __getattr__(self, name):
        return getattr(self._inner, name)


def corrupt_replica(replica, name: Optional[str] = None, factor: float = 1e3):
    """Make one fleet replica serve silently wrong answers: its model
    predictor is swapped for a :class:`CorruptingPredictor` while the
    replica stays alive, healthy and heartbeating — invisible to the
    liveness plane by construction.  Returns the wrapper (its ``calls``
    counter is the test's evidence the corrupted path actually served)."""
    target = name if name is not None else replica.server.registry.names()[0]
    entry = replica.server.registry.get(target)
    corrupting = CorruptingPredictor(entry.predictor, factor=factor)
    entry.predictor = corrupting
    return corrupting


def break_model(server, name: str, version: Optional[int] = None, **flaky_kw):
    """Swap a registered model's predictor for a :class:`FlakyPredictor`.

    Returns the wrapper (its ``calls`` counter is the test's evidence the
    fault fired).  Chaos-only: mutates the live registry entry in place.
    """
    entry = server.registry.get(name, version)
    flaky = FlakyPredictor(entry.predictor, **flaky_kw)
    entry.predictor = flaky
    return flaky
