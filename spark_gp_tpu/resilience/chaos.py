"""Deterministic fault injection: the proof harness for the resilience layer.

Every fault here is seed- or count-driven — no wall-clock races, no
randomized kill timers — so the chaos tests (``pytest -m chaos``) are
ordinary fast deterministic tier-1 tests, not flaky integration theater.

Fault classes:

* :func:`poison_expert` — corrupt the raw rows round-robin-assigned to
  one expert (NaN / inf / huge values), the data-fault that used to turn
  the whole BCM objective to ``inf``;
* :func:`failing_cholesky` — make the host Cholesky raise for the first
  N calls, driving the adaptive jitter ladder and the
  ``NotPositiveDefiniteException`` path;
* :class:`PreemptingCheckpointer` — hard-kills the process
  (``os._exit``, the SIGKILL analogue: no cleanup, no atexit) right
  after the k-th checkpoint save — a deterministic preemption for
  kill-and-resume tests;
* :class:`FlakyPredictor` — a predict path that fails and/or stalls on
  schedule, for circuit-breaker and poisoned-batch isolation tests.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import numpy as np


def poison_expert(
    x: np.ndarray,
    y: np.ndarray,
    expert: int,
    num_experts: int,
    kind: str = "nan",
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Corrupt every row that round-robin grouping assigns to ``expert``.

    ``parallel/experts.py``: expert ``j`` receives points ``j, j+E,
    j+2E, ...`` — so poisoning those rows poisons exactly one expert of
    the fitted stack.  ``kind``: ``"nan"`` (a NaN feature per row),
    ``"inf"`` (an infinite label), ``"huge"`` (1e300-scale features: the
    finite-but-catastrophic conditioning fault), ``"dup"`` (every row
    identical: an exactly singular expert Gram — the fault class the
    adaptive jitter ladder repairs without quarantine).  Returns
    corrupted copies; the inputs are untouched.
    """
    if not 0 <= expert < num_experts:
        raise ValueError(f"expert {expert} out of range [0, {num_experts})")
    x = np.array(x, dtype=np.float64, copy=True)
    y = np.array(y, dtype=np.float64, copy=True)
    rows = np.arange(expert, x.shape[0], num_experts)
    rng = np.random.default_rng(seed)
    cols = rng.integers(0, x.shape[1], size=rows.shape[0])
    if kind == "nan":
        x[rows, cols] = np.nan
    elif kind == "inf":
        y[rows] = np.inf
    elif kind == "huge":
        x[rows] *= 1e300
    elif kind == "dup":
        x[rows] = x[rows[0]]
        y[rows] = y[rows[0]]
    else:
        raise ValueError(f"unknown poison kind {kind!r}")
    return x, y


@contextlib.contextmanager
def failing_cholesky(times: int = 1):
    """Patch ``np.linalg.cholesky`` to raise ``LinAlgError`` for the first
    ``times`` calls (then behave normally).  Yields a one-element list
    holding the injected-failure count, so tests can assert the fault
    actually fired.  Drives the host jitter ladder
    (``ops.linalg.psd_safe_cholesky_np``) and, with a large ``times``,
    the ladder-exhausted ``NotPositiveDefiniteException`` path.
    """
    original = np.linalg.cholesky
    fired = [0]

    def chaotic(a, *args, **kwargs):
        if fired[0] < times:
            fired[0] += 1
            raise np.linalg.LinAlgError("chaos: injected Cholesky failure")
        return original(a, *args, **kwargs)

    np.linalg.cholesky = chaotic
    try:
        yield fired
    finally:
        np.linalg.cholesky = original


#: conventional exit status of a SIGKILLed process (128 + 9) — what a
#: cluster preemption looks like to the supervisor
PREEMPTION_EXIT_CODE = 137


class SimulatedPreemption(BaseException):
    """In-process preemption marker (``BaseException``: ordinary
    ``except Exception`` recovery code must not swallow a kill)."""


class PreemptingCheckpointer:
    """Device-checkpointer wrapper that dies right after the k-th save.

    Two kill modes: ``exit_process=True`` calls ``os._exit`` — no
    exception unwinding, no atexit, no buffered-file flushing, the
    closest in-process analogue of a SIGKILL preemption (subprocess
    tests); the default raises :class:`SimulatedPreemption`, which aborts
    the fit mid-segment without tearing down the interpreter — the fast
    deterministic variant for tier-1.  Because the wrapped saver's write
    is atomic (tmp + fsync + ``os.replace`` + checksum), the checkpoint
    on disk is the complete k-th state either way, and a restarted fit
    resumes from exactly there.
    """

    def __init__(self, inner, kill_after_saves: int,
                 exit_process: bool = False,
                 exit_code: int = PREEMPTION_EXIT_CODE) -> None:
        if kill_after_saves < 1:
            raise ValueError("kill_after_saves must be >= 1")
        self.inner = inner
        self.kill_after_saves = int(kill_after_saves)
        self.exit_process = bool(exit_process)
        self.exit_code = int(exit_code)
        self.saves = 0

    def save(self, state, meta: dict) -> None:
        self.inner.save(state, meta)
        self.saves += 1
        if self.saves >= self.kill_after_saves:
            if self.exit_process:
                os._exit(self.exit_code)
            raise SimulatedPreemption(
                f"preempted after checkpoint save #{self.saves}"
            )

    def load(self, template_state, meta: dict):
        return self.inner.load(template_state, meta)

    @property
    def path(self):
        return self.inner.path


class FlakyPredictor:
    """Predict path that fails / stalls on a deterministic schedule.

    Duck-types enough of :class:`~spark_gp_tpu.serve.batcher.
    BucketedPredictor` for the serving stack (everything else delegates
    to the wrapped predictor).  ``fail_first`` predicts raise
    ``exc_type``; with ``fail_forever`` every call raises; ``latency_s``
    sleeps before answering (slow-predict fault).
    """

    def __init__(
        self,
        inner,
        fail_first: int = 0,
        fail_forever: bool = False,
        latency_s: float = 0.0,
        exc_type: type = RuntimeError,
    ) -> None:
        self._inner = inner
        self.fail_first = int(fail_first)
        self.fail_forever = bool(fail_forever)
        self.latency_s = float(latency_s)
        self.exc_type = exc_type
        self.calls = 0

    def predict(self, x, *args, **kwargs):
        self.calls += 1
        if self.latency_s:
            time.sleep(self.latency_s)
        if self.fail_forever or self.calls <= self.fail_first:
            raise self.exc_type(
                f"chaos: injected predict failure (call {self.calls})"
            )
        return self._inner.predict(x, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def break_model(server, name: str, version: Optional[int] = None, **flaky_kw):
    """Swap a registered model's predictor for a :class:`FlakyPredictor`.

    Returns the wrapper (its ``calls`` counter is the test's evidence the
    fault fired).  Chaos-only: mutates the live registry entry in place.
    """
    entry = server.registry.get(name, version)
    flaky = FlakyPredictor(entry.predictor, **flaky_kw)
    entry.predictor = flaky
    return flaky
