"""Degradation ladder: classified execution failures + policy-driven
fallback re-execution.

The resilience layer's earlier pieces heal *numerical* faults — NaN
experts are quarantined (``quarantine.py``), singular Choleskys climb the
jitter ladder (``ops/linalg.py``), dead hosts are named within a deadline
(``parallel/coord.py``).  *Execution-environment* failures — an HBM
``RESOURCE_EXHAUSTED`` on a one-dispatch device fit, an XLA/Mosaic
compile failure, an exhausted jitter ladder on an f32 runtime, a
coordination timeout, a mixed-precision guard breach — used to propagate
raw.  This module closes that gap with three pieces:

* a **closed failure taxonomy** (:data:`FAILURE_CLASSES`) and
  :func:`classify_failure`, mapping raw ``XlaRuntimeError`` / framework
  exceptions into it (every class has a ``fallback.failures.*`` catalog
  entry — ``obs/names.py``);
* a **declarative, bounded degradation ladder** per entry point
  (:data:`LADDERS`), executed by the drivers below: a classified failure
  re-executes the work one rung down the same axis the system scales —
  smaller dispatches, stricter precision, host execution — instead of
  dying.  Fit: one-dispatch → segmented (halved segment batch) →
  host-f64; sharded fit: → DCN-fallback → single-host; predict: PPA
  chunk-size halving on OOM → host solve; a guard breach under
  ``GP_GUARD_ACTION=degrade``: strict-lane re-fit.  Every transition is
  deterministic, metered (``fallback.*`` metrics + span events), stamped
  into the run journal and the saved model's ``provenance_json``
  (``degradations=[...]``), and kill-switched by ``GP_FALLBACK=0``
  (today's raw propagation, bit-for-bit);
* the **single-classified-error guarantee**: when the ladder is
  exhausted the caller sees ONE :class:`DegradationExhaustedError`
  naming the failure class and the rung history (cause chained) — the
  invariant ``tools/soak.py`` asserts across randomized chaos campaigns.

Recovery policy lives HERE, on the host, outside every compiled program
(the design rule of docs/RESILIENCE.md); a rung re-execution dispatches
ordinary already-tested entry points with degraded knobs.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Callable, List, Optional

logger = logging.getLogger("spark_gp_tpu")

# --------------------------------------------------------------------------
# the closed taxonomy
# --------------------------------------------------------------------------

#: device/host allocation failure (HBM RESOURCE_EXHAUSTED, allocator OOM)
OOM = "oom"
#: XLA / Mosaic compilation or lowering failure
COMPILE = "compile"
#: non-finite objective the per-expert recovery could not attribute/repair
NON_FINITE_EXHAUSTED = "non_finite_exhausted"
#: a factorization that exhausted the adaptive jitter ladder
NOT_PSD_EXHAUSTED = "not_psd_exhausted"
#: a deadline-guarded multi-host coordination step timed out
COORD_TIMEOUT = "coord_timeout"
#: fit-time mixed-precision guard breached its lane bar (GP_GUARD_ACTION)
GUARD_BREACH = "guard_breach"
#: silent data corruption caught by the integrity plane
#: (resilience/integrity.py): a failed payload attestation, a magnitude
#: bound, a duplicate-dispatch disagreement, a diverged redundant panel —
#: never degraded IN PLACE (re-running with the corrupted host still in
#: the sum would reproduce the corruption); the remedy is an elastic
#: resume without the quarantined pid
SDC = "sdc"
#: everything else — NEVER degraded, always re-raised raw
UNKNOWN = "unknown"

FAILURE_CLASSES = (
    OOM, COMPILE, NON_FINITE_EXHAUSTED, NOT_PSD_EXHAUSTED,
    COORD_TIMEOUT, GUARD_BREACH, SDC, UNKNOWN,
)

#: message fragments identifying an allocation failure inside an
#: ``XlaRuntimeError`` (PJRT/XLA wording varies by backend/version; the
#: chaos injector uses the canonical first form)
_OOM_MARKERS = (
    "resource_exhausted", "out of memory", "attempting to allocate",
    "allocation failure",
)
#: message fragments identifying a compilation/lowering failure
_COMPILE_MARKERS = (
    "compilation failure", "failed to compile", "compile failed",
    "mosaic", "lowering failed", "internal: during compilation",
    "xla compilation",
)


class GuardBreachError(RuntimeError):
    """A non-strict precision lane breached its accuracy bar at fit time
    (``models/common.py _emit_precision_guard``) under
    ``GP_GUARD_ACTION=degrade`` — the ladder turns this into a
    strict-lane re-fit."""

    def __init__(self, lane: str, worst: float, bar: float):
        super().__init__(
            f"mixed_precision_guard: lane {lane!r} deviates {worst:.3e} "
            f"from strict (bar {bar:.1e}) and GP_GUARD_ACTION=degrade "
            "requested a strict-lane re-fit"
        )
        self.lane = lane
        self.worst = float(worst)
        self.bar = float(bar)


class DegradationExhaustedError(RuntimeError):
    """Every applicable rung failed: the ONE classified error the caller
    sees (cause chained to the last underlying failure).  ``degradations``
    is the full transition history, ``failure_class`` the final class."""

    def __init__(self, entry: str, failure_class: str, degradations: list,
                 cause: BaseException):
        rungs = " -> ".join(
            [degradations[0]["from"]] + [d["to"] for d in degradations]
        ) if degradations else "(none)"
        super().__init__(
            f"{entry}: degradation ladder exhausted "
            f"(final failure class {failure_class!r}, rungs {rungs}): {cause}"
        )
        self.entry = entry
        self.failure_class = failure_class
        self.degradations = list(degradations)


def enabled() -> bool:
    """The kill switch: ``GP_FALLBACK=0`` restores raw propagation —
    every driver becomes a straight call with zero try/except."""
    return os.environ.get("GP_FALLBACK", "").strip().lower() not in (
        "0", "false", "off",
    )


def classify_failure(exc: BaseException) -> str:
    """Map a raw exception into the closed taxonomy.

    Typed framework failures classify by type; ``XlaRuntimeError`` (and
    the chaos injectors' genuine instances of it) by message markers;
    a :class:`~spark_gp_tpu.resilience.retry.RetryBudgetExceededError`
    by its cause.  Anything unrecognized is :data:`UNKNOWN` — the ladder
    never degrades what it cannot name.
    """
    from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
    from spark_gp_tpu.resilience.quarantine import (
        ExpertQuarantineError,
        NonFiniteFitError,
    )
    from spark_gp_tpu.resilience.retry import RetryBudgetExceededError

    if isinstance(exc, DegradationExhaustedError):
        return exc.failure_class
    if isinstance(exc, GuardBreachError):
        return GUARD_BREACH
    from spark_gp_tpu.resilience.integrity import IntegrityError

    if isinstance(exc, IntegrityError):
        return SDC
    if isinstance(exc, NotPositiveDefiniteException):
        return NOT_PSD_EXHAUSTED
    if isinstance(exc, (NonFiniteFitError, ExpertQuarantineError)):
        return NON_FINITE_EXHAUSTED
    if isinstance(exc, RetryBudgetExceededError) and exc.__cause__ is not None:
        return classify_failure(exc.__cause__)
    try:
        from spark_gp_tpu.parallel.coord import CoordinationTimeoutError

        if isinstance(exc, CoordinationTimeoutError):
            return COORD_TIMEOUT
    except ImportError:  # hygiene-ok: optional coord backend, absence = no class
        pass
    # XlaRuntimeError (by name — jaxlib moves it between modules across
    # versions) and anything runtime-shaped classify by message
    if type(exc).__name__ == "XlaRuntimeError" or isinstance(
        exc, (RuntimeError, MemoryError)
    ):
        if isinstance(exc, MemoryError):
            return OOM
        msg = str(exc).lower()
        if any(marker in msg for marker in _OOM_MARKERS):
            return OOM
        if any(marker in msg for marker in _COMPILE_MARKERS):
            return COMPILE
    return UNKNOWN


# --------------------------------------------------------------------------
# metering — every transition lands in telemetry, the span tree, and the
# instr the fit journal is assembled from
# --------------------------------------------------------------------------


def record_failure(exc: BaseException, entry: str) -> str:
    """Classify + count one observed failure (``fallback.failures.*``);
    returns the class.  Usable standalone (the serve layer annotates its
    predict failures with it) — counting never implies degradation.
    Every observation also lands in the flight recorder
    (``obs/recorder.py``): the incident bundle's event log shows the
    failure sequence that LED to the terminal error, not just the
    terminal error."""
    cls = classify_failure(exc)
    from spark_gp_tpu.obs.recorder import RECORDER
    from spark_gp_tpu.obs.runtime import telemetry

    telemetry.inc(f"fallback.failures.{cls}", entry=entry)
    RECORDER.record(
        "fallback.failure", entry=entry, failure_class=cls,
        error=f"{type(exc).__name__}: {exc}"[:200],
    )
    return cls


def _record_transition(
    entry: str, cls: str, from_rung: str, to_rung: str,
    exc: BaseException, instr=None,
) -> dict:
    from spark_gp_tpu.obs import trace as obs_trace
    from spark_gp_tpu.obs.runtime import telemetry

    telemetry.inc("fallback.transitions", entry=entry)
    telemetry.inc(f"fallback.rung.{to_rung}", entry=entry)
    obs_trace.add_event(
        "fallback.engaged",
        entry=entry, failure_class=cls, from_rung=from_rung, to_rung=to_rung,
    )
    message = (
        f"degradation ladder [{entry}]: {cls} at rung {from_rung!r} — "
        f"re-executing at rung {to_rung!r} ({type(exc).__name__}: "
        f"{str(exc)[:200]})"
    )
    if instr is not None:
        instr.log_warning(message)
    else:
        logger.warning("%s", message)
    return {
        "entry": entry,
        "failure_class": cls,
        "from": from_rung,
        "to": to_rung,
        "error": f"{type(exc).__name__}: {exc}"[:200],
    }


def _stamp(instr, model, degradations: List[dict]) -> None:
    """Attach the transition history to everything the operator reads
    after the fact: the fit metrics (``fallback.engaged``), the instr the
    run journal is assembled from, and the model itself (``save_model``
    folds ``model.degradations`` into ``provenance_json``)."""
    targets = {id(instr): instr}
    model_instr = getattr(model, "instr", None)
    if model_instr is not None:
        targets[id(model_instr)] = model_instr
    for target in targets.values():
        target.degradations = list(degradations)
        target.log_metric("fallback.engaged", 1.0)
    if model is not None:
        model.degradations = list(degradations)


# --------------------------------------------------------------------------
# the declarative ladders
# --------------------------------------------------------------------------

#: rung order per entry point; per-class policy below selects which of a
#: ladder's rungs a failure class may fall to (docs/RESILIENCE.md table)
LADDERS = {
    "fit": (
        "native", "iterative", "matfree", "segmented", "host_f64",
        "strict_lane",
    ),
    "fit_sharded": ("sharded", "dcn_fallback", "single_host", "strict_lane"),
    "predict": ("chunked", "chunk_halved", "host_solve"),
    "ppa": ("device_solve", "host_solve"),
}

#: per-class candidate rungs at the ``fit`` entry, in order.  An OOM
#: tries the ``iterative`` solver rung FIRST: the CG/Lanczos lane
#: (ops/iterative.py) re-executes the SAME dispatch shape with the
#: factorization workspace — the peak resident of every exact fit
#: program — replaced by O(E s (k + r)) CG state, which is the cheapest
#: memory axis to degrade along (no smaller dispatches, no host sync).
#: Next comes ``matfree`` (ops/pallas_matvec.py): the same CG math with
#: the gram itself streamed — O(E s (k + r + tile)) residents — the rung
#: for stacks whose [E, s, s] gram alone exceeds memory; only then do
#: dispatches shrink (``segmented``) or leave the device (``host_f64``).
_FIT_POLICY = {
    OOM: ("iterative", "matfree", "segmented", "host_f64"),
    COMPILE: ("segmented", "host_f64"),
    NON_FINITE_EXHAUSTED: ("host_f64",),
    NOT_PSD_EXHAUSTED: ("host_f64",),
    GUARD_BREACH: ("strict_lane",),
}

#: classes the sharded-fit ladder degrades (everything else re-raises)
_SHARDED_POLICY = (OOM, COMPILE, COORD_TIMEOUT)

#: bounded chunk halvings before the predict ladder jumps to the host
MAX_PREDICT_HALVINGS = 8


def fallback_segment_chunk(checkpoint_interval: int) -> int:
    """The segmented rung's iteration batch: HALF the configured segment
    (``setCheckpointInterval``, default 10) — smaller dispatches along the
    same axis the checkpointed fit already segments on."""
    return max(1, int(checkpoint_interval) // 2)


class NullSegmentSaver:
    """In-memory stand-in for the device checkpointer: the segmented
    fallback rung runs ``fit_*_device_checkpointed``'s segment loop for
    its smaller dispatches WITHOUT persisting state (no checkpoint dir is
    configured on this fit — durability was never requested)."""

    path = None

    def load(self, template_state, meta: dict):
        return None

    def save(self, state, meta: dict) -> None:
        pass


def _fit_rung_applies(est, rung: str, cls: str, visited,
                      expert_size=None) -> bool:
    """Whether ``rung`` is a legal next step for this estimator + class.
    ``expert_size`` (when the caller has the stack) lets the iterative
    gate resolve the ``auto`` lane instead of comparing raw lane names.

    The gates keep pre-ladder behavior intact everywhere degradation
    cannot help: ``segmented`` needs the plain single-chip one-dispatch
    configuration (a checkpointed fit is already segmented; the batched
    multi-start has no segment driver); ``host_f64`` is skipped for
    numerical exhaustion when the runtime already computes in f64 (no
    precision headroom — the failure is a configuration problem and must
    keep raising the reference's advice); ``strict_lane`` only applies
    off the strict lane."""
    if rung in visited:
        return False
    if rung == "iterative":
        # the solver rung re-executes on the CG/Lanczos lane
        # (ops/iterative.py) — applicable only when the fit was not
        # already running it.  With the stack's expert size in hand the
        # ``auto`` lane resolves exactly; without it (no data at the
        # call site) an auto-over-large-experts fit may get one
        # redundant attempt, bounded by ``visited``.
        from spark_gp_tpu.ops.iterative import (
            active_solver_lane,
            resolve_solver,
        )

        lane = active_solver_lane()
        if expert_size is not None:
            return resolve_solver(int(expert_size), lane) != "iterative"
        return lane != "iterative"
    if rung == "matfree":
        # the matrix-free solver rung (ops/pallas_matvec.py) — applicable
        # only when the fit was not already running it AND the kernel
        # carries the streamed-matvec capability (incapable kernels would
        # silently re-run the materialized iterative program: same bytes,
        # same OOM, a wasted attempt)
        from spark_gp_tpu.kernels.base import supports_matfree
        from spark_gp_tpu.ops.iterative import (
            active_solver_lane,
            resolve_solver,
        )

        try:
            if not supports_matfree(est._get_kernel()):
                return False
        except Exception:  # noqa: BLE001 — capability unknowable: skip rung
            return False
        lane = active_solver_lane()
        if expert_size is not None:
            return resolve_solver(int(expert_size), lane) != "matfree"
        return lane != "matfree"
    if rung == "segmented":
        return (
            getattr(est, "_checkpoint_dir", None) is None
            and est._mesh is None
            and getattr(est, "_num_restarts", 1) == 1
            and est._resolved_optimizer() == "device"
        )
    if rung == "host_f64":
        if cls in (NON_FINITE_EXHAUSTED, NOT_PSD_EXHAUSTED):
            # numerical exhaustion degrades only where the rung actually
            # ADDS precision: an f32 runtime AND an unmeshed stack (the
            # families' f64 re-materialization covers single-chip fits
            # only — a sharded re-run would repeat the same f32 math and
            # mask the advice-bearing error for nothing)
            import jax

            if jax.config.jax_enable_x64 or est._mesh is not None:
                return False
        return True
    if rung == "strict_lane":
        from spark_gp_tpu.ops.precision import active_lane

        return active_lane() != "strict"
    return False


def _next_fit_rung(est, cls: str, visited, expert_size=None) -> Optional[str]:
    for rung in _FIT_POLICY.get(cls, ()):
        if _fit_rung_applies(est, rung, cls, visited, expert_size):
            return rung
    return None


@contextlib.contextmanager
def _fit_rung_scope(est, rung: str):
    """Bind one rung's execution overrides to the estimator for the span
    of an attempt: ``_fallback_mode`` steers the optimizer/segment
    dispatch (``common._resolved_optimizer`` / ``_segment_saver_and_chunk``),
    ``host_f64`` additionally runs under ``jax.enable_x64`` so f32
    runtimes re-execute with real precision headroom, and ``strict_lane``
    pins the process lane for the re-fit."""
    if rung == "native":
        yield
        return
    prev_mode = getattr(est, "_fallback_mode", None)
    if rung == "strict_lane":
        from spark_gp_tpu.ops.precision import set_precision_lane

        prev_lane = set_precision_lane("strict")
        try:
            yield
        finally:
            set_precision_lane(prev_lane)
        return
    if rung in ("iterative", "matfree"):
        # the solver rungs: pin the CG/Lanczos (or matrix-free streaming)
        # lane for the re-fit (the fit entry points carry it in their jit
        # keys, so the rung's dispatch compiles its own executable) and
        # restore after
        from spark_gp_tpu.ops.iterative import set_solver_lane

        prev_solver = set_solver_lane(rung)
        try:
            yield
        finally:
            set_solver_lane(prev_solver)
        return
    est._fallback_mode = rung
    try:
        if rung == "host_f64":
            import jax

            with jax.enable_x64():
                yield
        else:
            yield
    finally:
        est._fallback_mode = prev_mode


def run_fit_ladder(est, instr, attempt: Callable, data=None):
    """The fit entry point's ladder driver, wrapped around the complete
    per-family fit body (which itself wraps
    ``_run_with_expert_resilience`` — the per-expert numerical recovery
    runs INSIDE each rung; the ladder only sees what that layer could not
    repair).  ``attempt()`` must honor ``est._fallback_mode``.

    With ``data`` (the grouped expert stack) and a resolvable memory
    budget, the memory planner (``resilience/memplan.py``) picks the
    STARTING rung before the first dispatch: the largest predicted-safe
    configuration — the reactive ladder's OOM rungs as pre-sized first
    choices instead of crash-discovered fallbacks.  The ladder itself is
    unchanged underneath and stays the backstop: a failure despite a
    plan counts ``plan.miss`` and degrades exactly as before."""
    if not enabled():
        return attempt()
    from spark_gp_tpu.resilience import memplan

    plan = memplan.plan_fit_dispatch(est, instr, data)
    rung = "native"
    if plan is not None and plan.chosen != "native":
        # predicted-safe smaller config: start THERE (native was
        # predicted over budget, so it is never fallen back up to).
        # Even a fits=False decision starts at the SMALLEST candidate:
        # the model may over-predict, and dispatching the doomed larger
        # config first would only buy a crash the plan already priced.
        rung = plan.chosen
    visited = {"native", rung}
    degradations: List[dict] = []
    last_cls = UNKNOWN
    plan_missed = False
    while True:
        try:
            with _fit_rung_scope(est, rung):
                model = attempt()
        except Exception as exc:  # classified-failure-site: taxonomy dispatch
            last_cls = record_failure(exc, entry="fit")
            if (
                plan is not None and plan.fits and not plan_missed
                and last_cls == OOM
            ):
                # the plan ADMITTED this config and the allocator still
                # killed it — the miss the operator alerts on.  Counted
                # once per fit, for the OOM class only (the memory plan
                # predicts memory, not numerics); a fits=False decision
                # already counted its miss at plan time.
                plan_missed = True
                memplan.record_plan_miss("fit")
            nxt = _next_fit_rung(
                est, last_cls, visited,
                expert_size=(
                    None if data is None else int(data.x.shape[1])
                ),
            )
            if nxt is None:
                if degradations:
                    from spark_gp_tpu.obs.runtime import telemetry

                    telemetry.inc("fallback.exhausted", entry="fit")
                    raise DegradationExhaustedError(
                        "fit", last_cls, degradations, exc
                    ) from exc
                raise  # nothing engaged: today's raw propagation
            degradations.append(
                _record_transition("fit", last_cls, rung, nxt, exc, instr)
            )
            if last_cls == GUARD_BREACH:
                # the re-fit's metrics must describe the re-fit: scrub the
                # breaching attempt's guard legs so a strict re-fit whose
                # guard passes (strict emits none) doesn't read as breached
                for key in [
                    k for k in getattr(instr, "metrics", {})
                    if k.startswith("mixed_precision_guard")
                ]:
                    del instr.metrics[key]
            visited.add(nxt)
            rung = nxt
            continue
        if degradations:
            _stamp(instr, model, degradations)
        if plan is not None:
            # the journal is assembled from model.instr (a restart's own
            # instr may differ from the outer one the plan stamped) —
            # mirror the rows the same way _stamp mirrors degradations
            model_instr = getattr(model, "instr", None)
            if model_instr is not None and model_instr is not instr:
                model_instr.memory_plan = list(
                    getattr(instr, "memory_plan", []) or []
                )
        return model


def run_distributed_ladder(est, instr, data, active_set, prepare):
    """The ``fit_distributed`` ladder: sharded → DCN-fallback →
    single-host.  The DCN rung re-binds the KV-store coordination context
    (applicable only on multi-process runtimes where one is reachable —
    ``parallel/coord.dcn_fallback_available``); the single-host rung
    host-fetches the stack and re-runs the whole body unsharded (legal
    exactly when a host can see every row: single process, or a
    DCN-fallback stack which is host-local by construction)."""
    if not enabled():
        return est._fit_distributed_body(instr, data, active_set, prepare)

    import jax

    degradations: List[dict] = []
    rung = "sharded"

    def fetchable() -> bool:
        # single_host is legal ONLY when this host's stack is the WHOLE
        # dataset.  A DCN-fallback stack is host-local but holds 1/P of
        # the data — "degrading" host 0 to a local re-fit would silently
        # produce a model of one fragment, the exact wrong-results bug
        # coord.initialize exists to prevent.  Multi-host failures keep
        # raising their named CoordinationTimeoutError instead.
        ctx = getattr(est, "_dcn_ctx", None)
        if ctx is not None:
            return getattr(ctx, "num_processes", 2) <= 1
        return jax.process_count() == 1

    while True:
        try:
            if rung == "strict_lane":
                # guard breach under GP_GUARD_ACTION=degrade: the same
                # strict-lane re-fit the plain-fit ladder runs, over the
                # unchanged (possibly sharded) stack
                from spark_gp_tpu.ops.precision import set_precision_lane

                prev_lane = set_precision_lane("strict")
                try:
                    model = est._fit_distributed_body(
                        instr, data, active_set, prepare
                    )
                finally:
                    set_precision_lane(prev_lane)
            elif rung == "single_host":
                import numpy as np

                from spark_gp_tpu.parallel.experts import ExpertData

                local = ExpertData(
                    x=np.asarray(data.x),
                    y=np.asarray(data.y),
                    mask=np.asarray(data.mask),
                )
                mesh_prev = est._mesh
                est._mesh = None
                try:
                    model = est._fit_distributed_body(
                        instr, local, active_set, prepare
                    )
                finally:
                    est._mesh = mesh_prev
            elif rung == "dcn_fallback":
                from spark_gp_tpu.parallel import coord

                ctx_prev = getattr(est, "_dcn_ctx", None)
                est._dcn_ctx = coord.dcn_context()
                try:
                    model = est._fit_distributed_body(
                        instr, data, active_set, prepare
                    )
                finally:
                    est._dcn_ctx = ctx_prev
            else:
                model = est._fit_distributed_body(
                    instr, data, active_set, prepare
                )
        except Exception as exc:  # classified-failure-site: taxonomy dispatch
            cls = record_failure(exc, entry="fit_sharded")
            nxt = None
            if cls == GUARD_BREACH and rung != "strict_lane":
                from spark_gp_tpu.ops.precision import active_lane

                if active_lane() != "strict":
                    nxt = "strict_lane"
            elif cls in _SHARDED_POLICY:
                if rung == "sharded":
                    from spark_gp_tpu.parallel import coord

                    if coord.dcn_fallback_available(
                        getattr(est, "_dcn_ctx", None)
                    ):
                        nxt = "dcn_fallback"
                    elif fetchable():
                        nxt = "single_host"
                elif rung == "dcn_fallback" and fetchable():
                    nxt = "single_host"
            if nxt is None:
                if degradations:
                    from spark_gp_tpu.obs.runtime import telemetry

                    telemetry.inc("fallback.exhausted", entry="fit_sharded")
                    raise DegradationExhaustedError(
                        "fit_sharded", cls, degradations, exc
                    ) from exc
                raise
            degradations.append(
                _record_transition("fit_sharded", cls, rung, nxt, exc, instr)
            )
            if cls == GUARD_BREACH:
                # same scrub as the plain-fit ladder: the strict re-fit's
                # metrics must describe the re-fit, not the breach
                for key in [
                    k for k in getattr(instr, "metrics", {})
                    if k.startswith("mixed_precision_guard")
                ]:
                    del instr.metrics[key]
            rung = nxt
            continue
        if degradations:
            _stamp(instr, model, degradations)
        return model


def _dump_predict_incident(exc: BaseException, cls: str,
                           degradations: List[dict]) -> None:
    """Terminal predict failures bundle HERE (fits bundle in
    ``common._observed_fit``; predict has no observation shell): one
    incident artifact per terminal classified failure, debounced on the
    exception so a predict raising inside a larger wrapped scope never
    double-dumps."""
    if cls == UNKNOWN and not isinstance(exc, DegradationExhaustedError):
        return
    from spark_gp_tpu.obs import recorder as obs_recorder
    from spark_gp_tpu.obs import trace as obs_trace

    current = obs_trace.current_span()
    obs_recorder.dump_incident(
        reason="predict", exc=exc, failure_class=cls,
        root=getattr(current, "root_span", None),
        extra={"degradations": list(degradations)},
    )


def run_predict_ladder(
    attempt_at_chunk: Callable[[int], object],
    host_attempt: Callable[[], object],
    chunk: int,
    planned: bool = False,
):
    """The predict entry point's ladder (``models/ppa.py``): an OOM on a
    chunked dispatch halves the chunk (bounded —
    :data:`MAX_PREDICT_HALVINGS`), re-dispatching the whole request at
    the smaller shape; a chunk the halvings cannot shrink under the
    allocator's ceiling — or a compile failure — falls to the eager
    host-f64 solve.  Raw behavior with the ladder disabled.  ``planned``
    marks a chunk the memory plan admitted: an OOM despite it counts
    ``plan.miss`` (once), the same contract as the fit ladder."""
    if not enabled():
        return attempt_at_chunk(chunk)
    degradations: List[dict] = []
    halvings = 0
    plan_missed = False
    while True:
        try:
            return attempt_at_chunk(chunk)
        except Exception as exc:  # classified-failure-site: taxonomy dispatch
            cls = record_failure(exc, entry="predict")
            if planned and not plan_missed and cls == OOM:
                from spark_gp_tpu.resilience import memplan

                plan_missed = True
                memplan.record_plan_miss("predict")
            if (
                cls == OOM
                and chunk > 1
                and halvings < MAX_PREDICT_HALVINGS
            ):
                degradations.append(_record_transition(
                    "predict", cls, f"chunk_{chunk}", f"chunk_{chunk // 2}",
                    exc,
                ))
                chunk //= 2
                halvings += 1
                continue
            if cls in (OOM, COMPILE):
                degradations.append(_record_transition(
                    "predict", cls,
                    f"chunk_{chunk}" if halvings else "chunked",
                    "host_solve", exc,
                ))
                try:
                    return host_attempt()
                except Exception as host_exc:  # classified-failure-site
                    from spark_gp_tpu.obs.runtime import telemetry

                    telemetry.inc("fallback.exhausted", entry="predict")
                    err = DegradationExhaustedError(
                        "predict", classify_failure(host_exc), degradations,
                        host_exc,
                    )
                    _dump_predict_incident(err, err.failure_class, degradations)
                    raise err from host_exc
            if degradations:
                from spark_gp_tpu.obs.runtime import telemetry

                telemetry.inc("fallback.exhausted", entry="predict")
                err = DegradationExhaustedError(
                    "predict", cls, degradations, exc
                )
                _dump_predict_incident(err, cls, degradations)
                raise err from exc
            _dump_predict_incident(exc, cls, degradations)
            raise


def run_ppa_solve_ladder(device_attempt: Callable, host_attempt: Callable):
    """The magic-solve ladder (``models/ppa.magic_solve``): an OOM or
    compile failure in the device/sharded f64 solve re-executes the SAME
    solve on the host numpy path — slower O(m^3) single-thread work, but
    an answer.  Numerical failures (``NotPositiveDefiniteException``)
    stay raw on every branch: the ladder degrades execution environments,
    never the jitter policy."""
    if not enabled():
        return device_attempt()
    try:
        return device_attempt()
    except Exception as exc:  # classified-failure-site: taxonomy dispatch
        cls = record_failure(exc, entry="ppa")
        if cls not in (OOM, COMPILE):
            raise
        _record_transition("ppa", cls, "device_solve", "host_solve", exc)
        return host_attempt()
