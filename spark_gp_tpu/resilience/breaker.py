"""Circuit breaker: isolate a faulting model without taking down the server.

A model whose compiled predict raises (corrupted operands after a bad
reload, a device in a wedged state, a chaos-injected fault) must not
consume batcher dispatches, hold queue capacity, or drag down the other
models sharing the process.  The standard remedy is the three-state
breaker:

* **closed** — normal service; consecutive failures are counted, a
  success resets the count;
* **open** — after ``failure_threshold`` consecutive failures every call
  is rejected instantly with :class:`BreakerOpenError` (no device work,
  microsecond latency) for ``reset_timeout_s``;
* **half-open** — after the cooldown, exactly ONE probe request is let
  through; success closes the breaker, failure re-opens it for another
  full cooldown.

Thread-safe; time is injectable (monotonic by default) so the chaos
tests drive state transitions deterministically without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class BreakerOpenError(RuntimeError):
    """Request rejected without dispatch: the target's breaker is open."""

    #: machine-readable class on the wire (serve/codes.py): clients back
    #: off, the fleet router fails over to the next ring replica
    code = "shed.breaker"

    def __init__(self, name: str, retry_after_s: float) -> None:
        self.retry_after_s = max(0.0, retry_after_s)
        super().__init__(
            f"circuit breaker for {name!r} is open (target failing); "
            f"retry after {self.retry_after_s:.3f}s"
        )


class CircuitBreaker:
    """Per-target breaker guarding an unreliable call path."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        name: str = "target",
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout_s < 0:
            raise ValueError("reset_timeout_s must be >= 0")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.trip_count = 0  # times the breaker has opened (monotonic)

    # -- gate -------------------------------------------------------------
    def before_call(self) -> None:
        """Admission check; raises :class:`BreakerOpenError` when open.

        In half-open state admits exactly one concurrent probe — further
        callers are rejected until that probe reports back."""
        with self._lock:
            if self._state == self.OPEN:
                elapsed = self._clock() - self._opened_at
                if elapsed < self.reset_timeout_s:
                    raise BreakerOpenError(
                        self.name, self.reset_timeout_s - elapsed
                    )
                self._state = self.HALF_OPEN
                self._probe_in_flight = False
            if self._state == self.HALF_OPEN:
                if self._probe_in_flight:
                    raise BreakerOpenError(
                        self.name,
                        self.reset_timeout_s,
                    )
                self._probe_in_flight = True

    def abort_call(self) -> None:
        """Release an admission taken by :meth:`before_call` without
        recording an outcome — for failures BEFORE the guarded call runs
        (e.g. the target no longer exists).  Without this a half-open
        probe that dies pre-dispatch would pin ``_probe_in_flight`` and
        reject the target forever."""
        with self._lock:
            self._probe_in_flight = False

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False

    def trip(self) -> None:
        """Force the breaker open NOW, regardless of the consecutive-failure
        count — for out-of-band verdicts like the serve hang watchdog
        (``serve/lifecycle.py``), where one wedged execution is already
        proof the target must stop receiving dispatches."""
        with self._lock:
            if self._state != self.OPEN:
                self.trip_count += 1
            self._state = self.OPEN
            self._opened_at = self._clock()
            self._consecutive_failures = max(
                self._consecutive_failures, self.failure_threshold
            )
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            now_open = (
                self._state == self.HALF_OPEN
                or self._consecutive_failures >= self.failure_threshold
            )
            if now_open:
                if self._state != self.OPEN:
                    self.trip_count += 1
                self._state = self.OPEN
                self._opened_at = self._clock()
            self._probe_in_flight = False

    # -- introspection ----------------------------------------------------
    @property
    def state(self) -> str:
        """Current state, promoting open -> half_open after the cooldown
        (read-only view — the promotion is committed by before_call)."""
        with self._lock:
            if (
                self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s
            ):
                return self.HALF_OPEN
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            state = self._state
            if (
                state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s
            ):
                state = self.HALF_OPEN
            return {
                "state": state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "reset_timeout_s": self.reset_timeout_s,
                "trips": self.trip_count,
            }
