"""Numerical integrity plane: silent-data-corruption (SDC) defense.

Every robustness layer so far assumes a failing component *stops* —
crashes, hangs, OOMs, raises.  A flaky core that silently computes wrong
numbers defeats all of it: the blind pid-ordered sum in
``coord.DcnContext.allreduce_arrays`` folds one host's corrupted (NLL,
grad) into every host's optimizer state with no error ever raised, and a
corrupted serve replica returns garbage posteriors to live traffic.
This module is the trust plane that closes that gap:

* **attested collectives** — every payload published through
  ``coord.kv_allgather`` is sealed (:func:`seal`) with a content digest
  plus its publisher pid and round-qualified collective name; every
  reader verifies (:func:`unseal`) before the deterministic-order sum,
  so transport corruption (bit flips), identity confusion, and stale
  replays ("stuck" payloads) are attributed to the *publishing* pid at
  the gather, not discovered later as a mysteriously wrong objective.
  Array payloads additionally pass a magnitude attestation
  (:func:`bounds_violation`): an absurd-magnitude contribution names its
  publisher.  Non-finite values are deliberately NOT rejected — the DCN
  plane exchanges non-finite locals on purpose so per-expert recovery
  stays synchronized (``coord.DcnContext.wrap_value_and_grad``).
* **duplicate-dispatch spot checks** (:func:`run_spot_check`) — during a
  DCN-fallback fit, with probability p per objective evaluation
  (:func:`should_spot_check`, deterministic in the round index so every
  host agrees), one host republishes one expert block plus its claimed
  (NLL, |grad|₁) for it; every host recomputes the claim from the
  published block with the same compiled probe and the verdict falls out
  of the :data:`TOLERANCE_LADDER` — a disagreeing claim is definitive
  proof against the target (its compute or publish channel is wrong),
  a disagreeing verifier recompute earns that verifier a strike.
* **per-host trust ledger** (:class:`TrustLedger`) — the
  ``coord.LivenessLedger`` state-machine pattern one level up: trusted →
  suspect on a disagreement, suspect → quarantined on repeated ones
  (definitive evidence jumps straight to quarantined).  Verdicts stamp
  ``integrity.*`` metrics and span events; a quarantined host raises
  :class:`HostQuarantinedError` — classified ``sdc`` by
  ``fallback.classify_failure`` — identically on every host (verdicts
  are pure functions of published bytes), so the fleet stops together
  with one incident bundle naming the pid and the coordinated
  checkpoint intact for an elastic resume without the corrupted host.
* **redundancy tripwires** — ``ops/dist_linalg.py`` computes every
  diagonal Cholesky panel redundantly on all devices; the sampled
  per-panel cross-device comparison (:func:`panel_checked` picks the
  panels) turns that existing redundancy into a free SDC tripwire
  (:class:`PanelMismatchError` on divergence).
* **serve answer verification** — ``serve/router.py`` samples a
  fraction of requests for shadow double-dispatch and compares (μ, σ²)
  under the mixed-precision guard bar (:func:`answers_agree`); sustained
  per-replica mismatch evicts the replica from the ring.

``GP_INTEGRITY=0`` is the kill switch: no sealing, no verification, no
spot checks, no tripwires — bit-for-bit the pre-integrity fit.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

# --------------------------------------------------------------------------
# knobs — all env-tunable, all read at use time (tests flip them per case)
# --------------------------------------------------------------------------


def enabled() -> bool:
    """The kill switch: ``GP_INTEGRITY=0`` disables the whole plane."""
    return os.environ.get("GP_INTEGRITY", "").strip().lower() not in (
        "0", "false", "off",
    )


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def max_abs_bound() -> float:
    """``GP_INTEGRITY_MAX_ABS``: the magnitude attestation bar.  Finite
    values past it in a published array payload are attributed to the
    publisher as corruption.  The default is astronomically above any
    legitimate NLL/gradient/uⁱ statistic while far below where a
    high-exponent bit flip lands (~1e300)."""
    return _env_float("GP_INTEGRITY_MAX_ABS", 1e18)


def spot_check_p() -> float:
    """``GP_INTEGRITY_DUPCHECK_P``: per-evaluation probability of a
    duplicate-dispatch spot check during a DCN-fallback fit."""
    return _env_float("GP_INTEGRITY_DUPCHECK_P", 0.05)


def panel_sample_rate() -> float:
    """``GP_INTEGRITY_PANEL_SAMPLE``: fraction of diagonal panels the
    sharded Cholesky cross-device tripwire compares."""
    return _env_float("GP_INTEGRITY_PANEL_SAMPLE", 0.25)


def serve_verify_fraction() -> float:
    """``GP_INTEGRITY_SERVE_FRACTION``: fraction of router requests
    shadow-verified against a second replica."""
    return _env_float("GP_INTEGRITY_SERVE_FRACTION", 0.01)


def evict_after() -> int:
    """``GP_INTEGRITY_EVICT_AFTER``: replica mismatch strikes before the
    router evicts it from the ring."""
    return max(1, int(_env_float("GP_INTEGRITY_EVICT_AFTER", 2)))


def quarantine_after() -> int:
    """``GP_INTEGRITY_QUARANTINE_AFTER``: non-definitive disagreement
    strikes before the trust ledger quarantines a host."""
    return max(1, int(_env_float("GP_INTEGRITY_QUARANTINE_AFTER", 2)))


# --------------------------------------------------------------------------
# errors — all classify as the ``sdc`` failure class (resilience/fallback)
# --------------------------------------------------------------------------


class IntegrityError(RuntimeError):
    """Base of the trust plane's verdicts: numerical evidence attributed
    a wrong value to a specific publisher.  ``pid`` is the implicated
    identity (process id, or replica id on the serve plane), ``code`` the
    machine-readable verdict kind."""

    def __init__(self, message: str, *, pid=None, code: str = "integrity"):
        super().__init__(message)
        self.pid = pid
        self.code = code


class AttestationError(IntegrityError):
    """A published payload failed its attestation: content digest
    mismatch (transport/memory corruption), wrong claimed identity,
    a stale replayed round, or an absurd-magnitude contribution."""


class HostQuarantinedError(IntegrityError):
    """The trust ledger quarantined a host on duplicate-dispatch
    disagreement — the fit stops identically on every process; resume
    elastically without the named pid."""


class PanelMismatchError(IntegrityError):
    """Redundantly-computed diagonal Cholesky panels diverged across
    devices — device-level silent corruption inside a sharded solve."""


# --------------------------------------------------------------------------
# attestation seal: MAGIC + len(header) + JSON header + payload
# --------------------------------------------------------------------------

_MAGIC = b"GPIA1\n"


def _digest(payload: bytes) -> str:
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


def seal(name: str, pid: int, payload: bytes) -> bytes:
    """Wrap ``payload`` with its attestation header: content digest +
    publisher pid + the round-qualified collective name (binding the
    name defeats stale-replay/"stuck" corruption — an old round's sealed
    blob republished under a new round fails the name check)."""
    header = json.dumps(
        {"d": _digest(payload), "p": int(pid), "n": name}
    ).encode()
    return _MAGIC + len(header).to_bytes(4, "big") + header + payload


def unseal(
    name: str, pid: int, blob: bytes, verify: bool = True,
) -> bytes:
    """Strip (and, when ``verify``, check) a sealed payload.

    Unsealed blobs pass through untouched — direct ``kv_allgather``
    users outside the integrity plane, or peers running with integrity
    disabled, interoperate.  Verification failures raise
    :class:`AttestationError` attributed to the *claimed reading slot*
    ``pid`` (the publisher whose key this blob arrived under)."""
    if not blob.startswith(_MAGIC):
        return blob
    hlen = int.from_bytes(blob[len(_MAGIC):len(_MAGIC) + 4], "big")
    body_at = len(_MAGIC) + 4 + hlen
    try:
        header = json.loads(blob[len(_MAGIC) + 4:body_at])
    except ValueError:
        header = None
    payload = blob[body_at:]
    if not verify:
        return payload
    if header is None:
        raise AttestationError(
            f"collective {name!r}: pid {pid} published an unparseable "
            "attestation header (corrupt in transit)",
            pid=pid, code="header_corrupt",
        )
    if int(header.get("p", -1)) != int(pid):
        raise AttestationError(
            f"collective {name!r}: payload read from pid {pid}'s slot "
            f"claims pid {header.get('p')}",
            pid=pid, code="identity_mismatch",
        )
    if header.get("n") != name:
        raise AttestationError(
            f"collective {name!r}: pid {pid} republished a stale payload "
            f"sealed for {header.get('n')!r} (stuck/replayed round)",
            pid=pid, code="stale_replay",
        )
    if _digest(payload) != header.get("d"):
        raise AttestationError(
            f"collective {name!r}: pid {pid}'s payload fails its content "
            "digest — corrupted after sealing",
            pid=pid, code="digest_mismatch",
        )
    return payload


def bounds_violation(arrays) -> bool:
    """True when any *finite* element's magnitude exceeds the
    :func:`max_abs_bound` bar.  Non-finite values pass — the DCN plane
    exchanges them deliberately (synchronized per-expert recovery), and
    the non-finite lane (quarantine.py) owns that failure mode."""
    bound = max_abs_bound()
    for a in arrays:
        a = np.asarray(a)
        if a.size == 0 or not np.issubdtype(a.dtype, np.number):
            continue
        finite = np.isfinite(a)
        if finite.any() and float(np.abs(np.where(finite, a, 0.0)).max()) > bound:
            return True
    return False


# --------------------------------------------------------------------------
# deterministic sampling — every host must take the same branch, so every
# sampling decision is a pure hash of its index, never an RNG draw
# --------------------------------------------------------------------------


def _hash01(tag: str) -> float:
    h = hashlib.sha256(tag.encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


def should_spot_check(round_index: int, p: Optional[float] = None) -> bool:
    p = spot_check_p() if p is None else p
    return p > 0.0 and _hash01(f"dup/{round_index}") < p


def spot_check_target(round_index: int, num_processes: int) -> int:
    """The pid whose work round ``round_index``'s spot check audits."""
    h = hashlib.sha256(f"dup-target/{round_index}".encode()).digest()
    return int.from_bytes(h[:8], "big") % max(1, int(num_processes))


def panel_checked(k: int, rate: Optional[float] = None) -> bool:
    """Whether diagonal panel ``k`` is in this solve's tripwire sample."""
    rate = panel_sample_rate() if rate is None else rate
    return rate > 0.0 and _hash01(f"panel/{k}") < rate


# --------------------------------------------------------------------------
# tolerance ladder
# --------------------------------------------------------------------------

#: (rung name, relative bar) — a comparison passes at the first rung
#: whose bar it meets; meeting none is a disagreement.  The honest case
#: is *exact*: claim and recompute run the same compiled program on the
#: same bytes (np.savez round-trips arrays losslessly), so real SDC does
#: not hide inside "loose" — the wide rungs only absorb environments
#: where a reduction order differs legitimately.
TOLERANCE_LADDER = (("exact", 1e-12), ("tight", 1e-9), ("loose", 1e-5))


def ladder_rung(a, b) -> Optional[str]:
    """The first :data:`TOLERANCE_LADDER` rung ``a`` and ``b`` agree at,
    or ``None`` for a disagreement.  Matching non-finite patterns agree
    at ``exact`` (the non-finite lane owns those values; integrity only
    asks that both parties *report the same thing*)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.shape != b.shape:
        return None
    fa, fb = np.isfinite(a), np.isfinite(b)
    if not np.array_equal(fa, fb):
        return None
    if not fa.all():
        na, nb = a[~fa], b[~fb]
        # same non-finite kind in every slot (nan==nan, inf sign equal)
        if not np.array_equal(np.isnan(na), np.isnan(nb)):
            return None
        same_inf = np.isnan(na) | (na == nb)
        if not same_inf.all():
            return None
    if fa.any():
        scale = max(
            float(np.abs(a[fa]).max()), float(np.abs(b[fb]).max()), 1e-30
        )
        rel = float(np.abs(a[fa] - b[fb]).max()) / scale
    else:
        rel = 0.0
    for rung, bar in TOLERANCE_LADDER:
        if rel <= bar:
            return rung
    return None


def answers_agree(mean_a, var_a, mean_b, var_b, bar: float):
    """Serve-side answer comparison: two replicas' (μ, σ²) for the same
    rows, under ``bar`` (the mixed-precision guard bar — replicas serve
    the same model bytes, so honest answers agree far inside it).
    Returns ``(agree, worst_rel)``."""
    worst = 0.0
    for a, b in ((mean_a, mean_b), (var_a, var_b)):
        if a is None and b is None:
            continue
        if a is None or b is None:
            return False, float("inf")
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        if a.shape != b.shape or not (
            np.array_equal(np.isfinite(a), np.isfinite(b))
        ):
            return False, float("inf")
        finite = np.isfinite(a)
        if finite.any():
            scale = np.maximum(
                np.maximum(np.abs(a[finite]), np.abs(b[finite])), 1e-12
            )
            worst = max(
                worst,
                float((np.abs(a[finite] - b[finite]) / scale).max()),
            )
    return worst <= bar, worst


# --------------------------------------------------------------------------
# per-host trust ledger — the LivenessLedger state-machine pattern one
# level up: liveness tracks *presence*, trust tracks *correctness*
# --------------------------------------------------------------------------

TRUSTED = "trusted"
SUSPECT = "suspect"
QUARANTINED = "quarantined"


class TrustLedger:
    """trusted → suspect → quarantined escalation per identity.

    A *definitive* disagreement (failed digest, magnitude attestation,
    a spot-check claim contradicted by every recompute of the published
    bytes) quarantines immediately; non-definitive ones (a single
    verifier's recompute off) accumulate strikes and quarantine at
    :func:`quarantine_after`.  A clean observation repays one strike —
    a transient glitch decays back to trusted, a recurring one ratchets
    up.  Callbacks fire OUTSIDE the lock (they emit metrics/events,
    which may take other locks) — the ``LivenessLedger`` discipline.
    """

    def __init__(
        self,
        quarantine_after_strikes: Optional[int] = None,
        on_suspect: Optional[Callable[[object, str], None]] = None,
        on_quarantined: Optional[Callable[[object, str], None]] = None,
    ):
        self._threshold = quarantine_after_strikes
        self._on_suspect = on_suspect
        self._on_quarantined = on_quarantined
        self._lock = threading.Lock()
        self._strikes: Dict[object, int] = {}
        self._state: Dict[object, str] = {}

    def _bar(self) -> int:
        return (
            quarantine_after() if self._threshold is None
            else max(1, int(self._threshold))
        )

    def record_disagreement(
        self, ident, definitive: bool = False, reason: str = "",
    ) -> str:
        """Register numerical evidence against ``ident``; returns the
        new state."""
        fire = []
        with self._lock:
            if self._state.get(ident) == QUARANTINED:
                return QUARANTINED
            strikes = self._strikes.get(ident, 0) + 1
            self._strikes[ident] = strikes
            if definitive or strikes >= self._bar():
                state = QUARANTINED
            else:
                state = SUSPECT
            prev = self._state.get(ident, TRUSTED)
            self._state[ident] = state
            if state == SUSPECT and prev != SUSPECT and self._on_suspect:
                fire.append((self._on_suspect, ident, reason))
            if state == QUARANTINED and self._on_quarantined:
                fire.append((self._on_quarantined, ident, reason))
        for cb, ident_, reason_ in fire:
            cb(ident_, reason_)
        return state

    def record_clean(self, ident) -> str:
        """One agreeing observation repays one strike (never resurrects
        a quarantined identity — quarantine is terminal until
        :meth:`forget`)."""
        with self._lock:
            if self._state.get(ident) == QUARANTINED:
                return QUARANTINED
            strikes = max(0, self._strikes.get(ident, 0) - 1)
            self._strikes[ident] = strikes
            state = TRUSTED if strikes == 0 else SUSPECT
            self._state[ident] = state
            return state

    def state(self, ident) -> str:
        with self._lock:
            return self._state.get(ident, TRUSTED)

    def strikes(self, ident) -> int:
        with self._lock:
            return self._strikes.get(ident, 0)

    def suspects(self) -> List[object]:
        with self._lock:
            return sorted(
                i for i, s in self._state.items() if s == SUSPECT
            )

    def quarantined(self) -> List[object]:
        with self._lock:
            return sorted(
                i for i, s in self._state.items() if s == QUARANTINED
            )

    def forget(self, ident) -> None:
        """Drop an identity (a replaced host re-enters trusted)."""
        with self._lock:
            self._strikes.pop(ident, None)
            self._state.pop(ident, None)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "strikes": dict(self._strikes),
                "suspects": sorted(
                    i for i, s in self._state.items() if s == SUSPECT
                ),
                "quarantined": sorted(
                    i for i, s in self._state.items() if s == QUARANTINED
                ),
            }


def _emit(kind: str, **fields) -> None:
    """Metric + span event + flight record for one verdict — never
    raises (the trust plane must not replace the corruption it names
    with an observability failure)."""
    try:
        from spark_gp_tpu.obs import trace as obs_trace
        from spark_gp_tpu.obs.recorder import RECORDER
        from spark_gp_tpu.obs.runtime import telemetry

        telemetry.inc(f"integrity.{kind}")
        obs_trace.add_event(f"integrity.{kind}", **fields)
        RECORDER.record(f"integrity.{kind}", **fields)
    except Exception:  # noqa: BLE001 — see docstring
        pass


def make_trust_ledger() -> TrustLedger:
    """The fit plane's ledger: verdict transitions stamp ``integrity.*``
    metrics, span events and the flight recorder (whose buffer the
    incident bundle snapshots — a quarantine's evidence trail rides the
    bundle for free)."""
    return TrustLedger(
        on_suspect=lambda ident, reason: _emit(
            "host_suspect", pid=ident, reason=reason
        ),
        on_quarantined=lambda ident, reason: _emit(
            "host_quarantined", pid=ident, reason=reason
        ),
    )


# --------------------------------------------------------------------------
# duplicate-dispatch spot checks (DCN-fallback fits)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class DupCheckSpec:
    """What a spot check needs to audit this fit: the kernel/objective
    the probe evaluates under, and the host-local expert stack a target
    republishes blocks of."""

    kernel: object
    objective: str
    x: np.ndarray     # [E, n, p]
    y: np.ndarray     # [E, n]
    mask: np.ndarray  # [E, n]


def stage_spot_check(ctx, kernel, data, objective: str) -> None:
    """Arm duplicate-dispatch spot checks on a DCN context for the fit
    about to run.  Only the stateless marginal objective is auditable
    (the probe must be a pure function of the published block); latent
    objectives carry per-expert optimizer state and stay covered by the
    attestation/bounds layer alone."""
    spec = None
    if (
        enabled()
        and spot_check_p() > 0.0
        and objective == "marginal"
        and getattr(ctx, "num_processes", 1) >= 2
    ):
        spec = DupCheckSpec(
            kernel=kernel,
            objective=objective,
            x=np.asarray(data.x),
            y=np.asarray(data.y),
            mask=np.asarray(data.mask),
        )
    ctx.dup_check = spec


def expert_claim(kernel, theta, x_e, y_e, mask_e, objective) -> np.ndarray:
    """``[nll, |grad|₁]`` (f64) for ONE expert block — the deterministic
    probe both the target and every verifier run (the quarantine plane's
    per-expert health probe on a singleton stack: same compiled program,
    same input bytes, same answer)."""
    from spark_gp_tpu.parallel.experts import ExpertData
    from spark_gp_tpu.resilience.quarantine import expert_health

    data = ExpertData(
        x=np.asarray(x_e)[None],
        y=np.asarray(y_e)[None],
        mask=np.asarray(mask_e)[None],
    )
    nll, gnorm = expert_health(kernel, theta, data, objective)
    return np.asarray([float(nll[0]), float(gnorm[0])], dtype=np.float64)


_SKIP = np.zeros(0, dtype=np.float64)  # the non-target's gather marker


def run_spot_check(ctx, theta, round_index: int) -> None:
    """One duplicate-dispatch audit round, lockstep on every host.

    Protocol (two gathers on the DCN plane, so the payloads themselves
    ride the attested channel):

    1. ``dupc`` — the target (:func:`spot_check_target`) republishes one
       expert block ``(x_e, y_e, mask_e)`` plus its claimed probe value;
       everyone else publishes an empty marker.
    2. Every host recomputes the probe from the *published* block —
       identical bytes, identical program, so every host's local value
       ``L`` is identical — and publishes its recompute in ``dupv``.
    3. Verdicts are pure functions of the published values and ``L``,
       hence identical everywhere: a claim disagreeing with ``L`` is
       definitive against the target (all recomputes of its own
       published bytes contradict it); a verifier's published recompute
       disagreeing with ``L`` earns that verifier a non-definitive
       strike (its publish channel, and therefore possibly its ``vag``
       contributions, is corrupting values).

    Raises :class:`HostQuarantinedError` when the ledger quarantines.
    """
    spec = getattr(ctx, "dup_check", None)
    if spec is None:
        return
    target = spot_check_target(round_index, ctx.num_processes)
    theta = np.asarray(theta, dtype=np.float64)
    if ctx.process_id == target:
        active = np.flatnonzero(np.asarray(spec.mask).sum(axis=1) > 0)
        if active.size == 0:
            payload = [_SKIP]
        else:
            e = int(active[round_index % active.size])
            claim = expert_claim(
                spec.kernel, theta, spec.x[e], spec.y[e], spec.mask[e],
                spec.objective,
            )
            payload = [spec.x[e], spec.y[e], spec.mask[e], claim]
    else:
        payload = [_SKIP]
    parts = ctx.allgather_arrays("dupc", *payload)
    published = parts[target]
    if len(published) != 4:
        # the target had nothing auditable (fully masked stack): every
        # host sees the same marker and skips the round together
        return
    x_e, y_e, mask_e, claim = published
    local = expert_claim(
        spec.kernel, theta, x_e, y_e, mask_e, spec.objective
    )
    votes = ctx.allgather_arrays("dupv", local)
    _emit(
        "spot_checks", round=round_index, target=target,
        rung=ladder_rung(claim, local) or "disagree",
    )
    ledger = getattr(ctx, "trust", None)
    if ledger is None:
        ledger = ctx.trust = make_trust_ledger()
    if ladder_rung(claim, local) is None:
        _emit(
            "spot_check_disagreements", pid=target, via="claim",
            round=round_index,
        )
        ledger.record_disagreement(
            target, definitive=True, reason="spot_check_claim"
        )
        raise HostQuarantinedError(
            f"duplicate-dispatch spot check (round {round_index}): pid "
            f"{target}'s claimed (NLL, |grad|) for its republished expert "
            "block disagrees with every recompute of the same bytes — "
            "host quarantined; resume elastically without it",
            pid=target, code="spot_check_claim",
        )
    ledger.record_clean(target)
    for pid in range(ctx.num_processes):
        if pid == target:
            continue
        vote = votes[pid][0] if votes[pid] else _SKIP
        if ladder_rung(vote, local) is None:
            _emit(
                "spot_check_disagreements", pid=pid, via="verifier",
                round=round_index,
            )
            state = ledger.record_disagreement(
                pid, reason="spot_check_verifier"
            )
            if state == QUARANTINED:
                raise HostQuarantinedError(
                    f"duplicate-dispatch spot checks: pid {pid}'s "
                    "recomputed probe values repeatedly disagree with "
                    "every other host's — host quarantined; resume "
                    "elastically without it",
                    pid=pid, code="spot_check_verifier",
                )
        else:
            ledger.record_clean(pid)


# --------------------------------------------------------------------------
# model-artifact integrity (sha256 sidecars)
# --------------------------------------------------------------------------

SIDECAR_SUFFIX = ".sha256"

#: the named code ``CheckpointCorruptError`` carries for a failed model
#: sidecar (distinguishing it from a torn training checkpoint)
ARTIFACT_DIGEST_CODE = "model_sidecar_digest_mismatch"


def file_digest(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def write_sidecar(path: str) -> str:
    """Stamp ``<path>.sha256`` next to a written artifact; returns the
    hex digest."""
    hexd = file_digest(path)
    with open(path + SIDECAR_SUFFIX, "w") as fh:
        fh.write(hexd + "\n")
    return hexd


def verify_sidecar(path: str) -> Optional[bool]:
    """Check an artifact against its sidecar.  ``None`` when no sidecar
    exists (legacy artifact — nothing to verify against); raises
    ``CheckpointCorruptError`` (code :data:`ARTIFACT_DIGEST_CODE`) on a
    mismatch, so a fleet distributing corrupted model files refuses at
    bind time instead of serving garbage."""
    if not enabled():
        return None
    sidecar = path + SIDECAR_SUFFIX
    if not os.path.exists(sidecar):
        return None
    with open(sidecar) as fh:
        expected = fh.read().strip()
    actual = file_digest(path)
    if actual != expected:
        from spark_gp_tpu.utils.checkpoint import CheckpointCorruptError

        _emit("artifact_corrupt", path=path)
        err = CheckpointCorruptError(
            f"{path} fails its content checksum "
            f"(sidecar {expected[:12]}…, file {actual[:12]}…) — the model "
            "artifact was corrupted after it was written; refuse to load "
            f"it [code={ARTIFACT_DIGEST_CODE}]"
        )
        err.code = ARTIFACT_DIGEST_CODE
        raise err
    _emit("artifact_verified", path=path)
    return True
