"""Predictive memory planning: static admission replaces the reactive OOM ladder.

Until now the system learned a dispatch does not fit by crashing: the
degradation ladder (``resilience/fallback.py``) halves chunks/segments
*after* an ``XlaRuntimeError: RESOURCE_EXHAUSTED``, and the serve gate
(``serve/lifecycle.MemoryAdmissionGate``) shed on an *observed* global
memory high-water mark.  Following *Memory Safe Computations with XLA
Compiler* (PAPERS.md, arxiv 2206.14148), the compiler already knows the
peak bytes of every program before execution — this module turns that
knowledge into decisions made BEFORE the first dispatch:

* **budget** — :func:`memory_budget_bytes` resolves the device memory
  ceiling: a staged chaos limit (``chaos.memory_limit_bytes`` — the
  CPU-provable shrunken-runtime injector) > ``GP_MEMPLAN_LIMIT_BYTES`` >
  the backend's own ``memory_stats()['bytes_limit']``.  No budget means
  no plan constraint: every decision degrades to today's behavior.
* **prediction** — two sources.  (1) *Compiled*: ``obs/cost.py``'s
  signature-cached lower+compile path extracts
  ``compiled.memory_analysis()`` next to ``cost_analysis()``; every
  metered entry point's measured peak lands here via
  :func:`note_compiled_peak`.  (2) *Analytic*: shapes never compiled
  before are predicted by a small cost model keyed on
  ``(entry, family, E, s, m, lane/dtype, backend, rung)`` —
  :func:`fit_dispatch_bytes` / :func:`predict_dispatch_bytes` — and
  CALIBRATED upward whenever a compiled or gauge-measured peak exceeds
  the model (:func:`observe_measured`).  Predictions carry a
  configurable safety margin (``GP_MEMPLAN_MARGIN``, default 1.25), so
  ``predicted >= modeled-actual`` holds by construction.
* **decision** — ONE API, :func:`plan_dispatch`: candidates
  preferred-first, the largest predicted-safe configuration wins.
  Consumers: the fit ladder driver picks one-dispatch vs the (pre-sized)
  segmented rung up front (``fallback.run_fit_ladder``), the PPA predict
  sizes its chunk from the plan instead of halving after a crash
  (``models/ppa.py``), and the serve admission gate admits on
  predicted-per-request bytes against remaining headroom
  (``serve/lifecycle.py``).  The reactive ladder stays as the BACKSTOP:
  a wrong prediction re-engages it and counts ``plan.miss``.

Every decision is provenance-stamped (``instr.memory_plan`` →
run-journal ``memory_plan`` key; incident bundles carry the rows next to
the measured gauges) so a wrong prediction is a debuggable artifact, not
a mystery crash.  ``GP_MEMPLAN=0`` is the kill switch: planning off,
today's reactive behavior bit-for-bit.  Metrics: ``plan.hit`` /
``plan.miss`` / ``plan.shed`` / ``plan.margin_breach`` (obs/names.py).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

_forced: Optional[bool] = None

#: analytic-model dispatch-liveness factors per fit rung: how many
#: [E, s, s] gram-sized buffers are live at once inside the dispatched
#: program (per squared latent head — the multiclass Laplace jacfwd
#: crosses every head pair).  ``native`` (the whole L-BFGS loop as one
#: program) carries the gram, its factorization, the fused-inverse VJP
#: intermediates and the line-search pipeline — the CPU XLA programs
#: measure 9.5–12 gram-stacks live (memory_analysis; multiclass ~5.3 per
#: head pair), so 16 brackets them with headroom BEFORE the margin; the
#: ``segmented`` rung's smaller dispatches halve the in-flight depth
#: (the same axis the reactive ladder already degrades along);
#: ``host_f64`` re-materializes in f64 (the itemsize doubling is applied
#: by the caller via ``itemsize=8``).  Calibration ratchets these up
#: whenever reality measures bigger.
_FIT_RUNG_WORK_FACTOR = {
    "native": 16.0,
    "segmented": 8.0,
    "host_f64": 12.0,
    # the CG/Lanczos solver rung (ops/iterative.py): the gram stack, the
    # jittered copy the matvec closes over, and the autodiff residuals of
    # the three differentiable einsums stay [E, s, s]-sized, but the
    # factorization / explicit-inverse / SPD-VJP chains — the bulk of the
    # exact rungs' liveness — are replaced by skinny CG state accounted
    # separately below (O(E s (k + r)) workspace, not O(E s^2) factor)
    "iterative": 6.0,
    # the matrix-free rung (ops/pallas_matvec.py) carries NO gram-sized
    # resident at all — its byte model in fit_dispatch_bytes drops the
    # gram term entirely and accounts the streamed workspace separately;
    # the factor is unused but present so fit_model_key calibration has
    # a row to ratchet
    "matfree": 0.0,
}


def enabled() -> bool:
    """The kill switch, read at call time: ``set_memory_planning`` wins,
    else ``GP_MEMPLAN`` (default ON — planning is inert without a budget,
    so the default costs nothing on unconstrained runtimes)."""
    if _forced is not None:
        return _forced
    return os.environ.get("GP_MEMPLAN", "").strip().lower() not in (
        "0", "false", "off",
    )


def set_memory_planning(value: Optional[bool]) -> None:
    """Force planning on/off for this process (None = back to the env)."""
    global _forced
    _forced = value


def margin() -> float:
    """The safety margin multiplied into every prediction
    (``GP_MEMPLAN_MARGIN``, default 1.25, floored at 1.0): the headroom
    that keeps ``predicted >= actual`` true against model error."""
    raw = os.environ.get("GP_MEMPLAN_MARGIN", "").strip()
    try:
        value = float(raw) if raw else 1.25
    except ValueError:
        value = 1.25
    return max(1.0, value)


#: device-stats budget cache TTL: the budget is consulted on hot paths
#: (a plan per predict dispatch), and a ``memory_stats()`` device query
#: per request is the exact cost the admission gate's own throttle
#: exists to avoid.  The ceiling moves essentially never; chaos/env
#: overrides are read fresh (dict lookups).
_BUDGET_TTL_S = 0.25
_budget_cache: Tuple[float, Optional[float]] = (-float("inf"), None)


def memory_budget_bytes() -> Optional[float]:
    """The device memory ceiling the planner budgets against, or None
    (no budget — planning imposes no constraint).  Resolution order:
    staged chaos limit (the CPU-provable shrunken runtime) >
    ``GP_MEMPLAN_LIMIT_BYTES`` > the backend's reported ``bytes_limit``
    (cached for :data:`_BUDGET_TTL_S` — hot paths pay a clock read, not
    a device query)."""
    from spark_gp_tpu.resilience import chaos

    staged = chaos.staged_memory_limit()
    if staged is not None:
        return float(staged)
    raw = os.environ.get("GP_MEMPLAN_LIMIT_BYTES", "").strip()
    if raw:
        try:
            value = float(raw)
            return value if value > 0 else None
        except ValueError:
            pass
    global _budget_cache
    now = time.monotonic()
    sampled_at, cached = _budget_cache
    if now - sampled_at < _BUDGET_TTL_S:
        return cached
    value = None
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            value = float(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — no backend stats, no budget
        pass
    _budget_cache = (now, value)
    return value


def memory_in_use_bytes() -> Optional[float]:
    """Bytes in use RIGHT NOW — the per-request-scoped usage read the
    serve admission gate compares headroom against: device
    ``bytes_in_use`` when the backend reports it, else the CURRENT host
    RSS (``/proc/self/statm``; the old gate read the lifetime peak
    ``ru_maxrss``, which latched shed mode forever on the CPU fallback),
    else that peak as the last resort."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_in_use" in stats:
            return float(stats["bytes_in_use"])
    except Exception:  # noqa: BLE001 — fall through to the host reads
        pass
    try:
        with open("/proc/self/statm", encoding="ascii") as fh:
            rss_pages = int(fh.read().split()[1])
        return float(rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:  # noqa: BLE001 — non-Linux fallback
        pass
    try:
        import resource

        return float(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        )
    except Exception:  # noqa: BLE001 — no signal at all
        return None


# --------------------------------------------------------------------------
# the analytic byte models (raw — no margin; callers apply predicted_bytes)
# --------------------------------------------------------------------------


def fit_model_key(family: Optional[str], rung: str) -> Tuple:
    """The calibration key of one fit dispatch model: per family AND per
    rung, so a measured miss on (say) the multiclass native program never
    over-predicts every other family's fits."""
    return ("fit", family, rung)


def predict_model_key(mean_only: bool) -> Tuple:
    return ("predict", bool(mean_only))


def fit_dispatch_bytes(
    num_experts: int,
    expert_size: int,
    n_features: int,
    itemsize: int,
    rung: str = "native",
    n_targets: int = 1,
    family: Optional[str] = None,
) -> float:
    """Modeled RAW peak bytes of one fit dispatch at ``rung``.

    The dominant residents of a fit program are the expert stack
    (``[E, s, p]`` features + targets + mask), the theta-invariant gram
    cache, and ``k`` gram-sized ``[E, s, s]`` work buffers live at once
    (factorization, VJP intermediates, line-search pipeline) — ``k`` per
    rung from :data:`_FIT_RUNG_WORK_FACTOR`.  ``host_f64`` callers pass
    ``itemsize=8`` (the rung re-materializes the stack in f64).  This is
    a COST MODEL, not an accounting identity: calibration
    (:func:`observe_measured`) raises it wherever a compiled or measured
    peak proves it low, and the margin covers the rest.
    """
    e = float(max(1, num_experts))
    s = float(max(1, expert_size))
    p = float(max(1, n_features))
    stack = e * s * (p + 2.0 * max(1, n_targets)) * itemsize
    gram = e * s * s * itemsize
    heads = float(max(1, n_targets))
    k = _FIT_RUNG_WORK_FACTOR.get(rung, _FIT_RUNG_WORK_FACTOR["native"])
    # +1 gram for the theta-invariant cache (kernels/base.py) — counted
    # unconditionally: when the kernel opts out the model is merely
    # conservative, which is the safe direction.  The work term scales
    # with heads^2: the multiclass Laplace dK-stack jacobians cross every
    # latent-head pair.
    if rung == "matfree":
        # the matrix-free solver rung (ops/pallas_matvec.py): NO gram
        # term — not even the theta-invariant cache (the lane skips its
        # build; that cache IS the O(E s^2) block being refused).  Live
        # residents are the stack plus skinny per-expert state: the
        # rank-k preconditioner [E, s, k], the multi-RHS CG block and
        # carries (as the iterative rung), and the streamed matvec's
        # O(s·tile) row-panel working set (checkpointed AD recomputes
        # tiles, so gradients stay panel-sized too) — O(E s (k + r +
        # tile)) total, the whole point of the lane
        from spark_gp_tpu.ops.iterative import solver_config
        from spark_gp_tpu.ops.pallas_matvec import matvec_tile

        cfg = solver_config(int(s))
        cols = cfg.rank + 5.0 * (1.0 + cfg.probes) + float(
            matvec_tile(int(s))
        )
        raw = stack + e * s * cols * heads * itemsize
        return _calibrated(fit_model_key(family, rung), raw)
    raw = stack + (1.0 + k * heads * heads) * gram
    if rung == "iterative":
        # the solver rung's extra residents are SKINNY, not square: the
        # rank-k pivoted-Cholesky preconditioner [E, s, k], the multi-RHS
        # block [E, s, 1 + probes], and the four CG carries over it —
        # O(E s (k + r)) workspace where the exact rungs hold O(E s^2)
        # factors (why plan_fit_dispatch can admit it at sizes the native
        # rung cannot reach under the same budget)
        from spark_gp_tpu.ops.iterative import solver_config

        cfg = solver_config(int(s))
        cols = cfg.rank + 5.0 * (1.0 + cfg.probes)
        raw += e * s * cols * heads * itemsize
    return _calibrated(fit_model_key(family, rung), raw)


def predict_dispatch_bytes(
    rows: int,
    m: int,
    n_features: int,
    itemsize: int,
    mean_only: bool = False,
) -> float:
    """Modeled RAW peak bytes of one PPA predict dispatch of ``rows``
    test points against an ``m``-point active set: the ``[rows, m]``
    cross kernel (plus one einsum intermediate of the same shape), the
    ``[m, m]`` magic matrix (variance models), operands and outputs."""
    r = float(max(1, rows))
    m_f = float(max(1, m))
    p = float(max(1, n_features))
    # 4 cross-sized buffers live at once: the [rows, m] cross kernel, the
    # distance intermediate it is built from, and the einsum/product
    # temps (the CPU XLA predict programs measure ~13 cross-sizes of
    # TOTAL footprint at small m where operands dominate; 4 crosses +
    # operands brackets them with the margin on top)
    cross = 4.0 * r * m_f
    operators = m_f + (0.0 if mean_only else m_f * m_f)
    io = r * p + m_f * p + (1.0 if mean_only else 2.0) * r
    raw = (cross + operators + io) * itemsize
    return _calibrated(predict_model_key(mean_only), raw)


def predicted_bytes(raw: float) -> float:
    """A raw model estimate with the safety margin applied — THE number
    compared against budgets (so ``predicted >= raw-modeled actual``
    holds by construction)."""
    return float(raw) * margin()


# --------------------------------------------------------------------------
# calibration + compiled peaks (memory_analysis via obs/cost.py)
# --------------------------------------------------------------------------

_LOCK = threading.Lock()
#: model key -> multiplicative scale (>1 only): measured/compiled peaks
#: that exceeded the analytic model ratchet it up for the process life
_CALIBRATION: Dict[Tuple, float] = {}
#: entry name -> max compiled peak bytes observed (memory_analysis,
#: relayed by obs/cost.observe_call through the signature-cached
#: lower+compile path)
_COMPILED_PEAKS: Dict[str, float] = {}


def _calibrated(key: Tuple, raw: float) -> float:
    with _LOCK:
        scale = _CALIBRATION.get(key, 1.0)
    return raw * scale


def observe_measured(key: Tuple, raw_model_bytes: float,
                     measured_bytes: float) -> None:
    """Calibrate the analytic model from a measured peak (device gauges
    or a compiled ``memory_analysis``): when reality exceeds the model,
    the key's scale ratchets up so the NEXT prediction brackets it.
    Never ratchets down — under-prediction is the failure mode this
    plane exists to remove."""
    if raw_model_bytes <= 0 or measured_bytes <= 0:
        return
    scale = measured_bytes / raw_model_bytes
    if scale <= 1.0:
        return
    with _LOCK:
        if scale > _CALIBRATION.get(key, 1.0):
            _CALIBRATION[key] = scale


#: the calibration feedback slot: the dispatch sites
#: (``common._dispatch_raw_bytes``, the PPA chunk dispatcher) deposit
#: (model key, raw model bytes) just before dispatching; the compiled
#: peak relayed from the SAME thread's ``observe_call`` right after the
#: dispatch closes the loop.  Thread-local: dispatch and metering run on
#: the same thread by construction.
_EXPECT = threading.local()


def note_expected_dispatch(key: Tuple, raw_bytes: float) -> None:
    """Arm the calibration loop for the dispatch about to run: when cost
    metering relays its compiled ``memory_analysis`` peak, the analytic
    model under ``key`` is judged against it (:func:`observe_measured`).
    Overwritten by the next dispatch; consumed at most once."""
    _EXPECT.pending = (key, float(raw_bytes))


def note_compiled_peak(entry: str, peak_bytes: float) -> None:
    """Record one compiled entry point's ``memory_analysis`` peak (fed by
    ``obs/cost.observe_call`` whenever cost metering is on) — the
    compiler's own number, the ground truth the analytic model is judged
    against — and close the calibration loop against the armed dispatch
    expectation when one matches this entry's kind."""
    if not peak_bytes or peak_bytes <= 0:
        return
    with _LOCK:
        if peak_bytes > _COMPILED_PEAKS.get(entry, 0.0):
            _COMPILED_PEAKS[entry] = float(peak_bytes)
    pending = getattr(_EXPECT, "pending", None)
    if pending is None:
        return
    key, raw = pending
    kind = key[0]
    # kind guard: a stale fit expectation (metering was off for that
    # dispatch) must not be consumed by a later predict's relay
    if (kind == "fit" and entry.startswith("fit.")) or (
        kind == "predict" and entry.startswith(("predict.", "serve."))
    ):
        _EXPECT.pending = None
        observe_measured(key, raw, float(peak_bytes))


def compiled_peak(entry: str) -> Optional[float]:
    """Max compiled (memory_analysis) peak observed for ``entry``, or
    None when the entry was never metered."""
    with _LOCK:
        return _COMPILED_PEAKS.get(entry)


def compiled_peaks() -> Dict[str, float]:
    with _LOCK:
        return dict(_COMPILED_PEAKS)


def reset_calibration() -> None:
    """Drop calibration + compiled-peak state (tests)."""
    with _LOCK:
        _CALIBRATION.clear()
        _COMPILED_PEAKS.clear()


# --------------------------------------------------------------------------
# plan_dispatch — THE decision API
# --------------------------------------------------------------------------


@dataclass
class PlanDecision:
    """One admission decision: the largest predicted-safe candidate."""

    entry: str
    chosen: str
    raw_bytes: float            # modeled actual of the chosen config
    predicted_bytes: float      # raw * margin — the budgeted number
    budget_bytes: Optional[float]
    fits: bool                  # False: NOTHING fit; chosen = smallest
    margin: float = field(default_factory=margin)
    candidates: List[dict] = field(default_factory=list)

    def row(self) -> dict:
        """The provenance row journals/bundles carry (json-safe)."""
        return {
            "entry": self.entry,
            "chosen": self.chosen,
            "raw_bytes": self.raw_bytes,
            "predicted_bytes": self.predicted_bytes,
            "budget_bytes": self.budget_bytes,
            "fits": self.fits,
            "margin": self.margin,
            "candidates": list(self.candidates),
        }


def plan_dispatch(
    entry: str,
    candidates: Sequence[Tuple[str, float]],
    budget: Optional[float] = None,
) -> Optional[PlanDecision]:
    """Pick the largest predicted-safe configuration.

    ``candidates`` are ``(name, raw_model_bytes)`` preferred-first (the
    fastest / largest config first); the first whose margined prediction
    fits the budget wins.  Returns None when planning is off or no
    budget resolves (no constraint — callers keep today's behavior
    exactly), and a ``fits=False`` decision on the SMALLEST-predicted
    candidate when nothing fits (preference order need not be
    monotone-by-bytes — the fit ladder's iterative rung is preferred
    over segmented but not always smaller) — the caller dispatches it
    anyway and the reactive ladder stays the backstop."""
    if not enabled() or not candidates:
        return None
    if budget is None:
        budget = memory_budget_bytes()
    if budget is None:
        return None
    rows = [
        {
            "name": name,
            "raw_bytes": float(raw),
            "predicted_bytes": predicted_bytes(raw),
            "fits": predicted_bytes(raw) <= budget,
        }
        for name, raw in candidates
    ]
    chosen = next(
        (r for r in rows if r["fits"]),
        min(rows, key=lambda r: r["predicted_bytes"]),
    )
    decision = PlanDecision(
        entry=entry,
        chosen=chosen["name"],
        raw_bytes=chosen["raw_bytes"],
        predicted_bytes=chosen["predicted_bytes"],
        budget_bytes=float(budget),
        fits=bool(chosen["fits"]),
        candidates=rows,
    )
    from spark_gp_tpu.obs import trace as obs_trace
    from spark_gp_tpu.obs.runtime import telemetry

    telemetry.inc("plan.hit" if decision.fits else "plan.miss", entry=entry)
    obs_trace.add_event(
        "plan.decision",
        entry=entry, chosen=decision.chosen, fits=decision.fits,
        predicted_bytes=decision.predicted_bytes,
        budget_bytes=decision.budget_bytes,
    )
    return decision


def record_plan_miss(entry: str) -> None:
    """A reactive recovery engaged DESPITE a plan decision — the
    prediction was wrong in the dangerous direction.  Counted so an
    operator can alert on it; the journal/bundle rows show which."""
    from spark_gp_tpu.obs.runtime import telemetry

    telemetry.inc("plan.miss", entry=entry)


def stamp_decision(instr, decision: Optional[PlanDecision]) -> None:
    """Attach a decision row to the instr the run journal (and any
    incident bundle) is assembled from — the ``memory_plan`` key."""
    if decision is None or instr is None:
        return
    rows = list(getattr(instr, "memory_plan", []) or [])
    rows.append(decision.row())
    instr.memory_plan = rows


# --------------------------------------------------------------------------
# consumers
# --------------------------------------------------------------------------


def plan_fit_dispatch(est, instr, data) -> Optional[PlanDecision]:
    """The fit entry point's plan (called by ``fallback.run_fit_ladder``
    before the first attempt): choose the largest predicted-safe
    starting rung — ``native`` (one-dispatch) preferred, the ladder's
    ``segmented`` rung as the pre-sized smaller configuration when it
    applies to this estimator (same gates as the reactive rung:
    ``fallback._fit_rung_applies``).  Applies only to the on-device
    dispatch path (the host optimizer's per-evaluation programs are
    small); None = no constraint, run exactly today's path."""
    if not enabled() or data is None:
        return None
    try:
        if est._resolved_optimizer() != "device" or est._mesh is not None:
            return None
    except Exception:  # noqa: BLE001 — an unresolvable optimizer plans nothing
        return None
    budget = memory_budget_bytes()
    if budget is None:
        return None
    import numpy as np

    e, s = int(data.x.shape[0]), int(data.x.shape[1])
    p = int(data.x.shape[2])
    itemsize = int(np.dtype(data.x.dtype).itemsize)
    n_targets = int(data.y.shape[2]) if getattr(data.y, "ndim", 2) == 3 else 1
    family = type(est).__name__

    from spark_gp_tpu.ops.iterative import resolve_solver

    # the "native" candidate prices the program the fit will ACTUALLY
    # dispatch first: the iterative- (or matfree-) rung byte model when
    # the solver lane (pinned, or budget-aware auto over large experts)
    # already resolves there — mirroring common._dispatch_raw_bytes
    resolved = resolve_solver(
        s, num_experts=e, n_features=p, itemsize=itemsize
    )
    if resolved == "matfree":
        try:
            from spark_gp_tpu.kernels.base import supports_matfree

            native_rung = (
                "matfree" if supports_matfree(est._get_kernel())
                else "iterative"
            )
        except Exception:  # noqa: BLE001 — capability unknowable: price big
            native_rung = "iterative"
    elif resolved == "iterative":
        native_rung = "iterative"
    else:
        native_rung = "native"
    candidates = [
        ("native",
         fit_dispatch_bytes(e, s, p, itemsize, native_rung, n_targets,
                            family))
    ]
    from spark_gp_tpu.resilience import fallback

    if fallback._fit_rung_applies(
        est, "iterative", fallback.OOM, set(), expert_size=s
    ):
        # the CG/Lanczos solver rung as a PRE-SIZED choice: same dispatch
        # shape, skinny workspace instead of O(E s^2) factors — preferred
        # over shrinking dispatches when it fits.  (When the fit already
        # resolves to the iterative lane — pinned or auto over large
        # experts — the "native" candidate above IS that program, priced
        # by _dispatch_raw_bytes at the iterative rung, and no duplicate
        # row is offered.)
        candidates.append((
            "iterative",
            fit_dispatch_bytes(e, s, p, itemsize, "iterative", n_targets,
                               family),
        ))
    if fallback._fit_rung_applies(
        est, "matfree", fallback.OOM, set(), expert_size=s
    ):
        # the matrix-free rung as a PRE-SIZED choice below iterative:
        # same CG math with the gram streamed, O(E s (k + r + tile))
        # residents — the rung that admits expert sizes whose gram stack
        # alone exceeds the budget
        candidates.append((
            "matfree",
            fit_dispatch_bytes(e, s, p, itemsize, "matfree", n_targets,
                               family),
        ))
    if fallback._fit_rung_applies(est, "segmented", fallback.OOM, set()):
        candidates.append((
            "segmented",
            fit_dispatch_bytes(e, s, p, itemsize, "segmented", n_targets,
                               family),
        ))
    decision = plan_dispatch("fit", candidates, budget)
    stamp_decision(instr, decision)
    return decision


def plan_predict_chunk(
    chunk: int,
    m: int,
    n_features: int,
    itemsize: int,
    mean_only: bool,
) -> Optional[int]:
    """The PPA predict chunk, pre-sized: the largest chunk (halving down
    from the caller's default, bounded like the reactive ladder's
    halvings) whose margined prediction fits the budget.  Returns None
    when planning is off or no budget resolves (the caller keeps its
    default chunk — today's path bit-for-bit — and knows no plan is in
    force); returns 1 when even the smallest dispatch does not fit (it
    proceeds; the reactive ladder backstops)."""
    if not enabled() or chunk <= 1:
        return None
    budget = memory_budget_bytes()
    if budget is None:
        return None
    from spark_gp_tpu.resilience.fallback import MAX_PREDICT_HALVINGS

    candidates = []
    c = int(chunk)
    for _ in range(MAX_PREDICT_HALVINGS + 1):
        candidates.append(
            (str(c), predict_dispatch_bytes(c, m, n_features, itemsize,
                                            mean_only))
        )
        if c <= 1:
            break
        c //= 2
    decision = plan_dispatch("predict", candidates, budget)
    if decision is None:
        return None
    planned = int(decision.chosen) if decision.fits else 1
    return max(1, min(chunk, planned))


def predict_request_bytes(predictor, rows: int) -> Optional[float]:
    """Margined predicted bytes of one serve request of ``rows`` against
    a warmed :class:`~spark_gp_tpu.serve.batcher.BucketedPredictor` —
    the per-request cost the admission gate compares against remaining
    headroom.  Sized at the PADDED bucket shape (the dispatch that will
    actually run).  None when planning is off or the predictor does not
    expose its shape (duck-typed chaos wrappers delegate, so they do)."""
    if not enabled():
        return None
    try:
        import numpy as np

        padded = int(predictor.padded_rows(int(rows)))
        m = int(predictor.active_rows)
        p = int(predictor.n_features)
        itemsize = int(np.dtype(predictor.dtype).itemsize)
        mean_only = bool(predictor.mean_only)
    except Exception:  # noqa: BLE001 — no shape, no prediction (gate
        # falls back to its watermark hysteresis path)
        return None
    return predicted_bytes(
        predict_dispatch_bytes(padded, m, p, itemsize, mean_only)
    )
