"""Expert quarantine: adaptive repair and removal of poisoned BCM experts.

The training objective is ``sum_e NLL_e`` over the expert stack
(``models/likelihood.py``).  A single expert whose NLL or gradient is
non-finite — NaN rows from a failed preprocessing shard, a Gram matrix
past the edge of positive definiteness — previously poisoned the global
objective: the host optimizer raised ``NotPositiveDefiniteException`` at
the first evaluation and the device optimizer silently converged to NaN.

Recovery ladder (host-driven, outside the compiled hot path — clean fits
never pay for any of this):

1. **health probe** — one vmapped program evaluates every expert's NLL
   and gradient-magnitude independently at the initial hyperparameters;
2. **adaptive jitter escalation** — unhealthy experts retry with
   per-expert trace-relative diagonal boosts walked up the shared ladder
   (``ops.linalg.JITTER_SCHEDULE``), re-dispatching the same compiled
   probe with a traced jitter operand;
3. **quarantine** — experts still non-finite after the ladder are dropped
   from the BCM sum: their mask rows are zeroed (the masked Gram embedding
   turns them into inert identity blocks), their features replaced with a
   benign copy of a healthy expert's first point (so ``0 * NaN`` can never
   leak back in).  ``final_nll`` stays the optimizer's literal reduced
   sum; the full-stack-comparable figure is published alongside as
   ``final_nll_renormalized = final_nll * E_active / E_kept``
   (``models/common._log_renormalized_nll``).

The shapes of the stack never change, so every retry reuses the already
compiled fit executables, and sharded stacks keep their sharding.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from spark_gp_tpu.ops.linalg import JITTER_SCHEDULE
from spark_gp_tpu.parallel.experts import ExpertData


class NonFiniteFitError(RuntimeError):
    """A fit attempt produced a non-finite objective (detected on host)."""


class ExpertQuarantineError(RuntimeError):
    """Quarantine would drop every expert (or too many to trust the fit) —
    the failure is global, not a per-expert fault; the model configuration
    itself is numerically unusable (the classic remedy: increase sigma2)."""


#: shared tail of every "quarantine refused" message — the advice is the
#: same whichever guard fires
GLOBAL_FAILURE_ADVICE = (
    "the failure is global (increase sigma2 / check the data), not a "
    "quarantinable per-expert fault"
)


def renorm_factor(active: float, dropped: float) -> float:
    """``E_active / E_kept`` — the factor mapping the reduced BCM sum back
    to a full-stack-comparable NLL.  Exactly 1.0 when nothing is dropped;
    raises :class:`ExpertQuarantineError` when nothing would be kept.
    The single implementation behind ``QuarantineReport.renorm`` and the
    fit drivers' ``bcm_renorm`` metric.  The aggregation plane
    generalizes this count-based factor to arbitrary per-expert weights
    (``models/aggregation.weighted_renorm_factor`` — uniform unit
    weights with d drops reduce to exactly this quotient); both compose
    multiplicatively in ``final_nll_renormalized``."""
    kept = active - dropped
    if kept <= 0:
        raise ExpertQuarantineError(
            f"all {int(active)} active expert(s) are non-finite — "
            + GLOBAL_FAILURE_ADVICE
        )
    return active / kept


@dataclass(frozen=True)
class QuarantineReport:
    """Outcome of one diagnosis pass over the expert stack."""

    bad: np.ndarray      # bool [E] — non-finite after the whole ladder
    jitter: np.ndarray   # f64 [E] — per-expert relative jitter that fixed it
    num_active: int      # experts with any unmasked points before diagnosis

    @property
    def num_dropped(self) -> int:
        return int(self.bad.sum())

    @property
    def num_jittered(self) -> int:
        return int((self.jitter > 0).sum())

    @property
    def renorm(self) -> float:
        """``E_active / E_kept`` — multiply the reduced BCM sum by this to
        keep the reported NLL comparable to the full-expert objective
        (published as the ``final_nll_renormalized`` metric by the fit
        drivers).  Exactly 1.0 when nothing is dropped."""
        return renorm_factor(self.num_active, self.num_dropped)

    @property
    def clean(self) -> bool:
        return self.num_dropped == 0 and self.num_jittered == 0


@jax.jit
def _nonfinite_expert_impl(x, y, mask):
    real = mask > 0
    bad_x = jnp.any(~jnp.isfinite(x) & real[..., None], axis=(1, 2))
    bad_y = jnp.any(~jnp.isfinite(y) & real, axis=1)
    return bad_x | bad_y


def nonfinite_expert_mask(data: ExpertData) -> np.ndarray:
    """bool [E]: experts carrying any non-finite unmasked feature/label.

    The cheap pre-fit screen (one reduction over the stack, ~free next to
    a single objective evaluation): data-level NaN/inf faults are caught
    before the optimizer ever sees an ``inf`` objective."""
    return np.asarray(_nonfinite_expert_impl(data.x, data.y, data.mask))


@partial(jax.jit, static_argnums=0, static_argnames=("objective",))
def _expert_health_impl(kernel, theta, x, y, mask, jitter, *, objective):
    from spark_gp_tpu.models.likelihood import objective_fn

    obj = objective_fn(objective)

    def one(xe, ye, me, je):
        local = ExpertData(x=xe[None], y=ye[None], mask=me[None])
        extra = (je,) if objective == "marginal" else ()
        value, grad = jax.value_and_grad(
            lambda t: obj(kernel, t, local, *extra)
        )(theta)
        return value, jnp.sum(jnp.abs(grad))

    return jax.vmap(one)(x, y, mask, jitter)


def expert_health(
    kernel, theta, data: ExpertData, objective: str = "marginal",
    jitter=None,
):
    """``(nll [E], grad_l1 [E])`` — every expert probed independently.

    The per-expert decomposition of the exact training objective: one
    vmapped value-and-grad, so a single dispatch diagnoses the whole
    stack.  ``jitter`` (scalar or [E], trace-relative) feeds the marginal
    objective's escalation operand."""
    e = data.x.shape[0]
    dtype = data.x.dtype
    if jitter is None:
        jit_vec = jnp.zeros((e,), dtype=dtype)
    else:
        jit_vec = jnp.broadcast_to(
            jnp.asarray(jitter, dtype=dtype), (e,)
        )
    theta = jnp.asarray(theta, dtype=dtype)
    nll, gnorm = _expert_health_impl(
        kernel, theta, data.x, data.y, data.mask, jit_vec,
        objective=objective,
    )
    return np.asarray(nll), np.asarray(gnorm)


def _healthy(nll: np.ndarray, gnorm: np.ndarray) -> np.ndarray:
    return np.isfinite(nll) & np.isfinite(gnorm)


def diagnose_experts(
    kernel,
    theta,
    data: ExpertData,
    objective: str = "marginal",
    schedule=JITTER_SCHEDULE,
    allow_jitter: bool = True,
) -> QuarantineReport:
    """Probe every expert, escalate jitter for the unhealthy, report.

    Experts already healthy keep jitter 0 (their math is untouched);
    unhealthy experts walk the ladder rung by rung — each rung is one
    re-dispatch of the same compiled probe — and keep the first rung that
    makes them finite.  Experts the whole ladder cannot repair are marked
    ``bad``.  ``allow_jitter=False`` (the sharded fit paths, whose
    objective cannot carry the jitter operand) skips straight from the
    unjittered probe to quarantine.
    """
    e = data.x.shape[0]
    active = np.asarray(data.mask).sum(axis=1) > 0
    nll, gnorm = expert_health(kernel, theta, data, objective)
    healthy = _healthy(nll, gnorm) | ~active  # inert experts are fine
    jitter = np.zeros(e, dtype=np.float64)
    if allow_jitter and objective == "marginal" and not healthy.all():
        for tau in schedule[1:]:
            candidate = np.where(healthy, jitter, tau)
            nll_t, gnorm_t = expert_health(
                kernel, theta, data, objective, jitter=candidate
            )
            fixed = _healthy(nll_t, gnorm_t) & ~healthy
            jitter[fixed] = tau
            healthy |= fixed
            if healthy.all():
                break
    return QuarantineReport(
        bad=(~healthy) & active,
        jitter=jitter,
        num_active=int(active.sum()),
    )


def quarantine_experts(data: ExpertData, bad) -> ExpertData:
    """Return a stack with the ``bad`` experts made inert.

    Mask rows zeroed (the masked Gram embedding then contributes an exact
    0 to the likelihood), labels zeroed, and features replaced by a benign
    copy of the first healthy expert's first point — a fully-masked expert
    still flows through ``kernel.gram``, and ``0 * NaN`` would re-poison
    the sum.  Shapes (and therefore sharding and compiled executables) are
    unchanged.
    """
    bad = np.asarray(bad, dtype=bool)
    if not bad.any():
        return data
    if bad.all():
        raise ExpertQuarantineError(
            "every expert is non-finite — " + GLOBAL_FAILURE_ADVICE
        )
    return data.with_experts_masked(bad)
