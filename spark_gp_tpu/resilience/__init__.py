"""Fault tolerance for fit and serve.

The BCM objective is a *sum* of per-expert NLLs (PAPER.md; Deisenroth &
Ng, Distributed GPs), so one poisoned expert chunk — a NaN feature row
from a bad host, an ill-conditioned Gram — makes the whole objective
non-finite; one preempted host loses the optimizer state; one broken
model can wedge a serving process.  This package is the recovery layer,
and it deliberately lives OUTSIDE the compiled hot paths ("Memory Safe
Computations with XLA", PAPERS.md): clean fits and clean requests never
pay for it, failures re-dispatch the same compiled programs with repaired
operands.

* :mod:`~spark_gp_tpu.resilience.quarantine` — per-expert health probes,
  adaptive jitter escalation over the shared ladder
  (``ops.linalg.JITTER_SCHEDULE``), and BCM quarantine-with-
  renormalization for experts the ladder cannot repair.
* :mod:`~spark_gp_tpu.resilience.retry` — bounded retry-with-backoff for
  whole fit attempts and other host-side operations.
* :mod:`~spark_gp_tpu.resilience.breaker` — a circuit breaker
  (closed/open/half-open) isolating a faulting model on the serve path.
* :mod:`~spark_gp_tpu.resilience.chaos` — the deterministic fault-
  injection harness that proves all of the above end to end
  (``pytest -m chaos``).

See docs/RESILIENCE.md for the failure model and semantics.
"""

from spark_gp_tpu.resilience.breaker import (
    BreakerOpenError,
    CircuitBreaker,
)
from spark_gp_tpu.resilience.quarantine import (
    ExpertQuarantineError,
    NonFiniteFitError,
    QuarantineReport,
    diagnose_experts,
    expert_health,
    nonfinite_expert_mask,
    quarantine_experts,
)
from spark_gp_tpu.resilience.retry import RetryBudgetExceededError, retry_with_backoff

__all__ = [
    "BreakerOpenError",
    "CircuitBreaker",
    "ExpertQuarantineError",
    "NonFiniteFitError",
    "QuarantineReport",
    "RetryBudgetExceededError",
    "diagnose_experts",
    "expert_health",
    "nonfinite_expert_mask",
    "quarantine_experts",
    "retry_with_backoff",
]
