"""Bounded retry-with-backoff for host-side recovery actions.

Used around whole device-fit attempts (a preempted runtime, a transient
device error, a quarantine round that needs the fit re-dispatched) and
any other operation whose failure is plausibly transient.  Deliberately
tiny: deterministic exponential backoff (no randomized jitter — test
determinism is a design requirement of the chaos harness), a hard
attempt budget, and a hook per retry so callers can repair state (e.g.
quarantine an expert) between attempts.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger("spark_gp_tpu")


class RetryBudgetExceededError(RuntimeError):
    """Every attempt failed; carries the last underlying error as cause."""


def retry_with_backoff(
    fn: Callable,
    *,
    attempts: int = 3,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
):
    """Run ``fn()``; on a ``retry_on`` failure back off and retry.

    ``on_retry(attempt_index, exc)`` runs before each retry — the hook
    where fit recovery repairs its operands (quarantine, jitter) so the
    next attempt isn't a blind replay.  If the hook itself raises, that
    error propagates immediately (the failure is not retryable).  After
    ``attempts`` total tries the last error is re-raised wrapped in
    :class:`RetryBudgetExceededError` (cause chained).
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    delay = base_delay_s
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as exc:  # noqa: PERF203 — recovery path, not hot
            last = exc
            if attempt == attempts - 1:
                break
            logger.warning(
                "%s failed (attempt %d/%d): %s — backing off %.3fs",
                describe, attempt + 1, attempts, exc, delay,
            )
            if on_retry is not None:
                on_retry(attempt, exc)
            sleep(delay)
            delay = min(delay * 2.0, max_delay_s)
    raise RetryBudgetExceededError(
        f"{describe} failed after {attempts} attempts: {last}"
    ) from last
