"""ctypes bindings for the native data-plane runtime (gpdata.cpp).

Built on first use with g++ (the image ships no pybind11; ctypes over a C
ABI keeps the binding dependency-free).  The shared object is cached next to
the source keyed by a source hash, so rebuilds happen only when the C++
changes.  Every entry point degrades gracefully: if the toolchain or the
build is unavailable, ``available()`` is False and callers fall back to
numpy — the framework never hard-requires the native path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "gpdata.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _so_path() -> str:
    with open(_SRC, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"_gpdata_{digest}.so")


def _build(so_path: str) -> None:
    # Atomic build: compile to a temp name, rename into place.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            [
                "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
                _SRC, "-o", tmp,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            so = _so_path()
            if not os.path.exists(so):
                _build(so)
            lib = ctypes.CDLL(so)
            lib.gpdata_read_csv.restype = ctypes.c_int
            lib.gpdata_read_csv.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.gpdata_free.restype = None
            lib.gpdata_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
            lib.gpdata_zscore.restype = None
            lib.gpdata_zscore.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64,
                ctypes.c_int64,
            ]
            lib.gpdata_num_threads.restype = ctypes.c_int
            _lib = lib
        except Exception:  # hygiene-ok: optional native build; any failure = unavailable
            _build_failed = True
            _lib = None
        return _lib


def available() -> bool:
    """True if the native library built (or loaded from cache) successfully."""
    return _load() is not None


_ERRORS = {
    -1: "cannot open file",
    -2: "mmap failed",
    -3: "no data rows",
    -4: "allocation failed",
    -5: "malformed field or ragged row",
}


def read_csv(path: str, skip_rows: int = 0) -> np.ndarray:
    """Parallel CSV parse -> float64 ``[rows, cols]``.

    Raises ``RuntimeError`` when the native library is unavailable — callers
    that want transparent degradation should use
    :func:`spark_gp_tpu.data.datasets._read_csv`, which falls back to
    ``np.loadtxt``.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native gpdata library unavailable")
    out = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.gpdata_read_csv(
        os.fsencode(path), skip_rows, ctypes.byref(out),
        ctypes.byref(rows), ctypes.byref(cols),
    )
    if rc == -1 and not os.path.exists(path):
        raise FileNotFoundError(path)
    if rc != 0:
        raise ValueError(
            f"gpdata_read_csv({path!r}): {_ERRORS.get(rc, f'error {rc}')}"
        )
    try:
        arr = np.ctypeslib.as_array(out, shape=(rows.value, cols.value)).copy()
    finally:
        lib.gpdata_free(out)
    return arr


def zscore(x: np.ndarray) -> np.ndarray:
    """Column-standardize a float64 C-contiguous copy of ``x`` in native
    code (zero-variance columns left unscaled, Scaling.scala:18)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native gpdata library unavailable")
    x = np.ascontiguousarray(x, dtype=np.float64).copy()
    if x.ndim != 2:
        raise ValueError("zscore expects [rows, cols]")
    lib.gpdata_zscore(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        x.shape[0],
        x.shape[1],
    )
    return x
