// Native data-plane runtime: parallel CSV parsing + z-score scaling.
//
// The reference delegates its data plane to the Spark JVM runtime: CSV
// ingestion via the DataFrame reader (regression/examples/Airfoil.scala:26-33,
// classification/examples/MNIST.scala:20-26) and feature standardization as a
// two-pass RDD reduce (commons/util/Scaling.scala:10-25).  This file is the
// TPU framework's native equivalent of that runtime layer: the accelerator
// never touches it, but host-side ingest throughput decides how fast a
// 500k-row stress config (Year-Prediction-MSD) reaches the chip.
//
// Exposed as a plain C ABI consumed through ctypes (no pybind11 in the
// image); built on first use by spark_gp_tpu/native/__init__.py.
//
//   gpdata_read_csv   mmap the file, split at newline boundaries into one
//                     span per hardware thread, two passes (count rows /
//                     parse in place) so the output is a single contiguous
//                     row-major [rows, cols] float64 buffer with no
//                     inter-thread synchronization on the hot path.
//   gpdata_zscore     column-wise (x - mean) / std in parallel, zero-variance
//                     columns clamped to std=1 (Scaling.scala:18 semantics).
//   gpdata_free       release a buffer returned by gpdata_read_csv.

#include <cerrno>
#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Span {
  const char* begin;
  const char* end;
  int64_t rows = 0;       // data rows in this span (pass 1)
  int64_t row_base = 0;   // global index of this span's first row (prefix sum)
};

// A line is a data row iff it contains a non-whitespace character.
inline bool is_data_line(const char* b, const char* e) {
  for (const char* p = b; p < e; ++p) {
    if (*p != ' ' && *p != '\t' && *p != '\r') return true;
  }
  return false;
}

int64_t count_rows(const char* b, const char* e) {
  int64_t n = 0;
  const char* line = b;
  for (const char* p = b; p <= e; ++p) {
    if (p == e || *p == '\n') {
      if (is_data_line(line, p)) ++n;
      line = p + 1;
    }
  }
  return n;
}

// Parse one span's lines into out[row_base..)*cols.  Returns 0 on success,
// -1 on a malformed field / wrong column count (first error wins).
int parse_span(const Span& span, int64_t cols, double* out) {
  const char* line = span.begin;
  int64_t row = span.row_base;
  for (const char* p = span.begin; p <= span.end; ++p) {
    if (p == span.end || *p == '\n') {
      if (is_data_line(line, p)) {
        double* dst = out + row * cols;
        const char* f = line;
        for (int64_t c = 0; c < cols; ++c) {
          while (f < p && (*f == ' ' || *f == '\t')) ++f;
          // std::from_chars: locale-free and ~4x strtod throughput — CSV
          // float decode dominates the whole ingest pass.
          auto res = std::from_chars(f, p, dst[c]);
          if (res.ec != std::errc() || res.ptr == f)
            return -1;  // empty / non-numeric field
          f = res.ptr;
          while (f < p && (*f == ' ' || *f == '\t')) ++f;
          if (c + 1 < cols) {
            if (f >= p || (*f != ',' && *f != ';')) return -1;
            ++f;
          }
        }
        // allow trailing separator/whitespace only
        while (f < p && (*f == ' ' || *f == '\t' || *f == '\r' || *f == ','))
          ++f;
        if (f < p) return -1;  // extra columns
        ++row;
      }
      line = p + 1;
    }
  }
  return 0;
}

int64_t detect_cols(const char* b, const char* e) {
  const char* line = b;
  for (const char* p = b; p <= e; ++p) {
    if (p == e || *p == '\n') {
      if (is_data_line(line, p)) {
        int64_t cols = 1;
        bool in_field = false;
        for (const char* q = line; q < p; ++q) {
          if (*q == ',' || *q == ';') ++cols;
          (void)in_field;
        }
        return cols;
      }
      line = p + 1;
    }
  }
  return 0;
}

}  // namespace

extern "C" {

int gpdata_num_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n ? static_cast<int>(n) : 1;
}

void gpdata_free(double* buf) { std::free(buf); }

// Returns 0 on success; negative error codes:
//   -1 open/stat failed, -2 mmap failed, -3 empty/no data rows,
//   -4 allocation failed, -5 parse error (malformed field or ragged row).
int gpdata_read_csv(const char* path, int64_t skip_rows, double** out,
                    int64_t* out_rows, int64_t* out_cols) {
  *out = nullptr;
  *out_rows = 0;
  *out_cols = 0;

  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    ::close(fd);
    return st.st_size == 0 ? -3 : -1;
  }
  size_t size = static_cast<size_t>(st.st_size);
  void* map = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);
  if (map == MAP_FAILED) return -2;
  const char* data = static_cast<const char*>(map);
  const char* end = data + size;

  // Skip header rows (counting every line, data or not, like numpy skiprows).
  const char* begin = data;
  for (int64_t skipped = 0; skipped < skip_rows && begin < end; ++skipped) {
    const char* nl = static_cast<const char*>(
        memchr(begin, '\n', static_cast<size_t>(end - begin)));
    begin = nl ? nl + 1 : end;
  }

  int64_t cols = detect_cols(begin, end);
  if (cols <= 0) {
    munmap(map, size);
    return -3;
  }

  // Carve spans at newline boundaries, one per thread.
  int nt = gpdata_num_threads();
  int64_t bytes = end - begin;
  if (bytes < (1 << 16)) nt = 1;  // parsing overhead beats threading
  std::vector<Span> spans;
  spans.reserve(nt);
  const char* cursor = begin;
  for (int t = 0; t < nt && cursor < end; ++t) {
    const char* stop =
        (t == nt - 1) ? end : begin + (bytes * (t + 1)) / nt;
    if (stop < end) {
      const char* nl = static_cast<const char*>(
          memchr(stop, '\n', static_cast<size_t>(end - stop)));
      stop = nl ? nl + 1 : end;
    }
    spans.push_back(Span{cursor, stop});
    cursor = stop;
  }

  // Pass 1: count rows per span.
  {
    std::vector<std::thread> workers;
    for (auto& s : spans)
      workers.emplace_back([&s] { s.rows = count_rows(s.begin, s.end); });
    for (auto& w : workers) w.join();
  }
  int64_t total = 0;
  for (auto& s : spans) {
    s.row_base = total;
    total += s.rows;
  }
  if (total == 0) {
    munmap(map, size);
    return -3;
  }

  double* buf = static_cast<double*>(
      std::malloc(static_cast<size_t>(total) * cols * sizeof(double)));
  if (!buf) {
    munmap(map, size);
    return -4;
  }

  // Pass 2: parse in place, no synchronization (disjoint output ranges).
  std::vector<int> status(spans.size(), 0);
  {
    std::vector<std::thread> workers;
    for (size_t i = 0; i < spans.size(); ++i)
      workers.emplace_back([&, i] { status[i] = parse_span(spans[i], cols, buf); });
    for (auto& w : workers) w.join();
  }
  munmap(map, size);
  for (int s : status) {
    if (s != 0) {
      std::free(buf);
      return -5;
    }
  }

  *out = buf;
  *out_rows = total;
  *out_cols = cols;
  return 0;
}

// In-place column-wise standardization; std==0 columns clamped to 1
// (commons/util/Scaling.scala:18).
void gpdata_zscore(double* data, int64_t rows, int64_t cols) {
  if (rows <= 0 || cols <= 0) return;
  std::vector<double> mean(cols, 0.0), m2(cols, 0.0);
  // Column statistics: single pass, compensated enough for feature scaling
  // (two-pass mean/variance like Scaling.scala:13-16).
  for (int64_t c = 0; c < cols; ++c) {
    double s = 0.0;
    for (int64_t r = 0; r < rows; ++r) s += data[r * cols + c];
    mean[c] = s / rows;
  }
  for (int64_t c = 0; c < cols; ++c) {
    double s = 0.0;
    for (int64_t r = 0; r < rows; ++r) {
      double d = data[r * cols + c] - mean[c];
      s += d * d;
    }
    double var = s / rows;
    m2[c] = var > 0.0 ? std::sqrt(var) : 1.0;
  }
  int nt = gpdata_num_threads();
  std::vector<std::thread> workers;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = rows * t / nt, hi = rows * (t + 1) / nt;
    workers.emplace_back([&, lo, hi] {
      for (int64_t r = lo; r < hi; ++r)
        for (int64_t c = 0; c < cols; ++c)
          data[r * cols + c] = (data[r * cols + c] - mean[c]) / m2[c];
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
