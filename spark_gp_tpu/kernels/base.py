"""Functional covariance-kernel algebra.

The reference models kernels as *mutable objects* carrying hyperparameters and
training vectors set in place (kernel/Kernel.scala:12-98), with hand-derived
``trainingKernelAndDerivative`` methods per kernel.  That design cannot work
under JAX tracing and would forfeit autodiff.  Here a kernel is an immutable
*spec*:

* hyperparameters live in one flat vector ``theta`` (the exact layout the
  reference's L-BFGS-B consumes: composite kernels concatenate children,
  trainable scalars prepend their coefficient — SumOfKernels.scala:19-26,
  ScalarTimesKernel.scala:78-84);
* ``gram`` / ``cross`` / ``diag`` / ``self_diag`` are pure functions of
  ``(theta, X)``, safe under ``jit``, ``vmap``, ``shard_map`` and ``grad``;
  their heavy contractions route through :mod:`spark_gp_tpu.ops.distance`,
  which selects the MXU precision from the framework-wide lane policy
  (:mod:`spark_gp_tpu.ops.precision`) — kernel code never pins a raw
  ``lax.Precision`` (enforced by ``tools/check_precision_pins.py``);
* derivatives w.r.t. ``theta`` come from autodiff — there is no analogue of
  ``trainingKernelAndDerivative``'s hand algebra to maintain (the reference's
  finite-difference kernel tests are kept as oracles in ``tests/``);
* the **theta-invariant precompute plane**: kernels whose Gram matrix
  factors through a theta-independent structure (the squared-distance
  block for isotropic RBF/Matérn/RationalQuadratic, the raw inner-product
  matrix for DotProduct/Polynomial) declare ``prepare(x) -> cache`` and
  ``gram_from_cache(theta, cache)``.  Fit drivers build the cache ONCE
  per fit (outside the differentiated objective, under the gram-stage
  precision lane) and pass it as a traced operand into the hot loop, so
  every L-BFGS evaluation pays elementwise ``exp`` + Cholesky instead of
  re-running the O(s^2 p) MXU distance contraction ~40+ times per fit —
  the reference's precompute-and-carry design (RBFKernel.scala:37-48)
  recovered functionally.  ``prepare`` composes structurally through the
  Sum/Product/scale/override algebra; ARD kernels (theta-dependent
  weighted distances) and custom kernels without an invariant keep
  ``prepare = None`` and ride today's recompute path unchanged.

The composition DSL mirrors the reference's
(``1 * k1 + 0.5.const * k2``, kernel/package.scala:3-9):

>>> k = 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1)
>>> k = Scalar(1.0).between(0).and_(30) * ARDRBFKernel(5)
>>> k = Const(1.0) * EyeKernel()
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Kernel:
    """Covariance-function spec.  Immutable; all compute methods are pure.

    Contract (the functional analogue of kernel/Kernel.scala:12-98):

    * ``n_hypers`` — number of trainable hyperparameters.
    * ``init_theta()`` — initial hyperparameter vector, shape ``[n_hypers]``.
    * ``bounds()`` — elementwise box ``(lower, upper)`` for L-BFGS-B.
    * ``gram(theta, x)`` — ``[n, n]`` training kernel matrix.
    * ``cross(theta, x_test, x_train)`` — ``[t, n]`` cross kernel.
    * ``diag(theta, x)`` — ``[n]`` diagonal of ``gram`` (cheaper than gram).
    * ``self_diag(theta, x)`` — ``[t]`` of ``k(x_i, x_i)`` for *test* points
      (the batched ``selfKernel``, kernel/Kernel.scala:91).
    * ``white_noise_var(theta)`` — scalar white-noise variance presumed by
      the kernel (kernel/Kernel.scala:97); may depend on ``theta`` when a
      trainable scalar scales an ``EyeKernel``.
    * ``describe(theta)`` — human-readable form for instrumentation logs.
    * ``prepare(x) -> cache`` / ``gram_from_cache(theta, cache)`` — the
      OPTIONAL theta-invariant precompute hooks (module docstring).
      ``prepare`` is ``None`` (the class default) when the kernel has no
      invariant; when defined, ``gram_from_cache(theta, prepare(x))``
      must equal ``gram(theta, x)`` to float rounding for every theta —
      tested for all shipped kernels in tests/test_gram_cache.py.
    """

    n_hypers: int = 0

    #: Theta-invariant precompute hook.  ``None`` means "no invariant";
    #: kernels with one override this as a method.  Composites null it out
    #: per-instance (``self.prepare = None``) when any child lacks it, so
    #: ``kernel.prepare is None`` is THE capability test everywhere.
    prepare = None

    #: Matrix-free streaming hooks (the matfree solver lane,
    #: ops/pallas_matvec.py).  ``prepare_matvec(x)`` returns the SKINNY
    #: theta-invariant streaming operand — for every shipped family the
    #: ``[s, p]`` row stack itself (NOT the ``prepare()`` cache: that
    #: cache is the O(s²) distance block the matfree lane exists to never
    #: build).  ``matvec_from_prepared(theta, mcache, v, **kw)`` computes
    #: ``gram(theta, x) @ v`` by streaming row tiles (``**kw`` threads
    #: ``differentiable``/``tile``/``interpret`` through to
    #: ``ops.pallas_matvec.streamed_matvec``) and must match the
    #: materialized gram action to float rounding.  ``None`` (the class
    #: default) means "cannot stream" and keeps the materialized path
    #: bit-for-bit; composites null both per-instance when any child
    #: lacks them, so ``kernel.matvec_from_prepared is None`` is the one
    #: capability test (``supports_matfree``).
    prepare_matvec = None
    matvec_from_prepared = None

    def _spec(self) -> tuple:
        """Hashable identity of this kernel spec.  Kernels are immutable, so
        (type, spec) equality lets them be ``static_argnums`` of module-level
        ``jax.jit`` functions — compiled executables are then shared across
        estimator instances and repeated fits."""
        return ()

    def __hash__(self) -> int:
        return hash((type(self), self._spec()))

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._spec() == other._spec()

    def init_theta(self) -> np.ndarray:
        return np.zeros((0,), dtype=np.float64)

    def bounds(self) -> Tuple[np.ndarray, np.ndarray]:
        zero = np.zeros((0,), dtype=np.float64)
        return zero, zero

    def gram(self, theta: jax.Array, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def cross(self, theta: jax.Array, x_test: jax.Array, x_train: jax.Array) -> jax.Array:
        raise NotImplementedError

    def diag(self, theta: jax.Array, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def self_diag(self, theta: jax.Array, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def gram_from_cache(self, theta: jax.Array, cache) -> jax.Array:
        raise NotImplementedError(
            f"{type(self).__name__} declares no theta-invariant structure "
            "(prepare is None); callers must check kernel.prepare before "
            "taking the cached gram path"
        )

    def white_noise_var(self, theta: jax.Array) -> jax.Array:
        return jnp.zeros((), dtype=theta.dtype if hasattr(theta, "dtype") else jnp.float32)

    def describe(self, theta) -> str:
        return type(self).__name__

    # --- composition DSL -------------------------------------------------
    def __add__(self, other: "Kernel") -> "SumKernel":
        return SumKernel(self, other)

    def __rmul__(self, coeff: float) -> "Kernel":
        """``c * kernel`` makes the coefficient *trainable* in ``[0, inf)``,
        matching the reference's implicit ``toScalar`` (kernel/package.scala:4)."""
        return Scalar(float(coeff)) * self

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return Scalar(float(other)) * self
        if isinstance(other, Kernel):
            return ProductKernel(self, other)
        return NotImplemented


class StationaryKernel(Kernel):
    """Base for unit-variance stationary kernels: ``k(x, x) = 1``."""

    def diag(self, theta, x):
        return jnp.ones(x.shape[0], dtype=x.dtype)

    def self_diag(self, theta, x):
        return jnp.ones(x.shape[0], dtype=x.dtype)


class ScalarLengthscaleHypers(StationaryKernel):
    """Shared hyperparameter plumbing for stationary kernels with one
    trainable length-scale ``sigma`` bounded in ``[lower, upper]`` (the
    RBF/Matérn isotropic families)."""

    n_hypers = 1

    def __init__(self, sigma: float = 1.0, lower: float = 1e-6,
                 upper: float = math.inf):
        self.sigma0 = float(sigma)
        self.lower = float(lower)
        self.upper = float(upper)

    def _spec(self) -> tuple:
        return (self.sigma0, self.lower, self.upper)

    def init_theta(self):
        return np.array([self.sigma0], dtype=np.float64)

    def bounds(self):
        return (
            np.array([self.lower], dtype=np.float64),
            np.array([self.upper], dtype=np.float64),
        )


class ARDHypers(StationaryKernel):
    """Shared hyperparameter plumbing for ARD kernels: one trainable inverse
    length-scale ``beta`` per feature dimension (beta multiplies, the
    reference's ARDRBFKernel.scala:8-15 convention).  Construct with either
    a dimension count (uniform ``beta`` init) or an explicit beta vector."""

    def __init__(self, p_or_beta, beta: float = 1.0, lower=0.0,
                 upper=math.inf):
        if isinstance(p_or_beta, (int, np.integer)):
            beta0 = np.full((int(p_or_beta),), float(beta), dtype=np.float64)
        else:
            beta0 = np.asarray(p_or_beta, dtype=np.float64)
        self.beta0 = beta0
        self.n_hypers = beta0.shape[0]
        self.lower_b = np.broadcast_to(
            np.asarray(lower, dtype=np.float64), beta0.shape
        ).copy()
        self.upper_b = np.broadcast_to(
            np.asarray(upper, dtype=np.float64), beta0.shape
        ).copy()

    def _spec(self) -> tuple:
        return (
            tuple(self.beta0.tolist()),
            tuple(self.lower_b.tolist()),
            tuple(self.upper_b.tolist()),
        )

    def init_theta(self):
        return self.beta0.copy()

    def bounds(self):
        return self.lower_b, self.upper_b


class EyeKernel(Kernel):
    """Identity-matrix kernel: ``K = I`` on training points, 0 across sets,
    unit white-noise variance (kernel/Kernel.scala:142-163)."""

    n_hypers = 0

    def gram(self, theta, x):
        return jnp.eye(x.shape[0], dtype=x.dtype)

    def cross(self, theta, x_test, x_train):
        return jnp.zeros((x_test.shape[0], x_train.shape[0]), dtype=x_train.dtype)

    def diag(self, theta, x):
        return jnp.ones(x.shape[0], dtype=x.dtype)

    def self_diag(self, theta, x):
        # selfKernel(test) = 1 in the reference (kernel/Kernel.scala:161) —
        # the white-noise variance applies to any single point.
        return jnp.ones(x.shape[0], dtype=x.dtype)

    def white_noise_var(self, theta):
        return jnp.asarray(1.0)

    def prepare(self, x):
        # zero-byte shape/dtype carrier: the identity gram needs only n,
        # but the cache protocol transports arrays — a [n, 0] view costs
        # nothing and keeps the Eye ridge composable under vmap
        return jnp.zeros((x.shape[0], 0), dtype=x.dtype)

    def gram_from_cache(self, theta, cache):
        return jnp.eye(cache.shape[0], dtype=cache.dtype)

    def prepare_matvec(self, x):
        # same zero-byte carrier trick as prepare(): the identity matvec
        # needs nothing, but the protocol transports arrays under vmap
        return jnp.zeros((x.shape[0], 0), dtype=x.dtype)

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        return v

    def describe(self, theta) -> str:
        return "I"


class ThetaOverrideKernel(Kernel):
    """The same kernel spec started from a different hyperparameter point.

    Delegates every computation to the wrapped spec and overrides only
    ``init_theta`` — the mechanism behind multi-start hyperparameter
    optimization (``setNumRestarts``): restart r wraps the user's kernel
    with a perturbed starting point, so every fit path (host, device,
    sharded) works unchanged.

    The starting point is deliberately EXCLUDED from the jit-static
    identity (``_spec``): no traced computation reads ``init_theta`` —
    theta is always threaded as a dynamic argument — so wrappers around
    the same inner kernel share one compiled executable per program
    instead of recompiling every restart.  Consequence: two wrappers with
    different starting points compare equal; the override only matters on
    the host, where it is read directly.
    """

    def __init__(self, inner: Kernel, theta0) -> None:
        self.inner = inner
        self.theta0_ = tuple(float(v) for v in np.asarray(theta0).ravel())
        if len(self.theta0_) != inner.n_hypers:
            raise ValueError(
                f"theta0 has {len(self.theta0_)} entries; kernel has "
                f"{inner.n_hypers} hyperparameters"
            )
        self.n_hypers = inner.n_hypers
        if inner.prepare is None:
            self.prepare = None
        if inner.matvec_from_prepared is None:
            self.prepare_matvec = None
            self.matvec_from_prepared = None

    def _spec(self) -> tuple:
        return (self.inner,)

    def init_theta(self):
        return np.array(self.theta0_, dtype=np.float64)

    def bounds(self):
        return self.inner.bounds()

    def gram(self, theta, x):
        return self.inner.gram(theta, x)

    def cross(self, theta, x_test, x_train):
        return self.inner.cross(theta, x_test, x_train)

    def diag(self, theta, x):
        return self.inner.diag(theta, x)

    def self_diag(self, theta, x):
        return self.inner.self_diag(theta, x)

    def prepare(self, x):
        # theta0 plays no part: the cache is theta-invariant by contract,
        # so every restart's wrapper shares ONE cache with the base kernel
        return self.inner.prepare(x)

    def gram_from_cache(self, theta, cache):
        return self.inner.gram_from_cache(theta, cache)

    def prepare_matvec(self, x):
        return self.inner.prepare_matvec(x)

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        return self.inner.matvec_from_prepared(theta, mcache, v, **kw)

    def white_noise_var(self, theta):
        return self.inner.white_noise_var(theta)

    def describe(self, theta) -> str:
        return self.inner.describe(theta)


class _PairKernel(Kernel):
    """Shared composite plumbing for binary kernel combinations: children's
    hyperparameter vectors concatenate (``k1`` first), bounds likewise, and
    the (type, child-specs) pair is the jit-static identity."""

    def __init__(self, k1: Kernel, k2: Kernel) -> None:
        self.k1 = k1
        self.k2 = k2
        self.n_hypers = k1.n_hypers + k2.n_hypers
        if k1.prepare is None or k2.prepare is None:
            # the composite's cache is the tuple of child caches, so it
            # only exists when BOTH children carry an invariant
            self.prepare = None
        if (
            k1.matvec_from_prepared is None
            or k2.matvec_from_prepared is None
        ):
            # streaming composes the same way: both children or neither
            self.prepare_matvec = None
            self.matvec_from_prepared = None

    def _spec(self) -> tuple:
        return (self.k1, self.k2)

    def prepare(self, x):
        return (self.k1.prepare(x), self.k2.prepare(x))

    def _split(self, theta):
        return theta[: self.k1.n_hypers], theta[self.k1.n_hypers :]

    def init_theta(self):
        return np.concatenate([self.k1.init_theta(), self.k2.init_theta()])

    def bounds(self):
        lo1, hi1 = self.k1.bounds()
        lo2, hi2 = self.k2.bounds()
        return np.concatenate([lo1, lo2]), np.concatenate([hi1, hi2])


def _carries_white_noise(kernel: Kernel) -> bool:
    """Structurally, can this kernel spec EVER contribute a white-noise
    ridge?  Walks the composition tree for ``EyeKernel`` instead of
    evaluating ``white_noise_var`` at one theta: a trainable noise factor
    *initialized at zero* (``WhiteNoiseKernel(0.0, 0.0, 1.0)``) evaluates
    to 0 at ``init_theta`` yet can train to a nonzero ridge, so a numeric
    probe at a single point under-rejects.  A ``Const(0) * ...`` branch is
    genuinely inert (non-trainable zero) and passes."""
    if isinstance(kernel, EyeKernel):
        return True
    if isinstance(kernel, _PairKernel):
        return _carries_white_noise(kernel.k1) or _carries_white_noise(kernel.k2)
    if isinstance(kernel, TrainableScaleKernel):
        return _carries_white_noise(kernel.kernel)
    if isinstance(kernel, ConstScaleKernel):
        return kernel.c != 0.0 and _carries_white_noise(kernel.kernel)
    if isinstance(kernel, ThetaOverrideKernel):
        return _carries_white_noise(kernel.inner)
    # custom kernel specs: numeric fallback at the initial point
    return (
        float(
            np.asarray(kernel.white_noise_var(jnp.asarray(kernel.init_theta())))
        )
        != 0.0
    )


class ProductKernel(_PairKernel):
    """``k1 * k2`` — elementwise (Schur) product of two kernels, PSD by the
    Schur product theorem.  Capability beyond the reference (its algebra
    stops at Sum + scalar scaling, kernel/package.scala:3-9); the canonical
    use is quasi-periodic structure, ``RBFKernel(..) * PeriodicKernel(..)``.

    ``white_noise_var`` is 0, and factors carrying white noise are rejected
    at construction: the delta-ridge part of a product involves cross terms
    between one factor's continuous part at zero distance and the other's
    ridge, which the flat-scalar accounting cannot represent — add noise at
    the top level (``k1 * k2 + WhiteNoiseKernel(...)``) instead.  The check
    is structural (:func:`_carries_white_noise`), so a noise term that is
    zero at ``init_theta`` but trainable to a nonzero ridge is rejected too.
    """

    def __init__(self, k1: Kernel, k2: Kernel) -> None:
        super().__init__(k1, k2)
        for factor in (k1, k2):
            if _carries_white_noise(factor):
                raise ValueError(
                    "kernel products cannot contain white-noise factors "
                    "(the product's delta ridge is not representable as a "
                    "flat white_noise_var); add the noise at the top "
                    "level: k1 * k2 + WhiteNoiseKernel(...)"
                )

    def gram(self, theta, x):
        t1, t2 = self._split(theta)
        return self.k1.gram(t1, x) * self.k2.gram(t2, x)

    def gram_from_cache(self, theta, cache):
        t1, t2 = self._split(theta)
        c1, c2 = cache
        return self.k1.gram_from_cache(t1, c1) * self.k2.gram_from_cache(
            t2, c2
        )

    def cross(self, theta, x_test, x_train):
        t1, t2 = self._split(theta)
        return self.k1.cross(t1, x_test, x_train) * self.k2.cross(
            t2, x_test, x_train
        )

    def diag(self, theta, x):
        t1, t2 = self._split(theta)
        return self.k1.diag(t1, x) * self.k2.diag(t2, x)

    def self_diag(self, theta, x):
        t1, t2 = self._split(theta)
        return self.k1.self_diag(t1, x) * self.k2.self_diag(t2, x)

    def describe(self, theta) -> str:
        t1, t2 = self._split(np.asarray(theta))
        return f"({self.k1.describe(t1)}) * ({self.k2.describe(t2)})"


class SumKernel(_PairKernel):
    """``k1 + k2`` with concatenated hyperparameter vectors
    (SumOfKernels.scala:15-65).  Children share no hyperparameters."""

    def gram(self, theta, x):
        t1, t2 = self._split(theta)
        return self.k1.gram(t1, x) + self.k2.gram(t2, x)

    def gram_from_cache(self, theta, cache):
        t1, t2 = self._split(theta)
        c1, c2 = cache
        return self.k1.gram_from_cache(t1, c1) + self.k2.gram_from_cache(
            t2, c2
        )

    def prepare_matvec(self, x):
        return (self.k1.prepare_matvec(x), self.k2.prepare_matvec(x))

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        # (K1 + K2) v = K1 v + K2 v — two streamed passes, still no gram
        t1, t2 = self._split(theta)
        c1, c2 = mcache
        return self.k1.matvec_from_prepared(
            t1, c1, v, **kw
        ) + self.k2.matvec_from_prepared(t2, c2, v, **kw)

    def cross(self, theta, x_test, x_train):
        t1, t2 = self._split(theta)
        return self.k1.cross(t1, x_test, x_train) + self.k2.cross(t2, x_test, x_train)

    def diag(self, theta, x):
        t1, t2 = self._split(theta)
        return self.k1.diag(t1, x) + self.k2.diag(t2, x)

    def self_diag(self, theta, x):
        t1, t2 = self._split(theta)
        return self.k1.self_diag(t1, x) + self.k2.self_diag(t2, x)

    def white_noise_var(self, theta):
        t1, t2 = self._split(theta)
        return self.k1.white_noise_var(t1) + self.k2.white_noise_var(t2)

    def describe(self, theta) -> str:
        t1, t2 = np.asarray(theta)[: self.k1.n_hypers], np.asarray(theta)[self.k1.n_hypers :]
        parts = [self.k1.describe(t1), self.k2.describe(t2)]
        return " + ".join(p for p in parts if p)


class TrainableScaleKernel(Kernel):
    """``C * k`` with trainable ``C`` prepended to the hyperparameter vector
    (ScalarTimesKernel.scala:71-98)."""

    def __init__(self, kernel: Kernel, c: float, lower: float = 0.0, upper: float = math.inf):
        if c < 0:
            raise ValueError("C should be non-negative")
        self.kernel = kernel
        self.c0 = float(c)
        self.lower = float(lower)
        self.upper = float(upper)
        self.n_hypers = 1 + kernel.n_hypers
        if kernel.prepare is None:
            self.prepare = None
        if kernel.matvec_from_prepared is None:
            self.prepare_matvec = None
            self.matvec_from_prepared = None

    def _spec(self) -> tuple:
        return (self.kernel, self.c0, self.lower, self.upper)

    def init_theta(self):
        return np.concatenate([[self.c0], self.kernel.init_theta()])

    def bounds(self):
        lo, hi = self.kernel.bounds()
        return (
            np.concatenate([[self.lower], lo]),
            np.concatenate([[self.upper], hi]),
        )

    def gram(self, theta, x):
        return theta[0] * self.kernel.gram(theta[1:], x)

    def prepare(self, x):
        return self.kernel.prepare(x)

    def gram_from_cache(self, theta, cache):
        return theta[0] * self.kernel.gram_from_cache(theta[1:], cache)

    def prepare_matvec(self, x):
        return self.kernel.prepare_matvec(x)

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        # (C K) v = C (K v): the scale rides outside the stream
        return theta[0] * self.kernel.matvec_from_prepared(
            theta[1:], mcache, v, **kw
        )

    def cross(self, theta, x_test, x_train):
        return theta[0] * self.kernel.cross(theta[1:], x_test, x_train)

    def diag(self, theta, x):
        return theta[0] * self.kernel.diag(theta[1:], x)

    def self_diag(self, theta, x):
        return theta[0] * self.kernel.self_diag(theta[1:], x)

    def white_noise_var(self, theta):
        return theta[0] * self.kernel.white_noise_var(theta[1:])

    def describe(self, theta) -> str:
        t = np.asarray(theta)
        return f"{float(t[0]):.1e} * {self.kernel.describe(t[1:])}"


class ConstScaleKernel(Kernel):
    """``C * k`` with a fixed, non-trainable ``C``
    (ScalarTimesKernel.scala:41-59)."""

    def __init__(self, kernel: Kernel, c: float):
        if c < 0:
            raise ValueError("C should be non-negative")
        self.kernel = kernel
        self.c = float(c)
        self.n_hypers = kernel.n_hypers
        if kernel.prepare is None:
            self.prepare = None
        if kernel.matvec_from_prepared is None:
            self.prepare_matvec = None
            self.matvec_from_prepared = None

    def _spec(self) -> tuple:
        return (self.kernel, self.c)

    def init_theta(self):
        return self.kernel.init_theta()

    def bounds(self):
        return self.kernel.bounds()

    def gram(self, theta, x):
        return self.c * self.kernel.gram(theta, x)

    def prepare(self, x):
        return self.kernel.prepare(x)

    def gram_from_cache(self, theta, cache):
        return self.c * self.kernel.gram_from_cache(theta, cache)

    def prepare_matvec(self, x):
        return self.kernel.prepare_matvec(x)

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        return self.c * self.kernel.matvec_from_prepared(
            theta, mcache, v, **kw
        )

    def cross(self, theta, x_test, x_train):
        return self.c * self.kernel.cross(theta, x_test, x_train)

    def diag(self, theta, x):
        return self.c * self.kernel.diag(theta, x)

    def self_diag(self, theta, x):
        return self.c * self.kernel.self_diag(theta, x)

    def white_noise_var(self, theta):
        return self.c * self.kernel.white_noise_var(theta)

    def describe(self, theta) -> str:
        if self.c == 0:
            return ""
        return f"{self.c:.1e} * {self.kernel.describe(np.asarray(theta))}"


class Scalar:
    """Scalar-coefficient builder mirroring the reference DSL
    (ScalarTimesKernel.scala:100-141):

    >>> Scalar(1.0) * k                      # trainable in [0, inf)
    >>> Scalar(1.0).between(0).and_(30) * k  # trainable in [0, 30]
    >>> Scalar(1.0).below(10) * k            # trainable in [0, 10]
    >>> Scalar(1.0).const * k                # fixed
    """

    def __init__(self, c: float, lower: float = 0.0, upper: float = math.inf, trainable: bool = True):
        if trainable and not lower < upper:
            raise ValueError(
                "The scalar should either have its lower limit below its upper "
                "limit or not be trainable"
            )
        self.c = float(c)
        self.lower = lower
        self.upper = upper
        self.trainable = trainable

    def __mul__(self, kernel: Kernel) -> Kernel:
        if self.trainable:
            return TrainableScaleKernel(kernel, self.c, self.lower, self.upper)
        return ConstScaleKernel(kernel, self.c)

    def between(self, lower: float) -> "_Between":
        return _Between(self.c, lower, self.trainable)

    def below(self, upper: float) -> "Scalar":
        return Scalar(self.c, self.lower, upper, self.trainable)

    @property
    def const(self) -> "Scalar":
        return Scalar(self.c, self.c, self.c, trainable=False)


class _Between:
    def __init__(self, c: float, lower: float, trainable: bool):
        self._c = c
        self._lower = lower
        self._trainable = trainable

    def and_(self, upper: float) -> Scalar:
        return Scalar(self._c, self._lower, upper, self._trainable)


def Const(c: float) -> Scalar:
    """``Const(0.5) * k`` — a fixed scalar times a kernel (``0.5.const * k``)."""
    return Scalar(c).const


def WhiteNoiseKernel(initial: float, lower: float, upper: float) -> Kernel:
    """Trainable white noise: ``(initial between lower and upper) * EyeKernel``
    (kernel/Kernel.scala:166-169)."""
    return Scalar(initial, lower, upper) * EyeKernel()


# --- theta-invariant precompute plane (module docstring) ------------------


def gram_cache_enabled() -> bool:
    """The process-wide kill switch: ``GP_GRAM_CACHE=0`` disables the
    precompute plane everywhere (every fit then recomputes the distance
    stack per evaluation — today's pre-cache behavior).  Read on the host
    at cache-build time, so toggling between fits needs no retrace of the
    fit programs: the cache operand's pytree structure is part of the jit
    key and each setting selects its own compiled path.  The bench's
    ``fit_hot_loop`` section uses exactly this knob for its uncached leg."""
    import os

    return os.environ.get("GP_GRAM_CACHE", "1") != "0"


def supports_gram_cache(kernel: Kernel) -> bool:
    """True when ``kernel`` declares a theta-invariant structure AND the
    process knob has not disabled the plane."""
    return kernel.prepare is not None and gram_cache_enabled()


def supports_matfree(kernel: Kernel) -> bool:
    """True when ``kernel`` can run the matrix-free solver lane: it (and,
    for composites, every child) implements the streaming
    ``matvec_from_prepared`` protocol.  ``False`` — ARD metrics, custom
    kernels, products — keeps the materialized path bit-for-bit."""
    return kernel.matvec_from_prepared is not None


@functools.partial(jax.jit, static_argnums=0, static_argnames=("lane",))
def _prepare_stack_impl(kernel: Kernel, x, *, lane=None):
    from spark_gp_tpu.ops.precision import precision_lane_scope

    with precision_lane_scope(lane):
        return jax.vmap(kernel.prepare)(x)


def prepare_gram_cache(kernel: Kernel, x, lane=None):
    """Per-expert theta-invariant cache for an ``[E, s, p]`` expert stack,
    or ``None`` when the kernel has no invariant (``prepare is None``) or
    the plane is disabled (``GP_GRAM_CACHE=0``).

    Built as ONE jitted vmapped program under the gram-stage precision
    lane (``lane=None`` resolves the ambient lane at call time, like the
    fit entry points of models/likelihood.py) — so the compensated bf16
    build of the ``mixed`` lane is paid once per fit instead of once per
    L-BFGS evaluation, and the cached distances are bit-identical to what
    the per-eval rebuild would have produced at the same lane.
    """
    if not supports_gram_cache(kernel):
        return None
    from spark_gp_tpu.ops.precision import active_lane

    return _prepare_stack_impl(
        kernel, x, lane=active_lane() if lane is None else lane
    )


def masked_gram_stack(kernel: Kernel, theta, x, mask, cache=None):
    """``[E, s, s]`` stack of masked per-expert Gram matrices — THE gram
    build of every fit objective (marginal NLL, LOO, the Laplace families).

    ``cache=None`` (the fallback/compatibility path) evaluates
    ``kernel.gram`` on the raw rows exactly as before; a cache from
    :func:`prepare_gram_cache` routes through ``gram_from_cache``, so the
    differentiated objective never touches the distance contraction — per
    evaluation only the elementwise theta-map (exp for RBF) and the
    masking remain, and autodiff's backward pass shrinks accordingly.
    One home so the lint-style unit test (tests/test_gram_cache.py) can
    assert no fit objective calls ``kernel.gram`` when a cache is live.
    """
    from spark_gp_tpu.ops.linalg import masked_kernel_matrix

    if cache is None:
        return jax.vmap(
            lambda x_e, m_e: masked_kernel_matrix(kernel.gram(theta, x_e), m_e)
        )(x, mask)
    return jax.vmap(
        lambda c_e, m_e: masked_kernel_matrix(
            kernel.gram_from_cache(theta, c_e), m_e
        )
    )(cache, mask)
