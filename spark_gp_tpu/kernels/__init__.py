"""Covariance kernels and the composition DSL.

Functional re-design of the reference's ``commons/kernel/`` package — see
``base.py`` for the contract and the deliberate departures from the mutable
object design.
"""

from spark_gp_tpu.kernels.base import (
    Const,
    ConstScaleKernel,
    EyeKernel,
    Kernel,
    ProductKernel,
    Scalar,
    StationaryKernel,
    SumKernel,
    TrainableScaleKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.kernels.families import (
    ARDRationalQuadraticKernel,
    DotProductKernel,
    PeriodicKernel,
    PolynomialKernel,
    RationalQuadraticKernel,
    SpectralMixtureKernel,
)
from spark_gp_tpu.kernels.matern import (
    ARDMatern32Kernel,
    ARDMatern52Kernel,
    Matern12Kernel,
    Matern32Kernel,
    Matern52Kernel,
)
from spark_gp_tpu.kernels.rbf import ARDRBFKernel, RBFKernel

__all__ = [
    "Kernel",
    "StationaryKernel",
    "EyeKernel",
    "SumKernel",
    "ProductKernel",
    "TrainableScaleKernel",
    "ConstScaleKernel",
    "Scalar",
    "Const",
    "WhiteNoiseKernel",
    "RBFKernel",
    "ARDRBFKernel",
    "Matern12Kernel",
    "Matern32Kernel",
    "Matern52Kernel",
    "ARDMatern32Kernel",
    "ARDMatern52Kernel",
    "RationalQuadraticKernel",
    "ARDRationalQuadraticKernel",
    "PeriodicKernel",
    "DotProductKernel",
    "PolynomialKernel",
    "SpectralMixtureKernel",
]
