"""Matérn kernel family (ν = 1/2, 3/2, 5/2), isotropic and ARD.

Capability beyond the reference (akopich/spark-gp ships only RBF/ARD-RBF,
kernel/RBFKernel.scala / ARDRBFKernel.scala): the Matérn family is the
standard choice for physical processes whose sample paths are rougher than
the RBF's C-infinity draws — ν = 1/2 gives the exponential (OU) kernel,
3/2 and 5/2 once/twice-differentiable paths.

With r = |x_i - x_j| and length-scale ``sigma`` (same parameter convention
as :class:`~spark_gp_tpu.kernels.rbf.RBFKernel`):

    nu = 1/2:  k = exp(-r / sigma)
    nu = 3/2:  k = (1 + a) exp(-a),            a = sqrt(3) r / sigma
    nu = 5/2:  k = (1 + a + a^2 / 3) exp(-a),  a = sqrt(5) r / sigma

ARD variants follow the repo's ARD-RBF convention (beta multiplies:
r^2 = |(x_i - x_j) * beta|^2, one trainable inverse length-scale per
dimension, ARDRBFKernel.scala:8-15).

Autodiff note: ARD puts hyperparameters inside the sqrt, whose derivative
is 0/0 at coincident points; ``jnp.maximum(r2, eps)`` routes the gradient
through the constant branch there (exactly the true zero derivative) while
perturbing diagonal values by < 1e-12.  Gradients are FD-checked in
tests/test_kernels.py like every other kernel.
"""

from __future__ import annotations

import functools
import math

import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import ARDHypers, ScalarLengthscaleHypers
from spark_gp_tpu.ops.distance import (
    sq_dist,
    sq_dist_self,
    weighted_sq_dist,
    weighted_sq_dist_self,
)
from spark_gp_tpu.ops.pallas_matvec import (
    register_tile_transform,
    streamed_matvec,
)

_R2_FLOOR = 1e-24  # sqrt grad guard; sqrt(floor) = 1e-12 off the true diag


def _matern_of_a(nu2: int, a):
    """Matérn correlation as a function of the scaled distance a."""
    if nu2 == 1:
        return jnp.exp(-a)
    if nu2 == 3:
        return (1.0 + a) * jnp.exp(-a)
    if nu2 == 5:
        return (1.0 + a + a * a / 3.0) * jnp.exp(-a)
    raise ValueError(f"unsupported 2*nu = {nu2}")


def _safe_r(r2):
    return jnp.sqrt(jnp.maximum(r2, _R2_FLOOR))


def _matern_tile(nu2: int, theta, sqd):
    """The Matérn elementwise map — shared by gram / gram_from_cache /
    cross and the matfree lane's streamed tiles."""
    a = math.sqrt(nu2) * _safe_r(sqd) / theta[0]
    return _matern_of_a(nu2, a)


for _nu2 in (1, 3, 5):
    register_tile_transform(f"matern{_nu2}2")(
        functools.partial(_matern_tile, _nu2)
    )


class _MaternIso(ScalarLengthscaleHypers):
    """One trainable length-scale ``sigma`` in ``[lower, upper]``.  The
    subclass type distinguishes the ν variants for jit caching (Kernel
    hashes on ``(type, _spec())``)."""

    _nu2: int  # 2 * nu, set by subclasses

    def _k(self, theta, sqd):
        return _matern_tile(self._nu2, theta, sqd)

    def gram(self, theta, x):
        return self._k(theta, sq_dist_self(x))

    def prepare(self, x):
        # theta-invariant squared-distance block (kernels/base.py
        # protocol); sigma enters only through the elementwise _k map, so
        # one cached block serves every L-BFGS evaluation
        return sq_dist_self(x)

    def gram_from_cache(self, theta, cache):
        return self._k(theta, cache)

    def prepare_matvec(self, x):
        return x

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        from spark_gp_tpu.ops.pallas_matvec import TILE_TRANSFORMS

        return streamed_matvec(
            mcache, v, TILE_TRANSFORMS[f"matern{self._nu2}2"], theta,
            kind="sqdist", **kw
        )

    def cross(self, theta, x_test, x_train):
        return self._k(theta, sq_dist(x_test, x_train))

    def describe(self, theta) -> str:
        return (
            f"Matern{self._nu2}2Kernel("
            f"sigma={float(np.asarray(theta)[0]):.1e})"
        )


class Matern12Kernel(_MaternIso):
    """Exponential / Ornstein–Uhlenbeck kernel (Matérn ν = 1/2)."""

    _nu2 = 1


class Matern32Kernel(_MaternIso):
    """Matérn ν = 3/2: once-differentiable sample paths."""

    _nu2 = 3


class Matern52Kernel(_MaternIso):
    """Matérn ν = 5/2: twice-differentiable sample paths."""

    _nu2 = 5


class _MaternARD(ARDHypers):
    """Per-dimension inverse length-scales, ARD-RBF convention
    (``r^2 = |(x_i - x_j) * beta|^2``)."""

    _nu2: int

    def _of_sqd(self, theta, sqd):
        a = math.sqrt(self._nu2) * _safe_r(sqd)
        return _matern_of_a(self._nu2, a)

    def gram(self, theta, x):
        return self._of_sqd(theta, weighted_sq_dist_self(x, theta))

    def cross(self, theta, x_test, x_train):
        return self._of_sqd(theta, weighted_sq_dist(x_test, x_train, theta))

    def describe(self, theta) -> str:
        vals = ", ".join(f"{v:.1e}" for v in np.asarray(theta))
        return f"ARDMatern{self._nu2}2Kernel(beta=[{vals}])"


class ARDMatern32Kernel(_MaternARD):
    _nu2 = 3


class ARDMatern52Kernel(_MaternARD):
    _nu2 = 5
