"""Additional kernel families beyond the reference's RBF/ARD-RBF.

The reference ships exactly two covariance functions (kernel/RBFKernel.scala,
kernel/ARDRBFKernel.scala) plus the Eye/scale/sum algebra.  This module adds
the other standard families a GP practitioner reaches for, all as immutable
specs compatible with the composition DSL, jit-static hashing, and autodiff
(no hand-derived gradients; FD-checked in tests/test_kernels.py):

* :class:`RationalQuadraticKernel` — a scale mixture of RBFs,
  ``k = (1 + r^2 / (2 alpha sigma^2))^(-alpha)``; heavier tails than RBF,
  recovers it as ``alpha -> inf``.
* :class:`PeriodicKernel` — per-dimension ExpSineSquared,
  ``k = exp(-(2/ell^2) sum_d sin^2(pi (x_d - x'_d) / period))``; strictly
  repeating structure.
* :class:`DotProductKernel` — non-stationary linear kernel
  ``k = sigma0^2 + <x, x'>`` (Bayesian linear regression as a GP).
* :class:`PolynomialKernel` — ``k = (<x, x'> + c)^degree`` with a static
  integer degree and trainable offset ``c``.

All members ride the MXU: RationalQuadratic through
:func:`spark_gp_tpu.ops.distance.sq_dist`, Periodic through a cos/sin
feature-map matmul, the dot-product members through one contraction — all
via :func:`spark_gp_tpu.ops.distance.mxu_inner`, so every family sits on
the precision policy's gram lane (``ops/precision.py``: HIGHEST on
``strict``, the compensated split-bf16 path on ``mixed``) with zero
per-kernel plumbing.  None of them takes a distance ``sqrt``, so Matérn's
coincident-point guard (:data:`spark_gp_tpu.kernels.matern._R2_FLOOR`) has
no analogue here — every formula is smooth in ``theta`` at r = 0.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import ARDHypers, Kernel, StationaryKernel
from spark_gp_tpu.ops.distance import (
    mxu_inner,
    sq_dist,
    sq_dist_self,
    weighted_sq_dist,
)
from spark_gp_tpu.ops.pallas_matvec import (
    register_tile_transform,
    streamed_matvec,
)


@register_tile_transform("rq")
def _rq_tile(theta, sqd):
    """The rational-quadratic elementwise map — one definition shared by
    gram / gram_from_cache / cross and the matfree streamed tiles."""
    sigma, alpha = theta[0], theta[1]
    base = 1.0 + sqd / (2.0 * alpha * sigma * sigma)
    # exp/log form: ``base ** -alpha`` with a traced exponent lowers to
    # the same, but the explicit form keeps the alpha-gradient stable
    # (d/dalpha goes through log(base), never through pow's 0^0 corner).
    return jnp.exp(-alpha * jnp.log(base))


@register_tile_transform("dot")
def _dot_tile(theta, inner):
    """Dot-product elementwise map over an inner-product tile."""
    return theta[0] * theta[0] + inner


def _pair(value, default: float) -> tuple:
    """Broadcast a scalar-or-length-2 bound spec to a (v0, v1) tuple."""
    arr = np.broadcast_to(
        np.asarray(default if value is None else value, dtype=np.float64), (2,)
    )
    return (float(arr[0]), float(arr[1]))


class _TwoHyperStationary(StationaryKernel):
    """Shared plumbing for stationary kernels with two trainable
    hyperparameters: ``theta = [h0, h1]`` with per-hyperparameter box
    bounds.  ``lower``/``upper`` accept a scalar (applied to both) or a
    length-2 sequence (one bound per hyperparameter)."""

    n_hypers = 2

    def __init__(self, h0: float, h1: float, lower, upper,
                 default_lower: float = 1e-6):
        self.theta0_ = (float(h0), float(h1))
        self.lower_ = _pair(lower, default_lower)
        self.upper_ = _pair(upper, math.inf)

    def _spec(self) -> tuple:
        return (self.theta0_, self.lower_, self.upper_)

    def init_theta(self):
        return np.array(self.theta0_, dtype=np.float64)

    def bounds(self):
        return (
            np.array(self.lower_, dtype=np.float64),
            np.array(self.upper_, dtype=np.float64),
        )


class RationalQuadraticKernel(_TwoHyperStationary):
    """Rational quadratic: ``k = (1 + r^2 / (2 alpha sigma^2))^(-alpha)``.

    ``theta = [sigma, alpha]`` — length-scale and mixture-shape, trainable
    in ``[1e-6, inf)`` by default (the RBF bound convention,
    RBFKernel.scala:33-35).  ``lower``/``upper`` take a scalar or one bound
    per hyperparameter.
    """

    def __init__(self, sigma: float = 1.0, alpha: float = 1.0,
                 lower=None, upper=None):
        super().__init__(sigma, alpha, lower, upper)

    def _k(self, theta, sqd):
        return _rq_tile(theta, sqd)

    def gram(self, theta, x):
        return self._k(theta, sq_dist_self(x))

    def prepare(self, x):
        # theta-invariant squared-distance block (kernels/base.py
        # protocol): sigma and alpha both act through the elementwise _k
        return sq_dist_self(x)

    def gram_from_cache(self, theta, cache):
        return self._k(theta, cache)

    def prepare_matvec(self, x):
        return x

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        return streamed_matvec(
            mcache, v, _rq_tile, theta, kind="sqdist", **kw
        )

    def cross(self, theta, x_test, x_train):
        return self._k(theta, sq_dist(x_test, x_train))

    def describe(self, theta) -> str:
        t = np.asarray(theta)
        return (
            f"RationalQuadraticKernel(sigma={float(t[0]):.1e}, "
            f"alpha={float(t[1]):.1e})"
        )


class ARDRationalQuadraticKernel(ARDHypers):
    """ARD rational quadratic: ``k = (1 + |(x - x') * beta|^2 /
    alpha)^(-alpha)`` — one trainable inverse length-scale per feature
    dimension (the ARD-RBF beta-multiplies, no-1/2 convention,
    ARDRBFKernel.scala:8-15: ``alpha -> inf`` recovers ``ARDRBFKernel``
    with the SAME betas) plus the trainable mixture-shape ``alpha``
    APPENDED to the hyperparameter vector: ``theta = [beta_1..beta_p,
    alpha]``.  Beta bounds follow :class:`ARDHypers` (per-dimension,
    default ``[0, inf)`` so features can be pruned); ``alpha`` has its own
    box."""

    def __init__(self, p_or_beta, beta: float = 1.0, alpha: float = 1.0,
                 lower=0.0, upper=math.inf,
                 alpha_lower: float = 1e-6, alpha_upper: float = math.inf):
        super().__init__(p_or_beta, beta, lower, upper)
        self.alpha0 = float(alpha)
        self.alpha_bounds = (float(alpha_lower), float(alpha_upper))
        self.n_hypers = self.beta0.shape[0] + 1

    def _spec(self) -> tuple:
        return super()._spec() + (self.alpha0, self.alpha_bounds)

    def init_theta(self):
        return np.concatenate([self.beta0, [self.alpha0]])

    def bounds(self):
        return (
            np.concatenate([self.lower_b, [self.alpha_bounds[0]]]),
            np.concatenate([self.upper_b, [self.alpha_bounds[1]]]),
        )

    def _k(self, theta, x_a, x_b):
        beta, alpha = theta[:-1], theta[-1]
        base = 1.0 + weighted_sq_dist(x_a, x_b, beta) / alpha
        # exp/log form for alpha-gradient stability (see
        # RationalQuadraticKernel._k)
        return jnp.exp(-alpha * jnp.log(base))

    def gram(self, theta, x):
        return self._k(theta, x, x)

    def cross(self, theta, x_test, x_train):
        return self._k(theta, x_test, x_train)

    def describe(self, theta) -> str:
        t = np.asarray(theta)
        vals = ", ".join(f"{v:.1e}" for v in t[:-1])
        return (
            f"ARDRationalQuadraticKernel(beta=[{vals}], "
            f"alpha={float(t[-1]):.1e})"
        )


class PeriodicKernel(_TwoHyperStationary):
    """Exactly periodic kernel (MacKay's ExpSineSquared, per dimension):

    ``k = exp(-(2 / ell^2) * sum_d sin^2(pi (x_d - x'_d) / period))``

    ``theta = [period, ell]`` (``lower``/``upper``: scalar or one bound per
    hyperparameter).  The per-dimension form (not the Euclidean-
    distance variant some libraries use) is provably PSD in any dimension:
    with the feature map ``Phi(x) = [cos(2 pi x / period),
    sin(2 pi x / period)]`` the identity ``sum_d cos(2 pi (x_d - x'_d) /
    period) = <Phi(x), Phi(x')>`` gives ``k = e^(-P / ell^2) *
    e^(<Phi, Phi'> / ell^2)`` — an exponential of an inner product, hence a
    PSD power series.  That same identity is also the TPU-friendly
    implementation: one ``[n, 2p] x [2p, n']`` matmul on the MXU, smooth in
    ``period`` everywhere (no coincident-point sqrt guard needed).
    """

    def __init__(self, period: float = 1.0, lengthscale: float = 1.0,
                 lower=None, upper=None):
        super().__init__(period, lengthscale, lower, upper)

    def _phi(self, theta, x):
        u = (2.0 * jnp.pi / theta[0]) * x
        return jnp.concatenate([jnp.cos(u), jnp.sin(u)], axis=-1)

    def _k(self, theta, x_a, x_b):
        ell2 = theta[1] * theta[1]
        p_dims = x_a.shape[-1]
        # sum_d cos(2 pi (a_d - b_d) / period) as one feature-map matmul;
        # sum_d sin^2(pi d / period) = (P - sum_d cos(2 pi d / period)) / 2
        cos_sum = mxu_inner(self._phi(theta, x_a), self._phi(theta, x_b))
        # the exponent is a cancellation of O(p) terms; clamp at 0 so float
        # noise can never push k above 1 / above the exact diag() — the same
        # hazard ops/distance.py:35 clamps for squared distances
        return jnp.exp(jnp.minimum(cos_sum - p_dims, 0.0) / ell2)

    def gram(self, theta, x):
        return self._k(theta, x, x)

    def cross(self, theta, x_test, x_train):
        return self._k(theta, x_test, x_train)

    def describe(self, theta) -> str:
        t = np.asarray(theta)
        return (
            f"PeriodicKernel(period={float(t[0]):.1e}, "
            f"ell={float(t[1]):.1e})"
        )


class DotProductKernel(Kernel):
    """Linear (dot-product) kernel: ``k(x, x') = sigma0^2 + <x, x'>``.

    Non-stationary — ``diag`` grows with ``|x|^2``.  ``theta = [sigma0]``
    (the prior std of the bias weight), trainable in ``[0, inf)``.
    """

    n_hypers = 1

    def __init__(self, sigma0: float = 1.0, lower: float = 0.0,
                 upper: float = math.inf):
        self.s0 = float(sigma0)
        self.lower = float(lower)
        self.upper = float(upper)

    def _spec(self) -> tuple:
        return (self.s0, self.lower, self.upper)

    def init_theta(self):
        return np.array([self.s0], dtype=np.float64)

    def bounds(self):
        return (
            np.array([self.lower], dtype=np.float64),
            np.array([self.upper], dtype=np.float64),
        )

    def gram(self, theta, x):
        return theta[0] * theta[0] + mxu_inner(x, x)

    def prepare(self, x):
        # the inner-product matrix IS the invariant: sigma0 only shifts it
        return mxu_inner(x, x)

    def gram_from_cache(self, theta, cache):
        return theta[0] * theta[0] + cache

    def prepare_matvec(self, x):
        return x

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        return streamed_matvec(
            mcache, v, _dot_tile, theta, kind="inner", **kw
        )

    def cross(self, theta, x_test, x_train):
        return theta[0] * theta[0] + mxu_inner(x_test, x_train)

    def diag(self, theta, x):
        return theta[0] * theta[0] + jnp.sum(x * x, axis=-1)

    def self_diag(self, theta, x):
        return self.diag(theta, x)

    def describe(self, theta) -> str:
        return f"DotProductKernel(sigma0={float(np.asarray(theta)[0]):.1e})"


class PolynomialKernel(Kernel):
    """Polynomial kernel: ``k(x, x') = (<x, x'> + c)^degree``.

    ``degree`` is a static (non-trainable) positive integer baked into the
    spec hash; ``theta = [c]`` with ``c`` trainable in ``[0, inf)`` by
    default (``c > 0`` keeps the kernel PSD for any integer degree).
    """

    n_hypers = 1

    def __init__(self, degree: int = 2, c: float = 1.0,
                 lower: float = 0.0, upper: float = math.inf):
        degree = int(degree)
        if degree < 1:
            raise ValueError("degree must be a positive integer")
        self.degree = degree
        self.c0 = float(c)
        self.lower = float(lower)
        self.upper = float(upper)

    def _spec(self) -> tuple:
        return (self.degree, self.c0, self.lower, self.upper)

    def init_theta(self):
        return np.array([self.c0], dtype=np.float64)

    def bounds(self):
        return (
            np.array([self.lower], dtype=np.float64),
            np.array([self.upper], dtype=np.float64),
        )

    def _pow(self, base):
        # static integer power: unrolled multiplies, no pow-lowering corner
        out = base
        for _ in range(self.degree - 1):
            out = out * base
        return out

    def gram(self, theta, x):
        return self._pow(mxu_inner(x, x) + theta[0])

    def prepare(self, x):
        # the inner-product matrix is theta-invariant; the trainable
        # offset c and the static power act elementwise on it
        return mxu_inner(x, x)

    def gram_from_cache(self, theta, cache):
        return self._pow(cache + theta[0])

    def prepare_matvec(self, x):
        return x

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        # degree is a static spec attribute, so the closure carries only
        # python constants — legal inside the Pallas kernel body too
        return streamed_matvec(
            mcache, v, lambda par, inner: self._pow(inner + par[0]),
            theta, kind="inner", **kw
        )

    def cross(self, theta, x_test, x_train):
        return self._pow(mxu_inner(x_test, x_train) + theta[0])

    def diag(self, theta, x):
        return self._pow(jnp.sum(x * x, axis=-1) + theta[0])

    def self_diag(self, theta, x):
        return self.diag(theta, x)

    def describe(self, theta) -> str:
        return (
            f"PolynomialKernel(degree={self.degree}, "
            f"c={float(np.asarray(theta)[0]):.1e})"
        )


class SpectralMixtureKernel(Kernel):
    """Spectral mixture kernel (Wilson & Adams, *GP Kernels for Pattern
    Discovery and Extrapolation*, ICML'13, eq. 12):

    ``k(tau) = sum_q w_q  prod_d exp(-2 pi^2 tau_d^2 v_qd)
                           cos(2 pi tau_d mu_qd)``,   ``tau = x - x'``

    — a Q-component Gaussian mixture over the spectral density, dense in
    the stationary kernels: it subsumes RBF (Q=1, mu=0), quasi-periodic
    compositions, and learned multi-scale structure, making it the
    standard choice for pattern extrapolation.

    ``theta = [w (Q), mu (Q*p row-major), v (Q*p)]``: mixture weights,
    per-component per-dimension spectral means (frequencies) and
    variances (inverse squared length-scales up to ``2 pi^2``).  Bounds:
    ``w, mu in [0, inf)`` (cosine is even, so nonnegative frequencies
    lose nothing), ``v in [1e-6, inf)``.  Defaults follow the usual SM
    initialization shape: equal weights ``1/Q``, frequencies spread over
    ``(q+1) / (2Q)``, unit spectral variances — pass explicit arrays for
    data-driven inits (e.g. from an empirical-spectrum heuristic).

    Compute: the exponential part is Q weighted squared distances (MXU
    via :func:`weighted_sq_dist`); the cosine product is a per-dimension
    broadcast over ``tau`` — O(n n' p Q) elementwise, intended for the
    low-dimensional inputs SM is used on (time series, p <= ~10; the
    cross path streams through the PPA predictor's fixed-size chunks).
    """

    def __init__(self, p: int, q: int = 3, weights=None, means=None,
                 scales=None):
        self.p = int(p)
        self.q = int(q)
        w = np.full(self.q, 1.0 / self.q) if weights is None else (
            np.asarray(weights, dtype=np.float64)
        )
        if means is None:
            mu = np.tile(
                ((np.arange(self.q) + 1.0) / (2.0 * self.q))[:, None],
                (1, self.p),
            )
        else:
            mu = np.asarray(means, dtype=np.float64)
        v = np.ones((self.q, self.p)) if scales is None else (
            np.asarray(scales, dtype=np.float64)
        )
        if w.shape != (self.q,) or mu.shape != (self.q, self.p) \
                or v.shape != (self.q, self.p):
            raise ValueError(
                f"weights must be [{self.q}], means/scales [{self.q}, "
                f"{self.p}]; got {w.shape}, {mu.shape}, {v.shape}"
            )
        self.w0 = tuple(float(x) for x in w)
        self.mu0 = tuple(float(x) for x in mu.ravel())
        self.v0 = tuple(float(x) for x in v.ravel())

    @property
    def n_hypers(self) -> int:
        return self.q * (1 + 2 * self.p)

    def _spec(self) -> tuple:
        return (self.p, self.q, self.w0, self.mu0, self.v0)

    def init_theta(self):
        return np.concatenate([self.w0, self.mu0, self.v0])

    def bounds(self):
        n_qp = self.q * self.p
        lower = np.concatenate([
            np.zeros(self.q), np.zeros(n_qp), np.full(n_qp, 1e-6),
        ])
        return lower, np.full(self.q + 2 * n_qp, math.inf)

    def _split(self, theta):
        q, p = self.q, self.p
        w = theta[: q]
        mu = theta[q: q + q * p].reshape(q, p)
        v = theta[q + q * p:].reshape(q, p)
        return w, mu, v

    def _k(self, theta, x_a, x_b):
        w, mu, v = self._split(theta)
        tau = x_a[:, None, :] - x_b[None, :, :]          # [n, n', p]
        tau2 = tau * tau
        # per component: one weighted sq-dist exponent + one cos product
        expo = jnp.einsum("abp,qp->qab", tau2, -2.0 * jnp.pi ** 2 * v)
        cosp = jnp.prod(
            jnp.cos(2.0 * jnp.pi * tau[None, :, :, :] * mu[:, None, None, :]),
            axis=-1,
        )                                                # [q, n, n']
        return jnp.einsum("q,qab->ab", w, jnp.exp(expo) * cosp)

    def gram(self, theta, x):
        return self._k(theta, x, x)

    def cross(self, theta, x_test, x_train):
        return self._k(theta, x_test, x_train)

    def diag(self, theta, x):
        w, _, _ = self._split(theta)
        return jnp.full(x.shape[0], jnp.sum(w), dtype=x.dtype)

    def self_diag(self, theta, x):
        return self.diag(theta, x)

    def describe(self, theta) -> str:
        w, mu, _ = self._split(np.asarray(theta))
        top = int(np.argmax(w))
        return (
            f"SpectralMixtureKernel(q={self.q}, p={self.p}, "
            f"w_top={float(w[top]):.1e}, "
            f"mu_top={np.round(np.asarray(mu[top]), 3).tolist()})"
        )
