"""RBF and ARD-RBF kernels.

TPU-first re-design of RBFKernel.scala / ARDRBFKernel.scala: the reference
precomputes an O(n^2) squared-distance matrix with nested scalar loops and
carries it as object state; here the distance matrix is one MXU matmul
(``ops.distance``) recomputed under jit — XLA fuses the ``exp`` into the
surrounding computation and there is no mutable state to invalidate.

Hyperparameter derivatives are autodiff's job; the reference's analytic
formulas (RBFKernel.scala:56-64, ARDRBFKernel.scala:61-79) survive only as
finite-difference test oracles.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import ARDHypers, ScalarLengthscaleHypers
from spark_gp_tpu.ops.distance import (
    sq_dist,
    sq_dist_self,
    weighted_sq_dist,
    weighted_sq_dist_self,
)
from spark_gp_tpu.ops.pallas_matvec import (
    register_tile_transform,
    streamed_matvec,
)


@register_tile_transform("rbf")
def _rbf_tile(theta, sqd):
    """The RBF elementwise map — ONE definition shared by gram /
    gram_from_cache / cross and the matfree lane's streamed tiles."""
    sigma = theta[0]
    return jnp.exp(sqd / (-2.0 * sigma * sigma))


class RBFKernel(ScalarLengthscaleHypers):
    """``k(x_i, x_j) = exp(-|x_i - x_j|^2 / (2 sigma^2))`` with one trainable
    length-scale ``sigma`` bounded in ``[lower, upper]``
    (RBFKernel.scala:14-54; default bounds :15-16)."""

    def _k(self, theta, sqd):
        return _rbf_tile(theta, sqd)

    def gram(self, theta, x):
        return self._k(theta, sq_dist_self(x))

    def prepare(self, x):
        # theta-invariant structure (kernels/base.py protocol): the
        # pinned-diagonal squared-distance block — the reference's carried
        # object state (RBFKernel.scala:37-48), functional
        return sq_dist_self(x)

    def gram_from_cache(self, theta, cache):
        return self._k(theta, cache)

    def prepare_matvec(self, x):
        return x

    def matvec_from_prepared(self, theta, mcache, v, **kw):
        return streamed_matvec(
            mcache, v, _rbf_tile, theta, kind="sqdist", **kw
        )

    def cross(self, theta, x_test, x_train):
        return self._k(theta, sq_dist(x_test, x_train))

    def describe(self, theta) -> str:
        return f"RBFKernel(sigma={float(np.asarray(theta)[0]):.1e})"


class ARDRBFKernel(ARDHypers):
    """Automatic Relevance Determination RBF:
    ``k(x_i, x_j) = exp(-|(x_i - x_j) * beta|^2)`` with one trainable inverse
    length-scale per feature dimension (ARDRBFKernel.scala:20-46).

    Note the reference's convention (no factor 1/2, beta multiplies rather
    than divides) is kept so hyperparameter values are directly comparable.
    """

    def gram(self, theta, x):
        return jnp.exp(-weighted_sq_dist_self(x, theta))

    def cross(self, theta, x_test, x_train):
        return jnp.exp(-weighted_sq_dist(x_test, x_train, theta))

    def describe(self, theta) -> str:
        vals = ", ".join(f"{v:.1e}" for v in np.asarray(theta))
        return f"ARDRBFKernel(beta=[{vals}])"
