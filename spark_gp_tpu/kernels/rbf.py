"""RBF and ARD-RBF kernels.

TPU-first re-design of RBFKernel.scala / ARDRBFKernel.scala: the reference
precomputes an O(n^2) squared-distance matrix with nested scalar loops and
carries it as object state; here the distance matrix is one MXU matmul
(``ops.distance``) recomputed under jit — XLA fuses the ``exp`` into the
surrounding computation and there is no mutable state to invalidate.

Hyperparameter derivatives are autodiff's job; the reference's analytic
formulas (RBFKernel.scala:56-64, ARDRBFKernel.scala:61-79) survive only as
finite-difference test oracles.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import StationaryKernel
from spark_gp_tpu.ops.distance import sq_dist, weighted_sq_dist


class RBFKernel(StationaryKernel):
    """``k(x_i, x_j) = exp(-|x_i - x_j|^2 / (2 sigma^2))`` with one trainable
    length-scale ``sigma`` bounded in ``[lower, upper]``
    (RBFKernel.scala:14-54; default bounds :15-16)."""

    n_hypers = 1

    def __init__(self, sigma: float = 1.0, lower: float = 1e-6, upper: float = math.inf):
        self.sigma0 = float(sigma)
        self.lower = float(lower)
        self.upper = float(upper)

    def _spec(self) -> tuple:
        return (self.sigma0, self.lower, self.upper)

    def init_theta(self):
        return np.array([self.sigma0], dtype=np.float64)

    def bounds(self):
        return (
            np.array([self.lower], dtype=np.float64),
            np.array([self.upper], dtype=np.float64),
        )

    def _k(self, theta, sqd):
        sigma = theta[0]
        return jnp.exp(sqd / (-2.0 * sigma * sigma))

    def gram(self, theta, x):
        return self._k(theta, sq_dist(x, x))

    def cross(self, theta, x_test, x_train):
        return self._k(theta, sq_dist(x_test, x_train))

    def describe(self, theta) -> str:
        return f"RBFKernel(sigma={float(np.asarray(theta)[0]):.1e})"


class ARDRBFKernel(StationaryKernel):
    """Automatic Relevance Determination RBF:
    ``k(x_i, x_j) = exp(-|(x_i - x_j) * beta|^2)`` with one trainable inverse
    length-scale per feature dimension (ARDRBFKernel.scala:20-46).

    Note the reference's convention (no factor 1/2, beta multiplies rather
    than divides) is kept so hyperparameter values are directly comparable.
    """

    def __init__(self, p_or_beta, beta: float = 1.0, lower=0.0, upper=math.inf):
        if isinstance(p_or_beta, (int, np.integer)):
            beta0 = np.full((int(p_or_beta),), float(beta), dtype=np.float64)
        else:
            beta0 = np.asarray(p_or_beta, dtype=np.float64)
        self.beta0 = beta0
        self.n_hypers = beta0.shape[0]
        self.lower_b = np.broadcast_to(
            np.asarray(lower, dtype=np.float64), beta0.shape
        ).copy()
        self.upper_b = np.broadcast_to(
            np.asarray(upper, dtype=np.float64), beta0.shape
        ).copy()

    def _spec(self) -> tuple:
        return (
            tuple(self.beta0.tolist()),
            tuple(self.lower_b.tolist()),
            tuple(self.upper_b.tolist()),
        )

    def init_theta(self):
        return self.beta0.copy()

    def bounds(self):
        return self.lower_b, self.upper_b

    def gram(self, theta, x):
        return jnp.exp(-weighted_sq_dist(x, x, theta))

    def cross(self, theta, x_test, x_train):
        return jnp.exp(-weighted_sq_dist(x_test, x_train, theta))

    def describe(self, theta) -> str:
        vals = ", ".join(f"{v:.1e}" for v in np.asarray(theta))
        return f"ARDRBFKernel(beta=[{vals}])"
