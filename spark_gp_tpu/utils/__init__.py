"""Framework utilities: instrumentation, model selection harness,
serialization, checkpointing."""
