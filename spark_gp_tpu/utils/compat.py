"""JAX cross-version compatibility shims.

The codebase targets the stable top-level names (``jax.enable_x64``,
``jax.shard_map``, ``jax.distributed.is_initialized``); older runtimes
(jax 0.4.x) expose the same functionality under experimental/private
paths.  :func:`install_jax_compat` aliases the stable names onto the
installed jax when missing, so one codebase runs on both — called once
from the package ``__init__`` before any model module imports jax.
"""

from __future__ import annotations


def install_jax_compat() -> None:
    import jax

    if not hasattr(jax, "enable_x64"):
        # jax < 0.6: the x64 context manager lives in jax.experimental
        from jax.experimental import enable_x64

        jax.enable_x64 = enable_x64

    if not hasattr(jax, "shard_map"):
        # jax < 0.6: shard_map lives in jax.experimental.shard_map, and its
        # replication checker predates rules for several primitives the
        # model code uses (lax.while_loop raises "No replication rule for
        # while").  check_rep is a purely static checker, and upstream's
        # documented workaround for missing rules is to disable it — do so
        # by default while honoring an explicit caller choice.
        import functools

        from jax.experimental.shard_map import shard_map

        @functools.wraps(shard_map)
        def _shard_map(f=None, **kwargs):
            kwargs.setdefault("check_rep", False)
            if f is None:
                return lambda g: shard_map(g, **kwargs)
            return shard_map(f, **kwargs)

        # with the checker off, replication-aware rewrites are off too:
        # code returning a device-varying gradient through a P() out_spec
        # (models/likelihood._make_sharded_vag) must all-reduce it
        # explicitly — see shard_map_needs_explicit_grad_psum()
        _shard_map.compat_check_rep_disabled = True
        jax.shard_map = _shard_map

    if not hasattr(jax.lax, "pcast"):
        # jax < 0.7 has no varying/replicated type distinction (and the
        # compat shard_map above runs with the replication checker off),
        # so the cast is semantically an identity
        def _pcast(x, *args, **kwargs):
            return x

        jax.lax.pcast = _pcast

    if not hasattr(jax.distributed, "is_initialized"):  # collective-guard-ok (shim installer)
        # jax < 0.5 has no public probe; the coordination client handle
        # in jax._src.distributed.global_state is the same signal
        def _is_initialized() -> bool:
            try:
                from jax._src.distributed import global_state  # collective-guard-ok

                return global_state.client is not None
            except Exception:  # noqa: BLE001 — internals moved: assume no
                return False

        jax.distributed.is_initialized = _is_initialized  # collective-guard-ok


def shard_map_needs_explicit_grad_psum() -> bool:
    """True when the compat shard_map wrapper (check_rep disabled) is
    installed: the replication machinery that would otherwise turn a
    device-varying gradient into the global one at a ``P()`` out_spec is
    inactive, so the forward function must ``psum`` the gradient itself."""
    import jax

    return bool(getattr(jax.shard_map, "compat_check_rep_disabled", False))


def whole_loop_shard_map_supported() -> bool:
    """False on the old-jax compat wrapper: tracing the full L-BFGS
    ``while_loop`` *inside* shard_map wedges its compile for minutes+
    (observed: test_gpr_device_sharded never finishing).  Callers fall
    back to the plain jitted fit — GSPMD still partitions the sharded
    expert stack, at the cost of XLA choosing the collectives instead of
    the hand-placed per-iteration psum."""
    return not shard_map_needs_explicit_grad_psum()
