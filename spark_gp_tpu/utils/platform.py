"""Platform-selection hygiene for hooked JAX runtimes.

Some TPU site hooks (e.g. the axon tunnel shim) rewrite the resolved
``jax_platforms`` *config* at jax-import time — ``JAX_PLATFORMS=cpu`` in the
environment still resolves to ``"axon,cpu"``, and the first backend lookup
then blocks on an unreachable tunnel instead of running on CPU.  An explicit
``jax.config.update`` wins over the hook; this module restores the documented
env-var contract for every entry point (examples, bench, library import).
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the user's ``JAX_PLATFORMS`` env var over site hooks.

    No-op when the env var is unset, already in effect, or when a backend is
    already initialized (too late to change platforms safely).
    """
    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    import jax

    if jax.config.jax_platforms == plats:
        return
    if backends_already_initialized():
        return
    jax.config.update("jax_platforms", plats)


def preflight_backend(timeout_s: float = 90.0, fallback: str = "cpu") -> str:
    """Probe the default JAX backend in a SUBPROCESS; pin this process to
    ``fallback`` if the probe hangs or dies.  Returns the platform this
    process will use.

    A half-dead device tunnel hangs *inside a C call* during backend init,
    where no in-process timeout can interrupt it (the supervisor/worker
    rationale of ``bench.py``) — the only safe probe is a throwaway
    subprocess.  Entry points that must never wedge on a flaky accelerator
    (the ``examples/``) call this before their first jax touch.

    ``JAX_PLATFORMS`` pins are still PROBED (r5): device-site shell
    profiles export ``JAX_PLATFORMS=<tunnel>`` globally, so a pin is not
    reliable evidence of per-run user intent, and honoring a wedged pin
    forever is exactly the failure this function exists to prevent.  A
    pinned-but-hung backend falls back like an unpinned one; set
    ``GP_HONOR_PINNED_PLATFORM=1`` to wedge-on-principle instead.  No-op
    when a backend is already initialized in this process (too late to
    switch safely).
    """
    pinned = os.environ.get("JAX_PLATFORMS")
    first = pinned.split(",")[0] if pinned else None
    if pinned:
        honor_platform_env()
        if first == fallback or os.environ.get("GP_HONOR_PINNED_PLATFORM") == "1":
            return first
    if backends_already_initialized():
        import jax

        return jax.default_backend()

    cached = _read_healthy_marker()
    # a cached verdict only covers the platform it was measured on — a
    # healthy-cpu marker must not green-light an axon pin
    if cached is not None and (not pinned or cached == first):
        return cached

    import sys

    why = None
    # The probe must do REAL device work, not just name the backend:
    # today's axon tunnel failure mode (r5) registers the platform and
    # answers default_backend() in <1s while jax.devices() / the first
    # computation hangs forever — a name-only probe passes, caches a
    # healthy verdict, and the example wedges anyway.  One tiny computed
    # round trip catches every init-or-compute hang mode seen so far.
    probe_code = (
        "import os, jax, jax.numpy as jnp; "
        # re-assert any pin over site hooks, as honor_platform_env does
        "p = os.environ.get('JAX_PLATFORMS'); "
        "p and jax.config.update('jax_platforms', p); "
        "jax.block_until_ready(jnp.ones(()) + 1); "
        "print(jax.default_backend())"
    )
    # run_captured, not subprocess.run: a wedged tunnel runtime can spawn
    # helpers that inherit the probe's pipes — run()'s unbounded post-kill
    # drain would then defeat this very watchdog (utils/subproc.py)
    from spark_gp_tpu.utils.subproc import run_captured

    probe = run_captured([sys.executable, "-c", probe_code], timeout_s)
    if probe.timed_out:
        why = f"probe hung past {timeout_s:.0f}s (wedged device runtime)"
    elif probe.returncode == 0 and probe.stdout.strip():
        platform = probe.stdout.strip().splitlines()[-1]
        _write_healthy_marker(platform)
        return platform
    else:
        why = (
            f"probe exited rc={probe.returncode}; stderr tail: "
            + (probe.stderr or "").strip()[-300:]
        )
    import logging

    logging.getLogger(__name__).warning(
        "%s JAX backend failed its preflight probe — %s; falling back to "
        "%s for this process%s",
        f"pinned (JAX_PLATFORMS={pinned})" if pinned else "default",
        why, fallback,
        " (set GP_HONOR_PINNED_PLATFORM=1 to honor the pin regardless)"
        if pinned else "",
    )
    os.environ["JAX_PLATFORMS"] = fallback
    import jax

    jax.config.update("jax_platforms", fallback)
    return fallback


def _marker_path():
    """Marker file under a private 0700 per-user directory, or None when no
    trustworthy location exists (callers then skip caching).

    A fixed-name file in world-writable /tmp lets another local user
    pre-plant a symlink (followed by open-for-write) or a spoofed verdict
    that suppresses the probe.  The marker therefore lives in a directory
    we create 0700 and verify (not a symlink, owned by us, no group/other
    bits) before trusting; any anomaly falls back to a probe-always path.
    The file name carries an interpreter + jax-install fingerprint so a
    verdict from one python/jax environment can never suppress the probe
    in a different one whose backend init could still hang.
    """
    import hashlib
    import stat
    import sys
    import tempfile

    base = os.environ.get("XDG_RUNTIME_DIR") or tempfile.gettempdir()
    d = os.path.join(base, f"spark_gp_tpu-{os.getuid()}")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        st = os.lstat(d)
        if (
            not stat.S_ISDIR(st.st_mode)
            or st.st_uid != os.getuid()
            or (st.st_mode & 0o077)
        ):
            raise OSError("untrusted marker dir")
    except OSError:
        # unusable private dir (symlinked, group-writable, wrong owner):
        # disable caching outright — callers treat None as "always probe".
        # (A per-call mkdtemp would leak one directory per invocation.)
        return None
    h = hashlib.sha1()
    h.update(sys.executable.encode())
    h.update(sys.version.encode())
    try:
        import importlib.util

        spec = importlib.util.find_spec("jax")
        h.update((spec.origin or "").encode() if spec else b"nojax")
    except Exception:  # noqa: BLE001 — fingerprint is best-effort
        h.update(b"nojax")
    return os.path.join(d, f"preflight-{h.hexdigest()[:12]}")


def _read_healthy_marker():
    """Recent healthy-probe verdict, or None.

    A fresh verdict (default TTL 300 s; ``GP_PREFLIGHT_CACHE_TTL`` seconds,
    0 disables caching) lets back-to-back example runs on a healthy host
    skip the throwaway probe subprocess — a full jax import + backend init
    per invocation.  The TTL bounds the stale-verdict risk: a tunnel that
    died within the window can still wedge one run, exactly as it would
    have mid-computation anyway."""
    import json
    import time

    try:
        ttl = float(os.environ.get("GP_PREFLIGHT_CACHE_TTL", "300"))
    except ValueError:
        ttl = 300.0
    if ttl <= 0:
        return None
    path = _marker_path()
    if path is None:
        return None
    try:
        with open(path) as fh:
            marker = json.load(fh)
        # the verdict is only valid under the SAME effective pin: a
        # healthy probe under JAX_PLATFORMS=axon says nothing about what
        # an unpinned process's default backend resolution would do (and
        # vice versa)
        if marker.get("pin", "") != os.environ.get("JAX_PLATFORMS", ""):
            return None
        if time.time() - float(marker["ts"]) < ttl:
            return str(marker["platform"])
    except Exception:  # noqa: BLE001 — unreadable/absent marker: just probe
        pass
    return None


def _write_healthy_marker(platform: str) -> None:
    import json
    import time

    path = _marker_path()
    if path is None:
        return
    try:
        # O_NOFOLLOW: refuse to write through a pre-planted symlink even if
        # the directory checks in _marker_path were somehow bypassed
        fd = os.open(
            path,
            os.O_WRONLY | os.O_CREAT | os.O_TRUNC | getattr(os, "O_NOFOLLOW", 0),
            0o600,
        )
        with os.fdopen(fd, "w") as fh:
            json.dump(
                {
                    "ts": time.time(),
                    "platform": platform,
                    "pin": os.environ.get("JAX_PLATFORMS", ""),
                },
                fh,
            )
    except OSError:  # unwritable tmp: caching is best-effort only
        pass


def backends_already_initialized() -> bool:
    """True once any XLA backend client exists in this process.

    Single home for the private-API probe (``jax._src.xla_bridge``) so a
    JAX-internals move only needs fixing in one place; falls back to False
    (callers then rely on their own late-call error handling).
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:  # hygiene-ok: jax-internal probe; absence = not initialized
        return False


def machine_cache_dir(base: str) -> str:
    """``base`` extended with a host-machine fingerprint, for use as a
    persistent XLA compilation-cache directory.

    XLA's CPU backend persists ahead-of-time executables whose cache key
    does NOT include the host's CPU feature set; loading an entry written
    on a different CPU generation warns ``Target machine feature ... is
    not supported on the host machine`` and can SIGILL/segfault outright
    (observed: a cache written on an avx512+amx host crashed the test
    suite on a smaller host mid-``pjit``).  Keying the directory by a
    digest of the CPU model + feature flags makes every machine read only
    its own entries; stale directories from other machines are left
    behind, never loaded.
    """
    import hashlib
    import platform as _platform

    h = hashlib.sha1()
    h.update(_platform.machine().encode())
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("model name", "flags")):
                    h.update(line.encode())
                    # one physical CPU is enough; flags repeat per core
                    if line.startswith("flags"):
                        break
    except OSError:
        h.update(_platform.processor().encode())
    return f"{base}-{h.hexdigest()[:12]}"
