"""Platform-selection hygiene for hooked JAX runtimes.

Some TPU site hooks (e.g. the axon tunnel shim) rewrite the resolved
``jax_platforms`` *config* at jax-import time — ``JAX_PLATFORMS=cpu`` in the
environment still resolves to ``"axon,cpu"``, and the first backend lookup
then blocks on an unreachable tunnel instead of running on CPU.  An explicit
``jax.config.update`` wins over the hook; this module restores the documented
env-var contract for every entry point (examples, bench, library import).
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the user's ``JAX_PLATFORMS`` env var over site hooks.

    No-op when the env var is unset, already in effect, or when a backend is
    already initialized (too late to change platforms safely).
    """
    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    import jax

    if jax.config.jax_platforms == plats:
        return
    if backends_already_initialized():
        return
    jax.config.update("jax_platforms", plats)


def backends_already_initialized() -> bool:
    """True once any XLA backend client exists in this process.

    Single home for the private-API probe (``jax._src.xla_bridge``) so a
    JAX-internals move only needs fixing in one place; falls back to False
    (callers then rely on their own late-call error handling).
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False
