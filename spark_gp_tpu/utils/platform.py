"""Platform-selection hygiene for hooked JAX runtimes.

Some TPU site hooks (e.g. the axon tunnel shim) rewrite the resolved
``jax_platforms`` *config* at jax-import time — ``JAX_PLATFORMS=cpu`` in the
environment still resolves to ``"axon,cpu"``, and the first backend lookup
then blocks on an unreachable tunnel instead of running on CPU.  An explicit
``jax.config.update`` wins over the hook; this module restores the documented
env-var contract for every entry point (examples, bench, library import).
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the user's ``JAX_PLATFORMS`` env var over site hooks.

    No-op when the env var is unset, already in effect, or when a backend is
    already initialized (too late to change platforms safely).
    """
    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    import jax

    if jax.config.jax_platforms == plats:
        return
    if backends_already_initialized():
        return
    jax.config.update("jax_platforms", plats)


def preflight_backend(timeout_s: float = 90.0, fallback: str = "cpu") -> str:
    """Probe the default JAX backend in a SUBPROCESS; pin this process to
    ``fallback`` if the probe hangs or dies.  Returns the platform this
    process will use.

    A half-dead device tunnel hangs *inside a C call* during backend init,
    where no in-process timeout can interrupt it (the supervisor/worker
    rationale of ``bench.py``) — the only safe probe is a throwaway
    subprocess.  Entry points that must never wedge on a flaky accelerator
    (the ``examples/``) call this before their first jax touch.

    No-op when the user pinned ``JAX_PLATFORMS`` explicitly (their choice
    is re-asserted and honored, hang or not) or when a backend is already
    initialized in this process (too late to switch safely).
    """
    pinned = os.environ.get("JAX_PLATFORMS")
    if pinned:
        honor_platform_env()
        return pinned.split(",")[0]
    if backends_already_initialized():
        import jax

        return jax.default_backend()

    cached = _read_healthy_marker()
    if cached is not None:
        return cached

    import subprocess
    import sys

    why = None
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if probe.returncode == 0 and probe.stdout.strip():
            platform = probe.stdout.strip().splitlines()[-1]
            _write_healthy_marker(platform)
            return platform
        why = (
            f"probe exited rc={probe.returncode}; stderr tail: "
            + (probe.stderr or "").strip()[-300:]
        )
    except subprocess.TimeoutExpired:
        why = f"probe hung past {timeout_s:.0f}s (wedged device runtime)"
    import logging

    logging.getLogger(__name__).warning(
        "default JAX backend failed its preflight probe — %s; falling "
        "back to %s for this process", why, fallback,
    )
    os.environ["JAX_PLATFORMS"] = fallback
    import jax

    jax.config.update("jax_platforms", fallback)
    return fallback


def _marker_path() -> str:
    import tempfile

    return os.path.join(
        tempfile.gettempdir(), f"spark_gp_tpu_preflight_uid{os.getuid()}"
    )


def _read_healthy_marker():
    """Recent healthy-probe verdict, or None.

    A fresh verdict (default TTL 300 s; ``GP_PREFLIGHT_CACHE_TTL`` seconds,
    0 disables caching) lets back-to-back example runs on a healthy host
    skip the throwaway probe subprocess — a full jax import + backend init
    per invocation.  The TTL bounds the stale-verdict risk: a tunnel that
    died within the window can still wedge one run, exactly as it would
    have mid-computation anyway."""
    import json
    import time

    try:
        ttl = float(os.environ.get("GP_PREFLIGHT_CACHE_TTL", "300"))
    except ValueError:
        ttl = 300.0
    if ttl <= 0:
        return None
    try:
        with open(_marker_path()) as fh:
            marker = json.load(fh)
        if time.time() - float(marker["ts"]) < ttl:
            return str(marker["platform"])
    except Exception:  # noqa: BLE001 — unreadable/absent marker: just probe
        pass
    return None


def _write_healthy_marker(platform: str) -> None:
    import json
    import time

    try:
        with open(_marker_path(), "w") as fh:
            json.dump({"ts": time.time(), "platform": platform}, fh)
    except OSError:  # unwritable tmp: caching is best-effort only
        pass


def backends_already_initialized() -> bool:
    """True once any XLA backend client exists in this process.

    Single home for the private-API probe (``jax._src.xla_bridge``) so a
    JAX-internals move only needs fixing in one place; falls back to False
    (callers then rely on their own late-call error handling).
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False


def machine_cache_dir(base: str) -> str:
    """``base`` extended with a host-machine fingerprint, for use as a
    persistent XLA compilation-cache directory.

    XLA's CPU backend persists ahead-of-time executables whose cache key
    does NOT include the host's CPU feature set; loading an entry written
    on a different CPU generation warns ``Target machine feature ... is
    not supported on the host machine`` and can SIGILL/segfault outright
    (observed: a cache written on an avx512+amx host crashed the test
    suite on a smaller host mid-``pjit``).  Keying the directory by a
    digest of the CPU model + feature flags makes every machine read only
    its own entries; stale directories from other machines are left
    behind, never loaded.
    """
    import hashlib
    import platform as _platform

    h = hashlib.sha1()
    h.update(_platform.machine().encode())
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("model name", "flags")):
                    h.update(line.encode())
                    # one physical CPU is enough; flags repeat per core
                    if line.startswith("flags"):
                        break
    except OSError:
        h.update(_platform.processor().encode())
    return f"{base}-{h.hexdigest()[:12]}"
