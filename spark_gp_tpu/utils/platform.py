"""Platform-selection hygiene for hooked JAX runtimes.

Some TPU site hooks (e.g. the axon tunnel shim) rewrite the resolved
``jax_platforms`` *config* at jax-import time — ``JAX_PLATFORMS=cpu`` in the
environment still resolves to ``"axon,cpu"``, and the first backend lookup
then blocks on an unreachable tunnel instead of running on CPU.  An explicit
``jax.config.update`` wins over the hook; this module restores the documented
env-var contract for every entry point (examples, bench, library import).
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the user's ``JAX_PLATFORMS`` env var over site hooks.

    No-op when the env var is unset, already in effect, or when a backend is
    already initialized (too late to change platforms safely).
    """
    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    import jax

    if jax.config.jax_platforms == plats:
        return
    if backends_already_initialized():
        return
    jax.config.update("jax_platforms", plats)


def preflight_backend(timeout_s: float = 90.0, fallback: str = "cpu") -> str:
    """Probe the default JAX backend in a SUBPROCESS; pin this process to
    ``fallback`` if the probe hangs or dies.  Returns the platform this
    process will use.

    A half-dead device tunnel hangs *inside a C call* during backend init,
    where no in-process timeout can interrupt it (the supervisor/worker
    rationale of ``bench.py``) — the only safe probe is a throwaway
    subprocess.  Entry points that must never wedge on a flaky accelerator
    (the ``examples/``) call this before their first jax touch.

    No-op when the user pinned ``JAX_PLATFORMS`` explicitly (their choice
    is re-asserted and honored, hang or not) or when a backend is already
    initialized in this process (too late to switch safely).
    """
    pinned = os.environ.get("JAX_PLATFORMS")
    if pinned:
        honor_platform_env()
        return pinned.split(",")[0]
    if backends_already_initialized():
        import jax

        return jax.default_backend()

    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if probe.returncode == 0 and probe.stdout.strip():
            return probe.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    import logging

    logging.getLogger(__name__).warning(
        "default JAX backend failed its %.0fs preflight probe "
        "(unreachable or hung device runtime); falling back to %s for "
        "this process", timeout_s, fallback,
    )
    os.environ["JAX_PLATFORMS"] = fallback
    import jax

    jax.config.update("jax_platforms", fallback)
    return fallback


def backends_already_initialized() -> bool:
    """True once any XLA backend client exists in this process.

    Single home for the private-API probe (``jax._src.xla_bridge``) so a
    JAX-internals move only needs fixing in one place; falls back to False
    (callers then rely on their own late-call error handling).
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False
