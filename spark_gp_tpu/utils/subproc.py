"""Timeout-fenced subprocess capture hardened for half-dead device tunnels.

``subprocess.run(capture_output=True, timeout=...)`` has three hazards
around WEDGED accelerator runtimes, all rooted in one design choice: it
tracks the CHILD's lifetime through the PIPES' lifetime.

1. its post-kill pipe drain is an unbounded ``communicate()`` — a helper
   process spawned by the child (device tunnel shims do this) inherits
   the pipe write ends and keeps them open, so the drain blocks forever
   and the caller's own watchdog is defeated;
2. a child that EXITS cleanly while such a helper holds the pipes open
   still blocks ``communicate()`` for the full fence and gets misreported
   as a timeout — its exit code and a perfectly good result annotated
   away;
3. only the direct child is killed on timeout — the helpers survive and
   can hold the device or respawn the hang.

:func:`run_captured` separates the two lifetimes: daemon reader threads
drain the pipes continuously into buffers (no pipe-full deadlock, output
survives any kill), the main thread waits on the CHILD's exit with the
fence, and a timeout SIGKILLs the child's entire process group (it runs
in its own session) and reaps it.  The readers use raw ``os.read`` —
which returns WHATEVER bytes are available — never buffered-stream
``read(n)``, which blocks until n chars or EOF and would trap a small
result inside the read while a pipe holder postpones EOF forever.
Decoding is incremental with ``errors="replace"``: a kill can truncate
output mid-UTF-8-sequence, and libtpu/XLA stderr diagnostics are not
guaranteed clean UTF-8.
"""

from __future__ import annotations

import codecs
import os
import signal
import subprocess
import threading
from typing import NamedTuple


class CapturedRun(NamedTuple):
    returncode: int | None  # None = timed out (process group killed)
    stdout: str
    stderr: str

    @property
    def timed_out(self) -> bool:
        return self.returncode is None


def run_captured(cmd, timeout_s: float, env=None, cwd=None) -> CapturedRun:
    """Run ``cmd`` capturing text stdout/stderr; on timeout, kill the
    child's whole process group and STILL return the partial output."""
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        cwd=cwd,
        start_new_session=True,
    )
    buffers = {"out": [], "err": []}

    def _drain(stream, key):
        # raw os.read: returns as soon as ANY bytes are available, so
        # every chunk lands in the buffer immediately — a buffered
        # stream.read(n) would hold a sub-n result hostage until EOF
        decoder = codecs.getincrementaldecoder("utf-8")("replace")
        fd = stream.fileno()
        try:
            while True:
                chunk = os.read(fd, 65536)
                if not chunk:
                    break
                buffers[key].append(decoder.decode(chunk))
            buffers[key].append(decoder.decode(b"", True))
        except Exception:  # noqa: BLE001 — fd closed under us: keep buffer
            pass

    readers = [
        threading.Thread(target=_drain, args=(proc.stdout, "out"), daemon=True),
        threading.Thread(target=_drain, args=(proc.stderr, "err"), daemon=True),
    ]
    for t in readers:
        t.start()

    returncode = None
    try:
        returncode = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            # reap; bounded for unkillable D-state
            reaped = proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            reaped = None
        # Fence/exit race: the child may have exited on its own between the
        # timeout firing and the SIGKILL landing.  A killed child reaps as
        # -SIGKILL (negative); a NON-negative reaped code is the child's
        # own exit status — report it instead of misclassifying a
        # completed run (result, exit code and all) as a timeout.
        if reaped is not None and reaped >= 0:
            returncode = reaped
    # give the readers a moment to pull what's buffered; they may never
    # see EOF (a surviving pipe holder) — daemon threads, so not joining
    # to completion is safe, and the buffers keep everything read so far
    for t in readers:
        t.join(timeout=5)
    return CapturedRun(
        returncode, "".join(buffers["out"]), "".join(buffers["err"])
    )
