"""Preemption-safe L-BFGS training-state checkpointing.

JAX has no Spark-style lineage recomputation: if a long multi-host fit dies,
the optimizer state is gone (SURVEY.md §5, failure detection).  This module
persists optimizer state so a restarted fit resumes mid-run:

* **host optimizer** — :class:`LbfgsCheckpointer` saves theta, the iterate
  history window, the iteration count and the estimator seed each L-BFGS
  iteration; ``models/common._optimize_hypers`` resumes from the persisted
  iterate with the remaining iteration budget.
* **device optimizer** — :class:`DeviceOptimizerCheckpointer` round-trips
  the FULL ``_LbfgsState`` pytree between segments, so a killed fit
  resumes bit-exactly (``tests/test_checkpoint.py``, chaos kill-and-resume).

Durability contract (both writers): serialize to ``<path>.tmp``, fsync,
``os.replace`` — a preemption at ANY instant leaves either the previous
complete checkpoint or the new complete checkpoint, never a torn file.
Every payload carries a content checksum; a checkpoint that fails it (disk
corruption — atomicity rules out torn writes) raises
:class:`CheckpointCorruptError`, and one written under a different kernel
configuration raises :class:`CheckpointMismatchError` instead of silently
seeding (or being clobbered by) the wrong fit.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np


class CheckpointMismatchError(RuntimeError):
    """The checkpoint on disk belongs to a different configuration
    (kernel signature / theta shape) than the fit trying to resume from
    it.  Clear the checkpoint directory or use a distinct one per
    configuration."""


class CheckpointCorruptError(RuntimeError):
    """The checkpoint failed its content checksum — disk-level corruption
    (the atomic write protocol rules out torn writes).  Delete the file to
    restart the fit from scratch."""


class ElasticResumeError(RuntimeError):
    """An elastic (multi-host-coordinated) checkpoint cannot resume under
    the current configuration: its identity — kernel, objective, data
    fingerprint, stack shapes — differs from the fit trying to resume.
    Changing the PROCESS COUNT is fine (the iterate is replicated and the
    expert stack re-shards); changing what is being optimized is not, and
    silently restarting from scratch (the legacy warn-and-ignore) would
    discard a pod's worth of work without a trace — hence a hard error."""


def _fsync_replace(tmp: str, path: str) -> None:
    """The preemption-safe publish: flush ``tmp`` to stable storage, then
    atomically rename over ``path`` and fsync the directory entry.  A kill
    at any instant leaves a complete old or complete new checkpoint."""
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename is still atomic
    finally:
        os.close(dir_fd)


def _payload_checksum(payload: dict) -> str:
    """sha256 over the canonical JSON of everything except the checksum
    field itself."""
    body = {k: v for k, v in payload.items() if k != "checksum"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()


def _npz_digest(arrays) -> str:
    """sha256 over sorted ``name -> ndarray`` entries (the ``checksum``
    entry excluded) — the one digest both the device writer and reader
    must agree on byte-for-byte."""
    digest = hashlib.sha256()
    for key in sorted(k for k in arrays if k != "checksum"):
        digest.update(key.encode())
        digest.update(np.ascontiguousarray(arrays[key]).tobytes())
    return digest.hexdigest()


def kernel_signature(kernel, theta_dim: int) -> str:
    """Structural identity of a kernel config (values zeroed) — guards a
    checkpoint against being resumed under a different kernel that happens
    to share the hyperparameter count."""
    return kernel.describe(np.zeros(theta_dim))


class LbfgsCheckpointer:
    """Callback for ``scipy.optimize.minimize``: saves the optimizer's
    host-visible state every iteration.

    ``tag`` (the estimator class name) keys the file so GPR and GPC fits
    sharing a directory cannot cross-contaminate.  Beyond theta the
    payload carries the iteration count (the resume budget), a bounded
    window of recent iterates (the L-BFGS history scipy walks — recorded
    for diagnosis and for external warm-starting; scipy's own internal
    curvature pairs are not injectable) and the estimator ``seed`` (the
    fit's only RNG input — restart perturbations and active-set sampling
    derive from it deterministically).
    """

    HISTORY_WINDOW = 8

    def __init__(
        self, directory: str, kernel, tag: str = "gp",
        seed: int | None = None, elastic: dict | None = None,
    ) -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"lbfgs_state_{tag}.json")
        self.kernel = kernel
        self.seed = seed
        self.elastic = elastic
        self.iteration = 0
        self._history: list[list[float]] = []

    def build_payload(self, theta) -> dict:
        """Advance the iteration state and render the (deterministic)
        payload — split from the disk write so the coordinated writer
        (``parallel/coord.py``) can digest-verify the SAME payload every
        host would have written before only process 0 persists it."""
        theta = np.asarray(theta, dtype=np.float64)
        self.iteration += 1
        self._history.append(theta.tolist())
        del self._history[: -self.HISTORY_WINDOW]
        payload = {
            "format_version": 2,
            "iteration": self.iteration,
            "theta": theta.tolist(),
            "history": list(self._history),
            "seed": self.seed,
            "kernel": self.kernel.describe(theta),
            "kernel_sig": kernel_signature(self.kernel, theta.shape[0]),
        }
        if self.elastic is not None:
            payload["elastic"] = self.elastic
        payload["checksum"] = _payload_checksum(payload)
        return payload

    def write_payload(self, payload: dict) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
            fh.flush()
        _fsync_replace(tmp, self.path)

    def __call__(self, theta) -> None:
        from spark_gp_tpu.resilience import chaos

        self.write_payload(self.build_payload(theta))
        # tick AFTER the write (like run_segmented): "kill after N save
        # boundaries" must leave N COMPLETED saves on disk
        chaos.tick_kill_counter()
        _raise_if_preempted()


def _raise_if_preempted() -> None:
    """Preemption watcher hook (``parallel/coord.py``): if SIGTERM landed,
    the save that just completed is the coordinated final save — stop here
    instead of burning the eviction grace period on doomed iterations.
    The telemetry record happens HERE (not in the signal handler, where
    lock acquisition could self-deadlock the interrupted thread)."""
    from spark_gp_tpu.parallel import coord

    if coord.preemption_requested():
        coord.note_preemption_observed()
        coord.consume_preemption()  # acted on: must not poison later fits
        raise coord.PreemptedError(
            "preemption signalled: the checkpoint just written is the "
            "final coordinated save — resume after rescheduling"
        )


def payload_state(payload: dict):
    """``(iteration, theta, kernel_sig)`` from a host-checkpoint payload
    — THE one mapping, shared by the local loader below and the
    coordinated broadcast-resume path (``models/common.py``)."""
    return (
        payload["iteration"],
        np.asarray(payload["theta"], dtype=np.float64),
        payload.get("kernel_sig"),
    )


def load_checkpoint(directory: str, tag: str = "gp"):
    """Returns ``(iteration, theta, kernel_sig)`` or ``None`` if absent.

    Raises :class:`CheckpointCorruptError` on a checksum failure (v2
    payloads; v1 files predate checksums and load as-is)."""
    payload = load_checkpoint_payload(directory, tag)
    if payload is None:
        return None
    return payload_state(payload)


def load_checkpoint_payload(directory: str, tag: str = "gp"):
    """The full checksum-verified host-checkpoint payload dict (including
    the ``elastic`` stamp when present), or ``None`` if absent."""
    path = os.path.join(directory, f"lbfgs_state_{tag}.json")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        try:
            payload = json.load(fh)
        except ValueError as exc:
            raise CheckpointCorruptError(
                f"checkpoint {path} is not valid JSON: {exc}"
            ) from exc
    stored = payload.get("checksum")
    if stored is not None and stored != _payload_checksum(payload):
        raise CheckpointCorruptError(
            f"checkpoint {path} failed its content checksum — delete it to "
            "restart the fit from scratch"
        )
    return payload


class DeviceOptimizerCheckpointer:
    """Persists the FULL on-device L-BFGS state between K-iteration segments.

    Unlike :class:`LbfgsCheckpointer` (theta-only, host optimizer), this
    round-trips the entire ``_LbfgsState`` pytree — iterate, gradient,
    curvature history, line-search counters and the aux carry (the
    classifier's latent warm-start stack) — so a killed fit resumes exactly
    where it stopped, not merely from the last theta.  Written atomically
    (tmp + rename); a checkpoint from a different configuration (shape or
    meta mismatch) is ignored with a warning rather than trusted.
    """

    def __init__(self, directory: str, tag: str = "gp",
                 elastic: dict | None = None) -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"{tag}_device_lbfgs.npz")
        self.elastic = elastic

    def build_arrays(self, state, meta: dict) -> dict:
        """The complete named-array payload (checksum included) — split
        from the disk write so the coordinated writer (parallel/coord.py)
        can digest-verify every host's state before process 0 persists."""
        import jax

        if self.elastic is not None and "elastic" not in meta:
            meta = {**meta, "elastic": self.elastic}
        leaves = jax.tree.leaves(jax.device_get(state))
        arrays = {f"leaf_{i}": np.asarray(v) for i, v in enumerate(leaves)}
        arrays["meta_json"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8
        )
        arrays["checksum"] = np.frombuffer(
            _npz_digest(arrays).encode(), dtype=np.uint8
        )
        return arrays

    def write_arrays(self, arrays: dict) -> None:
        tmp = self.path + ".tmp.npz"
        np.savez(tmp, **arrays)
        _fsync_replace(tmp, self.path)

    def save(self, state, meta: dict) -> None:
        self.write_arrays(self.build_arrays(state, meta))

    def load(self, template_state, meta: dict):
        """Rebuild a state pytree from disk, or ``None`` when absent/stale.

        ``template_state`` (a freshly-initialized state of the current
        configuration) supplies the pytree structure; the stored leaves must
        match its shapes exactly.

        The ``"elastic"`` meta key — ``(process_count, mesh_shape,
        expert_assignment)``, stamped by the coordinated multi-host path —
        is compared SEPARATELY from the fit's identity: an identity match
        with a different elastic stamp is an **elastic resume** (a
        P-process fit continuing on P' processes: the iterate is
        replicated, only the expert stack re-sharded) and loads with a
        ``coord.elastic_resumes`` metric + span event; an identity
        MISMATCH on a payload carrying an elastic stamp raises
        :class:`ElasticResumeError` — silently restarting a pod-scale fit
        from scratch is exactly the wrong-results failure mode this layer
        exists to prevent.  Legacy (stampless) payloads keep the old
        warn-and-ignore semantics."""
        import warnings

        import jax

        if not os.path.exists(self.path):
            return None
        with np.load(self.path) as npz:
            if "checksum" in npz:
                stored = bytes(npz["checksum"]).decode()
                if stored != _npz_digest({k: npz[k] for k in npz.files}):
                    raise CheckpointCorruptError(
                        f"device checkpoint {self.path} failed its content "
                        "checksum — delete it to restart the fit from scratch"
                    )
            stored_meta = json.loads(bytes(npz["meta_json"]))
            template_leaves, treedef = jax.tree.flatten(template_state)
            want_meta = dict(meta)
            if self.elastic is not None and "elastic" not in want_meta:
                want_meta["elastic"] = self.elastic
            stored_elastic = stored_meta.pop("elastic", None)
            want_elastic = want_meta.pop("elastic", None)
            if stored_meta != want_meta:
                stored_procs = (
                    (stored_elastic or {}).get("process_count") or 1
                )
                if stored_procs > 1:
                    # a COORDINATED (multi-host) payload: silently ignoring
                    # it would discard a pod's worth of training with only
                    # a warning that scrolls by — hard-error instead.
                    # Single-process payloads keep the legacy
                    # warn-and-ignore (a stale local checkpoint is cheap
                    # to redo and often deliberate).
                    diff = sorted(
                        k for k in set(stored_meta) | set(want_meta)
                        if stored_meta.get(k) != want_meta.get(k)
                    )
                    raise ElasticResumeError(
                        f"device checkpoint {self.path} was written by a "
                        f"{stored_procs}-process coordinated fit but its "
                        f"identity differs from this fit (mismatched: "
                        f"{diff}) — it cannot seed this configuration; "
                        "clear the directory or fix the config to match "
                        "the interrupted run"
                    )
                warnings.warn(
                    f"ignoring device checkpoint {self.path}: configuration "
                    f"changed ({stored_meta} != {want_meta})",
                    stacklevel=2,
                )
                return None
            stored_procs_now = (stored_elastic or {}).get("process_count")
            want_procs = (want_elastic or {}).get("process_count")
            if (
                stored_elastic is not None
                and stored_procs_now != want_procs
            ):
                # count ELASTIC resumes only — a different PROCESS COUNT
                # than the save (the catalog's definition).  A same-count
                # stamp difference (e.g. a local re-mesh) resumes fine
                # but is not an elastic transition and must not light up
                # dashboards watching this counter.
                from spark_gp_tpu.obs import trace as _trace
                from spark_gp_tpu.obs.runtime import telemetry

                telemetry.inc("coord.elastic_resumes")
                _trace.add_event(
                    "coord.elastic_resume",
                    stored_process_count=stored_procs_now,
                    current_process_count=want_procs,
                )
            leaves = []
            for i, tmpl in enumerate(template_leaves):
                key = f"leaf_{i}"
                if (
                    key not in npz
                    or npz[key].shape != tuple(tmpl.shape)
                    or npz[key].dtype != tmpl.dtype
                ):
                    warnings.warn(
                        f"ignoring device checkpoint {self.path}: state "
                        f"layout changed",
                        stacklevel=2,
                    )
                    return None
                leaves.append(npz[key])
        return jax.tree.unflatten(treedef, leaves)


def kernel_fingerprint(kernel) -> str:
    """Process-stable FULL identity of a kernel spec: the type tree plus
    every spec constant (initial values, bounds), rendered recursively.

    Guards a device checkpoint against being resumed under a different
    kernel — or the same kernel family with different bounds — that happens
    to share ``theta_dim`` (``kernel_signature``'s describe-at-zeros is
    weaker: it omits bounds and spec constants).  ``hash(kernel)`` cannot
    serve here: it hashes the type object, which is id-based and not stable
    across processes.
    """
    from spark_gp_tpu.kernels.base import Kernel

    def render(v):
        if isinstance(v, Kernel):
            inner = ",".join(render(s) for s in v._spec())
            return f"{type(v).__name__}({inner})"
        if isinstance(v, tuple):
            return "(" + ",".join(render(s) for s in v) + ")"
        return repr(v)

    return render(kernel)


def segment_meta(kind, kernel, tol, log_space, theta0, x, y, mask, **extra) -> dict:
    """One home for the segmented-fit resume guard (shared by all four
    estimator families): everything that must match for a stored optimizer
    state to be resumable — likelihood kind, full kernel identity, tol,
    parameterization, stack shapes, and a content fingerprint of the data."""
    meta = {
        "kind": str(kind),
        "kernel": kernel_fingerprint(kernel),
        "tol": float(tol),
        "log_space": bool(log_space),
        # values, not just the count: a ThetaOverrideKernel (multi-start
        # wrapper) deliberately excludes its starting point from _spec, so
        # the kernel fingerprint alone cannot distinguish two fits of the
        # same spec started from different points — a finished checkpoint
        # from start A must not answer for a fit from start B
        "theta0": [float(v) for v in np.asarray(theta0).ravel()],
        "theta_dim": int(theta0.shape[0]),
        "num_experts": int(x.shape[0]),
        "expert_size": int(x.shape[1]),
        # same-shaped but different data must not resume a finished run's
        # state (it would return the stale theta with zero iterations)
        "data_fingerprint": data_fingerprint(x, y, mask),
    }
    meta.update(extra)
    return meta


def run_segmented(init, run, saver, meta, init_args, max_iter, chunk, log_space):
    """The shared resume loop of every family's checkpointed device fit:
    load-or-init the optimizer state (``jax.eval_shape`` supplies the
    template, so a resume skips the initial objective evaluation), advance
    it in ``chunk``-iteration segments of one compiled program each
    (``run(state, iter_limit) -> state``), and persist the full state
    pytree between dispatches.  Returns ``(theta, final_state)`` with
    ``theta`` mapped back out of log space."""
    import jax
    import jax.numpy as jnp

    from spark_gp_tpu.parallel import coord
    from spark_gp_tpu.resilience import chaos

    template = jax.eval_shape(init, *init_args)
    state = saver.load(template, meta)
    if state is None:
        state = init(*init_args)
    # SIGTERM watch scoped to the segment loop (the save boundaries that
    # can act on it); previous disposition restored — and a deferred
    # signal re-delivered — when the loop exits
    with coord.preemption_watch():
        while not bool(state.done) and int(state.n_iter) < max_iter:
            limit = jnp.asarray(
                min(int(state.n_iter) + chunk, max_iter), jnp.int32
            )
            state = run(state, limit)
            saver.save(state, meta)
            chaos.tick_kill_counter()
            _raise_if_preempted()
    theta = jnp.exp(state.theta) if log_space else state.theta
    return theta, state


def data_fingerprint(*arrays) -> list:
    """Cheap content fingerprint for checkpoint-staleness checks.

    f64 sums are reduction-order-stable for the same array/program, so the
    same data reproduces the same fingerprint across runs while different
    data (even same-shaped) almost surely does not — preventing a finished
    checkpoint from short-circuiting a fit on new data.
    """
    import jax.numpy as jnp

    vals = []
    for a in arrays:
        a64 = jnp.asarray(a).astype(jnp.float64)
        vals.extend([float(jnp.sum(a64)), float(jnp.sum(a64 * a64))])
    return vals
