"""L-BFGS training-state checkpointing.

JAX has no Spark-style lineage recomputation: if a long multi-host fit dies,
the optimizer state is gone (SURVEY.md §5, failure detection).  This hook
persists the current hyperparameter iterate each L-BFGS iteration so a
restarted fit can resume from the best theta via
``GaussianProcessRegression.setKernel(restored-kernel-with-theta0)`` or by
passing ``theta0`` directly to the optimizer.
"""

from __future__ import annotations

import json
import os

import numpy as np


class LbfgsCheckpointer:
    """Callback for ``scipy.optimize.minimize``: saves theta every iteration."""

    def __init__(self, directory: str, kernel) -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "lbfgs_state.json")
        self.kernel = kernel
        self.iteration = 0

    def __call__(self, theta) -> None:
        theta = np.asarray(theta, dtype=np.float64)
        self.iteration += 1
        payload = {
            "iteration": self.iteration,
            "theta": theta.tolist(),
            "kernel": self.kernel.describe(theta),
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self.path)


def load_checkpoint(directory: str):
    """Returns ``(iteration, theta)`` or ``None`` if no checkpoint exists."""
    path = os.path.join(directory, "lbfgs_state.json")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        payload = json.load(fh)
    return payload["iteration"], np.asarray(payload["theta"], dtype=np.float64)
