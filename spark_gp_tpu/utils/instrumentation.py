"""Structured training instrumentation.

Upgrade over the reference's Spark ``Instrumentation`` usage
(GaussianProcessCommons.scala:69,89,108 — three log lines): named, timed
phases with a metrics dict, standard :mod:`logging` output, and an optional
``jax.profiler`` trace context for TPU timeline capture.

Every phase also emits a span into the unified tracer
(:mod:`spark_gp_tpu.obs.trace`) and triggers a runtime-telemetry sample at
its boundary (:mod:`spark_gp_tpu.obs.runtime`) — one instrumentation call
site, three backends (log line, timing dict, trace tree).

Thread-safety: serve shares one instance across the submit thread, the
batcher thread, and metrics readers, so ``phase``/``log_metric``'s
read-modify-writes hold the same lock discipline ``ServingMetrics`` uses
(``ServingMetrics`` re-binds ``_lock`` in its ``__init__``; parent and
subclass state share ONE lock per instance).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from spark_gp_tpu.obs import runtime as _obs_runtime
from spark_gp_tpu.obs import trace as _obs_trace

logger = logging.getLogger("spark_gp_tpu")


@dataclass
class Instrumentation:
    """Collects per-phase wall-clock timings and scalar metrics for one fit."""

    name: str = "gp"
    timings: Dict[str, float] = field(default_factory=dict)
    metrics: Dict[str, float] = field(default_factory=dict)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def log_info(self, msg: str) -> None:
        logger.info("[%s] %s", self.name, msg)

    def log_warning(self, msg: str) -> None:
        logger.warning("[%s] %s", self.name, msg)

    @contextlib.contextmanager
    def phase(self, phase_name: str):
        start = time.perf_counter()
        try:
            # the phase IS a span: a fit's phases render as one tree under
            # the fit's root span, a serve load/warmup under the batch's
            with _obs_trace.span(phase_name, instr=self.name):
                yield
        except BaseException:  # hygiene-ok: failure-marker metric only — re-raised
            # a raising phase used to record only its timing — the metric
            # context vanished and an emitted metrics dict looked identical
            # to a healthy run's.  A "<phase>.failed" marker makes serve-path
            # (and fit-path) errors visible wherever metrics are shipped.
            with self._lock:
                self.metrics[f"{phase_name}.failed"] = 1.0
            raise
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                self.timings[phase_name] = (
                    self.timings.get(phase_name, 0.0) + elapsed
                )
            logger.info("[%s] phase %s: %.3fs", self.name, phase_name, elapsed)
            # memory gauge sample on the phase boundary (no-op unless a
            # fit capture is active — obs/runtime.py)
            _obs_runtime.on_phase_boundary(self.name, phase_name)

    def log_metric(self, key: str, value: float) -> None:
        with self._lock:
            self.metrics[key] = value
        logger.info("[%s] %s = %s", self.name, key, value)

    def log_success(self) -> None:
        logger.info("[%s] training succeeded; timings=%s", self.name, self.timings)


def phase_sync(*arrays) -> None:
    """Bench-mode phase-boundary synchronization.

    The device fit paths are deliberately async-pipelined: a dispatch
    returns immediately and the single ``device_get`` in
    ``_finalize_device_fit`` absorbs all compute, which is the right
    pipelining design but makes the per-phase wall-clock breakdown
    misleading (VERDICT r3 weak #2: ``optimize_hypers: 0.0066`` /
    ``sync_fetch: 1.0976`` of a 1.1 s fit).  With ``GP_SYNC_PHASES=1``
    (set by ``bench.py``) this blocks until the phase's device outputs are
    materialized, so each phase's timing carries its own compute; in
    production it is a no-op and the pipeline stays fully async.
    """
    if not sync_enabled():
        return
    import jax

    jax.block_until_ready([a for a in arrays if a is not None])


def sync_enabled() -> bool:
    """ONE definition of the ``GP_SYNC_PHASES`` gate, read at call time
    (bench.py toggles the variable between fits and reports the mode a fit
    actually ran in — both must agree with ``phase_sync`` above)."""
    return os.environ.get("GP_SYNC_PHASES", "").strip() not in ("", "0")


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """``jax.profiler`` trace context when a directory is given, no-op else.

    With no explicit directory, ``GP_TRACE_DIR`` (read at call time, like
    ``GP_SYNC_PHASES``) activates capture — TPU timeline capture on any
    existing entry point with zero code change (docs/ROOFLINE.md)."""
    if trace_dir is None:
        trace_dir = os.environ.get("GP_TRACE_DIR", "").strip() or None
    if trace_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
