"""Model persistence.

The reference has none (its models are not MLWritable — SURVEY.md §5); this
is a deliberate capability addition.  A fitted PPA model is small and
self-contained: theta [h], active set [m, p], magicVector [m],
magicMatrix [m, m] plus the kernel spec — saved as a single ``.npz`` with the
kernel spec pickled alongside (kernel specs are plain immutable Python
objects).
"""

from __future__ import annotations

import json
import pickle

import numpy as np

from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor

# On-disk contract version.  History:
#   (absent)  — pre-versioning files (seed .. r5): same array layout as v2,
#               minus this marker; loaded as legacy without complaint.
#   2         — identical layout, explicit version marker.
# Bump this ONLY on a layout/semantics change a current loader cannot read;
# readers accept every version <= FORMAT_VERSION and refuse newer files with
# a versioned error instead of a KeyError deep in predictor construction
# (the serve registry depends on this being a stable, explicit contract).
FORMAT_VERSION = 2


class ModelFormatError(ValueError):
    """A saved model's format_version is not loadable by this build."""


def _normalize(path: str) -> str:
    """np.savez appends '.npz' to bare paths; keep save/load symmetric."""
    return path if path.endswith(".npz") else path + ".npz"


def save_model(path: str, model, kind: str) -> None:
    raw = model.raw_predictor
    extras = {}
    # the additive PPA statistics, when the model carries them: persisting
    # u1/u2 keeps a reloaded regression model incrementally updatable
    # (ProjectedProcessRawPredictor.with_additional_data)
    if getattr(raw, "u1", None) is not None:
        extras["u1"] = raw.u1
        extras["u2"] = raw.u2
    # fit provenance: the PoE/BCM aggregate is only correct when every
    # contributing expert was accounted for (Healing Products of GPs,
    # PAPERS.md) — a model trained on a P-process pod records P, so a
    # wrong-results investigation can tell "coordinated product of P
    # hosts" from "one host's 1/P fragment" after the fact.  Extra npz
    # entry: pre-provenance loaders ignore it, no format bump needed.
    import jax

    # the fit-time covariate summary (obs/quality.summarize_covariates):
    # per-dim training moments + the active-set distance sketch the serve
    # drift monitor scores incoming rows against.  Carried on the model
    # directly (a load->save round trip) or on its fit instr (a fresh fit).
    instr = getattr(model, "instr", None)
    covariate_summary = (
        getattr(model, "covariate_summary", None)
        or (getattr(instr, "covariate_summary", None) if instr else None)
    )
    # the solver lane that produced the model (ops/iterative.py) plus the
    # iterative lane's convergence stats, mirroring gram_cache_engaged:
    # an iterative-lane model carries its stochastic-solver provenance
    # permanently, so a prediction-quality investigation can tell "CG at
    # residual 1e-6" from "exact factorization" after the fact
    fit_metrics = dict(getattr(instr, "metrics", {}) or {}) if instr else {}
    solver = {
        key: fit_metrics[key]
        for key in (
            "solver_lane", "solver.cg_iters", "solver.precond_rank",
            "solver.probes", "solver.residual",
        )
        if key in fit_metrics
    }
    # the aggregation plane's provenance (models/aggregation.py): the
    # predict policy the model was fitted under plus the fit-time
    # selection outcome — serve's registry binds the policy per version
    # from this record, so a model fitted under rbcm predicts under rbcm
    # wherever it is loaded
    aggregation = {
        key: fit_metrics[key]
        for key in (
            "agg.policy", "agg.effective_experts", "agg.selection_dropped",
            "agg.renorm",
        )
        if key in fit_metrics
    }
    extras["provenance_json"] = np.frombuffer(
        json.dumps({
            "process_count": jax.process_count(),
            # the degradation ladder's transition history (resilience/
            # fallback.py): a model produced through fallback re-execution
            # says so permanently — [] for a clean fit
            "degradations": list(getattr(model, "degradations", None) or ()),
            **({"solver": solver} if solver else {}),
            **({"aggregation": aggregation} if aggregation else {}),
            **(
                {"covariate_summary": covariate_summary}
                if covariate_summary else {}
            ),
        }).encode(),
        dtype=np.uint8,
    )
    np.savez(
        _normalize(path),
        format_version=np.array(FORMAT_VERSION),
        kind=np.array(kind),
        theta=raw.theta,
        active=raw.active,
        magic_vector=raw.magic_vector,
        # mean-only models (setPredictiveVariance(False)) have no [m, m]
        # operator; an empty sentinel round-trips to None
        magic_matrix=(
            np.zeros((0, 0)) if raw.magic_matrix is None else raw.magic_matrix
        ),
        kernel_pickle=np.frombuffer(
            pickle.dumps(raw.kernel), dtype=np.uint8
        ),
        **extras,
    )
    # content-digest sidecar (resilience/integrity.py): load_model and the
    # serve registry refuse a bit-rotted artifact with a classified error
    # instead of serving whatever a flipped bit deserializes to
    from spark_gp_tpu.resilience import integrity

    integrity.write_sidecar(_normalize(path))


def load_model(path: str):
    from spark_gp_tpu.models.gpc import GaussianProcessClassificationModel
    from spark_gp_tpu.models.gpc_mc import GaussianProcessMulticlassModel
    from spark_gp_tpu.models.gp_poisson import GaussianProcessPoissonModel
    from spark_gp_tpu.models.gpr import GaussianProcessRegressionModel
    from spark_gp_tpu.resilience import integrity

    # digest-gate FIRST: a corrupted artifact must fail with its sidecar
    # named (CheckpointCorruptError, code=model_sidecar_digest_mismatch),
    # not as a pickle/npz error — or worse, load cleanly with wrong bytes
    integrity.verify_sidecar(_normalize(path))
    with np.load(_normalize(path), allow_pickle=False) as data:
        # version-gate FIRST: a future layout must fail here with its
        # version named, not as an arbitrary KeyError below
        version = (
            int(data["format_version"]) if "format_version" in data else 1
        )
        if version > FORMAT_VERSION:
            raise ModelFormatError(
                f"{_normalize(path)} was saved with model format v{version}, "
                f"but this build reads up to v{FORMAT_VERSION}. Load it with "
                "the spark_gp_tpu version that wrote it, or re-save it from "
                "there with an older format."
            )
        kind = str(data["kind"])
        kernel = pickle.loads(data["kernel_pickle"].tobytes())
        provenance = (
            json.loads(bytes(data["provenance_json"]))
            if "provenance_json" in data else None
        )
        magic_matrix = data["magic_matrix"]
        raw = ProjectedProcessRawPredictor(
            kernel=kernel,
            theta=data["theta"],
            active=data["active"],
            magic_vector=data["magic_vector"],
            magic_matrix=None if magic_matrix.size == 0 else magic_matrix,
            # absent in pre-r4 files: loads fine, update() then refuses
            u1=data["u1"] if "u1" in data else None,
            u2=data["u2"] if "u2" in data else None,
        )
    if kind == "classification":
        model = GaussianProcessClassificationModel(raw)
    elif kind == "ep_classification":
        from spark_gp_tpu.models.gpc_ep import (
            GaussianProcessEPClassificationModel,
        )

        model = GaussianProcessEPClassificationModel(raw)
    elif kind == "multiclass":
        model = GaussianProcessMulticlassModel(raw)
    elif kind == "poisson":
        model = GaussianProcessPoissonModel(raw)
    else:
        model = GaussianProcessRegressionModel(raw)
    model.provenance = provenance
    # the drift scorer's input (obs/quality.py): restore the fit-time
    # covariate summary onto the model so the serve registry can bind a
    # DriftMonitor without re-reading provenance
    model.covariate_summary = (
        provenance.get("covariate_summary") if provenance else None
    )
    if provenance and provenance.get("degradations"):
        # restore the ladder's stamp onto the model object itself, so a
        # save->load->save round trip keeps the degradation history
        # permanent instead of silently laundering it to a clean fit
        model.degradations = provenance["degradations"]
    return model
