"""Model-selection harness: cross-validation, train/validation split,
one-vs-rest multiclass, evaluators.

Thin numpy counterparts of the stock Spark ML meta-algorithms the reference
examples lean on: ``CrossValidator`` (GPExample.scala:18-24), ``OneVsRest``
(Iris.scala:27-33), ``TrainValidationSplit`` (MNIST.scala:34-38) and the
RegressionEvaluator / MulticlassClassificationEvaluator metrics.
"""

from __future__ import annotations

import copy

import numpy as np


def rmse(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    err = np.asarray(y_true) - np.asarray(y_pred)
    return float(np.sqrt(np.mean(err * err)))


def accuracy(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    return float(np.mean(np.asarray(y_true) == np.asarray(y_pred)))


accuracy.greater_is_better = True  # Spark evaluator isLargerBetter=true


def nlpd(y_true: np.ndarray, mean: np.ndarray, var: np.ndarray) -> float:
    """Mean negative log predictive density under Gaussian predictive
    marginals — the proper scoring rule RMSE is not: it penalizes both
    error and miscalibrated uncertainty (R&W eq. 2.34; standard GP
    benchmark metric).  Consumes ``model.predict_with_var`` output;
    ``cross_validate`` routes to it via the ``needs_variance`` marker."""
    y = np.asarray(y_true, dtype=np.float64)
    mu = np.asarray(mean, dtype=np.float64)
    # floor: a degenerate zero predictive variance (sigma2=0 + noise-free
    # kernel at an inducing point) must score finitely terribly, not poison
    # the whole CV mean with inf.  float64.tiny fails that purpose both
    # ways: residual^2/tiny overflows to inf, while an exactly-interpolated
    # point scores log(tiny) ~ -354 (astronomically GOOD).  1e-12 keeps the
    # penalty finite (~1e12 per unit residual^2) and caps the reward for
    # exact interpolation at log(1e-12) ~ -13.8.
    v = np.maximum(np.asarray(var, dtype=np.float64), 1e-12)
    return float(
        np.mean(0.5 * (np.log(2.0 * np.pi * v) + (y - mu) ** 2 / v))
    )


nlpd.needs_variance = True


def kfold_indices(n: int, num_folds: int, seed: int = 0):
    """Shuffled k-fold split; yields (train_idx, test_idx)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    folds = np.array_split(perm, num_folds)
    for i in range(num_folds):
        test = folds[i]
        train = np.concatenate([folds[j] for j in range(num_folds) if j != i])
        yield train, test


class ParamGridBuilder:
    """Cartesian parameter grid over estimator SETTER names — the
    counterpart of Spark ML's ``ParamGridBuilder`` (Iris.scala:29-33, wired
    there with an empty grid).  Values are applied via
    ``getattr(est, name)(value)``, so any reference-named setter
    (``setSigma2``, ``setActiveSetSize``, ...) works unchanged::

        grid = (ParamGridBuilder()
                .addGrid("setSigma2", [1e-3, 1e-2])
                .addGrid("setActiveSetSize", [50, 100])
                .build())                       # 4 cells
    """

    def __init__(self):
        self._grid: dict = {}

    def addGrid(self, setter_name: str, values) -> "ParamGridBuilder":
        self._grid[setter_name] = list(values)
        return self

    def build(self) -> list:
        cells = [{}]
        for name, values in self._grid.items():
            cells = [dict(c, **{name: v}) for c in cells for v in values]
        return cells


class CrossValidationResult:
    """Grid-search outcome: per-cell mean scores, the winning cell, and
    (when ``refit``) the model refitted on the full data with the winning
    config — CrossValidator's ``bestModel`` semantics."""

    def __init__(self, scores, best_params, best_score, best_model):
        self.scores = scores          # list of (params_dict, mean_score)
        self.best_params = best_params
        self.best_score = best_score
        self.best_model = best_model

    def __repr__(self):
        return (
            f"CrossValidationResult(best_params={self.best_params}, "
            f"best_score={self.best_score:.6g}, cells={len(self.scores)})"
        )


def _apply_params(estimator, params: dict):
    est = copy.copy(estimator)
    for name, value in params.items():
        setter = getattr(est, name)
        ret = setter(value)
        # reference setters chain (return this); tolerate void setters too
        est = ret if ret is not None else est
    return est


def _score_folds(estimator, x, y, num_folds, metric, seed) -> float:
    scores = []
    for train_idx, test_idx in kfold_indices(x.shape[0], num_folds, seed):
        est = copy.copy(estimator)
        model = est.fit(x[train_idx], y[train_idx])
        if getattr(metric, "needs_variance", False):
            mean, var = model.predict_with_var(x[test_idx])
            scores.append(metric(y[test_idx], mean, var))
        else:
            scores.append(metric(y[test_idx], model.predict(x[test_idx])))
    return float(np.mean(scores))


def cross_validate(
    estimator,
    x: np.ndarray,
    y: np.ndarray,
    num_folds: int = 10,
    metric=rmse,
    seed: int = 0,
    param_grid=None,
    refit: bool = True,
):
    """K-fold cross-validation, optionally grid-searched.

    With ``param_grid=None`` (every reference example: CrossValidator with
    an empty grid, GPExample.scala:18-24) returns the mean metric over the
    folds as a float — the historical signature.

    With ``param_grid`` (a ``ParamGridBuilder().build()`` list, or any list
    of ``{setter_name: value}`` dicts) evaluates every cell on the SAME
    fold split, picks the best mean score — direction from
    ``metric.greater_is_better`` (default: lower is better, matching
    rmse/nlpd) — and, when ``refit``, refits the winning config on the full
    data.  Returns a :class:`CrossValidationResult`.
    """
    x = np.asarray(x)
    y = np.asarray(y)
    if param_grid is None:
        return _score_folds(estimator, x, y, num_folds, metric, seed)

    cells = list(param_grid) or [{}]
    larger_better = bool(getattr(metric, "greater_is_better", False))
    scores = []
    for params in cells:
        est = _apply_params(estimator, params)
        scores.append((dict(params), _score_folds(est, x, y, num_folds, metric, seed)))
    # a NaN-scoring cell (degenerate fit) must never win: min/max keep a
    # NaN first element because every comparison with NaN is False
    finite = [ps for ps in scores if np.isfinite(ps[1])]
    if not finite:
        raise ValueError(
            "every param-grid cell produced a non-finite CV score; "
            f"scores={scores}"
        )
    pick = max if larger_better else min
    best_params, best_score = pick(finite, key=lambda ps: ps[1])
    best_model = None
    if refit:
        best_model = _apply_params(estimator, best_params).fit(x, y)
    return CrossValidationResult(scores, best_params, best_score, best_model)


def train_validation_split(
    estimator,
    x: np.ndarray,
    y: np.ndarray,
    train_ratio: float = 0.8,
    metric=accuracy,
    seed: int = 0,
) -> float:
    """Single split fit/eval (TrainValidationSplit, MNIST.scala:34-38)."""
    x = np.asarray(x)
    y = np.asarray(y)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(x.shape[0])
    cut = int(train_ratio * x.shape[0])
    train_idx, test_idx = perm[:cut], perm[cut:]
    model = estimator.fit(x[train_idx], y[train_idx])
    return metric(y[test_idx], model.predict(x[test_idx]))


class OneVsRest:
    """Multiclass reduction over a binary classifier exposing
    ``predict_raw`` — the counterpart of Spark ML's OneVsRest
    (Iris.scala:26-27).  Picks the class whose binary model emits the largest
    positive raw score."""

    def __init__(self, classifier_factory):
        """``classifier_factory() -> estimator`` (a fresh estimator per class)."""
        self.classifier_factory = classifier_factory
        self.models_ = None
        self.classes_ = None

    def fit(self, x: np.ndarray, y: np.ndarray) -> "OneVsRest":
        x = np.asarray(x)
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        self.models_ = []
        for cls in self.classes_:
            est = self.classifier_factory()
            self.models_.append(est.fit(x, (y == cls).astype(np.float64)))
        return self

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        scores = np.stack(
            [m.predict_raw(x_test)[:, 1] for m in self.models_], axis=1
        )
        return self.classes_[np.argmax(scores, axis=1)]

    def predict_proba(self, x_test: np.ndarray) -> np.ndarray:
        """``[t, C]`` normalized per-class sigmoid scores.

        The C binary models are fitted independently, so their sigmoids
        need not sum to 1; this renormalizes them (the standard OvR
        calibration compromise — for jointly calibrated probabilities use
        the native ``GaussianProcessMulticlassClassifier``).  Computed in
        log space (softmax over ``log_sigmoid`` of the raw latents), so
        sigmoid saturation can neither zero out a row nor flip the argmax
        away from :meth:`predict`.  Column order follows ``classes_``.
        """
        from scipy.special import log_expit, softmax

        latents = np.stack(
            [m.predict_raw(x_test)[:, 1] for m in self.models_], axis=1
        )
        return softmax(log_expit(latents), axis=1)
