"""Model-selection harness: cross-validation, train/validation split,
one-vs-rest multiclass, evaluators.

Thin numpy counterparts of the stock Spark ML meta-algorithms the reference
examples lean on: ``CrossValidator`` (GPExample.scala:18-24), ``OneVsRest``
(Iris.scala:27-33), ``TrainValidationSplit`` (MNIST.scala:34-38) and the
RegressionEvaluator / MulticlassClassificationEvaluator metrics.
"""

from __future__ import annotations

import copy

import numpy as np


def rmse(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    err = np.asarray(y_true) - np.asarray(y_pred)
    return float(np.sqrt(np.mean(err * err)))


def accuracy(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    return float(np.mean(np.asarray(y_true) == np.asarray(y_pred)))


def nlpd(y_true: np.ndarray, mean: np.ndarray, var: np.ndarray) -> float:
    """Mean negative log predictive density under Gaussian predictive
    marginals — the proper scoring rule RMSE is not: it penalizes both
    error and miscalibrated uncertainty (R&W eq. 2.34; standard GP
    benchmark metric).  Consumes ``model.predict_with_var`` output;
    ``cross_validate`` routes to it via the ``needs_variance`` marker."""
    y = np.asarray(y_true, dtype=np.float64)
    mu = np.asarray(mean, dtype=np.float64)
    # floor: a degenerate zero predictive variance (sigma2=0 + noise-free
    # kernel at an inducing point) must score astronomically badly, not
    # poison the whole CV mean with log(0)/0-division inf/nan
    v = np.maximum(np.asarray(var, dtype=np.float64), np.finfo(np.float64).tiny)
    return float(
        np.mean(0.5 * (np.log(2.0 * np.pi * v) + (y - mu) ** 2 / v))
    )


nlpd.needs_variance = True


def kfold_indices(n: int, num_folds: int, seed: int = 0):
    """Shuffled k-fold split; yields (train_idx, test_idx)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    folds = np.array_split(perm, num_folds)
    for i in range(num_folds):
        test = folds[i]
        train = np.concatenate([folds[j] for j in range(num_folds) if j != i])
        yield train, test


def cross_validate(
    estimator,
    x: np.ndarray,
    y: np.ndarray,
    num_folds: int = 10,
    metric=rmse,
    seed: int = 0,
) -> float:
    """Mean metric over k folds (CrossValidator with an empty param grid —
    exactly how every reference example uses it)."""
    x = np.asarray(x)
    y = np.asarray(y)
    scores = []
    for train_idx, test_idx in kfold_indices(x.shape[0], num_folds, seed):
        est = copy.copy(estimator)
        model = est.fit(x[train_idx], y[train_idx])
        if getattr(metric, "needs_variance", False):
            mean, var = model.predict_with_var(x[test_idx])
            scores.append(metric(y[test_idx], mean, var))
        else:
            scores.append(metric(y[test_idx], model.predict(x[test_idx])))
    return float(np.mean(scores))


def train_validation_split(
    estimator,
    x: np.ndarray,
    y: np.ndarray,
    train_ratio: float = 0.8,
    metric=accuracy,
    seed: int = 0,
) -> float:
    """Single split fit/eval (TrainValidationSplit, MNIST.scala:34-38)."""
    x = np.asarray(x)
    y = np.asarray(y)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(x.shape[0])
    cut = int(train_ratio * x.shape[0])
    train_idx, test_idx = perm[:cut], perm[cut:]
    model = estimator.fit(x[train_idx], y[train_idx])
    return metric(y[test_idx], model.predict(x[test_idx]))


class OneVsRest:
    """Multiclass reduction over a binary classifier exposing
    ``predict_raw`` — the counterpart of Spark ML's OneVsRest
    (Iris.scala:26-27).  Picks the class whose binary model emits the largest
    positive raw score."""

    def __init__(self, classifier_factory):
        """``classifier_factory() -> estimator`` (a fresh estimator per class)."""
        self.classifier_factory = classifier_factory
        self.models_ = None
        self.classes_ = None

    def fit(self, x: np.ndarray, y: np.ndarray) -> "OneVsRest":
        x = np.asarray(x)
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        self.models_ = []
        for cls in self.classes_:
            est = self.classifier_factory()
            self.models_.append(est.fit(x, (y == cls).astype(np.float64)))
        return self

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        scores = np.stack(
            [m.predict_raw(x_test)[:, 1] for m in self.models_], axis=1
        )
        return self.classes_[np.argmax(scores, axis=1)]

    def predict_proba(self, x_test: np.ndarray) -> np.ndarray:
        """``[t, C]`` normalized per-class sigmoid scores.

        The C binary models are fitted independently, so their sigmoids
        need not sum to 1; this renormalizes them (the standard OvR
        calibration compromise — for jointly calibrated probabilities use
        the native ``GaussianProcessMulticlassClassifier``).  Computed in
        log space (softmax over ``log_sigmoid`` of the raw latents), so
        sigmoid saturation can neither zero out a row nor flip the argmax
        away from :meth:`predict`.  Column order follows ``classes_``.
        """
        from scipy.special import log_expit, softmax

        latents = np.stack(
            [m.predict_raw(x_test)[:, 1] for m in self.models_], axis=1
        )
        return softmax(log_expit(latents), axis=1)
