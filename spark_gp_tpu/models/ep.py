"""Expectation Propagation for binary GP classification (probit link).

Second inference engine beside the Laplace approximation (models/laplace.py)
— R&W ch. 3.6, Algorithms 3.5/3.6.  Capability beyond the reference
(akopich/spark-gp ships Laplace only): EP's Gaussian site approximations
match the per-site MOMENTS of the true posterior rather than its curvature
at the mode, which is known to calibrate binary-GP probabilities better
(Kuss & Rasmussen 2005), and the probit likelihood makes every moment
closed-form — no quadrature anywhere.

TPU re-design (vs the textbook's sequential site sweeps):

* **parallel EP**: every site updates simultaneously from the current
  posterior marginals — each iteration is ONE batched ``[E, s, s]``
  factorization (the same ``B = I + sqrt(T) K sqrt(T)`` form and fused
  batched Cholesky as the Laplace/GPR objectives) plus elementwise
  cavity/moment math on the VPU, instead of s rank-1 updates with
  data-dependent ordering.  Damping keeps the parallel fixed-point
  iteration stable (standard practice; see e.g. van Gerven et al. 2009).
* sites are carried as natural parameters ``(tau~, nu~) [E, s]`` with the
  same explicit-carry warm-start pattern as the Laplace latents: the
  optimizer threads them across hyperparameter evaluations.
* the EP log marginal likelihood log Z_EP (R&W eq. 3.65, in the
  numerically robust form of Alg 3.5 lines 52-58) is evaluated at the
  CONVERGED sites under ``stop_gradient``: at an EP fixed point the
  site-parameter sensitivities vanish from the gradient (Seeger 2005),
  so ``jax.grad`` of this expression w.r.t. theta reproduces the explicit
  formula R&W eq. 3.80 derives by hand — the same implicit-gradient trick
  the Laplace/multiclass modules use for their mode.

Labels follow the reference classifier's {0, 1} convention at the API and
are mapped to probit's native {-1, +1} internally.  Padded slots carry
zero site precision, contribute unit rows to B and zero to every sum.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.linalg import masked_kernel_matrix
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

_LOG2PI = 1.8378770664093453


def _log_ndtr(z):
    """log Phi(z), numerically stable on both tails."""
    return jax.scipy.special.log_ndtr(z)


def _npdf_over_cdf(z):
    """N(z; 0, 1) / Phi(z), stable for very negative z (where the ratio
    approaches -z): exp(log pdf - log cdf)."""
    log_pdf = -0.5 * (z * z + _LOG2PI)
    return jnp.exp(log_pdf - _log_ndtr(z))


class _EPState(NamedTuple):
    tau: jax.Array  # [E, s] site precisions (>= 0)
    nu: jax.Array  # [E, s] site precision-mean products
    delta: jax.Array  # scalar: max site-param change of the last sweep
    it: jax.Array  # int32


def _posterior_marginals(kmat, tau, nu):
    """Diagonal of Sigma = (K^-1 + diag(tau))^-1 and mu = Sigma nu, via the
    stable B-form (R&W eq. 3.66-3.68): Sigma = K - K S B^-1 S K with
    S = sqrt(tau), B = I + S K S — one batched Cholesky per call."""
    from spark_gp_tpu.ops.linalg import cholesky

    s = kmat.shape[-1]
    sq = jnp.sqrt(tau)
    eye = jnp.eye(s, dtype=kmat.dtype)
    b_mat = eye[None] + sq[:, :, None] * kmat * sq[:, None, :]
    chol_l = cholesky(b_mat)
    # V = L^-1 S K  ->  Sigma = K - V^T V
    v = jax.lax.linalg.triangular_solve(
        chol_l, sq[:, :, None] * kmat, left_side=True, lower=True
    )
    sigma_diag = jnp.diagonal(kmat, axis1=-2, axis2=-1) - jnp.sum(
        v * v, axis=-2
    )
    kn = jnp.einsum("eij,ej->ei", kmat, nu)
    mu = kn - jnp.einsum("eji,ej->ei", v, jnp.einsum("eij,ej->ei", v, nu))
    return sigma_diag, mu, chol_l


def _cavity(tau, nu, sigma_diag, mu):
    """Cavity parameters from the current posterior marginals — ONE home
    for the guards (non-positive cavity precision from float noise is
    clipped far below any meaningful precision): the fixed point the sites
    converge to and the marginal likelihood evaluated at it must use the
    identical cavity, or the stop_gradient implicit-gradient assumption
    breaks."""
    tau_cav = jnp.maximum(1.0 / jnp.maximum(sigma_diag, 1e-300) - tau, 1e-10)
    nu_cav = mu / jnp.maximum(sigma_diag, 1e-300) - nu
    mu_cav = nu_cav / tau_cav
    s2_cav = 1.0 / tau_cav
    return tau_cav, nu_cav, mu_cav, s2_cav


def _site_update(y_pm, mask, tau, nu, sigma_diag, mu):
    """One parallel moment-matching pass (R&W Alg 3.5 lines 5-13, all sites
    at once).  Returns undamped new site parameters."""
    tau_cav, nu_cav, mu_cav, s2_cav = _cavity(tau, nu, sigma_diag, mu)

    # probit moments (R&W eq. 3.58)
    denom = jnp.sqrt(1.0 + s2_cav)
    z = y_pm * mu_cav / denom
    ratio = _npdf_over_cdf(z)
    mu_hat = mu_cav + y_pm * s2_cav * ratio / denom
    s2_hat = s2_cav - s2_cav**2 * ratio / (1.0 + s2_cav) * (z + ratio)

    tau_new = jnp.maximum(1.0 / jnp.maximum(s2_hat, 1e-300) - tau_cav, 0.0)
    nu_new = mu_hat / jnp.maximum(s2_hat, 1e-300) - nu_cav
    # Invariant _ep_log_z's guards rely on: a zero-precision site carries
    # zero nu.  The clamp above can fire from float cancellation
    # (s2_hat == s2_cav to precision at extreme theta) with nu_new still
    # nonzero — zero it so the site is exactly flat, not inconsistent.
    nu_new = jnp.where(tau_new > 0.0, nu_new, 0.0)
    # padded slots stay exactly zero-precision
    return tau_new * mask, nu_new * mask


def ep_fit_sites(kmat, y_pm, mask, tau0, nu0, tol, max_sweeps=60, damping=0.7):
    """Run damped parallel EP to (approximate) fixed point.

    Returns ``(tau, nu, sweeps)``.  Not differentiated — the marginal
    likelihood consumes the converged sites under stop_gradient.
    """
    dtype = kmat.dtype
    # deriving the scalar carry from the (possibly sharded) data keeps its
    # device-variance type consistent with the body's outputs under
    # shard_map — a literal constant is "replicated" while the body's delta
    # is "varying", and lax.while_loop requires matching carry types
    # (laplace.py's zero-carry rationale)
    zero = jnp.zeros((), dtype) + 0.0 * jnp.sum(tau0)
    init = _EPState(
        tau=tau0,
        nu=nu0,
        delta=zero + jnp.inf,
        it=jnp.zeros((), jnp.int32),
    )

    def cond(st: _EPState):
        return jnp.logical_and(st.delta > tol, st.it < max_sweeps)

    def body(st: _EPState):
        sigma_diag, mu, _ = _posterior_marginals(kmat, st.tau, st.nu)
        tau_new, nu_new = _site_update(
            y_pm, mask, st.tau, st.nu, sigma_diag, mu
        )
        tau_d = (1.0 - damping) * st.tau + damping * tau_new
        nu_d = (1.0 - damping) * st.nu + damping * nu_new
        delta = jnp.maximum(
            jnp.max(jnp.abs(tau_d - st.tau)), jnp.max(jnp.abs(nu_d - st.nu))
        )
        return _EPState(tau=tau_d, nu=nu_d, delta=delta, it=st.it + 1)

    final = jax.lax.while_loop(cond, body, init)
    return final.tau, final.nu, final.it


def _ep_log_z(kmat, y_pm, mask, tau, nu):
    """log Z_EP at given sites, per expert — differentiable in ``kmat``.

    Derivation (R&W sec. 3.6, eq. 3.65, taken to natural parameters so
    zero-precision sites — padded slots, untouched sites — are exact):
    with sites ``t_i(f) = Ztilde_i N(f; mu_t_i, 1/tau_i)``,

        Z_EP = (prod_i Ztilde_i) * N(mu_t; 0, K + T^-1),

    and the moment-matching normalizer (R&W eq. 3.59)

        log Ztilde_i = log Phi(z_i) + 1/2 log(2 pi (s2cav_i + 1/tau_i))
                       + (mucav_i - mu_t_i)^2 / (2 (s2cav_i + 1/tau_i)).

    In natural parameters the 2 pi and log tau terms cancel between the
    product of normalizers and the Gaussian convolution
    (|K + T^-1| = |B| / prod tau), leaving

        log Z_EP = sum_i m_i [ log Phi(z_i) + 1/2 log1p(tau_i s2cav_i)
                     + (tau_i mucav_i^2 - 2 mucav_i nu_i + nu_i^2/tau_i)
                       / (2 (1 + tau_i s2cav_i)) ]
                   - 1/2 log|B| - 1/2 |L^-1 u|^2,   u_i = nu_i / sqrt(tau_i)

    with cavity params from the converged posterior marginals.  A
    zero-precision site has nu_i = 0 too: every guarded ratio is exactly 0
    and the slot contributes nothing (beyond its unit row in B).
    Verified against brute-force numerical integration of
    ``int Phi(y1 f1) Phi(y2 f2) N(f; 0, K) df`` in tests/test_ep.py.
    """
    from spark_gp_tpu.ops.linalg import chol_logdet

    sigma_diag, mu, chol_l = _posterior_marginals(kmat, tau, nu)
    _, _, mu_cav, s2_cav = _cavity(tau, nu, sigma_diag, mu)

    z = y_pm * mu_cav / jnp.sqrt(1.0 + s2_cav)
    term_sites = jnp.sum(_log_ndtr(z) * mask, axis=-1)

    pos = tau > 0.0
    r = tau * s2_cav
    nu2_over_tau = jnp.where(pos, nu * nu / jnp.where(pos, tau, 1.0), 0.0)
    term_norm = 0.5 * jnp.sum(mask * jnp.log1p(r), axis=-1)
    term_match = 0.5 * jnp.sum(
        mask * (tau * mu_cav**2 - 2.0 * mu_cav * nu + nu2_over_tau) / (1.0 + r),
        axis=-1,
    )

    half_logdet_b = 0.5 * chol_logdet(chol_l)
    u = jnp.where(pos, nu / jnp.sqrt(jnp.where(pos, tau, 1.0)), 0.0)
    w = jax.lax.linalg.triangular_solve(
        chol_l, u[..., None], left_side=True, lower=True
    )[..., 0]
    quad = 0.5 * jnp.sum(w * w, axis=-1)

    return term_sites + term_norm + term_match - half_logdet_b - quad


def batched_neg_logz_ep(
    kernel: Kernel, tol, theta, data: ExpertData, sites0, weights=None
):
    """Summed ``-log Z_EP`` over the local expert stack with gradient via
    the converged-sites stop_gradient trick; returns
    ``(nll, grad, (tau, nu))`` with the sites as the optimizer's warm-start
    carry (the Laplace latents' pattern).  ``weights`` is the aggregation
    plane's ``[E]`` per-expert vector (``models/aggregation.py``);
    ``None`` keeps the sum bit-for-bit."""
    from spark_gp_tpu.models.aggregation import weighted_expert_sum

    tau0, nu0 = sites0
    y_pm = (2.0 * data.y - 1.0) * data.mask  # {0,1} -> {-1,+1}, masked

    def nll(theta_):
        kmat = jax.vmap(
            lambda x, m: masked_kernel_matrix(kernel.gram(theta_, x), m)
        )(data.x, data.mask)
        tau, nu, _ = ep_fit_sites(
            jax.lax.stop_gradient(kmat), y_pm, data.mask, tau0, nu0, tol
        )
        tau = jax.lax.stop_gradient(tau)
        nu = jax.lax.stop_gradient(nu)
        log_z = _ep_log_z(kmat, y_pm, data.mask, tau, nu)
        return -weighted_expert_sum(log_z, weights), (tau, nu)

    (value, sites), grad = jax.value_and_grad(nll, has_aux=True)(theta)
    return value, grad, sites


@partial(jax.jit, static_argnums=(0, 1))
def _ep_vag_impl(kernel: Kernel, tol, theta, x, y, mask, tau0, nu0):
    data = ExpertData(x=x, y=y, mask=mask)
    return batched_neg_logz_ep(kernel, tol, theta, data, (tau0, nu0))


def make_ep_objective(kernel: Kernel, data: ExpertData, tol):
    """Single-device jitted ``(theta, (tau, nu)) -> (nll, grad, sites)``."""

    def obj(theta, sites):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        return _ep_vag_impl(
            kernel, float(tol), theta, data.x, data.y, data.mask, *sites
        )

    return obj


def make_sharded_ep_objective(kernel: Kernel, data: ExpertData, tol, mesh):
    """Sharded objective: experts and sites sharded, (value, grad)
    psum-reduced over ICI — the treeAggregate of GPC.scala:73-78 for the
    EP engine."""

    @partial(jax.jit, static_argnums=(0, 1, 2))
    def impl(kernel_, tol_, mesh_, theta, x, y, mask, tau0, nu0):
        @partial(
            jax.shard_map,
            mesh=mesh_,
            in_specs=(
                P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
                P(EXPERT_AXIS), P(EXPERT_AXIS),
            ),
            out_specs=(P(), P(), (P(EXPERT_AXIS), P(EXPERT_AXIS))),
        )
        def core(theta_, x_, y_, mask_, tau_, nu_):
            local = ExpertData(x=x_, y=y_, mask=mask_)
            value, grad, sites = batched_neg_logz_ep(
                kernel_, tol_, theta_, local, (tau_, nu_)
            )
            return (
                jax.lax.psum(value, EXPERT_AXIS),
                jax.lax.psum(grad, EXPERT_AXIS),
                sites,
            )

        return core(theta, x, y, mask, tau0, nu0)

    def obj(theta, sites):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        return impl(
            kernel, float(tol), mesh, theta, data.x, data.y, data.mask, *sites
        )

    return obj


@partial(jax.jit, static_argnums=0)
def ep_posterior_mean(kernel: Kernel, theta, x, mask, tau, nu):
    """Posterior latent mean at (theta, converged sites) — the PPA targets
    (GPClf.scala:62-65's substitution with EP's mu in place of the mode).
    Depends only on (theta, x, mask) and the sites, never the labels."""
    kmat = jax.vmap(
        lambda xe, me: masked_kernel_matrix(kernel.gram(theta, xe), me)
    )(x, mask)
    _, mu, _ = _posterior_marginals(kmat, tau, nu)
    return mu * mask


@partial(jax.jit, static_argnums=(0, 1, 2))
def fit_gpc_ep_device(
    kernel: Kernel, tol, log_space, theta0, lower, upper, x, y, mask, max_iter
):
    """Single-chip on-device EP classifier fit: the site pair rides as the
    optimizer's aux pytree carry (the Laplace latents' pattern — the
    optimizer is generic over the carry, so EP plugs straight in).
    Returns ``(theta, (tau, nu), latent_mu, nll, n_iter, n_fev,
    stalled)`` — the latent posterior mean (the PPA targets) is computed
    inside the same dispatch."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )

    data = ExpertData(x=x, y=y, mask=mask)

    def vag(theta, sites):
        return batched_neg_logz_ep(kernel, tol, theta, data, sites)

    if log_space:
        vag, theta0, lower, upper, from_u = log_reparam(vag, theta0, lower, upper)
    else:
        from_u = lambda t: t

    sites0 = (jnp.zeros_like(y), jnp.zeros_like(y))
    theta, f, sites, n_iter, n_fev, stalled = lbfgs_minimize_device(
        vag, theta0, lower, upper, sites0, max_iter=max_iter, tol=tol
    )
    theta = from_u(theta)
    # latent mean at (theta*, converged sites) INSIDE the same dispatch —
    # the PPA targets, without a second program recomputing the Gram stack
    kmat = jax.vmap(
        lambda xe, me: masked_kernel_matrix(kernel.gram(theta, xe), me)
    )(x, mask)
    _, mu, _ = _posterior_marginals(kmat, *sites)
    return theta, sites, mu * mask, f, n_iter, n_fev, stalled


@partial(jax.jit, static_argnums=(0, 1, 2))
def fit_gpc_ep_device_multistart(
    kernel: Kernel, tol, log_space, theta0_batch, lower, upper, x, y, mask,
    max_iter,
):
    """Multi-start single-chip EP fit: R restarts as ONE vmapped device
    program, the site pairs riding per lane.  Returns ``(theta_best,
    latent_mu_best, nll_best, n_iter, n_fev, stalled, f_all [R], best)``
    — the winner's latent mean computed in the same dispatch."""
    from spark_gp_tpu.optimize.lbfgs_device import multistart_minimize

    data = ExpertData(x=x, y=y, mask=mask)

    def vag(theta, sites):
        return batched_neg_logz_ep(kernel, tol, theta, data, sites)

    sites0 = (jnp.zeros_like(y), jnp.zeros_like(y))
    theta, sites, f, n_iter, n_fev, stalled, f_all, best = (
        multistart_minimize(
            vag, log_space, theta0_batch, lower, upper, sites0, max_iter, tol
        )
    )
    kmat = jax.vmap(
        lambda xe, me: masked_kernel_matrix(kernel.gram(theta, xe), me)
    )(x, mask)
    _, mu, _ = _posterior_marginals(kmat, *sites)
    return theta, mu * mask, f, n_iter, n_fev, stalled, f_all, best


@partial(jax.jit, static_argnums=(0, 1, 2, 3))
def fit_gpc_ep_device_sharded(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper, x, y, mask,
    max_iter,
):
    """Multi-chip on-device EP fit inside one shard_map: sites stay
    device-resident and sharded for the entire optimization (the EP
    counterpart of laplace.fit_gpc_device_sharded)."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(), P(), P(),
            P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
            P(),
        ),
        out_specs=(
            P(), (P(EXPERT_AXIS), P(EXPERT_AXIS)), P(EXPERT_AXIS),
            P(), P(), P(), P(),
        ),
    )
    def run(theta0_, lower_, upper_, x_, y_, mask_, max_iter_):
        local = ExpertData(x=x_, y=y_, mask=mask_)

        def vag(theta, sites):
            value, grad, sites_new = batched_neg_logz_ep(
                kernel, tol, theta, local, sites
            )
            return (
                jax.lax.psum(value, EXPERT_AXIS),
                jax.lax.psum(grad, EXPERT_AXIS),
                sites_new,
            )

        if log_space:
            vag, t0, lo, hi, from_u = log_reparam(vag, theta0_, lower_, upper_)
        else:
            vag, t0, lo, hi, from_u = vag, theta0_, lower_, upper_, (lambda t: t)

        sites0 = (jnp.zeros_like(y_), jnp.zeros_like(y_))
        theta, f, sites, n_iter, n_fev, stalled = lbfgs_minimize_device(
            vag, t0, lo, hi, sites0, max_iter=max_iter_, tol=tol
        )
        theta = from_u(theta)
        kmat = jax.vmap(
            lambda xe, me: masked_kernel_matrix(kernel.gram(theta, xe), me)
        )(x_, mask_)
        _, mu, _ = _posterior_marginals(kmat, *sites)
        return theta, sites, mu * mask_, f, n_iter, n_fev, stalled

    return run(theta0, lower, upper, x, y, mask, max_iter)


