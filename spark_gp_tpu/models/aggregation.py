"""Expert aggregation plane: predict-time weighting + fit-time selection.

The reference's plain product-of-experts sum (GPC.scala:73-78,
GaussianProcessCommons.scala:73-78) treats every expert identically: at
predict time each expert's precision enters the product at unit weight
(overconfident in data voids — *Healing Products of Gaussian Processes*,
arXiv 2102.07106), and at fit time every expert pays its full
Cholesky/CG evaluation even when its chunk duplicates another expert's
information (*Gaussian Experts Selection using Graphical Models*,
arXiv 2102.01496).  This module is the ONE home of both remedies:

**Predict-time policy** — ``GP_AGG_POLICY`` in {``poe`` (default, today's
plain product bit-for-bit), ``gpoe`` (differential-entropy/uniform
beta = 1/E weights), ``rbcm`` (prior-corrected entropy-weighted
precisions, Deisenroth & Ng ICML'15), ``healed`` (normalized entropy
weights — a convex combination of expert precisions that can never be
more confident than its sharpest expert)}.  Selection mirrors the
precision (``ops/precision.py``) and solver (``ops/iterative.py``)
lanes: :func:`set_agg_policy` process-wide /
``GaussianProcessParams.setAggregationPolicy`` fluent veneer /
:func:`agg_policy_scope` trace-local, resolved into the predict
programs' jit cache keys via :func:`agg_jit_key` so a policy switch
recompiles instead of reusing the old policy's executables.  The weight
formulas themselves live in ``models/poe.py`` (`_local_moments` /
`_aggregate`) — they are vmapped per-expert reductions riding the
existing chunking, sharding and precision lanes.

**Fit-time selection** — ``GP_AGG_SELECT=1`` scores expert redundancy
from order-invariant random-feature sketches of each expert's (x, y)
rows BEFORE any Cholesky/CG evaluation is paid, and drops (or
down-weights, ``GP_AGG_SELECT_MODE=downweight``) the redundant ones.
Drop mode physically compacts the stack to the kept experts — the
``[E, s, s]`` batch shrinks, so the redundant experts' factorizations
are never paid at all — while the weight ALGEBRA is shared with
quarantine (``ExpertData.with_experts_masked``: a masked expert's Gram
becomes an inert identity block contributing exactly 0 to every
reduction, so mid-fit ``w_e = 0`` composes with the weighted-NLL sum by
construction).  ``quarantine.renorm_factor`` generalizes to
:func:`weighted_renorm_factor` and the per-expert weights ride the fit
objectives as the optional ``weights`` operand of
``likelihood.batched_nll`` / ``loo.batched_loo_nll`` / the Laplace
families' :func:`weighted_expert_sum`.

Why sketches are centered: round-robin grouping deals experts iid rows
of ONE distribution, so every expert's mean feature vector converges to
the same expectation — raw cosine similarity of mean-feature sketches
would read "everything is redundant".  Centering across the stack keeps
only each expert's sampling fluctuation: independent chunks give
near-orthogonal residuals (cosine ~ 0 in high sketch dimension) while
duplicated/overlapping chunks share their fluctuation (cosine ~ 1).
Exactly-identical sketches are additionally caught on the raw vectors
(their centered residuals can cancel when nearly the whole stack is one
duplicate class).
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

# --------------------------------------------------------------------------
# the aggregation-policy lane (the solver-lane pattern, ops/iterative.py)
# --------------------------------------------------------------------------

AGG_POLICIES = ("poe", "gpoe", "rbcm", "healed")

_POLICY_OVERRIDE: Optional[str] = None
_SCOPE = threading.local()


def _validate_policy(policy, source: str) -> str:
    policy = str(policy).strip().lower()
    if policy not in AGG_POLICIES:
        raise ValueError(
            f"{source}={policy!r} is not an aggregation policy; use one of "
            f"{sorted(AGG_POLICIES)}"
        )
    return policy


def active_agg_policy() -> str:
    """The policy in effect: innermost :func:`agg_policy_scope`, else the
    :func:`set_agg_policy` process override, else ``GP_AGG_POLICY``, else
    ``poe`` (today's plain product, bit-for-bit)."""
    scoped = getattr(_SCOPE, "policy", None)
    if scoped is not None:
        return scoped
    if _POLICY_OVERRIDE is not None:
        return _POLICY_OVERRIDE
    env = os.environ.get("GP_AGG_POLICY")
    if env is None or not env.strip():
        return "poe"
    return _validate_policy(env, "GP_AGG_POLICY")


def set_agg_policy(policy):
    """Process-wide policy setter (the programmatic twin of
    ``GP_AGG_POLICY``).  ``None`` clears the override.  Returns the
    previous override so callers can restore it.  The PoE predictor
    carries the resolved policy in its jit cache keys (it is a static
    argument of the predict programs), so switching between fits/builds
    recompiles."""
    global _POLICY_OVERRIDE
    previous = _POLICY_OVERRIDE
    _POLICY_OVERRIDE = (
        None if policy is None else _validate_policy(policy, "set_agg_policy")
    )
    return previous


def policy_engaged() -> bool:
    """True when an aggregation policy was EXPLICITLY bound (scope,
    process override, or ``GP_AGG_POLICY``).  Consumers with a
    historical non-``poe`` default (``gpr.poe_predictor``'s documented
    robust-BCM default) defer to the plane only when it was engaged —
    an untouched plane never silently changes their behavior."""
    return (
        getattr(_SCOPE, "policy", None) is not None
        or _POLICY_OVERRIDE is not None
        or bool(os.environ.get("GP_AGG_POLICY", "").strip())
    )


def resolve_predictor_mode(mode=None, default: str = "rbcm") -> str:
    """The PoE predict mode for a ``mode=None`` caller: the explicitly
    engaged policy wins; otherwise ``default`` (the consumer's
    historical behavior).  An explicit ``mode`` passes through
    untouched (``models/poe.py`` validates it)."""
    if mode is not None:
        return str(mode)
    return active_agg_policy() if policy_engaged() else default


@contextlib.contextmanager
def agg_policy_scope(policy):
    """Pin the policy for the duration of a trace/block.  ``None`` is a
    no-op — the ambient policy applies."""
    if policy is None:
        yield
        return
    policy = _validate_policy(policy, "agg_policy_scope")
    prev = getattr(_SCOPE, "policy", None)
    _SCOPE.policy = policy
    try:
        yield
    finally:
        _SCOPE.policy = prev


def agg_jit_key() -> str:
    """The hashable static the PoE predict entry points carry in their
    jit cache keys — the resolved policy string (every policy is one
    distinct compiled reduction; there are no trace-time tuning knobs on
    the predict side).  Resolved at CALL time, exactly like the
    precision and solver lanes."""
    return active_agg_policy()


# --------------------------------------------------------------------------
# fit-time correlation-aware expert subset selection
# --------------------------------------------------------------------------


def selection_enabled() -> bool:
    """``GP_AGG_SELECT`` truthy engages fit-time selection; default off —
    the clean fit path stays bit-for-bit."""
    return os.environ.get("GP_AGG_SELECT", "").strip().lower() in (
        "1", "on", "true", "yes",
    )


def selection_threshold() -> float:
    """Centered-sketch cosine similarity at (or above) which two experts
    count as redundant (``GP_AGG_SELECT_THRESHOLD``, default 0.95)."""
    raw = os.environ.get("GP_AGG_SELECT_THRESHOLD", "").strip()
    try:
        return float(raw) if raw else 0.95
    except ValueError:
        return 0.95


def selection_mode() -> str:
    """``drop`` (default): redundant experts are removed from the stack
    before any factorization (w_e = 0 exactly, realized by compaction —
    the batched ``[E, s, s]`` work shrinks and their Cholesky/CG is
    never paid).  ``downweight``: every member of a redundancy group of
    size g keeps its data but enters the weighted NLL at w_e = 1/g."""
    raw = os.environ.get("GP_AGG_SELECT_MODE", "").strip().lower()
    if raw in ("", "drop"):
        return "drop"
    if raw == "downweight":
        return "downweight"
    raise ValueError(
        f"GP_AGG_SELECT_MODE={raw!r} is not a selection mode; use 'drop' "
        "or 'downweight'"
    )


def sketch_dim() -> int:
    """Random-feature sketch width (``GP_AGG_SKETCH_DIM``, default 64 —
    pairs of cos/sin features, so the effective dimension is 2 * 32)."""
    raw = os.environ.get("GP_AGG_SKETCH_DIM", "").strip()
    try:
        return max(4, int(raw)) if raw else 64
    except ValueError:
        return 64


def expert_sketches(data, dim: Optional[int] = None, seed: int = 0):
    """Order-invariant per-expert random-feature sketches ``[E, d]``.

    Each expert's sketch is the masked MEAN of random Fourier features
    ``[cos(z W), sin(z W)]`` over its rows ``z = (x, y)`` (standardized
    against the whole stack's masked moments so the fixed N(0,1)
    frequencies are scale-appropriate).  A mean over rows is invariant
    to row order and robust to the ragged tail, so two experts holding
    the same points — in any order, at any padding — sketch identically.
    Pure host numpy: selection is a pre-fit host step, O(E s d) flops,
    noise next to one objective evaluation."""
    x = np.asarray(data.x, dtype=np.float64)
    y = np.asarray(data.y, dtype=np.float64)
    m = np.asarray(data.mask, dtype=np.float64)
    if y.ndim == 3:  # multi-head latent stacks sketch head 0 (a
        y = y[..., 0]  # redundancy diagnostic, not a statistic)
    z = np.concatenate([x, y[..., None]], axis=-1)  # [E, s, p+1]
    w = m[..., None]
    n = max(float(w.sum()), 1.0)
    mu = (z * w).sum(axis=(0, 1)) / n
    var = (np.square(z - mu) * w).sum(axis=(0, 1)) / n
    z = (z - mu) / np.sqrt(var + 1e-12)
    half = max(2, (dim if dim is not None else sketch_dim()) // 2)
    rng = np.random.default_rng(seed)
    freqs = rng.normal(size=(z.shape[-1], half))
    proj = z @ freqs  # [E, s, half]
    feats = np.concatenate([np.cos(proj), np.sin(proj)], axis=-1)
    n_e = np.maximum(m.sum(axis=1), 1.0)[:, None]
    return (feats * w).sum(axis=1) / n_e  # [E, 2*half]


def redundancy_matrix_host(sketches: np.ndarray) -> np.ndarray:
    """Host-numpy redundancy scorer — the PARITY ORACLE for the jitted
    device scorer below, and the ``GP_AGG_DEVICE_SCORE=0`` fallback.
    Same math as :func:`redundancy_matrix` (which dispatches here when
    the device path is disabled or unavailable)."""
    s = np.asarray(sketches, dtype=np.float64)
    resid = s - s.mean(axis=0, keepdims=True)
    norms = np.linalg.norm(resid, axis=1)
    floor = 1e-12 + 1e-9 * np.linalg.norm(s, axis=1)
    unit = resid / np.maximum(norms, floor)[:, None]
    sim = unit @ unit.T
    # raw-identity catch: ||s_i - s_j||^2 via the gram, no [E, E, d] blow-up
    sq = np.sum(np.square(s), axis=1)
    d2 = np.maximum(sq[:, None] + sq[None, :] - 2.0 * (s @ s.T), 0.0)
    scale = np.maximum(np.maximum(sq[:, None], sq[None, :]), 1e-24)
    sim = np.where(d2 <= 1e-12 * scale, 1.0, sim)
    np.fill_diagonal(sim, 1.0)
    return sim


def _redundancy_matrix_jax(s):
    """The jitted device scorer's trace body: one batched centered-cosine
    over the ``[E, d]`` sketch block — two [E, d] matmuls and elementwise
    dressing, all on-device, replacing the host round-trip for the O(E^2 d)
    part of selection.  Mirrors :func:`redundancy_matrix_host` term for
    term (tests/test_aggregation.py holds them to parity)."""
    import jax.numpy as jnp

    resid = s - jnp.mean(s, axis=0, keepdims=True)
    norms = jnp.linalg.norm(resid, axis=1)
    floor = 1e-12 + 1e-9 * jnp.linalg.norm(s, axis=1)
    unit = resid / jnp.maximum(norms, floor)[:, None]
    sim = unit @ unit.T
    sq = jnp.sum(jnp.square(s), axis=1)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (s @ s.T), 0.0)
    scale = jnp.maximum(jnp.maximum(sq[:, None], sq[None, :]), 1e-24)
    sim = jnp.where(d2 <= 1e-12 * scale, 1.0, sim)
    eye = jnp.eye(s.shape[0], dtype=bool)
    return jnp.where(eye, 1.0, sim)


def redundancy_matrix(sketches: np.ndarray) -> np.ndarray:
    """``[E, E]`` pairwise redundancy scores in [-1, 1].

    Cosine similarity of the ACROSS-STACK-CENTERED sketches (module
    docstring: raw mean-feature sketches of iid chunks all converge to
    the same expectation; only the residual fluctuation identifies
    shared data), with (near-)identical RAW sketches forced to 1.0 —
    when nearly every expert is one duplicate class the centered
    residuals cancel to zero and the cosine alone would miss them.

    The scoring runs ON DEVICE by default (one jitted batched
    centered-cosine — the matmul-shaped O(E^2 d) work the host loop used
    to round-trip); ``GP_AGG_DEVICE_SCORE=0`` or any device failure
    falls back to the bit-for-bit host oracle
    (:func:`redundancy_matrix_host`)."""
    if os.environ.get("GP_AGG_DEVICE_SCORE", "").strip() == "0":
        return redundancy_matrix_host(sketches)
    try:
        import jax
        import jax.numpy as jnp

        s = jnp.asarray(np.asarray(sketches, dtype=np.float64))
        sim = np.asarray(jax.jit(_redundancy_matrix_jax)(s))
        return sim.astype(np.float64)
    except Exception:  # noqa: BLE001 — scoring must never fail selection
        return redundancy_matrix_host(sketches)


@dataclass(frozen=True)
class SelectionReport:
    """Outcome of one correlation-aware selection pass over the stack."""

    drop: np.ndarray      # bool [E] — redundant, masked out (drop mode)
    weights: np.ndarray   # f64 [E] — post-selection per-expert weights
    num_active: int       # experts with any unmasked points beforehand
    mode: str             # 'drop' | 'downweight'
    threshold: float

    @property
    def num_dropped(self) -> int:
        return int(self.drop.sum())

    @property
    def num_kept(self) -> int:
        return self.num_active - self.num_dropped

    @property
    def renorm(self) -> float:
        """``E_active / sum(w)`` — the weighted generalization of the
        quarantine renormalization (``quarantine.renorm_factor``),
        mapping the weighted/reduced NLL sum back to a full-stack
        comparable figure.  Exactly 1.0 when selection changed nothing."""
        return weighted_renorm_factor(self.weights, self.num_active)

    @property
    def clean(self) -> bool:
        return self.num_dropped == 0 and bool(
            np.all(self.weights[self.weights > 0] == 1.0)
        )


def weighted_renorm_factor(weights, active: float) -> float:
    """``E_active / sum(w_e)`` — :func:`quarantine.renorm_factor`
    generalized from a dropped-expert COUNT to arbitrary per-expert
    weights: uniform w_e = 1 with d drops gives exactly
    ``active / (active - d)``, the quarantine factor.  Raises the same
    :class:`~spark_gp_tpu.resilience.quarantine.ExpertQuarantineError`
    when no weight remains."""
    from spark_gp_tpu.resilience.quarantine import (
        GLOBAL_FAILURE_ADVICE,
        ExpertQuarantineError,
    )

    total = float(np.sum(np.asarray(weights, dtype=np.float64)))
    if total <= 0:
        raise ExpertQuarantineError(
            f"aggregation weights sum to {total} over {int(active)} active "
            "expert(s) — " + GLOBAL_FAILURE_ADVICE
        )
    return float(active) / total


def effective_expert_count(weights) -> float:
    """Participation ratio ``(sum w)^2 / sum w^2`` — E for uniform
    weights, 1.0 when one expert carries everything, 0.0 for an empty
    weight vector.  THE scalar the health/quality snapshots and the
    ``agg.effective_experts`` metric report."""
    w = np.asarray(weights, dtype=np.float64)
    denom = float(np.sum(np.square(w)))
    if denom <= 0:
        return 0.0
    return float(np.square(np.sum(w)) / denom)


def select_experts(
    data, threshold: Optional[float] = None, mode: Optional[str] = None,
    seed: int = 0,
) -> SelectionReport:
    """Score redundancy and pick the expert subset, greedily first-kept:
    walking experts in stack order, each kept expert claims every
    not-yet-claimed expert whose similarity reaches the threshold as its
    redundancy group; claimed experts are dropped (w_e = 0, ``drop``
    mode) or down-weighted to 1/|group| (``downweight`` mode).  Already
    fully-masked experts (mesh padding, prior quarantine) stay at
    w_e = 0 and never claim anyone."""
    mask = np.asarray(data.mask, dtype=np.float64)
    active = mask.sum(axis=1) > 0
    e = mask.shape[0]
    thr = selection_threshold() if threshold is None else float(threshold)
    mode = selection_mode() if mode is None else str(mode)
    sim = redundancy_matrix(expert_sketches(data, seed=seed))
    drop = np.zeros(e, dtype=bool)
    weights = np.where(active, 1.0, 0.0)
    claimed = ~active  # inactive experts are out of the game entirely
    for i in range(e):
        if claimed[i]:
            continue
        claimed[i] = True
        dups = np.flatnonzero((sim[i] >= thr) & ~claimed)
        claimed[dups] = True
        if dups.size == 0:
            continue
        if mode == "drop":
            drop[dups] = True
            weights[dups] = 0.0
        else:
            group_w = 1.0 / (1.0 + dups.size)
            weights[i] = group_w
            weights[dups] = group_w
    return SelectionReport(
        drop=drop,
        weights=weights,
        num_active=int(active.sum()),
        mode=mode,
        threshold=thr,
    )


# --------------------------------------------------------------------------
# the one weighted reduction the fit objectives share
# --------------------------------------------------------------------------


def weighted_expert_sum(per_expert, weights=None):
    """``sum_e w_e v_e`` over a ``[E, ...]`` per-expert stack, reducing
    every axis — the ONE weighted-sum home the marginal NLL
    (``likelihood.batched_nll``), the LOO pseudo-likelihood
    (``loo.batched_loo_nll``) and the Laplace families' evidence sums
    share, so resilience (quarantine's w_e = 0 via masking) and
    aggregation (selection's fractional w_e) compose through a single
    reduction.  ``weights=None`` is the exact unweighted ``jnp.sum`` —
    callers keep their bit-for-bit default path by not passing it."""
    import jax.numpy as jnp

    if weights is None:
        return jnp.sum(per_expert)
    w = jnp.asarray(weights, per_expert.dtype)
    return jnp.sum(
        w.reshape(w.shape + (1,) * (per_expert.ndim - 1)) * per_expert
    )
