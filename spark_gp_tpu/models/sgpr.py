"""Titsias collapsed SGPR ELBO — the variational training objective.

``setObjective("elbo")`` trains the SAME projected-process model the
reference builds (the PPA predictor: R&W eq. 8.27; the SGPR optimal
variational posterior is algebraically identical to it) but optimizes the
hyperparameters against the collapsed variational bound of Titsias,
*Variational Learning of Inducing Variables in Sparse GPs*, AISTATS'09,
eq. 9:

    ELBO = log N(y | 0, Q_nn + sigma2 I) - 1/(2 sigma2) tr(K_nn - Q_nn)

with ``Q_nn = K_nm K_mm^-1 K_mn``.  The first term is the DTC/projected-
process marginal the reference's pipeline implicitly targets; the trace
term penalizes unexplained variance, closing DTC's known failure mode
(overconfident fits when the inducing set is too small; the bound is
monotone in m and always <= the exact log marginal — pinned by a test).

Everything reduces to the SAME statistics the PPA build already uses —
U1 = sum_e K_me K_em, u2 = sum_e K_me y_e — plus two scalars
(y.y, tr K_nn), all linear sums over the expert stack: per evaluation
one [m, m] Cholesky + two triangular solves on top of one vmapped cross
pass.  The active (inducing) set is selected by the configured provider
BEFORE optimization and held fixed — matching the reference's pipeline
shape, with the hyperparameters now trained on a principled bound.

Distribution note: unlike the per-expert NLL (a psum of local scalars),
the ELBO is a NONLINEAR function of the global sums, so the multi-chip
path deliberately rides jit/GSPMD — the expert-stacked sums partition
across devices with XLA-inserted all-reduces and the small [m, m]
algebra replicates — instead of the hand-written shard_map paths
(``tests/test_sgpr.py`` pins sharded == single).

With this objective, ``sigma2`` IS the Gaussian likelihood noise.
Through the estimator the kernel is the usual noise-augmented model
kernel (user kernel + ``sigma2 * EyeKernel``, GaussianProcessCommons
.scala:18 — the same convention as every other fit path and the PPA
build): the Eye component adds a ``sigma2`` nugget to ``K_mm`` (a benign
regularizer on the inducing gram), contributes nothing to ``K_mn``
(zero cross terms), and inflates the trace term by the CONSTANT
``-N/2`` (no gradient effect) — so the optimized surface is the Titsias
bound of the augmented-kernel model.  Avoid stacking an additional
trainable ``WhiteNoiseKernel`` on top: its nugget would train against
the bound's trace term rather than the likelihood noise.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.linalg import cholesky


def batched_elbo_nll(kernel: Kernel, theta, data, active, sigma2):
    """Negative collapsed ELBO over the expert stack (GPflow's SGPR
    formulation: A = L^-1 K_mn / sigma, B = I + A A^T).

    ``active`` is the fixed [m, p] inducing set, ``sigma2`` the Gaussian
    noise; both ride as traced operands so one compiled program serves
    every fit.  Padded slots are masked out of every sum.
    """
    m = active.shape[0]
    sigma2 = jnp.asarray(sigma2, dtype=data.x.dtype)
    sigma = jnp.sqrt(sigma2)

    # Replicated [m, m] factor first: the per-expert statistics below are
    # accumulated in the WHITENED domain a_e = L^-1 K_me / sigma.  Summing
    # a_e a_e^T keeps B = I + sum PSD by construction — whiten-then-square.
    # (Square-then-whiten, i.e. L^-1 U1 L^-T from the PPA's U1 statistic,
    # carries the normal equations' squared conditioning: in float32 its
    # solve noise exceeds B's unit eigenvalue floor and chol(B) NaNs — the
    # same conditioning hazard models/common.py documents for the f64 PPA
    # build, solved there by precision and here by formulation.)
    kmm = kernel.gram(theta, active)
    jitter = 1e-6 * jnp.mean(jnp.diagonal(kmm))
    chol_l = cholesky(kmm + jitter * jnp.eye(m, dtype=kmm.dtype))

    # --- global statistics: linear sums over the (shardable) expert axis
    def per_expert(xe, ye, me):
        kme = kernel.cross(theta, active, xe) * me[None, :]  # [m, s]
        ae = (
            jax.scipy.linalg.solve_triangular(chol_l, kme, lower=True)
            / sigma
        )  # [m, s] whitened
        yem = ye * me
        return (
            ae @ ae.T,                                      # [m, m]
            ae @ (yem / sigma),                             # [m]
            jnp.sum(yem * yem),
            jnp.sum(kernel.self_diag(theta, xe) * me),
            jnp.sum(me),
        )

    aat, ay, yy, tr_knn, n = jax.tree.map(
        lambda s: jnp.sum(s, axis=0),
        jax.vmap(per_expert)(data.x, data.y, data.mask),
    )

    b = jnp.eye(m, dtype=aat.dtype) + aat
    chol_b = cholesky(b)
    c = jax.scipy.linalg.solve_triangular(chol_b, ay, lower=True)

    log_det_b = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol_b)))
    elbo = (
        -0.5 * n * jnp.log(2.0 * math.pi * sigma2)
        - 0.5 * log_det_b
        - 0.5 * yy / sigma2
        + 0.5 * jnp.sum(c * c)
        - 0.5 * tr_knn / sigma2
        + 0.5 * jnp.trace(aat)  # tr(Q_nn) / (2 sigma2)
    )
    return -elbo
