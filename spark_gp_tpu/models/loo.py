"""Per-expert leave-one-out cross-validation diagnostics (R&W §5.4.2).

For a GP regressor whose per-expert noise-augmented Gram is ``K``, the
exact LOO predictive moments at fixed hyperparameters are closed-form in
one factorization (Rasmussen & Williams eqs. 5.10-5.12):

    mu_{-i}     = y_i - [K^-1 y]_i / [K^-1]_ii
    sigma2_{-i} = 1 / [K^-1]_ii
    log p(y_i | y_{-i}) = -1/2 log(2 pi sigma2_{-i})
                          - (y_i - mu_{-i})^2 / (2 sigma2_{-i})

The BCM expert split makes this exact *within each expert*: each point's
LOO conditions on its expert's other points — the same conditioning
structure the training objective itself sums over
(GaussianProcessRegression.scala:55-68 treats experts as independent), so
the per-expert LOO is the honest diagnostic for the model actually being
fit.  One batched ``[E, s, s]`` inverse (the Pallas fused pass on TPU,
Cholesky elsewhere — ``ops.pallas_linalg.spd_inv_logdet``) yields every
point's diagnostics; nothing here is O(N^2).

The reference has no model-criticism tooling at all; this module is a
TPU-native addition in the spirit of its quality bars.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel, masked_gram_stack
from spark_gp_tpu.parallel.experts import group_for_experts, ungroup


def loo_moments(kernel: Kernel, theta, x, y, mask, cache=None):
    """``[E, s, ...]`` expert stack -> per-slot (mu, var, log_density).

    Traceable core, shared by the jitted diagnostics below and the LOO
    training objective (:func:`batched_loo_nll`) — autodiff flows through
    the batched inverse's custom VJP.  Padded slots ride through the
    identity embedding of ``masked_kernel_matrix`` (K^-1 diagonal 1,
    alpha 0): their values are benign constants with zero theta-gradient,
    never NaN; callers drop them via the mask.  ``cache`` is the
    theta-invariant gram cache (kernels/base.py): the LOO hot loop skips
    the distance contraction exactly like the marginal objective.
    """
    from spark_gp_tpu.ops.pallas_linalg import spd_inv_logdet

    kmat = masked_gram_stack(kernel, theta, x, mask, cache)
    ym = y * mask
    kinv, _ = spd_inv_logdet(kmat)
    alpha = jnp.einsum("eij,ej->ei", kinv, ym)
    diag = jnp.diagonal(kinv, axis1=-2, axis2=-1)
    var = 1.0 / diag
    resid = alpha * var  # y_i - mu_{-i}
    mu = ym - resid
    log_density = -0.5 * (
        jnp.log(2.0 * math.pi * var) + resid * resid / var
    )
    return mu, var, log_density


def batched_loo_nll(kernel: Kernel, theta, data, weights=None, cache=None):
    """Negative LOO log pseudo-likelihood over the expert stack.

    ``-L_LOO(theta)`` of R&W eq. 5.13 — the alternative hyperparameter
    objective ``setObjective("loo")`` minimizes in place of the marginal
    NLL (``models/likelihood.batched_nll``).  More robust under model
    misspecification: it scores held-out predictive density rather than
    data fit (R&W §5.4.2 discussion).  Same signature as ``batched_nll``
    (including the theta-invariant ``cache`` operand and the aggregation
    plane's per-expert ``weights`` — ``models/aggregation.py``; ``None``
    keeps the unweighted sum bit-for-bit) so every fit entry point can
    swap it in.
    """
    from spark_gp_tpu.models.aggregation import weighted_expert_sum

    _, _, log_density = loo_moments(
        kernel, theta, data.x, data.y, data.mask, cache
    )
    return -weighted_expert_sum(log_density * data.mask, weights)


@partial(jax.jit, static_argnums=0)
def _loo_impl(kernel: Kernel, theta, x, y, mask):
    return loo_moments(kernel, theta, x, y, mask)


def loo_diagnostics(
    kernel: Kernel,
    theta,
    x: np.ndarray,
    y: np.ndarray,
    dataset_size_for_expert: int,
    dtype=None,
) -> dict:
    """Exact per-expert LOO diagnostics for ``(x [N, p], y [N])``.

    Returns original-point-order arrays ``loo_mean`` / ``loo_var`` /
    ``loo_log_density`` ``[N]`` plus the two classic scalar summaries:
    ``loo_rmse`` and ``loo_log_pseudo_likelihood`` (the sum of per-point
    log densities — R&W eq. 5.11, the model-selection criterion L_LOO).
    """
    data = group_for_experts(x, y, dataset_size_for_expert, dtype=dtype)
    theta = jnp.asarray(theta, dtype=data.x.dtype)
    mu, var, logp = _loo_impl(kernel, theta, data.x, data.y, data.mask)
    n = int(np.asarray(x).shape[0])
    loo_mean = ungroup(np.asarray(mu), n)
    loo_var = ungroup(np.asarray(var), n)
    loo_logp = ungroup(np.asarray(logp), n)
    resid = np.asarray(y, dtype=loo_mean.dtype) - loo_mean
    return {
        "loo_mean": loo_mean,
        "loo_var": loo_var,
        "loo_log_density": loo_logp,
        "loo_rmse": float(np.sqrt(np.mean(resid**2))),
        "loo_log_pseudo_likelihood": float(loo_logp.sum()),
    }
