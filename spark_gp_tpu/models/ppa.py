"""Projected Process Approximation: distributed statistics + the "magic" solve.

Reference semantics (ProjectedGaussianProcessHelper.scala, R&W ch. 8.3.4):

* Distributed stage — against the broadcast m-point active set A, accumulate
  over all experts
      U1 = sum_e K_mn_e K_mn_e^T     (m x m)
      u2 = sum_e K_mn_e y_e          (m)
  (PGPH.scala:20-36, a treeAggregate).  Here: vmapped per-expert matmuls on
  the MXU, summed over the local expert shard, ``psum`` across chips.

* Solve stage — with sn2 = total white-noise variance of the optimal kernel
  (NB: the reference uses ``kernel.whiteNoiseVar * trainingKernel()`` of the
  *noise-augmented* kernel, so sn2 = sigma2 + any trained WhiteNoise
  coefficient, and K_mm below includes the +sn2*I diagonal):

      PD          = sn2 * K_mm + U1
      magicVector = PD^-1 u2                          (posterior mean weights)
      magicMatrix = sn2 * PD^-1 - K_mm^-1             (R&W eq. 8.27 covariance)

  (PGPH.scala:49-60.)  The reference asserts positive definiteness with a
  full eigendecomposition and then computes two explicit inverses via LU; we
  Cholesky-factor PD and K_mm once each — the factorizations *are* the PD
  check — and build magicMatrix from triangular solves against I (it is
  genuinely consumed as a full matrix by the per-point predictive variance).

* Predict stage (GaussianProcessCommons.scala:118-126):
      mean_i = k(x_i, A) magicVector
      var_i  = k(x_i, x_i) + k(x_i, A) magicMatrix k(x_i, A)^T
  batched over test points as two einsums.

The m x m solve runs in float64 on host CPU by default: it is a one-time
O(m^3) cost (m ~ 1000 -> milliseconds) and the condition numbers that arise
with sigma2 as small as 1e-4 (Airfoil.scala:21) genuinely need f64; the hot
per-iteration expert math stays in device f32.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
from spark_gp_tpu.ops.precision import active_lane, precision_lane_scope
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS


def _flat_stats(kernel: Kernel, theta, active, xf, yf, maskf):
    """(K_mn K_nm, K_mn Y) over a flat ``[c, p]`` point chunk — one big
    MXU matmul with the m axis as rows, instead of c/s tiny per-expert
    matmuls (the expert structure is irrelevant to these sums).  ``yf`` is
    ``[c]`` (single target) or ``[c, C]`` (multi-target: the multiclass
    latent heads share U1 and differ only in the right-hand sides)."""
    from spark_gp_tpu.ops.distance import mxu_inner

    kmn = kernel.cross(theta, active, xf) * maskf[None, :]  # [m, c]
    # Lane-immune by construction: every caller runs the (U1, u2)
    # accumulation in f64 (models/common.py casts under jax.enable_x64 —
    # the one-time stats feed a condition-squared normal-equations solve),
    # and mxu_inner routes f64 inputs to the plain HIGHEST contraction
    # regardless of the precision lane (ops/distance.py)
    u1 = mxu_inner(kmn, kmn)
    ym = yf * (maskf if yf.ndim == 1 else maskf[:, None])
    u2 = kmn @ ym
    return u1, u2


# Cap on the [m, chunk] cross-kernel intermediate (elements).  64M f64
# entries = 512 MB — comfortably inside a v5e's HBM next to the data.
_STATS_CHUNK_ELEMS = 64 * 1024 * 1024


def kmn_stats(kernel: Kernel, theta, active, data: ExpertData):
    """Accumulation of (U1 [m,m], u2 [m]) over all experts.

    Flattens the expert stack (the sums don't care about expert boundaries
    — PGPH.scala:25-35 just adds per-expert pieces) and processes it in
    memory-bounded chunks via ``lax.scan``, each chunk one MXU matmul.
    """
    e, s, p = data.x.shape
    u1, u2 = _kmn_stats_flat(
        kernel, theta, active,
        data.x.reshape(e * s, p),
        data.y.reshape(e * s),
        data.mask.reshape(e * s),
    )
    return u1, u2


def _kmn_stats_flat(kernel: Kernel, theta, active, xf, yf, maskf):
    """Chunked (U1, U2) accumulation over flat points; ``yf`` is ``[n]``
    or ``[n, C]`` (see ``_flat_stats``)."""
    n_flat, p = xf.shape
    m = active.shape[0]
    chunk = max(1, min(n_flat, _STATS_CHUNK_ELEMS // max(m, 1)))
    n_chunks = -(-n_flat // chunk)
    if n_chunks <= 1:
        return _flat_stats(kernel, theta, active, xf, yf, maskf)

    pad = n_chunks * chunk - n_flat
    # Pad features with copies of the first point, not zeros — the mask
    # already excludes padding from the sums, but a custom kernel may be
    # non-finite at the zero point and NaN * 0 would poison U1 (same benign-
    # padding convention as group_for_experts).
    xf = jnp.concatenate([xf, jnp.broadcast_to(xf[:1], (pad, p))], axis=0)
    yf = jnp.pad(yf, ((0, pad),) + ((0, 0),) * (yf.ndim - 1))
    maskf = jnp.pad(maskf, ((0, pad),))

    def body(carry, args):
        u1, u2 = carry
        du1, du2 = _flat_stats(kernel, theta, active, *args)
        return (u1 + du1, u2 + du2), None

    init = (
        jnp.zeros((m, m), dtype=xf.dtype),
        jnp.zeros((m,) + yf.shape[1:], dtype=xf.dtype),
    )
    (u1, u2), _ = jax.lax.scan(
        body,
        init,
        (
            xf.reshape((n_chunks, chunk, p)),
            yf.reshape((n_chunks, chunk) + yf.shape[1:]),
            maskf.reshape(n_chunks, chunk),
        ),
    )
    return u1, u2


@partial(jax.jit, static_argnums=0)
def kmn_stats_jit(kernel: Kernel, theta, active, x, y, mask):
    """Jitted (U1, U2) over an expert stack.  Rank-generic in the targets:
    ``y [E, s]`` gives the reference's single-target u2 ``[m]``;
    ``y [E, s, C]`` gives one shared U1 and per-column U2 ``[m, C]`` (the
    multiclass PPA build — the C latent stacks share the kernel and active
    set, so everything but the right-hand sides is common)."""
    e, s, p = x.shape
    return _kmn_stats_flat(
        kernel, theta, active,
        x.reshape(e * s, p),
        y.reshape((e * s,) + y.shape[2:]),
        mask.reshape(e * s),
    )


@partial(jax.jit, static_argnums=(0, 1))
def _sharded_kmn_stats_impl(kernel: Kernel, mesh, theta, active, x, y, mask):
    """Sharded (U1, U2): experts sharded, active set replicated, one psum
    over ICI (PGPH.scala:25-35).  Rank-generic in ``y`` exactly like
    :func:`kmn_stats_jit` (``[E, s]`` -> u2 ``[m]``; ``[E, s, C]`` ->
    U2 ``[m, C]``)."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS)),
        out_specs=(P(), P()),
    )
    def sharded(theta_, active_, x_, y_, mask_):
        e, s, p = x_.shape
        u1, u2 = _kmn_stats_flat(
            kernel, theta_, active_,
            x_.reshape(e * s, p),
            y_.reshape((e * s,) + y_.shape[2:]),
            mask_.reshape(e * s),
        )
        return (
            jax.lax.psum(u1, EXPERT_AXIS),
            jax.lax.psum(u2, EXPERT_AXIS),
        )

    return sharded(theta, active, x, y, mask)


def make_sharded_kmn_stats(kernel: Kernel, mesh):
    """Sharded (U1, u2) accumulation: active set replicated (the broadcast,
    PGPH.scala:23), experts sharded, one psum over ICI (PGPH.scala:25-35)."""

    return lambda theta, active, data: _sharded_kmn_stats_impl(
        kernel, mesh, theta, active, data.x, data.y, data.mask
    )


def kmn_stats_sharded(kernel: Kernel, mesh, theta, active, x, y, mask):
    """Public raw-array entry to the sharded (U1, U2) accumulation — the
    mesh counterpart of :func:`kmn_stats_jit`, same rank-generic targets."""
    return _sharded_kmn_stats_impl(kernel, mesh, theta, active, x, y, mask)


@partial(jax.jit, static_argnums=0)
def _kmn_stats_x64_from32_impl(kernel: Kernel, theta32, active64, x32, y32, mask32):
    """Fused f64 (U1, u2) statistics taking the *f32 device* optimum directly.

    The upcasts happen inside the one program so the optimizer's device theta
    chains into the PPA stage with zero host round-trips — on high-RTT
    runtimes (tunneled TPU, multi-host pods) every intermediate
    ``np.asarray`` costs a full sync.  Requires ``jax.enable_x64()`` at call
    time.  Returns ``(u1, u2, theta64)`` so the caller can fetch everything
    in a single ``device_get``.
    """
    theta64 = theta32.astype(jnp.float64)
    data = ExpertData(
        x=x32.astype(jnp.float64),
        y=y32.astype(jnp.float64),
        mask=mask32.astype(jnp.float64),
    )
    u1, u2 = kmn_stats(kernel, theta64, active64, data)
    return u1, u2, theta64


@partial(jax.jit, static_argnums=(0, 1))
def _sharded_kmn_stats_x64_from32_impl(
    kernel: Kernel, mesh, theta32, active64, x32, y32, mask32
):
    """Sharded variant of :func:`_kmn_stats_x64_from32_impl`: experts sharded,
    active set replicated, one psum over ICI (PGPH.scala:25-35)."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS)),
        out_specs=(P(), P(), P()),
    )
    def sharded(theta_, active_, x_, y_, mask_):
        theta64 = theta_.astype(jnp.float64)
        local = ExpertData(
            x=x_.astype(jnp.float64),
            y=y_.astype(jnp.float64),
            mask=mask_.astype(jnp.float64),
        )
        u1, u2 = kmn_stats(kernel, theta64, active_, local)
        return (
            jax.lax.psum(u1, EXPERT_AXIS),
            jax.lax.psum(u2, EXPERT_AXIS),
            theta64,
        )

    return sharded(theta32, active64, x32, y32, mask32)


# One escalating-jitter policy for every magic-solve dispatch branch (host
# numpy, replicated device, mesh-sharded) — the framework-wide adaptive
# ladder of ops/linalg.py (trace-relative diagonal boosts, unjittered
# first, escalating to 1.2e-4).  A matrix that exhausts the schedule
# raises NotPositiveDefiniteException with the reference's advice
# identically on all branches (PGPH.scala:9-11).
from spark_gp_tpu.ops.linalg import (  # noqa: E402 — policy import
    JITTER_SCHEDULE as _JITTER_SCHEDULE,
    jittered_np as _jittered,
)

# Above this active-set size the O(m^3) magic solve moves off the host
# single-thread numpy path onto the device (XLA f64): at m=1000 the host
# solve is milliseconds, at m >= ~2k the device's parallel triangular
# solves win by an order of magnitude (SURVEY.md §2.3 TP row — the m-axis
# is the scaling direction the reference never had).
_DEVICE_SOLVE_MIN_M = 2048


def magic_solve(
    kernel: Kernel,
    theta,
    active,
    u1,
    u2,
    solve_dtype=np.float64,
    mesh=None,
    with_variance: bool = True,
):
    """f64 solve for (magicVector, magicMatrix) — PGPH.scala:49-60.

    Dispatches by m: host numpy below ``_DEVICE_SOLVE_MIN_M`` (cheap,
    avoids device round-trips for the common m ~ 100..1000); above it, the
    jitted device solver — sharded over ``mesh`` when one with >1 devices
    is supplied (the blocked distributed Cholesky of ops/dist_linalg.py,
    scaling the O(m^3) with chips), replicated otherwise.  All three paths
    are parity-tested against each other.

    ``with_variance=False`` returns ``(magicVector, None)``: the two
    inverse builds behind magicMatrix are the dominant O(m^3) cost and the
    [m, m] result the dominant model memory — a mean-only model skips both
    (setPredictiveVariance rationale).
    """
    theta64 = np.asarray(theta, dtype=solve_dtype)
    active64 = np.asarray(active, dtype=solve_dtype)
    if active64.shape[0] >= _DEVICE_SOLVE_MIN_M:
        from spark_gp_tpu.resilience.fallback import run_ppa_solve_ladder

        if mesh is not None and mesh.devices.size > 1:
            def device_attempt():
                return sharded_magic_solve(
                    kernel, theta64, active64, u1, u2, mesh,
                    with_variance=with_variance,
                )
        else:
            def device_attempt():
                return magic_solve_device(
                    kernel, theta64, active64, u1, u2,
                    with_variance=with_variance,
                )

        # degradation ladder: an OOM/compile failure in the device f64
        # solve re-executes on the host numpy path (slow but an answer);
        # numerical NotPositiveDefiniteException stays raw on every branch
        return run_ppa_solve_ladder(
            device_attempt,
            lambda: _host_magic_solve(
                kernel, theta64, active64, u1, u2, solve_dtype, with_variance
            ),
        )
    return _host_magic_solve(
        kernel, theta64, active64, u1, u2, solve_dtype, with_variance
    )


def _host_magic_solve(
    kernel, theta64, active64, u1, u2, solve_dtype, with_variance
):
    """The host numpy f64 solve — the small-m default and the magic-solve
    ladder's last rung."""
    kmm, sn2 = _gram_f64_on_host(kernel, theta64, active64)
    u1 = np.asarray(u1, dtype=solve_dtype)
    u2 = np.asarray(u2, dtype=solve_dtype)

    pd_mat = sn2 * kmm + u1

    return _solve_magic_np(pd_mat, kmm, u2, sn2, with_variance=with_variance)


@partial(jax.jit, static_argnums=(0, 6))
def _magic_solve_device_impl(
    kernel: Kernel, theta, active, u1, u2, tau, with_variance=True,
    cache=None,
):
    """One jitted f64 solve attempt with trace-relative jitter ``tau`` (a
    traced scalar: every escalation reuses the same executable).  Returns
    the solution plus a finiteness flag (Cholesky of an indefinite matrix
    yields NaN, checked on host — can't raise under jit).  ``cache`` is
    the ACTIVE-SET theta-invariant gram cache (kernels/base.py), built
    once by the caller so jitter escalations re-dispatching this program
    skip the [m, m] distance contraction."""
    m = active.shape[0]
    kmm = (
        kernel.gram(theta, active) if cache is None
        else kernel.gram_from_cache(theta, cache)
    )
    sn2 = kernel.white_noise_var(theta)
    eye = jnp.eye(m, dtype=u1.dtype)

    def chol(mat, rel_jitter):
        from spark_gp_tpu.ops.linalg import cholesky

        sym = 0.5 * (mat + mat.T)
        return cholesky(sym + (rel_jitter * jnp.trace(sym) / m) * eye)

    l_pd = chol(sn2 * kmm + u1, tau)

    def chol_solve(l, b):
        y = jax.lax.linalg.triangular_solve(
            l, b, left_side=True, lower=True
        )
        return jax.lax.linalg.triangular_solve(
            l, y, left_side=True, lower=True, transpose_a=True
        )

    # single-target u2 [m] or multi-target U2 [m, C] (ndim is trace-static)
    mv = chol_solve(l_pd, u2 if u2.ndim == 2 else u2[:, None])
    magic_vector = mv if u2.ndim == 2 else mv[:, 0]
    ok = jnp.all(jnp.isfinite(jnp.diagonal(l_pd)))
    if not with_variance:
        return magic_vector, jnp.zeros((0, 0), u1.dtype), ok
    l_mm = chol(kmm, tau)
    magic_matrix = sn2 * chol_solve(l_pd, eye) - chol_solve(l_mm, eye)
    ok = ok & jnp.all(jnp.isfinite(jnp.diagonal(l_mm)))
    return magic_vector, magic_matrix, ok


@partial(jax.jit, static_argnums=0)
def _prepare_active_cache_impl(kernel: Kernel, active):
    return kernel.prepare(active)


def magic_solve_device(
    kernel: Kernel, theta64, active64, u1, u2, with_variance: bool = True
):
    """Device f64 magic solve for large active sets (m >~ 2k): Cholesky +
    triangular solves as one XLA program, with the same escalating
    trace-relative jitter semantics as the host path
    (:func:`_psd_safe_cholesky`) driven from the host — each retry re-runs
    the same compiled executable with a bigger traced jitter scalar.
    The active set's theta-invariant gram cache is built ONCE out here, so
    escalation retries reuse the [m, m] distance block instead of
    re-contracting it per attempt (models/common.py precompute plane; f64,
    hence lane-immune like the rest of the stats path).
    """
    from spark_gp_tpu.kernels.base import supports_gram_cache
    from spark_gp_tpu.resilience import chaos

    # chaos choke point: a staged device OOM/compile fault surfaces here,
    # where a real allocator failure on the [m, m] solve would
    chaos.maybe_injected_failure("ppa.magic_solve")
    with jax.enable_x64():
        theta_d = jnp.asarray(theta64, dtype=jnp.float64)
        active_d = jnp.asarray(active64, dtype=jnp.float64)
        u1_d = jnp.asarray(u1, dtype=jnp.float64)
        u2_d = jnp.asarray(u2, dtype=jnp.float64)
        cache_d = (
            _prepare_active_cache_impl(kernel, active_d)
            if supports_gram_cache(kernel) else None
        )
        for k, tau in enumerate(_JITTER_SCHEDULE):
            mv, mm, ok = _magic_solve_device_impl(
                kernel, theta_d, active_d, u1_d, u2_d,
                jnp.asarray(tau, jnp.float64), with_variance, cache_d,
            )
            if bool(ok):
                if k > 0:
                    import logging

                    logging.getLogger("spark_gp_tpu").warning(
                        "device magic solve required relative jitter %.3e "
                        "for positive definiteness", tau,
                    )
                return np.asarray(mv), (
                    np.asarray(mm) if with_variance else None
                )
    raise NotPositiveDefiniteException()


def _gram_f64_on_host(kernel: Kernel, theta64, active64):
    """Evaluate K_mm and the white-noise variance in float64 on the host CPU,
    regardless of the global x64 flag (the device hot path stays f32)."""
    enable_x64 = jax.enable_x64

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    import contextlib

    ctx = jax.default_device(cpu) if cpu is not None else contextlib.nullcontext()
    with enable_x64(), ctx:
        kmm = np.asarray(kernel.gram(jnp.asarray(theta64), jnp.asarray(active64)))
        sn2 = float(np.asarray(kernel.white_noise_var(jnp.asarray(theta64))))
    return kmm, sn2


def _psd_safe_cholesky(mat, name):
    """Cholesky under the shared adaptive jitter ladder (ops/linalg.py).

    The distributed U1 = sum K_mn K_nm accumulates on-device in float32; its
    smallest eigenvalues carry O(eps_f32 * lambda_max) noise which can push a
    mathematically-PSD matrix slightly indefinite.  Repairing with jitter
    proportional to trace/m perturbs the solution far less than the PPA
    approximation error itself; a matrix the whole ladder cannot repair is
    genuinely bad and raises NotPositiveDefiniteException (with the
    reference's "increase sigma2" advice, PGPH.scala:9-11).
    """
    from spark_gp_tpu.ops.linalg import psd_safe_cholesky_np

    return psd_safe_cholesky_np(mat, name)


def _solve_magic_np(pd_mat, kmm, u2, sn2, with_variance: bool = True):
    """numpy f64 Cholesky solves; raises NotPositiveDefiniteException."""
    l_pd = _psd_safe_cholesky(pd_mat, "sigma2*K_mm + Kmn*Knm")

    def chol_solve_np(l, b):
        from scipy.linalg import solve_triangular

        y = solve_triangular(l, b, lower=True)
        return solve_triangular(l, y, lower=True, trans=1)

    magic_vector = chol_solve_np(l_pd, u2)
    if not with_variance:
        return magic_vector, None
    l_mm = _psd_safe_cholesky(kmm, "K_mm")
    eye = np.eye(pd_mat.shape[0])
    pd_inv = chol_solve_np(l_pd, eye)
    kmm_inv = chol_solve_np(l_mm, eye)
    magic_matrix = sn2 * pd_inv - kmm_inv
    return magic_vector, magic_matrix


@functools.lru_cache(maxsize=8)
def _sharded_solve_helpers(mesh):
    """Per-mesh jitted helper programs for the sharded magic solve, cached
    so repeated solves don't re-trace/re-compile (jax.jit caches by wrapped
    callable identity — fresh lambdas per call would defeat it).

    All three run as programs with replicated outputs: multi-host legality
    requires it — eager jnp/np ops on row-sharded global arrays that span
    other hosts' devices raise (same restriction as gpc._labels_are_01).
    """
    from jax.sharding import NamedSharding

    rep = NamedSharding(mesh, P())
    finite_ok = jax.jit(
        lambda a, b: jnp.all(jnp.isfinite(a)) & jnp.all(jnp.isfinite(b)),
        out_shardings=rep,
    )
    replicate = jax.jit(lambda a: a, out_shardings=rep)
    combine = jax.jit(lambda a, b, s: s * a - b, out_shardings=rep)
    return finite_ok, replicate, combine


def sharded_magic_solve(
    kernel: Kernel, theta64, active64, u1, u2, mesh, block: int = 128,
    with_variance: bool = True,
):
    """Mesh-sharded f64 magic solve: the m x m factorizations run as the
    blocked distributed Cholesky of :mod:`spark_gp_tpu.ops.dist_linalg`
    (rows sharded over the mesh, per-panel psum + all-gather over ICI), so
    the O(m^3) PPA solve scales with device count — the SURVEY §2.3
    tensor-parallel stretch row the reference has no counterpart for (its
    solve is driver-local, PGPH.scala:54-59).

    Same escalating-jitter semantics and failure advice as the host/device
    paths; m is padded to mesh-size * block granularity with an identity
    block (padded rows solve to zero / slice away exactly).
    """
    from spark_gp_tpu.ops import dist_linalg
    from spark_gp_tpu.resilience import chaos

    chaos.maybe_injected_failure("ppa.magic_solve")
    with jax.enable_x64():
        theta_d = jnp.asarray(theta64, dtype=jnp.float64)
        kmm = np.asarray(kernel.gram(theta_d, jnp.asarray(active64)))
        sn2 = float(np.asarray(kernel.white_noise_var(theta_d)))
        m = active64.shape[0]
        gran = mesh.devices.size * block
        m_pad = -(-m // gran) * gran

        pd = sn2 * kmm + np.asarray(u1, dtype=np.float64)
        pd = 0.5 * (pd + pd.T)
        kmm = 0.5 * (kmm + kmm.T)
        u2_arr = np.asarray(u2, dtype=np.float64)
        u2_pad = np.zeros((m_pad,) + u2_arr.shape[1:])
        u2_pad[:m] = u2_arr
        eye_scale_pd = np.trace(pd) / m
        eye_scale_mm = np.trace(kmm) / m

        finite_ok, replicate, combine = _sharded_solve_helpers(mesh)

        for k, tau in enumerate(_JITTER_SCHEDULE):
            pd_pad = dist_linalg.pad_spd(_jittered(pd, tau, eye_scale_pd), m_pad)
            l_pd = dist_linalg.sharded_cholesky(mesh, jnp.asarray(pd_pad), block)
            if with_variance:
                kmm_pad = dist_linalg.pad_spd(
                    _jittered(kmm, tau, eye_scale_mm), m_pad
                )
                l_mm = dist_linalg.sharded_cholesky(
                    mesh, jnp.asarray(kmm_pad), block
                )
            else:
                # mean-only: K_mm is never factored (the whole point at
                # large m), and the retry gate must not depend on it —
                # matching the host/device branches' semantics
                l_mm = l_pd
            if not bool(finite_ok(l_pd, l_mm)):
                continue
            if k > 0:
                import logging

                logging.getLogger("spark_gp_tpu").warning(
                    "sharded magic solve required relative jitter %.3e "
                    "for positive definiteness", tau,
                )
            magic_vector = np.asarray(
                replicate(dist_linalg.sharded_chol_solve(mesh, l_pd, u2_pad, block))
            )[:m]
            if not with_variance:
                return magic_vector, None
            eye_pad = jnp.eye(m_pad, dtype=jnp.float64)
            pd_inv = dist_linalg.sharded_chol_solve(mesh, l_pd, eye_pad, block)
            kmm_inv = dist_linalg.sharded_chol_solve(mesh, l_mm, eye_pad, block)
            magic_matrix = np.asarray(
                combine(pd_inv, kmm_inv, jnp.asarray(sn2, jnp.float64))
            )[:m, :m]
            return magic_vector, magic_matrix
    raise NotPositiveDefiniteException()


def _as_float(x_test, n_features: int):
    """Normalize test inputs before the jitted predict programs: integer
    dtypes must not drag theta/active/magic operators to an integer dtype
    (a lengthscale of 1.2 would silently truncate to 1), and a feature-
    count mismatch must fail here with a readable message instead of a
    broadcast error deep inside jit."""
    x_test = jnp.asarray(x_test)
    if x_test.ndim != 2 or x_test.shape[1] != n_features:
        raise ValueError(
            f"x_test must be [t, {n_features}] (the model was fitted on "
            f"{n_features} features); got shape {tuple(x_test.shape)}"
        )
    if not jnp.issubdtype(x_test.dtype, jnp.floating):
        x_test = x_test.astype(jnp.promote_types(x_test.dtype, jnp.float32))
    return x_test


@dataclass
class ProjectedProcessRawPredictor:
    """Serializable (mean, variance) predictor against the m-point model —
    the counterpart of GaussianProjectedProcessRawPredictor
    (GaussianProcessCommons.scala:118-126).

    Model size: theta [h], active [m, p], magic_vector [m],
    magic_matrix [m, m] — independent of N.
    """

    kernel: Kernel
    theta: np.ndarray
    active: np.ndarray
    magic_vector: np.ndarray
    # None for mean-only models (setPredictiveVariance(False))
    magic_matrix: Optional[np.ndarray]
    # The raw PPA statistics behind the solve (U1 = sum K_mn K_nm [m, m],
    # u2 = sum K_mn y [m], f64).  They are ADDITIVE over data points, which
    # is what makes incremental updates possible (with_additional_data):
    # new observations fold in with one O(m^3) re-solve, no refit.  Only
    # REGRESSION fits store them (common.py _keeps_update_statistics): the
    # Laplace families' statistics sum over latent modes, where folding in
    # raw labels/counts would be silently wrong; pre-r4 checkpoints lack
    # them entirely.
    u1: Optional[np.ndarray] = None
    u2: Optional[np.ndarray] = None

    def with_additional_data(self, x_new, y_new) -> "ProjectedProcessRawPredictor":
        """Fold new observations into the fitted model: the PPA statistics
        are per-point sums (U1 += C C^T, u2 += C y with C = K(active, x_new)
        — PGPH.scala:27-29's treeAggregate is exactly this sum), so an
        update costs one [m, t] cross kernel + one O(m^3) magic re-solve at
        the FIXED hyperparameters and active set.  Capability beyond the
        reference (whose model is frozen at produceModel); statistically
        this is the projected process with its inducing set and kernel held
        fixed — re-fit when the new data plausibly shifts the
        hyperparameters.
        """
        if self.u1 is None or self.u2 is None:
            raise ValueError(
                "this model does not carry updatable PPA statistics: only "
                "regression fits store them (the Laplace families' "
                "statistics are over latent targets — refit those; pre-r4 "
                "saves lack them — refit to enable incremental updates)"
            )
        x_new = np.asarray(x_new, dtype=np.float64)
        y_new = np.asarray(y_new, dtype=np.float64)
        if x_new.ndim != 2 or x_new.shape[1] != self.active.shape[1]:
            raise ValueError(
                f"x_new must be [t, {self.active.shape[1]}], got "
                f"{tuple(x_new.shape)}"
            )
        if y_new.shape != (x_new.shape[0],):
            raise ValueError(
                f"y_new must be [{x_new.shape[0]}], got {tuple(y_new.shape)}"
            )
        # f64 on the host CPU regardless of the global x64 flag (same
        # precision rationale as the fit-time statistics accumulation)
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            cpu = None
        import contextlib

        u1 = np.asarray(self.u1, dtype=np.float64).copy()
        u2 = np.asarray(self.u2, dtype=np.float64).copy()
        m = self.active.shape[0]
        # bounded-memory accumulation, like the prediction path: the
        # [m, chunk] cross intermediate is capped, so 'streaming update'
        # holds for arbitrarily large t (u1 += c c^T per chunk is the same
        # sum in a different bracketing)
        chunk = max(1, self._PREDICT_CHUNK_ELEMS // max(1, m))
        ctx = jax.default_device(cpu) if cpu is not None else contextlib.nullcontext()
        with jax.enable_x64(), ctx:
            theta64 = jnp.asarray(self.theta, dtype=jnp.float64)
            active64 = jnp.asarray(self.active, dtype=jnp.float64)
            for start in range(0, x_new.shape[0], chunk):
                cross = np.asarray(
                    self.kernel.cross(
                        theta64, active64,
                        jnp.asarray(x_new[start : start + chunk]),
                    )
                )  # [m, <=chunk]
                u1 += cross @ cross.T
                u2 += cross @ y_new[start : start + chunk]
        magic_vector, magic_matrix = magic_solve(
            self.kernel, self.theta, self.active, u1, u2,
            with_variance=self.magic_matrix is not None,
        )
        return ProjectedProcessRawPredictor(
            kernel=self.kernel,
            theta=self.theta,
            active=self.active,
            magic_vector=np.asarray(magic_vector),
            magic_matrix=(
                None if self.magic_matrix is None else np.asarray(magic_matrix)
            ),
            u1=u1,
            u2=u2,
        )

    def predict_fn(self):
        """Returns a jittable ``x_test [t, p] -> (mean [t], var [t])``."""
        if self.magic_matrix is None:
            raise ValueError(
                "model was fitted with setPredictiveVariance(False); "
                "no variance operator is available"
            )
        return partial(_predict_impl, self.kernel)

    # cap on the [t, m] cross-kernel intermediate per dispatch: 32M entries
    # (256 MB f64) — predictions on millions of rows stream through in
    # fixed-size chunks instead of materializing one [t, m] matrix.
    _PREDICT_CHUNK_ELEMS = 32 * 1024 * 1024

    def predict_mean(self, x_test):
        """Mean-only prediction ``[t]`` — skips the O(t m^2) variance
        einsum entirely; works on full and mean-only models alike (the
        cheap path for every caller that discards the variance)."""
        return self._run(x_test, mean_only=True)[0]

    def predict_with_cov(self, x_test):
        """``(mean [t], cov [t, t])`` — the full joint predictive
        covariance (see :func:`_predict_cov_impl`).  Unchunked: the result
        itself is O(t^2)."""
        if self.magic_matrix is None:
            raise ValueError(
                "model was fitted with setPredictiveVariance(False); "
                "no covariance operator is available"
            )
        x_test = _as_float(x_test, self.active.shape[1])
        dtype = x_test.dtype
        return _predict_cov_jit(
            self.kernel,
            jnp.asarray(self.theta, dtype=dtype),
            jnp.asarray(self.active, dtype=dtype),
            jnp.asarray(self.magic_vector, dtype=dtype),
            jnp.asarray(self.magic_matrix, dtype=dtype),
            x_test,
            lane=active_lane(),
        )

    def __call__(self, x_test):
        """``(mean [t], var [t])`` — ``var`` is None for mean-only models."""
        return self._run(x_test, mean_only=self.magic_matrix is None)

    def _run(self, x_test, mean_only: bool):
        x_test = _as_float(x_test, self.active.shape[1])
        dtype = x_test.dtype
        args = (
            self.kernel,
            jnp.asarray(self.theta, dtype=dtype),
            jnp.asarray(self.active, dtype=dtype),
            jnp.asarray(self.magic_vector, dtype=dtype),
        ) + (() if mean_only else (jnp.asarray(self.magic_matrix, dtype=dtype),))
        predict = _predict_mean_jit if mean_only else _predict_jit
        lane = active_lane()
        m = max(1, self.active.shape[0])
        # clamped to the request: a dispatch never exceeds t rows, so the
        # ladder's halvings walk down from the size that actually OOMed
        chunk = max(1, min(self._PREDICT_CHUNK_ELEMS // m, x_test.shape[0]))
        from spark_gp_tpu.resilience import memplan
        from spark_gp_tpu.resilience.fallback import run_predict_ladder

        itemsize = int(jnp.dtype(dtype).itemsize)
        # memory plan (resilience/memplan.py): with a resolvable device
        # budget the chunk is PRE-SIZED to the largest predicted-safe
        # dispatch — the reactive halving ladder's rungs as first
        # choices.  No budget / GP_MEMPLAN=0: None — the default chunk,
        # today's path bit-for-bit.
        planned_chunk = memplan.plan_predict_chunk(
            chunk, m, self.active.shape[1], itemsize, mean_only
        )
        if planned_chunk is not None:
            chunk = planned_chunk

        def dispatch_bytes(rows: int) -> float:
            # the chaos allocator model's 'allocation size' for one chunk
            # dispatch — the same raw model the plan budgeted with; also
            # arms the calibration loop (the metered compiled peak of
            # this dispatch judges the model)
            raw = memplan.predict_dispatch_bytes(
                rows, m, self.active.shape[1], itemsize, mean_only
            )
            memplan.note_expected_dispatch(
                memplan.predict_model_key(mean_only), raw
            )
            return raw

        # degradation ladder (resilience/fallback.py): an OOM on a chunk
        # dispatch halves the chunk — re-dispatching the request at a
        # shape that fits under the allocator's ceiling — bounded, then
        # the eager host-f64 solve as the last rung.  Clean requests run
        # exactly the pre-ladder path.
        return run_predict_ladder(
            lambda c: self._run_at_chunk(
                x_test, args, predict, lane, dtype, mean_only, c,
                dispatch_bytes,
            ),
            lambda: self._host_predict(x_test, mean_only),
            chunk,
            planned=planned_chunk is not None,
        )

    def _run_at_chunk(
        self, x_test, args, predict, lane, dtype, mean_only: bool, chunk: int,
        dispatch_bytes=None,
    ):
        from spark_gp_tpu.resilience import chaos

        from spark_gp_tpu.obs import cost as obs_cost

        bytes_of = dispatch_bytes if dispatch_bytes is not None else (
            lambda rows: None
        )
        t = x_test.shape[0]
        if t <= chunk:
            chaos.maybe_injected_failure(
                "predict.chunk", rows=t, nbytes=bytes_of(t)
            )
            # measured flops/bytes per predict dispatch (obs/cost.py,
            # GP_XLA_COST) — the gp_xla_*_total{entry="predict.ppa"} series
            out = obs_cost.observed_call(
                "predict.ppa", predict,
                *args, jnp.asarray(x_test, dtype=dtype), lane=lane,
            )
            return (out, None) if mean_only else out
        # fixed chunk shape (last chunk padded) -> one compiled executable
        means, vars_ = [], []
        for start in range(0, t, chunk):
            part = x_test[start : start + chunk]
            pad = chunk - part.shape[0]
            if pad:
                part = jnp.concatenate(
                    [part, jnp.broadcast_to(part[:1], (pad, part.shape[1]))]
                )
            chaos.maybe_injected_failure(
                "predict.chunk", rows=chunk, nbytes=bytes_of(chunk)
            )
            out = obs_cost.observed_call(
                "predict.ppa", predict,
                *args, jnp.asarray(part, dtype=dtype), lane=lane,
            )
            mean, var = (out, None) if mean_only else out
            means.append(mean[: chunk - pad] if pad else mean)
            if var is not None:
                vars_.append(var[: chunk - pad] if pad else var)
        return (
            jnp.concatenate(means),
            jnp.concatenate(vars_) if vars_ else None,
        )

    def _host_predict(self, x_test, mean_only: bool):
        """Eager f64 host-CPU prediction — the predict ladder's last rung.

        Deliberately UNJITTED (a compile-failure fallback must not compile)
        and pinned to the host CPU device with x64 enabled, at a small
        fixed chunk so the [t, m] cross intermediate stays bounded.  Bit
        accuracy: f64, so at least as accurate as the device path it
        replaces (slower — this rung answers, it does not race)."""
        import contextlib

        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            cpu = None
        ctx = (
            jax.default_device(cpu) if cpu is not None
            else contextlib.nullcontext()
        )
        mean_only = mean_only or self.magic_matrix is None
        with jax.enable_x64(), ctx:
            theta = jnp.asarray(np.asarray(self.theta), dtype=jnp.float64)
            active = jnp.asarray(np.asarray(self.active), dtype=jnp.float64)
            mv = jnp.asarray(np.asarray(self.magic_vector), dtype=jnp.float64)
            mm = (
                None if mean_only
                else jnp.asarray(np.asarray(self.magic_matrix), jnp.float64)
            )
            x64 = jnp.asarray(np.asarray(x_test), dtype=jnp.float64)
            m = max(1, active.shape[0])
            chunk = max(1, (self._PREDICT_CHUNK_ELEMS // 8) // m)
            means, vars_ = [], []
            for start in range(0, x64.shape[0], chunk):
                part = x64[start : start + chunk]
                if mean_only:
                    means.append(
                        _predict_mean_impl(self.kernel, theta, active, mv, part)
                    )
                else:
                    mean, var = _predict_impl(
                        self.kernel, theta, active, mv, mm, part
                    )
                    means.append(mean)
                    vars_.append(var)
            mean = jnp.concatenate(means)
            return (mean, None) if mean_only else (mean, jnp.concatenate(vars_))


def _predict_impl(kernel, theta, active, magic_vector, magic_matrix, x_test):
    """mean = cross . magicVector ; var = k(x,x) + cross . magicMatrix . crossT
    (GaussianProcessCommons.scala:121-125), batched over test points."""
    cross = kernel.cross(theta, x_test, active)  # [t, m]
    mean = cross @ magic_vector
    var = kernel.self_diag(theta, x_test) + jnp.einsum(
        "tm,mk,tk->t", cross, magic_matrix, cross
    )
    return mean, var


def _predict_cov_impl(kernel, theta, active, magic_vector, magic_matrix, x_test):
    """Full joint predictive covariance between test points:
    ``Cov = K_tt + Cross . magicMatrix . Cross^T`` — the off-diagonal
    extension of the per-point variance formula (same magic matrix, R&W
    eq. 8.27; its diagonal equals ``var`` exactly since the Eye component
    of the noise-augmented kernel contributes only on the diagonal).
    Capability beyond the reference, which never exposes joint structure
    (GaussianProcessCommons.scala:124 computes scalars per row); needed
    for coherent posterior sampling / Thompson-style acquisition.
    O(t^2) memory by nature — intended for modest t."""
    cross = kernel.cross(theta, x_test, active)  # [t, m]
    mean = cross @ magic_vector
    cov = kernel.gram(theta, x_test) + cross @ magic_matrix @ cross.T
    # exact symmetry (float rounding in the two matmuls breaks it at the
    # ~1e-14 level, which a downstream Cholesky would amplify)
    return mean, 0.5 * (cov + cov.T)


def _predict_mean_impl(kernel, theta, active, magic_vector, x_test):
    """Mean-only prediction: ``cross . magicVector`` (no [m, m] operator)."""
    return kernel.cross(theta, x_test, active) @ magic_vector


# The chunked-predict programs carry the precision lane (ops/precision.py)
# in their jit keys, like the fit entry points in models/likelihood.py:
# the cross-kernel build inside rides the gram lane, and switching lanes
# between predictions must recompile rather than silently reuse the old
# lane's executables.
def _lane_jit(impl):
    def with_lane(kernel, *operands, lane=None):
        with precision_lane_scope(lane):
            return impl(kernel, *operands)

    return jax.jit(with_lane, static_argnums=0, static_argnames=("lane",))


_predict_cov_jit = _lane_jit(_predict_cov_impl)
_predict_jit = _lane_jit(_predict_impl)
_predict_mean_jit = _lane_jit(_predict_mean_impl)


@partial(jax.jit, static_argnums=0, static_argnames=("lane",))
def guard_probe_predict_mean(
    kernel: Kernel, theta, active, magic_vector, x_test, *, lane
):
    """Posterior-mean probe at an EXPLICIT lane — the predict leg of the
    fit-time mixed_precision_guard (models/common.py).  ``lane`` is
    static so the strict and non-strict evaluations compile separately
    and can be compared within one process."""
    with precision_lane_scope(lane):
        return _predict_mean_impl(kernel, theta, active, magic_vector, x_test)
