"""Shared estimator plumbing: the param/flag system and the training skeleton.

Counterparts of commons/GaussianProcessParams.scala (param definitions,
defaults and fluent setters — names preserved verbatim per the API contract)
and commons/GaussianProcessCommons.scala (noise-augmented kernel factory,
expert grouping, hyperparameter optimization driver, PPA model production).

TPU-specific additions: ``setMesh`` (a ``jax.sharding.Mesh`` to shard the
expert axis over; ``None`` = single device) and ``setCheckpointDir``
(periodic L-BFGS state checkpointing — the reference has no resume story,
SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
from typing import Callable, Optional, Union

import numpy as np

from spark_gp_tpu.kernels.base import Const, EyeKernel, Kernel
from spark_gp_tpu.models.active_set import ActiveSetProvider, RandomActiveSetProvider
from spark_gp_tpu.models import ppa
from spark_gp_tpu.optimize.lbfgsb import minimize_lbfgsb
from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
from spark_gp_tpu.parallel.mesh import shard_experts
from spark_gp_tpu.utils.instrumentation import Instrumentation, phase_sync


class GaussianProcessParams:
    """Fluent parameter mixin; defaults match GaussianProcessParams.scala:32-53."""

    def __init__(self) -> None:
        self._kernel_factory: Callable[[], Kernel] = _default_kernel_factory
        self._dataset_size_for_expert: int = 100
        self._active_set_size: int = 100
        self._sigma2: float = 1e-3
        self._active_set_provider: ActiveSetProvider = RandomActiveSetProvider
        self._max_iter: int = 100
        self._tol: float = 1e-6
        self._seed: int = 0
        self._mesh = None
        self._checkpoint_dir: Optional[str] = None
        self._checkpoint_interval: int = 10
        self._optimizer: str = "auto"
        self._hyper_space: str = "auto"
        self._profile_dir: Optional[str] = None
        self._predictive_variance: bool = True
        self._num_restarts: int = 1
        self._restart_scale: float = 0.5
        self._expert_quarantine: bool = True
        self._fit_retries: int = 2

    # --- reference setter names (GaussianProcessParams.scala:32-53) -------
    def setKernel(self, value: Union[Kernel, Callable[[], Kernel]]):
        """A kernel *factory* (zero-arg callable), or a Kernel spec directly —
        kernels here are immutable so sharing one spec is safe."""
        if isinstance(value, Kernel):
            self._kernel_factory = lambda: value
        else:
            self._kernel_factory = value
        return self

    def setDatasetSizeForExpert(self, value: int):
        self._dataset_size_for_expert = int(value)
        return self

    def setActiveSetSize(self, value: int):
        self._active_set_size = int(value)
        return self

    def setSigma2(self, value: float):
        self._sigma2 = float(value)
        return self

    def setActiveSetProvider(self, value: ActiveSetProvider):
        self._active_set_provider = value
        return self

    def setMaxIter(self, value: int):
        self._max_iter = int(value)
        return self

    def setTol(self, value: float):
        self._tol = float(value)
        return self

    def setSeed(self, value: int):
        self._seed = int(value)
        return self

    def setAggregationDepth(self, value: int):
        """API parity no-op: the reference declares this Spark ML param
        (GaussianProcessParams.scala:9) but never forwards it to either
        ``treeAggregate`` call (GPC.scala:73, PGPH.scala:25), and on TPU
        the reduction topology is XLA's choice — psum over ICI picks the
        ring/tree shape itself.  Accepted (and validated) so reference
        call sites port without edits."""
        if int(value) < 1:
            raise ValueError("aggregation depth must be >= 1")
        return self

    # --- TPU-native extensions -------------------------------------------
    def setMesh(self, mesh):
        """Shard the expert axis over this ``jax.sharding.Mesh`` (1-D)."""
        self._mesh = mesh
        return self

    def setPredictiveVariance(self, value: bool):
        """``True`` (default, the reference's behavior): build the [m, m]
        magic matrix so the model predicts variances.  ``False``: mean-only
        model — skips the two O(m^3) inverse builds in the magic solve and
        the [m, m] operator in the saved model, the dominant cost and
        memory at large active sets (m ~ 10^4: ~800 MB f64 and most of the
        solve time buys nothing if variances are never read)."""
        self._predictive_variance = bool(value)
        return self

    def setNumRestarts(self, value: int, scale: float = 0.5):
        """Multi-start hyperparameter optimization (capability beyond the
        reference, which runs L-BFGS-B from the kernel's initial values
        once, GaussianProcessCommons.scala:84-86).  GP marginal likelihoods
        are multimodal; ``value`` > 1 runs the fit from the user's starting
        point plus ``value - 1`` seeded perturbations of it (log-normal
        when the log hyper-space applies, else relative-scale normal,
        clipped to the box bounds) and keeps the model with the lowest
        final NLL.  ``scale`` controls the perturbation width.  Each
        restart is a COMPLETE fit — including the PPA model build — so at
        very large active sets (m >~ 10^4, where the two O(m^3) inverse
        builds dominate) pair restarts with
        ``setPredictiveVariance(False)`` or a moderate m.  Not combinable
        with ``setCheckpointDir`` (the restarts would overwrite one state
        file)."""
        if int(value) < 1:
            raise ValueError("number of restarts must be >= 1")
        self._num_restarts = int(value)
        self._restart_scale = float(scale)
        return self

    def setProfileDir(self, path: Optional[str]):
        """Capture a ``jax.profiler`` trace of the fit into this directory
        (viewable in TensorBoard/Perfetto).  ``None`` (default) disables
        profiling.  The reference has no tracing at all (SURVEY.md §5 —
        three Instrumentation log lines); a TPU framework without timeline
        capture is undebuggable, so this is a first-class estimator flag.
        """
        self._profile_dir = path
        return self

    def setCheckpointDir(self, path: Optional[str]):
        """Persist optimizer state for kill-and-resume durability.

        Host optimizer: theta is saved every L-BFGS iteration.  Device
        optimizer: the fit runs in ``checkpointInterval``-iteration segments
        and the FULL L-BFGS state (iterate, history, aux) is persisted
        between segments; a matching checkpoint in this directory resumes
        the fit mid-run automatically.
        """
        self._checkpoint_dir = path
        return self

    def setCheckpointInterval(self, iters: int):
        """Device-optimizer segment length: iterations between checkpoints
        (default 10).  Smaller = finer resume granularity, one extra host
        sync per segment."""
        if int(iters) < 1:
            raise ValueError("checkpoint interval must be >= 1")
        self._checkpoint_interval = int(iters)
        return self

    def setExpertQuarantine(self, value: bool):
        """``True`` (default): experts whose NLL or gradient is non-finite
        — NaN data rows, Gram matrices past the edge of positive
        definiteness — are repaired (adaptive jitter escalation over the
        ``ops.linalg.JITTER_SCHEDULE`` ladder) or, failing that, dropped
        from the BCM sum with renormalization (``resilience/quarantine.py``)
        instead of poisoning the global objective.  A failure affecting
        EVERY expert still raises (that is a configuration problem — the
        classic remedy is increasing sigma2 — not a per-expert fault).
        ``False``: the pre-quarantine behavior — any non-finite expert
        fails the fit."""
        self._expert_quarantine = bool(value)
        return self

    def setFitRetries(self, value: int):
        """Recovery budget: how many times a failed fit attempt is retried
        (with backoff) after quarantine/jitter repair — and how many times
        a transiently-failing device fit is re-dispatched.  Default 2;
        0 disables retries (first failure is final)."""
        if int(value) < 0:
            raise ValueError("fit retries must be >= 0")
        self._fit_retries = int(value)
        return self

    def setPrecisionLane(self, value: str):
        """Mixed-precision lane for the MXU contractions
        (:mod:`spark_gp_tpu.ops.precision`): ``"strict"`` (default —
        HIGHEST everywhere, today's exact numerics), ``"mixed"``
        (compensated split-bf16 gram/cross builds + 3-pass bf16x3 linalg
        matmuls — ~2x the matmul-rate ceiling with accuracy recovered
        structurally), or ``"fast"`` (1-pass bf16 gram builds —
        experiments only).  Cholesky, triangular solves and the f64 PPA
        statistics are lane-immune.  The setter is a fluent veneer over
        the PROCESS-wide knob (``set_precision_lane`` /
        ``GP_PRECISION_LANE``): lanes are resolved into the fit/predict
        programs' jit keys, so the setting takes effect from the next
        fit on.  Every fit at a non-default lane emits a
        ``mixed_precision_guard`` artifact (max relative |delta NLL| /
        |delta grad| / |delta predict| vs the strict lane on a probe
        expert) into its Instrumentation metrics, with a loud warning
        when the lane's accuracy bar is breached."""
        from spark_gp_tpu.ops.precision import set_precision_lane

        set_precision_lane(value)
        return self

    def setSolverLane(self, value: str):
        """Dense-linear-algebra solver lane for the fit objectives
        (:mod:`spark_gp_tpu.ops.iterative`): ``"exact"`` (default —
        today's batched Cholesky/Pallas factorizations, bit-for-bit),
        ``"iterative"`` (batched preconditioned CG + stochastic Lanczos
        quadrature: every per-evaluation O(s^3) factorization becomes
        O(t s^2) batched matmul work — the MXU's shape — at a documented
        stochastic tolerance on the log-det/trace legs; the unlock for
        expert sizes s in the thousands), or ``"auto"`` (iterative once
        s reaches ``GP_SOLVER_AUTO_THRESHOLD``, default 1024).  The
        setter is a fluent veneer over the PROCESS-wide knob
        (``set_solver_lane`` / ``GP_SOLVER_LANE``); the fit entry points
        carry the resolved lane in their jit cache keys, so the setting
        takes effect from the next fit on.  The engaged lane and the
        iterative lane's convergence stats (``solver.cg_iters`` /
        ``solver.residual`` / ...) land in the fit metrics, the run
        journal, and the saved model's ``provenance_json``."""
        from spark_gp_tpu.ops.iterative import set_solver_lane

        set_solver_lane(value)
        return self

    def setAggregationPolicy(self, value: str):
        """Expert-aggregation policy for the prediction plane
        (:mod:`spark_gp_tpu.models.aggregation`): ``"poe"`` (default —
        the reference's plain product-of-experts, bit-for-bit today's
        numerics), ``"gpoe"`` (generalized PoE: uniform 1/E tempering,
        calibrated variances at any E), ``"rbcm"`` (robust Bayesian
        committee machine: entropy-weighted experts with the prior
        correction — the strongest default for disjoint experts), or
        ``"healed"`` (rBCM entropy weights clamped >= 0 and renormalized
        to a convex combination: removes rBCM's variance blow-up when
        experts are weak far from data).  The setter is a fluent veneer
        over the PROCESS-wide knob (``set_agg_policy`` /
        ``GP_AGG_POLICY``); predictors resolve the policy at build time
        and carry it in their jit cache keys, so the setting takes
        effect from the next fit/predict on.  The engaged policy and
        the fit-time selection weights (``agg.*``) land in the fit
        metrics, the run journal, and the saved model's
        ``provenance_json``."""
        from spark_gp_tpu.models.aggregation import set_agg_policy

        set_agg_policy(value)
        return self

    def setOptimizer(self, value: str):
        """``"host"`` — SciPy L-BFGS-B driving the jitted objective (one
        device dispatch per evaluation; bitwise closest to the reference's
        Breeze LBFGSB).  ``"device"`` — the entire projected-L-BFGS loop runs
        on device in one XLA program (``optimize/lbfgs_device.py``); fastest
        on high-dispatch-latency runtimes and multi-host pods.  ``"auto"``
        (default) — device on TPU, host elsewhere: every host-driven
        objective evaluation costs a full host<->device round trip, which on
        remote/tunneled TPU runtimes is ~100x the evaluation itself."""
        if value not in ("auto", "host", "device"):
            raise ValueError("optimizer must be 'auto', 'host' or 'device'")
        self._optimizer = value
        return self

    def _resolved_optimizer(self) -> str:
        if getattr(self, "_fallback_mode", None) == "host_f64":
            # the degradation ladder's host rung (resilience/fallback.py):
            # re-execute the failed fit host-driven, whatever was asked for
            return "host"
        if getattr(self, "_dcn_ctx", None) is not None:
            # DCN-fallback fits interleave a KV-store allreduce into every
            # objective evaluation — only the host-driven optimizer has a
            # host boundary per evaluation to do that at (the device
            # optimizer's whole L-BFGS loop is one XLA program)
            return "host"
        if self._optimizer != "auto":
            return self._optimizer
        import jax

        return "device" if jax.default_backend() == "tpu" else "host"

    # --- degradation-ladder plumbing (resilience/fallback.py) -------------
    def _fallback_segmented(self) -> bool:
        """True while the ladder's ``segmented`` rung is executing: the
        device fit routes through the checkpointed segment driver with an
        in-memory saver and a halved segment batch."""
        return getattr(self, "_fallback_mode", None) == "segmented"

    def _segment_saver_and_chunk(self, file_tag: str, data):
        """``(saver, chunk)`` for a segmented device fit: the real
        coordinated checkpointer + configured interval when a checkpoint
        dir is set; the ladder's in-memory null saver + HALVED segment
        batch when the segmented fallback rung re-executes a fit that
        never asked for durability."""
        if self._checkpoint_dir is not None:
            return (
                self._make_device_checkpointer(file_tag, data),
                self._checkpoint_interval,
            )
        from spark_gp_tpu.resilience.fallback import (
            NullSegmentSaver,
            fallback_segment_chunk,
        )

        return (
            NullSegmentSaver(),
            fallback_segment_chunk(self._checkpoint_interval),
        )

    def _host_f64_operands(self, data, extra=(), cache=None):
        """``(data, extra, cache)`` with the expert stack re-materialized
        in float64 and the gram cache dropped WHEN the ladder's
        ``host_f64`` rung is executing over an unmeshed stack (the rung
        runs under ``jax.enable_x64``, so an f32 runtime gets real
        precision headroom; the f64 recompute path is the exact reference
        semantics) — and the inputs untouched otherwise.  The gate lives
        HERE so the four families' host branches stay one unconditional
        call and cannot drift."""
        if (
            getattr(self, "_fallback_mode", None) != "host_f64"
            or self._mesh is not None
        ):
            return data, extra, cache
        import jax.numpy as jnp

        def cast(a):
            return jnp.asarray(np.asarray(a), dtype=jnp.float64)

        data64 = ExpertData(x=cast(data.x), y=cast(data.y), mask=cast(data.mask))
        # extras may carry a None placeholder slot (the aggregation
        # plane's (None, weights) marginal-extras shape) — pass it through
        extra64 = tuple(cast(e) if e is not None else None for e in extra)
        return data64, extra64, None

    def _device_fit_op(self) -> str:
        """Chaos choke-point name of the device-fit dispatch about to run
        (``resilience/chaos.maybe_injected_failure``): staged faults scope
        to one dispatch shape, so e.g. an injected one-dispatch OOM leaves
        the segmented rung's smaller dispatches clean."""
        if self._checkpoint_dir is not None or self._fallback_segmented():
            return "fit.device.segment"
        if self._mesh is not None:
            return "fit.device.sharded"
        return "fit.device.one_dispatch"

    def _dispatch_raw_bytes(self, data):
        """Modeled RAW peak bytes of the device-fit dispatch about to run
        (``resilience/memplan.fit_dispatch_bytes`` at the CURRENT rung) —
        the 'allocation size' the chaos memory-budget injector compares
        against its staged limit, and the quantity the memory plan
        guarantees ``predicted >= raw`` for.  None for sharded dispatches
        (per-chip footprints are not modeled yet — ROADMAP item 3 needs
        the sharded-tile model)."""
        if self._mesh is not None:
            return None
        from spark_gp_tpu.ops.iterative import resolve_solver
        from spark_gp_tpu.resilience import memplan

        resolved = resolve_solver(
            int(data.x.shape[1]),
            num_experts=int(data.x.shape[0]),
            n_features=int(data.x.shape[2]),
            itemsize=int(np.dtype(data.x.dtype).itemsize),
        )
        if self._checkpoint_dir is not None or self._fallback_segmented():
            rung = "segmented"
        elif resolved == "matfree":
            # the matrix-free lane streams the gram — O(E·s·(k+r+tile))
            # resident — but only for matvec-capable kernels; others run
            # the materialized iterative program and must be priced as it
            from spark_gp_tpu.kernels.base import supports_matfree

            rung = (
                "matfree" if supports_matfree(self._get_kernel())
                else "iterative"
            )
        elif resolved == "iterative":
            # the CG/Lanczos solver lane (by knob, auto-threshold, or the
            # ladder's iterative rung — all of which resolve here) has
            # the skinny-workspace byte model, not the factor-stack one
            rung = "iterative"
        else:
            rung = "native"
        n_targets = (
            int(data.y.shape[2]) if getattr(data.y, "ndim", 2) == 3 else 1
        )
        family = type(self).__name__
        raw = memplan.fit_dispatch_bytes(
            int(data.x.shape[0]), int(data.x.shape[1]),
            int(data.x.shape[2]), int(np.dtype(data.x.dtype).itemsize),
            rung, n_targets, family,
        )
        # arm the calibration loop: the dispatch about to run is the one
        # whose metered compiled peak should judge this model estimate
        memplan.note_expected_dispatch(
            memplan.fit_model_key(family, rung), raw
        )
        return raw

    def setHyperSpace(self, value: str):
        """Coordinate system for hyperparameter optimization.

        ``"log"`` — optimize u = log(theta) (requires positive initial values
        and non-negative lower bounds).  ``"linear"`` — raw coordinates, the
        reference's exact setup (GaussianProcessCommons.scala:84-86).
        ``"auto"`` (default) — log when applicable, else linear: GP marginal
        likelihoods are badly scaled in linear coordinates (the amplitude
        hyperparameter dominates and L-BFGS can collapse into the
        constant-kernel optimum, as the airfoil config does in any
        precision), and log-domain optimization is the standard remedy.
        """
        if value not in ("auto", "log", "linear"):
            raise ValueError("hyper space must be 'auto', 'log' or 'linear'")
        self._hyper_space = value
        return self

    def _use_log_space(self, kernel) -> bool:
        from spark_gp_tpu.optimize.lbfgsb import log_space_applicable

        if self._hyper_space == "linear":
            return False
        applicable = log_space_applicable(kernel.init_theta(), kernel.bounds()[0])
        if self._hyper_space == "log" and not applicable:
            raise ValueError(
                "log hyper space requires theta0 > 0 and lower bounds >= 0"
            )
        return applicable

    # snake_case aliases for pythonic call sites
    set_kernel = setKernel
    set_dataset_size_for_expert = setDatasetSizeForExpert
    set_active_set_size = setActiveSetSize
    set_sigma2 = setSigma2
    set_active_set_provider = setActiveSetProvider
    set_max_iter = setMaxIter
    set_tol = setTol
    set_seed = setSeed
    set_aggregation_depth = setAggregationDepth
    set_mesh = setMesh
    set_predictive_variance = setPredictiveVariance
    set_profile_dir = setProfileDir
    set_checkpoint_dir = setCheckpointDir
    set_checkpoint_interval = setCheckpointInterval
    set_optimizer = setOptimizer
    set_precision_lane = setPrecisionLane
    set_solver_lane = setSolverLane
    set_aggregation_policy = setAggregationPolicy
    set_hyper_space = setHyperSpace
    set_num_restarts = setNumRestarts
    set_expert_quarantine = setExpertQuarantine
    set_fit_retries = setFitRetries

    def get_params(self) -> dict:
        return {
            "datasetSizeForExpert": self._dataset_size_for_expert,
            "activeSetSize": self._active_set_size,
            "sigma2": self._sigma2,
            "maxIter": self._max_iter,
            "tol": self._tol,
            "seed": self._seed,
        }


def _default_kernel_factory() -> Kernel:
    from spark_gp_tpu.kernels.rbf import RBFKernel

    return RBFKernel()


class GaussianProcessCommons(GaussianProcessParams):
    """Shared training skeleton (GaussianProcessCommons.scala:15-115)."""

    # Regression overrides to True: its PPA statistics sum over the raw
    # targets, so they stay meaningful for incremental updates.  Laplace
    # families sum over latent modes — stats are fit-internal there.
    _keeps_update_statistics: bool = False

    @contextlib.contextmanager
    def _stack_mesh(self, data):
        """Context manager resolving the mesh for a ``fit_distributed`` call:
        uses ``setMesh(...)`` when given, else the stack's own NamedSharding;
        restores the estimator's mesh on exit (the estimator stays reusable
        for plain ``fit``)."""
        mesh_prev = self._mesh
        if self._mesh is None:
            from jax.sharding import NamedSharding

            sh = getattr(data.x, "sharding", None)
            if not isinstance(sh, NamedSharding):
                raise ValueError(
                    "fit_distributed needs setMesh(...) or a "
                    "NamedSharding-sharded expert stack"
                )
            self._mesh = sh.mesh
        try:
            yield
        finally:
            self._mesh = mesh_prev

    def _get_kernel(self) -> Kernel:
        """User kernel + sigma2 * I — the noise-augmented model kernel
        (GaussianProcessCommons.scala:18)."""
        return self._kernel_factory() + Const(self._sigma2) * EyeKernel()

    @contextlib.contextmanager
    def _dcn_scope(self):
        """Bind the process's DCN coordination context (parallel/coord.py)
        to this fit: inside the scope the optimizer is forced host-side,
        every objective evaluation's (value, grad) is KV-allreduced, the
        (U1, u2) statistics are KV-allreduced, and checkpoints run the
        coordinated protocol.  ``None`` (single process / native
        global-array backends) leaves every path untouched."""
        from spark_gp_tpu.parallel import coord

        prev = getattr(self, "_dcn_ctx", None)
        prev_flag = getattr(self, "_fit_is_distributed", False)
        self._dcn_ctx = coord.dcn_context()
        # the scope marker is separate from the ctx: global-array pods run
        # fit_distributed WITHOUT a DCN ctx but still need coordinated
        # checkpoints — while a plain per-host fit() on the same pod must
        # keep plain local writers (_coord_ctx_for_checkpoint)
        self._fit_is_distributed = True
        try:
            yield self._dcn_ctx
        finally:
            self._dcn_ctx = prev
            self._fit_is_distributed = prev_flag

    def _coord_ctx_for_checkpoint(self):
        """The coordination context checkpoint writers should use: the DCN
        fit context when one is bound; else — ONLY inside a
        ``fit_distributed`` scope — the process's cached bare context on
        real multi-process (global-array) runtimes (cached so its round
        counters stay monotonic across fits); else ``None``.  A plain
        per-host ``fit()`` on a pod keeps plain local writers: two
        INDEPENDENT fits must never rendezvous on shared KV gathers (the
        digests would spuriously mismatch) or resume from each other's
        payloads."""
        from spark_gp_tpu.parallel import coord

        ctx = getattr(self, "_dcn_ctx", None)
        if ctx is not None:
            return ctx
        if not getattr(self, "_fit_is_distributed", False):
            return None
        return coord.checkpoint_coordination_context()

    def _observed_fit(self, instr: Instrumentation, run):
        """Observability shell around one COMPLETE public fit: opens the
        root span every phase span nests under, activates the runtime
        capture (compile counting + phase-boundary memory sampling), and
        stamps the returned model with its ``run_journal``
        (obs/runtime.py) — persisted next to the checkpoints when a
        checkpoint dir (or ``GP_RUN_JOURNAL_DIR``) is configured.

        ``run()`` is the whole fit (restarts, recovery, everything); with
        tracing off (``GP_TRACING=0``) this is a straight call — the
        bench's observability section measures exactly that difference.

        This shell is also the forensics plane's fit-side anchor
        (obs/recorder.py): the fit's trace id is minted here — stitched
        over the coordination KV plane on multi-host fits, so every
        host's journal shares one id — and a TERMINAL classified failure
        escaping the fit dumps exactly one incident bundle (failing span
        tree, recorder events, rung history, compile/memory deltas)
        before re-raising.  Successfully-degraded fits journal normally.
        """
        from spark_gp_tpu.obs import recorder as obs_recorder
        from spark_gp_tpu.obs import runtime as obs_runtime
        from spark_gp_tpu.obs import trace as obs_trace

        if not obs_trace.tracing_enabled():
            # tracing off: no spans, no capture, no journal — but the
            # forensics contract (one bundle per terminal classified
            # failure) rides the INDEPENDENT recorder gate, so the
            # failure shell stays active (its bundle just has no span
            # tree).  GP_RECORDER=0 is the recorder's own kill switch.
            try:
                return run()
            except Exception as exc:  # classified-failure-site: bundle + re-raise
                obs_recorder.record_fit_failure(
                    exc, entry=f"fit.{instr.name}", instr=instr,
                    directory=self._checkpoint_dir,
                )
                raise
        from spark_gp_tpu.parallel import coord

        stitch_ctx = (
            self._coord_ctx_for_checkpoint()
            if getattr(self, "_fit_is_distributed", False) else None
        )
        token = coord.stitch_trace_token(stitch_ctx)
        with obs_runtime.trace_token_scope(token):
            with obs_runtime.fit_capture(instr.name) as cap:
                root = None
                try:
                    with obs_trace.span(
                        f"fit.{instr.name}", family=type(self).__name__,
                        trace_token=token,
                    ) as root:
                        model = run()
                except Exception as exc:  # classified-failure-site: bundle + re-raise
                    obs_recorder.record_fit_failure(
                        exc, entry=f"fit.{instr.name}", instr=instr,
                        root=root, capture=cap,
                        directory=self._checkpoint_dir,
                    )
                    raise
        journal_instr = getattr(model, "instr", None) or instr
        model.run_journal = obs_runtime.write_run_journal(
            journal_instr, root, cap,
            mesh=self._mesh, journal_dir=self._checkpoint_dir,
            trace_token=token,
        )
        return model

    def _fit_with_restarts(self, outer_instr: Instrumentation, fit_once):
        """Multi-start driver (setNumRestarts): ``fit_once(kernel, instr)``
        must return a fitted model carrying
        ``model.instr.metrics['final_nll']``.  Restart 0 is the user's
        starting point on ``outer_instr`` (which already carries the
        grouping metrics/timings); each further restart wraps the kernel
        with a seeded perturbed ``init_theta`` on a fresh instr seeded from
        the outer one — the fit paths themselves are untouched.  Returns
        the lowest-NLL model, its instr annotated with every restart's NLL.
        """
        from spark_gp_tpu.kernels.base import ThetaOverrideKernel

        kernel = self._get_kernel()
        if self._num_restarts <= 1:
            model = fit_once(kernel, outer_instr)
            self._log_renormalized_nll(model.instr)
            return model
        if self._checkpoint_dir is not None:
            raise ValueError(
                "setNumRestarts(>1) is not combinable with "
                "setCheckpointDir (restarts would overwrite one state file)"
            )
        theta_batch = self._restart_theta_batch(kernel)
        # Snapshot the pre-fit state BEFORE any restart runs: later restarts
        # must inherit the grouping metrics/timings only, not restart 0's
        # fit results (phase() accumulates, so copying afterwards would
        # double-count optimize/PPA timings on a non-0 winner).
        base_metrics = dict(outer_instr.metrics)
        base_timings = dict(outer_instr.timings)
        best_model, best_nll, best_r = None, np.inf, -1
        nlls = []
        for r in range(self._num_restarts):
            if r == 0:
                # restart 0 keeps the user's starting point but is wrapped
                # too: all restarts then share ONE jit-static kernel
                # identity (ThetaOverrideKernel excludes theta0 from its
                # spec), so every fit program compiles exactly once
                instr_r = outer_instr
            else:
                instr_r = Instrumentation(name=outer_instr.name)
                instr_r.metrics.update(base_metrics)
                instr_r.timings.update(base_timings)
            kernel_r = ThetaOverrideKernel(kernel, theta_batch[r])
            from spark_gp_tpu.resilience.quarantine import NonFiniteFitError

            try:
                model = fit_once(kernel_r, instr_r)
            except NonFiniteFitError:
                # one diverged restart (NaN objective from a wild starting
                # point) is the multi-start driver's NORMAL business — it
                # scores inf and the best finite restart wins, exactly the
                # pre-detection behavior.  Only every-restart failure
                # escalates (below) to the fit-level recovery driver.
                nlls.append(np.inf)
                continue
            nll = float(model.instr.metrics.get("final_nll", np.inf))
            nlls.append(nll if np.isfinite(nll) else np.inf)
            if nlls[-1] < best_nll:
                best_model, best_nll, best_r = model, nlls[-1], r
        if best_model is None:
            from spark_gp_tpu.resilience.quarantine import NonFiniteFitError

            raise NonFiniteFitError(
                "every restart produced a non-finite final NLL — the model "
                "configuration is numerically unusable at these settings"
            )
        for r, nll in enumerate(nlls):
            best_model.instr.log_metric(f"restart_{r}_nll", nll)
        best_model.instr.log_metric("num_restarts", self._num_restarts)
        best_model.instr.log_metric("best_restart", best_r)
        self._log_renormalized_nll(best_model.instr)
        return best_model

    def _group(self, x: np.ndarray, y: np.ndarray) -> ExpertData:
        data = group_for_experts(x, y, self._dataset_size_for_expert)
        if self._mesh is not None:
            data = shard_experts(data, self._mesh)
        return data

    def _group_screened(self, instr: Instrumentation, x, y) -> ExpertData:
        """Group + the pre-fit data screen: experts carrying non-finite
        rows (the NaN-from-a-bad-shard fault class) are quarantined HERE,
        before the optimizer ever sees an ``inf`` objective.  Every
        estimator family's ``fit`` routes through this.

        The screen runs in pure numpy on the raw rows — round-robin
        grouping assigns row ``i`` to expert ``i % E``
        (``parallel/experts.py``), so the bad-expert set follows from the
        bad-row set with zero device work and zero per-shape compiles on
        the clean path.  (The distributed entry point, where no host holds
        the rows, uses the jitted ``nonfinite_expert_mask`` instead.)"""
        bad_experts = None
        if self._expert_quarantine:
            x_np = np.asarray(x)
            finite = self._finite_row_mask(x_np, y)
            if finite is not None:
                from spark_gp_tpu.parallel.experts import num_experts_for

                e = num_experts_for(
                    x_np.shape[0], self._dataset_size_for_expert
                )
                bad_experts = np.zeros(e, dtype=bool)
                bad_experts[np.flatnonzero(~finite) % e] = True
        data = self._group(x, y)
        if bad_experts is not None:
            if bad_experts.shape[0] < data.x.shape[0]:
                # a mesh shard pads the expert axis; padded experts are
                # inert and never bad — extend the mask to the padded
                # length or the quarantine broadcast fails
                bad_experts = np.pad(
                    bad_experts, (0, data.x.shape[0] - bad_experts.shape[0])
                )
            data = self._apply_quarantine(
                instr, data, bad_experts, "data screen"
            )
        return data

    @staticmethod
    def _finite_row_mask(x, y=None):
        """bool [N] mask of rows whose features (and labels, when given)
        are all finite — or ``None`` when every row passes (the common
        case; callers skip all filtering work).  The ONE implementation
        behind the pre-fit expert screen and the provider-row filters, so
        the three consumers cannot drift."""
        finite = np.all(np.isfinite(x), axis=1)
        if y is not None:
            y2d = np.asarray(y).reshape(x.shape[0], -1)
            finite &= np.all(np.isfinite(y2d), axis=1)
        return None if finite.all() else finite

    def _screen_rows(self, x: np.ndarray, y: np.ndarray):
        """Row-level companion of the expert screen: the active-set
        providers sample from the RAW host rows (not the quarantined
        stack), so poisoned rows must never be offered to them — an active
        set with one NaN row re-poisons the PPA statistics the quarantine
        just cleaned.  Returns filtered ``(x, y)`` views (the originals
        when everything is finite)."""
        if not self._expert_quarantine:
            return x, y
        finite = self._finite_row_mask(x, y)
        if finite is None:
            return x, y
        return x[finite], np.asarray(y)[finite]

    def _log_renormalized_nll(self, instr) -> None:
        """When experts were quarantined, publish the full-stack-comparable
        objective: ``final_nll_renormalized = final_nll * bcm_renorm``
        (``E_active / E_kept`` — resilience/quarantine.py).  ``final_nll``
        itself stays the optimizer's literal reduced-sum objective; tooling
        comparing fits across configurations should read the renormalized
        metric when it is present.  Idempotent (pure recomputation)."""
        if instr is None:
            return
        renorm = instr.metrics.get("bcm_renorm")
        # selection's weighted renormalization (agg.renorm — the
        # quarantine factor's weighted generalization) composes
        # multiplicatively: both map a reduced sum back to full-stack
        agg_renorm = instr.metrics.get("agg.renorm")
        if agg_renorm is not None and float(agg_renorm) != 1.0:
            renorm = (1.0 if renorm is None else renorm) * float(agg_renorm)
        if renorm is not None and "final_nll" in instr.metrics:
            instr.log_metric(
                "final_nll_renormalized", instr.metrics["final_nll"] * renorm
            )

    def _provider_rows_filter(self, x):
        """``(x_filtered, n_orig, row_filter)`` for the latent-target
        estimator families: their providers sample raw host rows while
        their targets are ungrouped per-point latents of the ORIGINAL
        length — so both sides must be filtered by the same finite-row
        mask, or a poisoned row re-enters through the active set while
        the targets misalign.  ``row_filter`` applies that mask to an
        ungrouped [n_orig] target vector."""
        n_orig = x.shape[0]
        if not self._expert_quarantine:
            return x, n_orig, (lambda t: t)
        finite = self._finite_row_mask(x)
        if finite is None:
            return x, n_orig, (lambda t: t)
        return x[finite], n_orig, (lambda t: np.asarray(t)[finite])

    def _gram_cache(self, instr, data: ExpertData):
        """Build the theta-invariant per-expert gram cache ONCE per fit
        (the precompute plane, kernels/base.py): one jitted vmapped
        ``prepare`` pass over the ``[E, s, p]`` stack, under the ambient
        gram-stage precision lane — so the ``mixed`` lane's compensated
        bf16 distance build is paid once instead of per L-BFGS
        evaluation.  Returns ``None`` (and the fit keeps today's
        recompute path bit-for-bit) when the kernel declares no invariant
        (ARD / custom ``prepare=None`` kernels), when ``GP_GRAM_CACHE=0``,
        or for the ELBO objective (dominated by cross-kernel terms the
        self-distance cache does not cover).  Memory cost: one extra
        ``[E, s, s]`` stack (docs/ROOFLINE.md).  The decision is recorded
        as the ``gram_cache_engaged`` metric so artifacts can prove which
        path a fit ran.  A fit resolving to the MATFREE lane with a
        matvec-capable kernel also skips the build: the prepare() cache
        IS the O(E·s²) distance block that lane refuses to materialize —
        building it would reinstate the exact allocation the lane was
        admitted to avoid."""
        from spark_gp_tpu.kernels.base import (
            prepare_gram_cache,
            supports_matfree,
        )
        from spark_gp_tpu.ops.iterative import resolve_solver

        kernel = self._get_kernel()
        if getattr(self, "_objective", "marginal") == "elbo":
            cache = None
        elif supports_matfree(kernel) and resolve_solver(
            int(data.x.shape[1]),
            num_experts=int(data.x.shape[0]),
            n_features=int(data.x.shape[2]),
            itemsize=int(np.dtype(data.x.dtype).itemsize),
        ) == "matfree":
            cache = None
        else:
            cache = prepare_gram_cache(kernel, data.x)
        if instr is not None:
            instr.log_metric("gram_cache_engaged", float(cache is not None))
        return cache

    def _apply_quarantine(self, instr, data, bad, source: str) -> ExpertData:
        """Drop ``bad`` experts from the stack; account for renormalization.

        ``experts_active_initial`` is pinned at the first drop so repeated
        recovery rounds accumulate against the original denominator;
        ``bcm_renorm`` is the factor that maps the reduced BCM sum back to
        a full-stack-comparable NLL (``resilience/quarantine.py``)."""
        from spark_gp_tpu.resilience.quarantine import (
            GLOBAL_FAILURE_ADVICE,
            ExpertQuarantineError,
            quarantine_experts,
            renorm_factor,
        )

        bad = np.asarray(bad, dtype=bool)
        n_bad = int(bad.sum())
        if n_bad == 0:
            return data
        active = int((np.asarray(data.mask).sum(axis=1) > 0).sum())
        if n_bad >= active:
            # count against ACTIVE experts, not the stack length: a
            # mesh-padded stack carries inert all-masked experts that are
            # never flagged bad, and masking every real expert would let
            # the fit "converge" on zero data
            raise ExpertQuarantineError(
                f"{source}: all {active} active expert(s) are non-finite — "
                + GLOBAL_FAILURE_ADVICE
            )
        data = quarantine_experts(data, bad)  # raises when all experts bad
        base = instr.metrics.get("experts_active_initial")
        if base is None:
            base = float(active)
            instr.log_metric("experts_active_initial", base)
        dropped = instr.metrics.get("experts_quarantined", 0.0) + n_bad
        instr.log_metric("experts_quarantined", dropped)
        renorm = renorm_factor(base, dropped)
        instr.log_metric("bcm_renorm", renorm)
        if getattr(instr, "agg_weights", None) is not None:
            # quarantine composes with the aggregation plane through the
            # same masking: a quarantined expert's weight is exactly 0
            from spark_gp_tpu.models.aggregation import effective_expert_count

            w = np.asarray(instr.agg_weights, dtype=np.float64).copy()
            w[bad] = 0.0
            instr.agg_weights = w
            instr.log_metric(
                "agg.effective_experts", effective_expert_count(w)
            )
        instr.log_warning(
            f"{source}: quarantined {n_bad} non-finite expert(s) "
            f"({int(dropped)}/{int(base)} total dropped); BCM objective "
            f"renormalized by {renorm:.4f}"
        )
        # the quarantine transition as a span event: the run journal (and
        # any trace view) shows WHEN in the fit the drop happened
        from spark_gp_tpu.obs import trace as obs_trace

        obs_trace.add_event(
            "experts.quarantined",
            count=n_bad, source=source, total_dropped=int(dropped),
        )
        return data

    def _apply_expert_selection(self, instr, data):
        """Correlation-aware expert subset selection (the aggregation
        plane's fit-time half, ``models/aggregation.py``) — scores expert
        redundancy from order-invariant sketches BEFORE any objective
        evaluation is paid, then either physically compacts the stack to
        the kept experts (``drop`` mode: the redundant experts' w_e = 0
        is realized by never paying their Cholesky/CG evaluations at
        all — the [E, s, s] batch shrinks, unlike quarantine's inert
        identity blocks which must preserve compiled shapes mid-fit) or
        hands back fractional per-expert weights for the marginal
        objective's weighted-NLL operand (``downweight`` mode).

        Returns ``(data, extra)`` where ``extra`` is ``()`` (clean /
        drop) or the marginal extras tail ``(None, weights)`` — slot 0
        is the resilience layer's jitter operand, filled in by
        ``recover`` if an escalation retry happens.  Off by default
        (``GP_AGG_SELECT``): the clean fit path stays bit-for-bit."""
        from spark_gp_tpu.models import aggregation as agg

        if not agg.selection_enabled():
            return data, ()
        mode = agg.selection_mode()
        objective = getattr(self, "_objective", "marginal")
        if mode == "downweight" and objective != "marginal":
            # only the marginal fit drivers thread the weight operand;
            # masking is objective-independent (the inert identity blocks
            # contribute exactly 0 to every family's reduction)
            if instr is not None:
                instr.log_warning(
                    "aggregation selection: downweight mode requires the "
                    "marginal objective; falling back to drop semantics "
                    f"for objective {objective!r}"
                )
            mode = "drop"
        report = agg.select_experts(data, mode=mode, seed=self._seed)
        weights = np.asarray(report.weights, dtype=np.float64)
        if instr is not None:
            # the ACTUAL policy weights, for _emit_expert_quality and the
            # run journal — not the uniform-renorm approximation
            instr.agg_weights = weights
            instr.log_metric("agg.selection_dropped", float(report.num_dropped))
            instr.log_metric("agg.renorm", report.renorm)
            instr.log_metric(
                "agg.effective_experts", agg.effective_expert_count(weights)
            )
        if report.clean:
            return data, ()
        from spark_gp_tpu.obs import trace as obs_trace

        obs_trace.add_event(
            "experts.deselected",
            dropped=report.num_dropped, mode=report.mode,
            threshold=report.threshold,
        )
        if report.mode == "downweight":
            import jax.numpy as jnp

            if instr is not None:
                instr.log_warning(
                    "aggregation selection: "
                    f"{int(np.sum((weights > 0) & (weights < 1.0)))} "
                    f"expert(s) down-weighted of {report.num_active} "
                    f"(threshold {report.threshold:.2f}); weighted "
                    f"objective renormalizes by {report.renorm:.4f}"
                )
            return data, (None, jnp.asarray(weights, dtype=data.x.dtype))
        keep = np.flatnonzero(~report.drop)
        if instr is not None:
            # kept experts all carry w_e = 1 in the compacted stack — the
            # quality rows must line up with the stack the fit actually ran
            instr.agg_weights = weights[keep]
            instr.log_warning(
                f"aggregation selection: dropped {report.num_dropped} "
                f"redundant expert(s) of {report.num_active} before "
                f"factorization (threshold {report.threshold:.2f}); "
                f"objective renormalizes by {report.renorm:.4f}"
            )
        import jax.numpy as jnp

        idx = jnp.asarray(keep)
        return (
            ExpertData(x=data.x[idx], y=data.y[idx], mask=data.mask[idx]),
            (),
        )

    def _run_with_expert_resilience(self, instr, data, run_fit):
        """Bounded recovery driver around one COMPLETE fit attempt.

        ``run_fit(data, resilience_extra, gram_cache) -> model`` is the
        whole optimize→PPA pipeline; on a non-finite failure
        (``NotPositiveDefiniteException`` from any factorization,
        ``NonFiniteFitError`` from a device fit) the per-expert health
        probe runs at theta0, unhealthy experts walk the adaptive jitter
        ladder, irreparable ones are quarantined, and the fit is retried
        with backoff (``resilience/retry.py``) — recovery lives out here
        on the host, never inside the compiled programs.  A failure the
        diagnosis cannot attribute to specific experts (every expert
        healthy in isolation) is re-raised untouched.

        The theta-invariant gram cache is built HERE, once, and reused
        verbatim by jitter-escalation retries (the jitter operand changes,
        the stack does not); a quarantine retry rebuilds it — quarantine
        replaces the dropped experts' feature rows with benign copies, so
        the cached distances of those experts are stale (masked-out, but
        rebuilt anyway so the cached path can never read poisoned
        distances the uncached path would not).
        """
        # fit-time expert selection runs FIRST (models/aggregation.py):
        # the gram cache must be built from the post-selection stack, and
        # drop-mode masking must be in place before any objective runs
        data, sel_extra = self._apply_expert_selection(instr, data)
        cache = self._gram_cache(instr, data)
        if not self._expert_quarantine or self._fit_retries < 1:
            return run_fit(data, sel_extra, cache)
        from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
        from spark_gp_tpu.resilience.quarantine import (
            NonFiniteFitError,
            diagnose_experts,
        )
        from spark_gp_tpu.resilience.retry import (
            RetryBudgetExceededError,
            retry_with_backoff,
        )

        state = {"data": data, "extra": sel_extra, "cache": cache}
        objective = getattr(self, "_objective", "marginal")

        def attempt():
            return run_fit(state["data"], state["extra"], state["cache"])

        # the health probe needs a per-expert-DECOMPOSABLE objective; the
        # ELBO is a nonlinear function of global sums, so its faults are
        # diagnosed through the marginal per-expert NLL as a proxy (data
        # and conditioning faults are objective-independent)
        probe_objective = objective if objective in ("marginal", "loo") else "marginal"

        def recover(attempt_idx, exc):
            from spark_gp_tpu.obs import trace as obs_trace

            obs_trace.add_event(
                "fit.retry", attempt=attempt_idx + 1,
                error=type(exc).__name__,
            )
            kernel = self._get_kernel()
            report = diagnose_experts(
                kernel, kernel.init_theta(), state["data"],
                objective=probe_objective,
                # the sharded objectives cannot carry the jitter operand
                # (shard_map signature), and only the marginal objective
                # threads it — other paths go straight from the probe to
                # quarantine
                allow_jitter=(objective == "marginal" and self._mesh is None),
            )
            if report.clean:
                raise exc  # not a per-expert fault; surface the original
            if report.num_jittered:
                import jax.numpy as jnp

                instr.log_metric("experts_jittered", report.num_jittered)
                obs_trace.add_event(
                    "experts.jittered", count=report.num_jittered
                )
                instr.log_warning(
                    f"fit recovery: {report.num_jittered} expert(s) "
                    "repaired by adaptive jitter escalation "
                    f"(max relative jitter {report.jitter.max():.1e})"
                )
                # slot 0 is the jitter operand; any trailing aggregation
                # weights (the selection extras tail) must survive the
                # escalation retry
                state["extra"] = (
                    jnp.asarray(report.jitter, dtype=state["data"].x.dtype),
                ) + tuple(state["extra"][1:])
                # per-expert jitter levels ride into the post-fit quality
                # telemetry (_emit_expert_quality) and the run journal
                instr.expert_jitter = np.asarray(
                    report.jitter, dtype=np.float64
                )
            if report.num_dropped:
                state["data"] = self._apply_quarantine(
                    instr, state["data"], report.bad, "fit recovery"
                )
                # the repaired stack has fresh (benign) feature rows for
                # the dropped experts — rebuild the distance cache from it
                state["cache"] = self._gram_cache(None, state["data"])
            instr.log_metric("fit_retries", float(attempt_idx + 1))

        try:
            return retry_with_backoff(
                attempt,
                attempts=self._fit_retries + 1,
                base_delay_s=0.05,
                retry_on=(NotPositiveDefiniteException, NonFiniteFitError),
                on_retry=recover,
                describe=f"{type(self).__name__} fit",
            )
        except RetryBudgetExceededError as err:
            raise err.__cause__ from err  # the underlying failure is the story

    def _checkpoint_tag(self) -> str:
        """Checkpoint file tag: class name, plus the objective when it is
        not the default — a marginal-NLL checkpoint must never seed (or be
        overwritten by) a ``setObjective("loo")`` fit in the same dir.
        ELBO fits additionally carry the objective-surface digest (set by
        ``_elbo_setup``): two ELBO fits over different inducing sets or
        sigma2 are DIFFERENT objectives."""
        objective = getattr(self, "_objective", "marginal")
        name = type(self).__name__
        if objective == "marginal":
            return name
        name = f"{name}-{objective}"
        salt = getattr(self, "_objective_salt", None)
        if objective == "elbo" and salt:
            name += f"-{salt}"
        return name

    def _make_checkpointer(self, kernel):
        if self._checkpoint_dir is None:
            return None
        from spark_gp_tpu.parallel import coord
        from spark_gp_tpu.utils.checkpoint import LbfgsCheckpointer

        ctx = self._coord_ctx_for_checkpoint()
        inner = LbfgsCheckpointer(
            self._checkpoint_dir, kernel, tag=self._checkpoint_tag(),
            seed=self._seed,
            elastic=coord.elastic_meta(
                self._mesh,
                process_count=None if ctx is None else ctx.num_processes,
            ),
        )
        if ctx is None:
            return inner
        # multi-host: barrier-agreed save step, process 0 writes, every
        # peer digest-verifies through the KV store (parallel/coord.py)
        return coord.CoordinatedLbfgsCheckpointer(inner, ctx)

    def _make_device_checkpointer(self, file_tag: str, data):
        """The device-optimizer counterpart: PR 2's atomic npz writer,
        stamped with the elastic-resume metadata and wrapped in the
        coordinated protocol on multi-process runtimes.  One home so the
        four estimator families cannot wire it differently."""
        from spark_gp_tpu.parallel import coord
        from spark_gp_tpu.utils.checkpoint import DeviceOptimizerCheckpointer

        ctx = self._coord_ctx_for_checkpoint()
        inner = DeviceOptimizerCheckpointer(
            self._checkpoint_dir, file_tag,
            elastic=coord.elastic_meta(
                self._mesh,
                num_experts=int(data.x.shape[0]),
                expert_size=int(data.x.shape[1]),
                process_count=None if ctx is None else ctx.num_processes,
            ),
        )
        if ctx is None:
            return inner
        return coord.CoordinatedDeviceCheckpointer(inner, ctx)

    def _optimize_hypers(
        self,
        instr: Instrumentation,
        kernel: Kernel,
        value_and_grad: Callable,
        callback=None,
    ) -> np.ndarray:
        """L-BFGS-B over the box-constrained hyperparameters
        (GaussianProcessCommons.scala:66-92)."""
        instr.log_info("Optimising the kernel hyperparameters")
        from spark_gp_tpu.parallel import coord as coord_mod
        from spark_gp_tpu.resilience import chaos

        # chaos choke point for the host-driven optimizer (the jitted
        # objective dispatches can OOM/fail-compile exactly like the
        # one-dispatch device programs; fallback ladder + soak proof)
        chaos.maybe_injected_failure("fit.host")

        dcn = getattr(self, "_dcn_ctx", None)
        if dcn is not None:
            # the DCN analogue of the objective's cross-host psum: every
            # evaluation's local (value, grad) is deterministically summed
            # over the KV store, so each host's L-BFGS walks the IDENTICAL
            # global-objective trajectory (parallel/coord.py)
            value_and_grad = dcn.wrap_value_and_grad(value_and_grad)
        try:
            return self._optimize_hypers_body(
                instr, kernel, value_and_grad, callback
            )
        finally:
            if dcn is not None:
                # disarm the integrity spot-check spec: it described THIS
                # fit's stack/kernel, and the context is a long-lived
                # singleton a later fit (possibly a latent one, which
                # cannot be audited) will reuse
                dcn.dup_check = None

    def _optimize_hypers_body(
        self,
        instr: Instrumentation,
        kernel: Kernel,
        value_and_grad: Callable,
        callback=None,
    ) -> np.ndarray:
        from spark_gp_tpu.parallel import coord as coord_mod

        theta0 = kernel.init_theta()
        done_iters = 0
        if self._checkpoint_dir is not None:
            # resume the host optimizer from the last persisted iterate,
            # with the REMAINING iteration budget (a preempted 100-iter fit
            # killed at 60 runs 40 more, not another 100)
            from spark_gp_tpu.utils.checkpoint import (
                CheckpointMismatchError,
                kernel_signature,
            )

            ck = self._load_host_resume_state()
            if ck is not None:
                expected = kernel_signature(kernel, theta0.shape[0])
                if np.asarray(ck[1]).shape != theta0.shape or (
                    ck[2] is not None and ck[2] != expected
                ):
                    raise CheckpointMismatchError(
                        f"checkpoint in {self._checkpoint_dir!r} (tag "
                        f"{self._checkpoint_tag()!r}) was written under a "
                        f"different kernel configuration "
                        f"({ck[2]!r} != {expected!r}) — clear the directory "
                        "or use a distinct one per configuration"
                    )
                instr.log_info(
                    f"Resuming from checkpoint (iteration {ck[0]})"
                )
                theta0 = np.asarray(ck[1])
                done_iters = int(ck[0])
                instr.log_metric("resumed_from_iteration", done_iters)
                if callback is not None and hasattr(callback, "iteration"):
                    # the checkpointer keeps counting from where the
                    # preempted run stopped, so the persisted iteration
                    # number stays the fit-global budget marker
                    callback.iteration = done_iters
        lower, upper = kernel.bounds()
        with instr.phase("optimize_hypers"):
            if done_iters >= self._max_iter:
                # the checkpoint already sits AT the iteration budget (a
                # preemption right after the final save): running even one
                # more iteration would walk theta past the uninterrupted
                # fit's result, and every crash/resume cycle would drift it
                # further.  Evaluate once for the final NLL and return the
                # persisted iterate untouched.
                from spark_gp_tpu.optimize.lbfgsb import OptimizeResult

                value, _ = value_and_grad(theta0)
                res = OptimizeResult(
                    theta=np.asarray(theta0, dtype=np.float64),
                    fun=float(np.asarray(value)),
                    nit=0,
                    nfev=1,
                    success=True,
                    message=(
                        "checkpoint already at the iteration budget; "
                        "returning the persisted iterate"
                    ),
                )
            else:
                # SIGTERM watch only while a save boundary exists to act
                # on it (the per-iteration checkpoint callback); restored
                # — and a deferred signal re-delivered — on exit
                watch = (
                    coord_mod.preemption_watch()
                    if self._checkpoint_dir is not None
                    else contextlib.nullcontext()
                )
                with watch:
                    res = minimize_lbfgsb(
                        value_and_grad,
                        theta0,
                        lower,
                        upper,
                        max_iter=self._max_iter - done_iters,
                        tol=self._tol,
                        callback=callback,
                        log_space=self._use_log_space(kernel),
                    )
        instr.log_metric("lbfgs_iters", res.nit)
        instr.log_metric("lbfgs_nfev", res.nfev)
        instr.log_metric("final_nll", res.fun)
        instr.log_metric("lbfgs_stalled", 0.0 if res.success else 1.0)
        if not res.success:
            instr.log_warning(
                "L-BFGS-B terminated abnormally (not converged): "
                f"{res.message} — the returned hyperparameters are the best "
                "iterate seen, not a certified optimum."
            )
        instr.log_info("Optimal kernel: " + kernel.describe(res.theta))
        return res.theta

    def _load_host_resume_state(self):
        """``(iteration, theta, kernel_sig)`` from the host checkpoint, or
        ``None`` — with two multi-host duties the plain loader has not:

        * only process 0 is guaranteed to hold the file (it is the
          coordinated writer, and after rescheduling the others may sit on
          fresh machines), so its payload is broadcast over the KV store
          and every process resumes from the identical state;
        * a payload stamped by a different process count is an **elastic
          resume** — counted (``coord.elastic_resumes``) and span-marked,
          then resumed normally: the host iterate is replicated.
        """
        import json as _json

        from spark_gp_tpu.utils.checkpoint import load_checkpoint_payload

        ctx = self._coord_ctx_for_checkpoint()
        payload = None
        if ctx is None or ctx.process_id == 0:
            payload = load_checkpoint_payload(
                self._checkpoint_dir, tag=self._checkpoint_tag()
            )
        if ctx is not None and ctx.num_processes > 1:
            blob = _json.dumps(payload or {}).encode()
            parts = ctx.allgather_bytes("ckpt_resume", blob)
            payload = _json.loads(parts[0].decode()) or None
        if payload is None:
            return None
        elastic = payload.get("elastic")
        if elastic is not None:
            current_p = 1 if ctx is None else ctx.num_processes
            if elastic.get("process_count") not in (None, current_p):
                from spark_gp_tpu.obs import trace as obs_trace
                from spark_gp_tpu.obs.runtime import telemetry

                telemetry.inc("coord.elastic_resumes")
                obs_trace.add_event(
                    "coord.elastic_resume",
                    stored_process_count=elastic.get("process_count"),
                    current_process_count=current_p,
                )
        from spark_gp_tpu.utils.checkpoint import payload_state

        return payload_state(payload)

    def _restart_theta_batch(self, kernel) -> np.ndarray:
        """``[R, h]`` multi-start starting points: row 0 is the user's
        ``init_theta``, rows 1+ seeded perturbations (log-normal in log
        hyper-space; else additive with a per-coordinate scale relative to
        ``|theta0|`` where nonzero and the finite bound span otherwise, so
        zero-initialized coordinates are explored too), clipped to the box.
        One definition shared by the sequential driver and the batched
        on-device multi-start so both explore identical points."""
        theta0 = kernel.init_theta()
        lower, upper = kernel.bounds()
        use_log = self._use_log_space(kernel)
        rng = np.random.default_rng(self._seed ^ 0x5EED5)
        span = np.where(
            np.isfinite(upper - lower) & (upper > lower), upper - lower, 1.0
        )
        lin_scale = np.where(np.abs(theta0) > 0.0, np.abs(theta0), span)
        rows = [theta0]
        for _ in range(1, self._num_restarts):
            eps = self._restart_scale * rng.standard_normal(theta0.shape)
            if use_log:
                t_r = np.exp(np.log(theta0) + eps)
            else:
                t_r = theta0 + eps * lin_scale
            rows.append(np.clip(t_r, lower, upper))
        return np.stack(rows)

    def _report_multistart_nlls(self, instr, fetched):
        """Per-restart reporting shared by the batched device multi-start
        paths: raises the sequential driver's error when every lane's NLL
        is non-finite, else logs each restart's NLL and the restart count
        (``best_restart`` is a scalar pending entry logged by the fetch)."""
        nlls = np.asarray(fetched["restart_nlls"], dtype=np.float64)
        if not np.any(np.isfinite(nlls)):
            from spark_gp_tpu.resilience.quarantine import NonFiniteFitError

            raise NonFiniteFitError(
                "every restart produced a non-finite final NLL — the model "
                "configuration is numerically unusable at these settings"
            )
        for r, nll in enumerate(nlls):
            instr.log_metric(f"restart_{r}_nll", float(nll))
        instr.log_metric("num_restarts", self._num_restarts)
        self._log_renormalized_nll(instr)

    def _use_batched_multistart(self) -> bool:
        """The batched one-dispatch multi-start applies on the plain
        single-chip device path only (the sequential driver covers mesh /
        checkpoint / host combinations)."""
        return (
            self._num_restarts > 1
            and self._resolved_optimizer() == "device"
            and self._mesh is None
            and self._checkpoint_dir is None
        )

    def _run_fit_distributed(self, name: str, data, active_set, prepare):
        """Shared shell of every estimator's ``fit_distributed``: resolve
        the mesh from the stack, log the stack shape, run the pre-fit data
        screen, normalize an explicit active set to f64, then run
        ``prepare(instr, active64, data) -> fit_once(kernel, instr_r)``
        through the multi-start driver.  ``prepare`` MUST use the ``data``
        it is handed (the screened stack — quarantined experts masked
        out), never the caller's own closure capture, or the quarantine
        is silently discarded.  Estimator-specific validation/target
        preparation lives in ``prepare`` (label-domain checks, one-hot
        construction, ...)."""
        instr = Instrumentation(name=name)
        from spark_gp_tpu.resilience import fallback

        with self._stack_mesh(data), self._dcn_scope():
            # observation shell INSIDE the mesh context but around the
            # whole body: the data screen's quarantine events and the
            # restart driver land in one root span (the gpr.py convention).
            # The degradation ladder (sharded -> DCN-fallback ->
            # single-host) wraps the body; GP_FALLBACK=0 restores the
            # straight call.
            return self._observed_fit(
                instr,
                lambda: fallback.run_distributed_ladder(
                    self, instr, data, active_set, prepare
                ),
            )

    def _fit_distributed_body(self, instr, data, active_set, prepare):
        import jax

        instr.log_metric("num_experts", int(data.x.shape[0]))
        instr.log_metric("expert_size", int(data.x.shape[1]))
        screenable = (
            jax.process_count() == 1
            # DCN-fallback stacks are host-local even on multi-process
            # clusters: the screen (and with_experts_masked) can fetch them
            or getattr(self, "_dcn_ctx", None) is not None
        )
        if self._expert_quarantine and screenable:
            # same pre-fit data screen as the in-process fit paths: a
            # bad shard's NaN rows must not poison the mesh-wide psum
            from spark_gp_tpu.resilience.quarantine import (
                nonfinite_expert_mask,
            )

            bad = nonfinite_expert_mask(data)
            if bad.any():
                data = self._apply_quarantine(
                    instr, data, bad, "data screen"
                )
        elif self._expert_quarantine:
            # the screen (and with_experts_masked) host-fetch the
            # stack, which a cross-process sharding cannot satisfy —
            # skip rather than crash every clean multihost fit
            instr.log_warning(
                "expert quarantine screen skipped: the stack spans "
                f"{jax.process_count()} processes and cannot be "
                "host-fetched for diagnosis"
            )
        active64 = (
            None if active_set is None
            else np.asarray(active_set, dtype=np.float64)
        )
        fit_once = prepare(instr, active64, data)
        return self._fit_with_restarts(instr, fit_once)

    def _optimize_latent_host(self, instr, kernel, objective, f0):
        """Host-driven L-BFGS-B over a latent-carrying jitted objective
        ``(theta, f0) -> (value, grad, f_new)``: the latent warm start is
        carried across evaluations (the explicit-state version of the
        reference's in-place RDD mutation, GPClf.scala:53-60) and settled
        with one final evaluation at theta* (GPClf.scala:60's foreach).
        Shared by every Laplace-family estimator; returns
        ``(theta_opt, f_final)``."""
        state = {"f": f0}

        def value_and_grad(theta):
            value, grad, f_new = objective(theta, state["f"])
            state["f"] = f_new
            return value, grad

        theta_opt = self._optimize_hypers(
            instr, kernel, value_and_grad,
            callback=self._make_checkpointer(kernel),
        )
        _, _, f_final = objective(theta_opt, state["f"])
        return theta_opt, f_final

    def _log_device_optimizer_result(
        self, instr, kernel, theta_host, nll, n_iter, n_fev, stalled
    ):
        """Uniform diagnostics for a completed on-device fit — one home for
        the metric names and the stall warning every estimator reports."""
        instr.log_metric("lbfgs_iters", int(n_iter))
        instr.log_metric("lbfgs_nfev", int(n_fev))
        instr.log_metric("final_nll", float(nll))
        instr.log_metric("lbfgs_stalled", float(bool(stalled)))
        if bool(stalled):
            instr.log_warning(
                "device L-BFGS stalled (line search exhausted before "
                "convergence) — returned hyperparameters are the best "
                "iterate seen, not a certified optimum."
            )
        instr.log_info("Optimal kernel: " + kernel.describe(theta_host))

    def _select_active(self, kernel, theta, x, y_targets, data) -> np.ndarray:
        """Run the configured provider — ONE home for the selection logic,
        used at the reference's point in the pipeline (post-optimization,
        ``_projected_process``) and, for the ELBO objective, up front at
        the initial theta (the inducing set must exist before training)."""
        provider = self._active_set_provider
        if x is None:
            # distributed mode: no host holds the rows — the provider
            # selects from the sharded stack itself (data.y carries the
            # targets: labels for GPR, latent modes for GPC)
            provider = self._dcn_safe_provider(provider)
            active = provider.from_stack(
                self._active_set_size, data, kernel,
                np.asarray(theta, dtype=np.float64), self._seed,
                self._mesh,
            )
        elif getattr(provider, "uses_fit_outputs", True):
            # The provider receives the noise-augmented model kernel, as
            # the reference passes getKernel
            # (GaussianProcessCommons.scala:43) — the greedy provider's
            # Seeger scores divide by its whiteNoiseVar.
            targets = y_targets() if callable(y_targets) else y_targets
            active = provider(
                self._active_set_size, x, targets, kernel, theta, self._seed
            )
        else:
            active = provider(
                self._active_set_size, x, None, kernel, None, self._seed
            )
        return np.asarray(active)

    def _dcn_safe_provider(self, provider):
        """In DCN-fallback mode a ``from_stack`` provider that runs mesh
        collectives (k-means Lloyd, greedy Seeger) would compute over the
        LOCAL stack only — every host silently selecting a different
        active set, the classic diverged-cluster wrong-results bug.  Until
        those providers grow a KV-coordinated path, fall back (loudly) to
        the uniform draw, whose DCN route is exact."""
        if getattr(self, "_dcn_ctx", None) is None:
            return provider
        from spark_gp_tpu.models.active_set import _RandomActiveSetProvider

        if isinstance(provider, _RandomActiveSetProvider):
            return provider
        import warnings

        warnings.warn(
            f"{type(provider).__name__} has no DCN-coordinated "
            "implementation; falling back to uniform sampling for this "
            "multi-host fit (the KV-store fallback mode cannot run "
            "cross-host mesh collectives).",
            stacklevel=2,
        )
        from spark_gp_tpu.models.active_set import RandomActiveSetProvider

        return RandomActiveSetProvider

    def _projected_process(
        self,
        instr: Instrumentation,
        kernel: Kernel,
        theta_opt: np.ndarray,
        x: Optional[np.ndarray],
        y_targets: Optional[np.ndarray],
        data: ExpertData,
        active_override: Optional[np.ndarray] = None,
    ) -> ppa.ProjectedProcessRawPredictor:
        """Active set -> distributed (U1, u2) -> magic solve -> predictor
        (GaussianProcessCommons.scala:40-59).

        ``y_targets`` may be a value or a zero-arg callable; a callable is
        resolved ONLY when the provider actually reads targets
        (``uses_fit_outputs``) — for the classifiers the targets are the
        device-resident latent stacks, and fetching them is a host sync the
        random/kmeans providers never need.
        """
        import jax.numpy as jnp

        if active_override is not None:
            # explicitly-supplied set (fit_distributed(active_set=...), or
            # an objective that selected it before optimization)
            active = np.asarray(active_override)
        else:
            with instr.phase("active_set"):
                active = self._select_active(
                    kernel, theta_opt, x, y_targets, data
                )

        # The (U1, u2) accumulation runs in float64 (XLA emulates f64 on TPU;
        # this stage is one-time, not the per-iteration hot loop).  In f32 the
        # ~1e-7 relative entry noise of U1, amplified by the
        # condition-squaring of the normal equations (sigma2 as small as 1e-4,
        # Airfoil.scala:21), costs real accuracy: airfoil 10-fold RMSE
        # degrades from 2.0 to 2.8.
        import jax

        with instr.phase("kmn_stats"), jax.enable_x64():
            theta_dev = jnp.asarray(
                np.asarray(theta_opt, dtype=np.float64), dtype=jnp.float64
            )
            active_dev = jnp.asarray(
                np.asarray(active, dtype=np.float64), dtype=jnp.float64
            )
            x64 = data.x.astype(jnp.float64)
            y64 = data.y.astype(jnp.float64)
            mask64 = data.mask.astype(jnp.float64)
            if self._mesh is not None:
                stats_fn = ppa.make_sharded_kmn_stats(kernel, self._mesh)
                u1, u2 = stats_fn(
                    theta_dev, active_dev, ExpertData(x=x64, y=y64, mask=mask64)
                )
            else:
                u1, u2 = ppa.kmn_stats_jit(
                    kernel, theta_dev, active_dev, x64, y64, mask64
                )
            u1 = np.asarray(u1)
            u2 = np.asarray(u2)
            dcn = getattr(self, "_dcn_ctx", None)
            if dcn is not None:
                # the (U1, u2) psum's DCN analogue: each host's sums over
                # its local experts, reduced deterministically over the KV
                # store — every host then runs the identical magic solve
                u1, u2 = dcn.allreduce_arrays("kmn_stats", u1, u2)

        return self._build_predictor(
            instr, kernel, theta_opt, active, u1, u2, data=data
        )

    def _build_predictor(
        self, instr: Instrumentation, kernel: Kernel, theta, active, u1, u2,
        data: Optional[ExpertData] = None,
    ) -> ppa.ProjectedProcessRawPredictor:
        """Shared tail of both fit paths: the host f64 magic solve
        (PGPH.scala:49-60) and the serializable raw predictor.  ``data``
        (the fitted expert stack, when the caller has it) feeds the
        fit-time mixed-precision guard below."""
        active64 = np.asarray(active, dtype=np.float64)
        with instr.phase("magic_solve"):
            magic_vector, magic_matrix = ppa.magic_solve(
                kernel, theta, active64, u1, u2, mesh=self._mesh,
                with_variance=self._predictive_variance,
            )
        self._emit_precision_guard(
            instr, kernel, theta, active64, magic_vector, data
        )
        self._emit_solver_stats(instr, kernel, theta, data)
        self._emit_aggregation_stats(instr, data)
        self._emit_expert_quality(instr, kernel, theta, data)
        self._emit_covariate_summary(instr, data, active64)
        keep_stats = self._keeps_update_statistics
        return ppa.ProjectedProcessRawPredictor(
            kernel=kernel,
            theta=np.asarray(theta, dtype=np.float64),
            active=active64,
            magic_vector=magic_vector,
            magic_matrix=magic_matrix,
            # the additive statistics behind the solve: kept ONLY on
            # regression models, where they enable model.update()
            # (ProjectedProcessRawPredictor.with_additional_data).  The
            # Laplace families' statistics are sums over LATENT targets —
            # folding raw labels/counts into them would be silently wrong,
            # and storing an unusable [m, m] f64 per model is dead weight.
            u1=np.asarray(u1, dtype=np.float64) if keep_stats else None,
            u2=np.asarray(u2, dtype=np.float64) if keep_stats else None,
        )

    def _emit_precision_guard(
        self, instr, kernel, theta, active64, magic_vector, data
    ) -> None:
        """The fit-time accuracy tripwire of the mixed-precision lanes.

        At any non-``strict`` lane (ops/precision.py), re-evaluate the
        objective, its gradient, and the posterior mean on ONE probe
        expert under both the fitted lane and ``strict``, and publish the
        relative deltas as ``mixed_precision_guard.*`` metrics — so a bad
        lane choice (a kernel/data combination whose cancellation the
        compensated path cannot carry) is detected AT FIT TIME with a
        loud warning, not discovered as drift in production predictions.
        The probe is one expert and <= 32 predict rows: O(s^2) work, noise
        next to the fit itself.  bench.py forwards the deltas into its
        ``precision_lanes`` artifact."""
        from spark_gp_tpu.ops.precision import GUARD_BARS, active_lane

        lane = active_lane()
        instr.metrics["precision_lane"] = lane
        if lane == "strict" or data is None:
            return
        import jax

        if jax.process_count() > 1 and getattr(self, "_dcn_ctx", None) is None:
            # probing needs the first expert's rows on this host, which a
            # cross-process sharding cannot satisfy (same restriction as
            # the quarantine data screen; DCN-fallback stacks are local
            # and probe fine) — skip rather than crash
            instr.log_warning(
                "mixed_precision_guard skipped: the stack spans "
                f"{jax.process_count()} processes and cannot be "
                "host-probed"
            )
            return
        import jax.numpy as jnp

        from spark_gp_tpu.models.likelihood import guard_probe_value_and_grad
        from spark_gp_tpu.models.ppa import guard_probe_predict_mean

        dtype = data.x.dtype
        x_p = data.x[:1]
        # multi-head latent targets ([E, s, C], the multiclass stacks)
        # probe head 0 — this is a numeric delta probe, not a statistic
        y_p = data.y[:1] if data.y.ndim == 2 else data.y[:1, :, 0]
        mask_p = data.mask[:1]
        theta_p = jnp.asarray(np.asarray(theta), dtype=dtype)
        active_p = jnp.asarray(active64, dtype=dtype)
        mv = np.asarray(magic_vector)
        mv_p = jnp.asarray(mv if mv.ndim == 1 else mv[:, 0], dtype=dtype)
        x_rows = data.x[0][: min(32, data.x.shape[1])]

        from spark_gp_tpu.ops.iterative import solver_jit_key

        # the guard varies the PRECISION lane only; the solver lane is
        # pinned to whatever the fit actually ran, so an iterative-lane
        # fit's guard compares iterative-vs-iterative numerics (the
        # stochastic log-det legs cancel instead of reading as a breach)
        solver = solver_jit_key()

        def probes(lane_name):
            nll, grad = guard_probe_value_and_grad(
                kernel, theta_p, x_p, y_p, mask_p, lane=lane_name,
                solver=solver,
            )
            mean = guard_probe_predict_mean(
                kernel, theta_p, active_p, mv_p, x_rows, lane=lane_name
            )
            return (
                float(np.asarray(nll)),
                np.asarray(grad, dtype=np.float64),
                np.asarray(mean, dtype=np.float64),
            )

        nll_s, grad_s, mean_s = probes("strict")
        nll_l, grad_l, mean_l = probes(lane)

        def rel(delta, scale):
            return float(delta / max(scale, 1e-30))

        # Each leg's denominator is floored at a problem-scale quantity,
        # not just 1e-30: |nll_strict| crosses zero when log|K| cancels
        # the quadratic term, max|grad_strict| is near zero when the
        # probe expert sits at a stationary point of ITS own NLL, and a
        # zero-mean posterior makes max|mean_strict| tiny — any of these
        # would blow a healthy O(eps) absolute delta into a spurious
        # breach.  The per-point NLL contribution is O(1), so the probe's
        # row count floors the NLL and gradient legs; the probe labels'
        # RMS floors the predict leg.
        nll_scale = max(
            abs(nll_s), float(np.asarray(mask_p, dtype=np.float64).sum()), 1.0
        )
        y_scale = float(
            np.sqrt(np.mean(np.square(np.asarray(y_p, dtype=np.float64))))
        )
        d_nll = rel(abs(nll_l - nll_s), nll_scale)
        d_grad = rel(
            float(np.max(np.abs(grad_l - grad_s), initial=0.0)),
            max(float(np.max(np.abs(grad_s), initial=0.0)), nll_scale),
        )
        d_pred = rel(
            float(np.max(np.abs(mean_l - mean_s), initial=0.0)),
            max(float(np.max(np.abs(mean_s), initial=0.0)), y_scale),
        )
        instr.log_metric("mixed_precision_guard.delta_nll_rel", d_nll)
        instr.log_metric("mixed_precision_guard.delta_grad_rel", d_grad)
        instr.log_metric("mixed_precision_guard.delta_predict_rel", d_pred)
        bar = GUARD_BARS.get(lane, 1e-3)
        worst = max(d_nll, d_grad, d_pred)
        breach = float(not np.isfinite(worst) or worst > bar)
        instr.log_metric("mixed_precision_guard.breach", breach)
        if breach:
            instr.log_warning(
                f"mixed_precision_guard: lane {lane!r} deviates from the "
                f"strict lane beyond its bar ({worst:.3e} > {bar:.1e}) on "
                "the probe expert — this kernel/data combination should "
                "run on the strict lane (setPrecisionLane('strict'))"
            )
            from spark_gp_tpu.ops.precision import guard_action
            from spark_gp_tpu.resilience import fallback

            if guard_action() == "degrade" and fallback.enabled():
                # GP_GUARD_ACTION=degrade: escalate the breach into the
                # degradation ladder, which re-runs this fit on the strict
                # lane (resilience/fallback.py).  Default ("log") keeps
                # the pre-ladder warn-only behavior bit-for-bit.
                raise fallback.GuardBreachError(lane, worst, bar)

    def _emit_solver_stats(self, instr, kernel, theta, data) -> None:
        """The solver lane's fit-time provenance (ops/iterative.py).

        ALWAYS stamps the engaged lane (``solver_lane`` — ``exact`` /
        ``iterative`` / ``matfree``, resolved against the fitted stack's
        expert size for ``auto``) so every artifact can prove which
        solver produced the model, mirroring ``gram_cache_engaged``.  On
        the iterative/matfree lanes additionally runs one post-fit PCG
        convergence probe at the FITTED hyperparameters over a bounded
        expert sub-stack and publishes the knobs + achieved residuals:
        ``solver.cg_iters``, ``solver.precond_rank``, ``solver.probes``,
        ``solver.residual`` (obs/names.py catalog; the run journal and
        the saved model's ``provenance_json`` carry them).  A matfree
        fit's probe runs through the SAME injected streamed matvec the
        fit executed — never a materialized stand-in — and additionally
        stamps ``solver.matfree_engaged`` / ``solver.matvec_tiles``.
        Cost: one objective-sized dispatch on <= 8 experts; never fails
        a fit."""
        from spark_gp_tpu.ops import iterative as it_ops

        if instr is None:
            return
        lane = it_ops.active_solver_lane()
        resolved = (
            it_ops.resolve_solver(
                int(data.x.shape[1]), lane,
                num_experts=int(data.x.shape[0]),
                n_features=int(data.x.shape[2]),
                itemsize=int(np.dtype(data.x.dtype).itemsize),
            )
            if data is not None else lane if lane != "auto" else "exact"
        )
        instr.metrics["solver_lane"] = resolved
        if resolved not in ("iterative", "matfree") or not (
            self._probeable_stack(data)
        ):
            return
        try:
            import jax.numpy as jnp

            from spark_gp_tpu.kernels.base import (
                masked_gram_stack,
                supports_matfree,
            )

            probe = min(8, int(data.x.shape[0]))
            x_p = data.x[:probe]
            y_p = (
                data.y[:probe] if getattr(data.y, "ndim", 2) == 2
                else data.y[:probe, :, 0]
            )
            mask_p = data.mask[:probe]
            theta_p = jnp.asarray(
                np.asarray(theta, dtype=np.float64), dtype=data.x.dtype
            )
            matfree = resolved == "matfree" and supports_matfree(kernel)
            if matfree:
                from spark_gp_tpu.models.likelihood import (
                    masked_matfree_operator,
                )
                from spark_gp_tpu.ops.pallas_matvec import matvec_tiles

                _, mv_sg, diag_sg, col_sg = masked_matfree_operator(
                    kernel, theta_p, x_p, mask_p
                )
                report = it_ops.solver_report(
                    None, y_p * mask_p,
                    matvec=mv_sg, diag=diag_sg, col_fn=col_sg,
                )
                instr.log_metric("solver.matfree_engaged", 1.0)
                instr.log_metric(
                    "solver.matvec_tiles",
                    float(matvec_tiles(int(data.x.shape[1]))),
                )
            else:
                kmat = masked_gram_stack(kernel, theta_p, x_p, mask_p)
                report = it_ops.solver_report(kmat, y_p * mask_p)
                if resolved == "matfree":
                    # lane requested matfree but the kernel carries no
                    # matvec: the fit ran the materialized fallback
                    instr.log_metric("solver.matfree_engaged", 0.0)
            instr.log_metric("solver.cg_iters", float(report["cg_iters"]))
            instr.log_metric(
                "solver.precond_rank", float(report["precond_rank"])
            )
            instr.log_metric("solver.probes", float(report["probes"]))
            instr.log_metric("solver.residual", float(report["residual"]))
        except Exception:  # noqa: BLE001 — telemetry must never fail a fit
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "iterative-solver convergence probe failed", exc_info=True
            )

    def _emit_aggregation_stats(self, instr, data) -> None:
        """The aggregation plane's fit-time provenance
        (``models/aggregation.py``).

        ALWAYS stamps the engaged predict policy (``agg.policy``) so
        every artifact can prove which expert aggregation the model's
        predictions will run under — mirroring ``solver_lane`` /
        ``precision_lane``.  When fit-time selection ran, the selection
        telemetry (``agg.selection_dropped`` / ``agg.renorm`` /
        ``agg.effective_experts``) was already logged by
        ``_apply_expert_selection``; otherwise the effective expert
        count falls back to the active-expert count (uniform unit
        weights).  Never fails a fit."""
        from spark_gp_tpu.models import aggregation as agg

        if instr is None:
            return
        instr.metrics["agg.policy"] = agg.active_agg_policy()
        if (
            "agg.effective_experts" in instr.metrics
            or not self._probeable_stack(data)
        ):
            return
        try:
            active = np.asarray(data.mask).sum(axis=1) > 0
            instr.log_metric(
                "agg.effective_experts",
                agg.effective_expert_count(active.astype(np.float64)),
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail a fit
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "aggregation telemetry failed", exc_info=True
            )

    def _probeable_stack(self, data) -> bool:
        """Whether the fitted stack can be host-probed for post-fit
        telemetry — the same restriction as the precision guard and the
        quarantine screen: cross-process shardings cannot be fetched
        (DCN-fallback stacks are host-local and probe fine)."""
        import jax

        return (
            data is not None
            and (
                jax.process_count() == 1
                or getattr(self, "_dcn_ctx", None) is not None
            )
        )

    def _emit_expert_quality(self, instr, kernel, theta, data) -> None:
        """Fit-time per-expert quality telemetry (the statistical health
        plane's fit-side leg, ISSUE 13 / obs/quality.py).

        One vmapped probe of the per-expert marginal NLL at theta* —
        the same per-expert decomposition the quarantine diagnosis uses
        (``resilience/quarantine.expert_health``; the marginal objective
        is the documented proxy for the non-decomposable families) —
        plus the per-expert adaptive-jitter level the recovery driver
        settled on and the ACTUAL aggregation weight w_e the expert
        entered the objective with (``instr.agg_weights`` when the
        aggregation plane's selection ran — quarantine composed in as
        w_e = 0; the uniform renormalization otherwise).  Stamped onto
        the instr
        as ``expert_quality`` (the run journal persists it —
        ``gpctl quality`` renders the table) with scalar spread metrics
        for dashboards.  Cost: one extra objective-evaluation-sized
        dispatch per fit; ``GP_EXPERT_TELEMETRY=0`` disables."""
        import os

        if instr is None or not self._probeable_stack(data):
            return
        if os.environ.get("GP_EXPERT_TELEMETRY", "").strip().lower() in (
            "0", "off", "false",
        ):
            return
        try:
            from spark_gp_tpu.resilience.quarantine import expert_health

            # multi-head latent stacks ([E, s, C]) probe head 0 — this is
            # a relative spread diagnostic, not a statistic (the
            # precision guard's convention)
            y = data.y if getattr(data.y, "ndim", 2) == 2 else data.y[..., 0]
            probe = ExpertData(x=data.x, y=y, mask=data.mask)
            jitter = getattr(instr, "expert_jitter", None)
            nll, _ = expert_health(
                kernel, np.asarray(theta, dtype=np.float64), probe,
                "marginal", jitter=jitter,
            )
            mask = np.asarray(data.mask)
            active = mask.sum(axis=1) > 0
            renorm = float(instr.metrics.get("bcm_renorm", 1.0))
            agg_w = getattr(instr, "agg_weights", None)
            if agg_w is not None and np.asarray(agg_w).shape[0] == len(active):
                # the ACTUAL aggregation-plane weight w_e each expert
                # enters the weighted objective with (fit-time selection
                # and quarantine composed) — not the uniform-renorm
                # approximation this column used to report
                weights = np.asarray(agg_w, dtype=np.float64)
            else:
                weights = np.where(active, renorm, 0.0)
            jit_arr = (
                np.zeros(nll.shape[0]) if jitter is None
                else np.broadcast_to(
                    np.asarray(jitter, dtype=np.float64), nll.shape
                )
            )
            finite = active & np.isfinite(nll)
            act_nll = nll[finite]
            cap = 512  # journal stays bounded for E in the thousands
            instr.expert_quality = {
                "objective": "marginal_proxy",
                "experts": int(nll.shape[0]),
                "active": int(active.sum()),
                "nll": [float(v) for v in nll[:cap]],
                "jitter": [float(v) for v in jit_arr[:cap]],
                "weight": [float(v) for v in weights[:cap]],
                "truncated": bool(nll.shape[0] > cap),
            }
            if act_nll.size:
                instr.log_metric(
                    "expert_quality.nll_spread",
                    float(act_nll.max() - act_nll.min()),
                )
                instr.log_metric(
                    "expert_quality.nll_std", float(act_nll.std())
                )
            instr.log_metric(
                "expert_quality.jitter_max", float(jit_arr.max(initial=0.0))
            )
            instr.log_metric(
                "expert_quality.weight_min",
                float(weights.min(initial=renorm)) if nll.shape[0] else 0.0,
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail a fit
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "per-expert quality telemetry failed", exc_info=True
            )

    def _emit_covariate_summary(self, instr, data, active64) -> None:
        """Compact training-covariate summary (per-dim moments + the
        active-set-centroid distance sketch, ``obs/quality.
        summarize_covariates``) stamped onto the instr; the saved model
        carries it in ``provenance_json`` so serve can score incoming
        rows for input drift against THIS fit's training mass.
        ``GP_COVARIATE_SUMMARY=0`` disables (one host fetch of the
        stack per fit is the cost)."""
        import os

        if instr is None or not self._probeable_stack(data):
            return
        if os.environ.get("GP_COVARIATE_SUMMARY", "").strip().lower() in (
            "0", "off", "false",
        ):
            return
        try:
            from spark_gp_tpu.obs.quality import summarize_covariates

            x = np.asarray(data.x)
            mask = np.asarray(data.mask)
            rows = x.reshape(-1, x.shape[-1])[mask.reshape(-1) > 0]
            instr.covariate_summary = summarize_covariates(
                rows, active=active64, seed=self._seed
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail a fit
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "covariate summary failed", exc_info=True
            )

    def _finalize_device_fit(
        self,
        instr: Instrumentation,
        kernel: Kernel,
        theta_dev,
        pending: dict,
        x: Optional[np.ndarray],
        targets_fn: Optional[Callable[[], np.ndarray]],
        data: ExpertData,
        active_override: Optional[np.ndarray] = None,
    ):
        """Device-pipelined PPA build: the optimizer's *device* theta chains
        straight into the f64 (U1, u2) statistics program, and everything —
        theta, the statistics, and the ``pending`` optimizer scalars — comes
        back to the host in ONE ``device_get``.

        On runtimes where every host<->device sync costs a full RTT (tunneled
        TPU, multi-host pods where the driver sync stalls the ICI collective)
        this turns the ~8 blocking transfers of the naive fit into one.

        ``targets_fn`` lazily materializes the provider's y-targets (the
        classifier's latent modes live on device; fetching them is a sync we
        skip unless the provider actually reads them).

        Returns ``(raw_predictor, fetched)`` with ``fetched`` mapping the
        pending keys to host values.
        """
        import jax
        import jax.numpy as jnp

        provider = self._active_set_provider
        with instr.phase("active_set"):
            if active_override is not None:
                active = active_override
            elif x is None:
                # distributed mode: sharded-stack selection; theta stays on
                # device (from_stack casts it to the stack dtype itself)
                active = provider.from_stack(
                    self._active_set_size, data, kernel, theta_dev,
                    self._seed, self._mesh,
                )
            elif getattr(provider, "uses_fit_outputs", True):
                # e.g. greedy Seeger scores read theta and the targets: a
                # host sync is unavoidable for this provider family.
                theta_host = np.asarray(theta_dev, dtype=np.float64)
                active = provider(
                    self._active_set_size, x, targets_fn(), kernel,
                    theta_host, self._seed,
                )
            else:
                active = provider(
                    self._active_set_size, x, None, kernel, None, self._seed,
                )
        active64 = np.asarray(active, dtype=np.float64)

        with instr.phase("kmn_stats"), jax.enable_x64():
            active_dev = jnp.asarray(active64)
            if self._mesh is not None:
                u1_dev, u2_dev, theta64_dev = (
                    ppa._sharded_kmn_stats_x64_from32_impl(
                        kernel, self._mesh, theta_dev, active_dev,
                        data.x, data.y, data.mask,
                    )
                )
            else:
                u1_dev, u2_dev, theta64_dev = ppa._kmn_stats_x64_from32_impl(
                    kernel, theta_dev, active_dev, data.x, data.y, data.mask
                )
            phase_sync(u1_dev, u2_dev)

        keys = list(pending.keys())
        with instr.phase("sync_fetch"):
            vals = jax.device_get(
                [theta64_dev, u1_dev, u2_dev] + [pending[k] for k in keys]
            )
        theta64, u1, u2 = vals[0], vals[1], vals[2]
        fetched = dict(zip(keys, vals[3:]))
        for key, val in fetched.items():
            arr = np.asarray(val)
            if arr.ndim != 0:
                continue  # non-scalar diagnostics (e.g. per-restart NLLs)
            instr.log_metric(
                key, int(arr) if np.issubdtype(arr.dtype, np.integer) else float(arr)
            )
        if bool(np.asarray(fetched.get("lbfgs_stalled", False))):
            # The device optimizer's line search exhausted without an
            # acceptable step — the analogue of the host path's
            # success=False.  The fit still produces a model from the best
            # iterate, but a production run should treat this as suspect.
            instr.log_warning(
                "device L-BFGS stalled (line search exhausted before "
                "convergence) — returned hyperparameters are the best "
                "iterate seen, not a certified optimum."
            )
        instr.log_info("Optimal kernel: " + kernel.describe(theta64))

        raw = self._build_predictor(
            instr, kernel, theta64, active64, u1, u2, data=data
        )
        return raw, fetched
