"""Laplace approximation for binary GP classification.

Per-expert semantics follow GaussianProcessClassifier.likelihoodAndGradient
(GaussianProcessClassifier.scala:74-129):

* Newton optimization of the latent posterior mode f (R&W Algorithm 3.1)
  with objective-increase checking and step halving — here a
  ``lax.while_loop`` whose termination matches the reference's
  ``|oldObj - newObj| > tol && step > tol``;
* the approximate log marginal likelihood log Z and its hyperparameter
  gradient via R&W Algorithm 5.1, including the third-derivative implicit
  correction (s2/s3 terms).

TPU re-design notes:

* the Newton loop is BATCH-level: one ``while_loop`` over the whole
  ``[E, s, s]`` stack with per-expert masked updates (the hardware-friendly
  equivalent of Spark's independent per-partition loops), so each
  iteration's B = I + sqrtW K sqrtW factor/invert is ONE fused batched pass
  — the Pallas SPD kernel on TPU, XLA batched Cholesky elsewhere (the same
  MXU-utilization argument as the GPR objective);
* dK/dtheta comes from ``jax.jacfwd`` of the (masked) Gram function —
  exactly the quantities the reference assembles kernel-by-kernel by hand
  (trainingKernelAndDerivative) but for any composite kernel for free;
* the Newton loop needs no autodiff through it: Algorithm 5.1's gradient only
  uses the converged state (implicit-function theorem), so the while_loop is
  never differentiated;
* W, gradients and objective terms are masked so padded points contribute
  exactly nothing (B has unit rows at padding -> logdet contribution 0).

The latent warm start (the reference mutates f inside its cached RDD across
L-BFGS evaluations, GPClf.scala:53-60) is explicit carried state here: the
objective returns the new ``f`` stack and the optimizer closure feeds it back.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_gp_tpu.kernels.base import Kernel, masked_gram_stack
from spark_gp_tpu.obs import cost as obs_cost
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.ops.linalg import masked_kernel_matrix
from spark_gp_tpu.optimize.lbfgs_device import lbfgs_state_donation
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS, sharded_cache_operand


class _NewtonState(NamedTuple):
    f: jax.Array  # [E, s]
    old_obj: jax.Array  # [E]
    new_obj: jax.Array  # [E]
    step: jax.Array  # [E]


def _posterior_terms_batch(kmat, y, mask, f):
    """Quantities of Algorithms 3.1/5.1 evaluated at latent f, for the whole
    ``[E, s, ...]`` expert stack at once.

    The factorization of B = I + sqrtW K sqrtW splits by backend exactly
    like the GPR objective (likelihood.py:44-56): on TPU the fused batched
    Pallas pass materializes B^-1 + log|B| in one kernel (the explicit
    inverse is numerically benign — B's eigenvalues are >= 1 by
    construction, and it makes every downstream application a batched
    matmul on the MXU); elsewhere one batched Cholesky is kept as the
    ``factor`` and applications are triangular solves — materializing
    inverses per Newton iteration would be ~3x the work there.

    Returns ``(pi, w, sqw, factor, logdet, grad_log_p)`` with ``factor``
    a tagged pair consumed by :func:`_apply_binv` / :func:`_binv_full`.
    """
    from spark_gp_tpu.ops.pallas_linalg import _use_pallas, spd_inv_logdet

    from spark_gp_tpu.ops.linalg import chol_logdet, cholesky

    pi = jax.nn.sigmoid(f)
    w = pi * (1.0 - pi) * mask
    sqw = jnp.sqrt(w)
    eye = jnp.eye(kmat.shape[-1], dtype=kmat.dtype)
    b_mat = eye[None] + sqw[:, :, None] * kmat * sqw[:, None, :]
    grad_log_p = (y - pi) * mask
    if it_ops.resolve_solver(kmat.shape[-1]) in ("iterative", "matfree"):
        # (matfree resolves here too: the Laplace B systems are
        # materialized-operator solves — the matrix-free memory win is
        # marginal-NLL-scoped, and regressing to the batched Cholesky
        # under GP_SOLVER_LANE=matfree would be strictly worse)
        # the CG/Lanczos solver lane (ops/iterative.py): no full
        # factorization — ``B v`` applications become pivoted-Cholesky
        # preconditioned multi-RHS CG solves (B's eigenvalues are >= 1,
        # but its CONDITIONING is 1 + lambda_max(K W), into the
        # thousands on dense grams — unpreconditioned f32 CG diverges
        # there) and log|B| the preconditioned SLQ estimate.  The rank-k
        # preconditioner is built ONCE here and carried in the factor
        # tuple, so the Newton-step solve, the convergence-time full
        # inverse, and the log-det all share it.  Inside the Newton
        # while_loop the unused log-det is DCE'd by XLA; each iteration
        # pays O(t s^2) batched-matmul work instead of O(s^3).
        precond = it_ops.build_spd_preconditioner(b_mat)
        return (
            pi, w, sqw, ("iter", (b_mat, precond)),
            it_ops.spd_logdet(b_mat, precond=precond),
            grad_log_p,
        )
    if _use_pallas(b_mat):
        binv, logdet = spd_inv_logdet(b_mat)
        return pi, w, sqw, ("inv", binv), logdet, grad_log_p
    chol_l = cholesky(b_mat)
    return pi, w, sqw, ("chol", chol_l), chol_logdet(chol_l), grad_log_p


def _apply_binv(factor, v):
    """``B^-1 v`` per expert (``v`` is ``[E, s]``)."""
    from spark_gp_tpu.ops.linalg import chol_solve

    tag, mat = factor
    if tag == "inv":
        return jnp.einsum("eij,ej->ei", mat, v)
    if tag == "iter":
        b_mat, precond = mat
        return it_ops.spd_solve(b_mat, v, precond=precond)
    return chol_solve(mat, v)


def _binv_full(factor):
    """Explicit ``B^-1 [E, s, s]`` — convergence-time only on the Cholesky
    branch (the Algorithm 5.1 terms genuinely consume the full inverse,
    matching the reference's solve-against-diag(sqw), GPClf.scala:115-116).
    On the iterative lane the inverse is one s-column multi-RHS CG solve:
    still no factorization (every step is a batched matmul on the MXU),
    but O(t s^3) work — paid ONCE per objective evaluation at the
    converged mode, not per Newton iteration like the exact lanes'
    factorizations.
    """
    from spark_gp_tpu.ops.linalg import chol_solve

    tag, mat = factor
    if tag == "inv":
        return mat
    if tag == "iter":
        b_mat, precond = mat
        eye = jnp.broadcast_to(
            jnp.eye(b_mat.shape[-1], dtype=b_mat.dtype), b_mat.shape
        )
        return it_ops.spd_solve(b_mat, eye, precond=precond)
    eye = jnp.broadcast_to(
        jnp.eye(mat.shape[-1], dtype=mat.dtype), mat.shape
    )
    return chol_solve(mat, eye)


def _newton_a_batch(kmat, w, sqw, factor, grad_log_p, f):
    """a = b - sqrtW B^-1 sqrtW K b with b = W f + grad_log_p
    (GPClf.scala:100-101), batched over experts."""
    b = w * f + grad_log_p
    kb = jnp.einsum("eij,ej->ei", kmat, b)
    return b - sqw * _apply_binv(factor, sqw * kb)


def _objective_batch(a, f_new, y, mask):
    """-a^T f / 2 + sum log sigmoid((2y-1) * f) over real points
    (GPClf.scala:102), per expert."""
    return -0.5 * jnp.sum(a * f_new, axis=-1) + jnp.sum(
        mask * jax.nn.log_sigmoid((2.0 * y - 1.0) * f_new), axis=-1
    )


def laplace_mode_batch(kmat, y, mask, f0, tol):
    """Newton loop with per-expert step halving over the whole stack;
    returns (f_modes [E, s], new_obj [E]).

    Termination and acceptance mirror GPClf.scala:91-111 per expert: a
    candidate is accepted iff its objective beats ``old_obj``, else the
    step halves; an expert whose own condition has failed freezes (masked
    updates) while the others keep iterating — one batched while_loop for
    the stack instead of E data-dependent loops, so every iteration's
    factorizations land on the MXU as one batched (Pallas) pass.
    """
    dtype = kmat.dtype
    # Deriving the carries from f0 (0 * sum) keeps their device-variance
    # type consistent with the data under shard_map: a literal constant is
    # "replicated" while the body's outputs are "varying", and
    # lax.while_loop requires the carry types to match.
    zero = jnp.zeros((), dtype=dtype) + 0.0 * jnp.sum(f0, axis=-1)  # [E]
    init = _NewtonState(
        f=f0,
        old_obj=zero - jnp.inf,
        new_obj=zero + jnp.finfo(dtype).min,
        step=zero + 1.0,
    )

    def running(state: _NewtonState):
        return jnp.logical_and(
            jnp.abs(state.old_obj - state.new_obj) > tol, state.step > tol
        )

    def cond(state: _NewtonState):
        return jnp.any(running(state))

    def body(state: _NewtonState):
        _, w, sqw, factor, _, grad_log_p = _posterior_terms_batch(
            kmat, y, mask, state.f
        )
        a = _newton_a_batch(kmat, w, sqw, factor, grad_log_p, state.f)
        f_cand = (1.0 - state.step)[:, None] * state.f + state.step[
            :, None
        ] * jnp.einsum("eij,ej->ei", kmat, a)
        obj_cand = _objective_batch(a, f_cand, y, mask)
        accept = obj_cand > state.old_obj
        run = running(state)
        upd = run & accept
        return _NewtonState(
            f=jnp.where(upd[:, None], f_cand, state.f),
            old_obj=jnp.where(upd, state.new_obj, state.old_obj),
            new_obj=jnp.where(upd, obj_cand, state.new_obj),
            step=jnp.where(run & ~accept, state.step / 2.0, state.step),
        )

    final = jax.lax.while_loop(cond, body, init)
    return final.f, final.new_obj


def _dk_stack(kernel: Kernel, theta, x, mask, cache=None):
    """dK/dtheta for every expert: ``[E, s, s, h]`` via vmapped jacfwd.

    With a theta-invariant ``cache`` the jacobian runs through
    ``gram_from_cache`` — the forward-mode tangents never traverse the
    distance contraction, only the elementwise theta-map."""

    if cache is None:
        def one(x_e, m_e):
            return jax.jacfwd(
                lambda t: masked_kernel_matrix(kernel.gram(t, x_e), m_e)
            )(theta)

        return jax.vmap(one)(x, mask)

    def one_cached(c_e, m_e):
        return jax.jacfwd(
            lambda t: masked_kernel_matrix(
                kernel.gram_from_cache(t, c_e), m_e
            )
        )(theta)

    return jax.vmap(one_cached)(cache, mask)


def batched_neg_logz(
    kernel: Kernel, tol, theta, data: ExpertData, f0, cache=None,
    weights=None,
):
    """Sum over the local expert stack; returns (nll, grad, f_stack).

    ``weights`` ([E]) is the aggregation plane's per-expert weight
    operand (``models/aggregation.py``): the evidence and its gradient
    become ``sum_e w_e (.)_e`` — one weighted reduction shared with the
    marginal/LOO objectives, so quarantine masking (w_e = 0 via the
    inert identity block) and selection down-weighting compose
    identically here.  ``None`` keeps the unweighted sums bit-for-bit.

    Everything batch-level — the Newton loop, the Algorithm 5.1 gradient
    assembly (GPClf.scala:113-128) and the dK/dtheta stack — so the inner
    factorizations are one fused batched pass per iteration.  ``cache``
    is the theta-invariant gram cache (kernels/base.py): both the Gram
    stack AND the dK/dtheta jacobian then skip the distance contraction.
    """

    kmat = masked_gram_stack(kernel, theta, data.x, data.mask, cache)
    y, mask = data.y, data.mask
    f, new_obj = laplace_mode_batch(kmat, y, mask, f0, tol)

    # Recompute converged-state quantities (identical to the reference's
    # final-iteration values: f no longer changes).
    pi, w, sqw, factor, logdet, grad_log_p = _posterior_terms_batch(
        kmat, y, mask, f
    )
    a = _newton_a_batch(kmat, w, sqw, factor, grad_log_p, f)
    binv = _binv_full(factor)  # Alg 5.1 consumes the full inverse

    # log|B| = 2 sum log diag chol(B)  (GPClf.scala:113's cholesky diag sum)
    log_z = new_obj - 0.5 * logdet

    # Algorithm 5.1 auxiliaries (GPClf.scala:115-126), inverse-based:
    #   R = sqrtW B^-1 sqrtW
    #   sum_rows(C * C) = diag(K sqrtW B^-1 sqrtW K) with C = L^-1 sqrtW K
    r_mat = sqw[:, :, None] * binv * sqw[:, None, :]
    ksq = kmat * sqw[:, None, :]  # [E, s, s] = K diag(sqw)
    csum = jnp.einsum("eij,ejk,eik->ei", ksq, binv, ksq)
    # d^3/df^3 log p(y|f) = -(2 pi - 1) pi (1 - pi)  (GPClf.scala:118 in the
    # algebraically equivalent pi^2 exp(-f) form).
    d3_log_p = -(2.0 * pi - 1.0) * pi * (1.0 - pi) * mask
    kdiag = jnp.diagonal(kmat, axis1=-2, axis2=-1)
    s2 = -0.5 * (kdiag - csum) * d3_log_p

    dk = _dk_stack(kernel, theta, data.x, mask, cache)  # [E, s, s, h]

    s1 = 0.5 * jnp.einsum("es,esth,et->eh", a, dk, a) - 0.5 * jnp.einsum(
        "esth,est->eh", dk, r_mat
    )
    b_vecs = jnp.einsum("esth,et->esh", dk, grad_log_p)
    s3 = b_vecs - jnp.einsum(
        "eij,ejh->eih", kmat, jnp.einsum("eij,ejh->eih", r_mat, b_vecs)
    )
    grad_log_z = s1 + jnp.einsum("es,esh->eh", s2, s3)

    from spark_gp_tpu.models.aggregation import weighted_expert_sum

    if weights is None:
        return -jnp.sum(log_z), -jnp.sum(grad_log_z, axis=0), f
    w = jnp.asarray(weights, log_z.dtype)
    return (
        -weighted_expert_sum(log_z, w),
        -jnp.sum(w[:, None] * grad_log_z, axis=0),
        f,
    )


# --- single-expert wrappers (tests / parity oracles) ----------------------


def laplace_mode(kmat, y, mask, f0, tol):
    """Single-expert Newton loop — thin wrapper over the batch core."""
    f, obj = laplace_mode_batch(
        kmat[None], y[None], mask[None], f0[None], tol
    )
    return f[0], obj[0]


def expert_neg_logz_and_grad(kernel: Kernel, tol, theta, x, y, mask, f0):
    """One expert's (-log Z, -dlogZ/dtheta, f_mode) — GPClf.scala:74-129.
    Thin wrapper over the batch core (the production path)."""
    data = ExpertData(x=x[None], y=y[None], mask=mask[None])
    neg_z, neg_grad, f = batched_neg_logz(kernel, tol, theta, data, f0[None])
    return neg_z, neg_grad, f[0]


@partial(jax.jit, static_argnums=(0, 1), static_argnames=("solver",))
def _laplace_impl(
    kernel: Kernel, tol, theta, x, y, mask, f0, cache=None, *, solver=None
):
    with it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        return batched_neg_logz(kernel, tol, theta, data, f0, cache)


def make_laplace_objective(kernel: Kernel, data: ExpertData, tol, cache=None):
    """Single-device jitted ``(theta, f0) -> (nll, grad, f_new)``.  Kernel and
    tol are static args of a module-level jit (executable reuse across fits).
    ``cache`` is the theta-invariant gram cache (kernels/base.py), resident
    on device across the host optimizer's evaluations."""

    def obj(theta, f0):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        # measured flops/bytes per evaluation (obs/cost.py, GP_XLA_COST)
        return obs_cost.observed_call(
            "fit.host_objective", _laplace_impl,
            kernel, float(tol), theta, data.x, data.y, data.mask, f0, cache,
            solver=it_ops.solver_jit_key(),
        )

    return obj


def _make_sharded_logz(
    kernel: Kernel, tol, mesh, cache_specs=(),
    cache_of=lambda maybe_cache: None,
):
    """shard_map'd ``(theta, f, x, y, mask[, cache]) -> (value, grad,
    f_new)`` core, shared by the host-driven objective, the one-dispatch
    fit and the segmented checkpointing loop.  ``(cache_specs, cache_of)``
    come from :func:`parallel.mesh.sharded_cache_operand`."""

    in_specs = (
        P(), P(EXPERT_AXIS),
        P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
    ) + tuple(cache_specs)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P(), P(EXPERT_AXIS)),
    )
    def core(theta, f_carry, x_, y_, mask_, *maybe_cache):
        local = ExpertData(x=x_, y=y_, mask=mask_)
        cache = cache_of(maybe_cache)
        value, grad, f_new = batched_neg_logz(
            kernel, tol, theta, local, f_carry, cache
        )
        # The Laplace gradient is assembled manually (Alg 5.1), not by
        # differentiating w.r.t. the replicated theta, so unlike the GPR
        # path it DOES need its own psum.
        return (
            jax.lax.psum(value, EXPERT_AXIS),
            jax.lax.psum(grad, EXPERT_AXIS),
            f_new,
        )

    return core


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def _sharded_laplace_impl(
    kernel: Kernel, tol, mesh, theta, x, y, mask, f0, cache=None, *,
    solver=None,
):
    with it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        core = _make_sharded_logz(kernel, tol, mesh, cache_specs, cache_of)
        return core(theta, f0, x, y, mask, *cache_args)


def make_sharded_laplace_objective(
    kernel: Kernel, data: ExpertData, tol, mesh, cache=None
):
    """Sharded objective: experts and latent state sharded, (value, grad)
    psum-reduced over ICI — the treeAggregate of GPC.scala:73-78."""

    def obj(theta, f0):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        return _sharded_laplace_impl(
            kernel, float(tol), mesh, theta, data.x, data.y, data.mask, f0,
            cache, solver=it_ops.solver_jit_key(),
        )

    return obj


# --- fully on-device fits (see likelihood.py counterparts) ----------------


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def fit_gpc_device(
    kernel: Kernel, tol, log_space, theta0, lower, upper, x, y, mask,
    max_iter, cache=None, *, solver=None,
):
    """Single-chip on-device classifier fit; the latent warm-start stack is
    the optimizer's auxiliary carry.  Returns (theta, f_latents, nll, n_iter,
    n_fev, stalled).  ``cache`` sits outside the L-BFGS while_loop and is
    reused by every evaluation's gram + dK/dtheta builds.  ``solver`` is
    the static solver lane (ops/iterative.py; the estimator passes the
    resolved lane so switching lanes between fits recompiles)."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )

    with it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)

        def vag(theta, f_carry):
            value, grad, f_new = batched_neg_logz(
                kernel, tol, theta, data, f_carry, cache
            )
            return value, grad, f_new

        if log_space:
            vag, theta0, lower, upper, from_u = log_reparam(
                vag, theta0, lower, upper
            )
        else:
            from_u = lambda t: t

        f0 = jnp.zeros_like(y)
        theta, f, f_final, n_iter, n_fev, stalled = lbfgs_minimize_device(
            vag, theta0, lower, upper, f0, max_iter=max_iter, tol=tol
        )
        return from_u(theta), f_final, f, n_iter, n_fev, stalled


# --- segmented device fit: checkpoint/resume (likelihood.py counterpart) --


def _gpc_segment_vag(
    kernel: Kernel, tol, mesh, log_space, data: ExpertData, cache=None
):
    from spark_gp_tpu.optimize.lbfgs_device import log_transform_vag

    if mesh is None:

        def base(theta, f_carry):
            value, grad, f_new = batched_neg_logz(
                kernel, tol, theta, data, f_carry, cache
            )
            return value, grad, f_new

    else:
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        core = _make_sharded_logz(kernel, tol, mesh, cache_specs, cache_of)

        def base(theta, f_carry):
            return core(
                theta, f_carry, data.x, data.y, data.mask, *cache_args
            )

    return log_transform_vag(base) if log_space else base


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",)
)
def gpc_device_segment_init(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper, x, y, mask,
    cache=None, *, solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import lbfgs_init_state

    with it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        vag = _gpc_segment_vag(kernel, tol, mesh, log_space, data, cache)
        t0 = jnp.log(theta0) if log_space else theta0
        return lbfgs_init_state(vag, t0, jnp.zeros_like(y))


# the L-BFGS state carry is donated — consumed once per segment and
# replaced by the return value (optimize/lbfgs_device.lbfgs_state_donation)
@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",),
    donate_argnums=lbfgs_state_donation(4),
)
def gpc_device_segment_run(
    kernel: Kernel, tol, mesh, log_space, state, lower, upper, x, y, mask,
    iter_limit, cache=None, *, solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_run_segment,
        log_transform_bounds,
    )

    with it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        vag = _gpc_segment_vag(kernel, tol, mesh, log_space, data, cache)
        lo, hi = (
            log_transform_bounds(lower, upper) if log_space
            else (lower, upper)
        )
        return lbfgs_run_segment(vag, state, lo, hi, iter_limit, tol)


def fit_gpc_device_checkpointed(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper,
    data: ExpertData, max_iter: int, chunk: int, saver, cache=None,
):
    """Segmented on-device classifier fit with state persistence — see
    likelihood.fit_gpr_device_checkpointed.  The aux carry here is the
    latent warm-start stack, so a resume continues from the settled modes,
    not from zero latents.  Returns (theta, f_latents, nll, n_iter, n_fev,
    stalled).  The gram cache rides every segment dispatch (it is derived
    state, rebuilt per fit — never part of the persisted checkpoint).
    """
    from spark_gp_tpu.utils.checkpoint import run_segmented, segment_meta

    meta = segment_meta(
        "gpc", kernel, tol, log_space, theta0, data.x, data.y, data.mask
    )
    solver = it_ops.solver_jit_key()

    def init(theta0_, lower_, upper_, x_, y_, mask_):
        return gpc_device_segment_init(
            kernel, float(tol), mesh, log_space, theta0_, lower_, upper_,
            x_, y_, mask_, cache, solver=solver,
        )

    def run(state, limit):
        return gpc_device_segment_run(
            kernel, float(tol), mesh, log_space, state, lower, upper,
            data.x, data.y, data.mask, limit, cache, solver=solver,
        )

    theta, state = run_segmented(
        init, run, saver, meta,
        (theta0, lower, upper, data.x, data.y, data.mask),
        max_iter, chunk, log_space,
    )
    return theta, state.aux, state.f, state.n_iter, state.n_fev, state.stalled


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",)
)
def fit_gpc_device_sharded(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper, x, y, mask,
    max_iter, cache=None, *, solver=None,
):
    """Multi-chip on-device classifier fit inside one shard_map: latent
    stacks stay device-resident and sharded for the entire optimization;
    the (expert-sharded) gram cache rides into each local program."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )
    from spark_gp_tpu.utils.compat import whole_loop_shard_map_supported

    if not whole_loop_shard_map_supported():
        # old-jax compat (utils/compat.py): the L-BFGS while_loop inside
        # shard_map wedges the compile; GSPMD partitions the same stack
        return fit_gpc_device(
            kernel, tol, log_space, theta0, lower, upper, x, y, mask,
            max_iter, cache, solver=solver,
        )

    with it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        in_specs = (
            P(), P(), P(),
            P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
            P(),
        ) + cache_specs

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(), P(EXPERT_AXIS), P(), P(), P(), P()),
        )
        def run(theta0_, lower_, upper_, x_, y_, mask_, max_iter_,
                *maybe_cache):
            local = ExpertData(x=x_, y=y_, mask=mask_)
            local_cache = cache_of(maybe_cache)

            def vag(theta, f_carry):
                value, grad, f_new = batched_neg_logz(
                    kernel, tol, theta, local, f_carry, local_cache
                )
                return (
                    jax.lax.psum(value, EXPERT_AXIS),
                    jax.lax.psum(grad, EXPERT_AXIS),
                    f_new,
                )

            if log_space:
                vag, t0, lo, hi, from_u = log_reparam(
                    vag, theta0_, lower_, upper_
                )
            else:
                vag, t0, lo, hi, from_u = (
                    vag, theta0_, lower_, upper_, (lambda t: t)
                )

            f0 = jnp.zeros_like(y_)
            theta, f, f_final, n_iter, n_fev, stalled = lbfgs_minimize_device(
                vag, t0, lo, hi, f0, max_iter=max_iter_, tol=tol
            )
            return from_u(theta), f_final, f, n_iter, n_fev, stalled

        return run(theta0, lower, upper, x, y, mask, max_iter, *cache_args)


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def fit_gpc_device_multistart(
    kernel: Kernel, tol, log_space, theta0_batch, lower, upper, x, y, mask,
    max_iter, cache=None, *, solver=None,
):
    """Multi-start single-chip classifier fit: R restarts as ONE vmapped
    device program (see lbfgs_device.lbfgs_minimize_device_multistart); the
    latent warm-start stacks ride per-lane ([R, E, s] total), while ONE
    gram cache broadcasts to every lane (theta-invariant).  Returns
    ``(theta_best, f_latents_best, nll_best, n_iter, n_fev, stalled,
    f_all [R], best)``."""
    from spark_gp_tpu.optimize.lbfgs_device import multistart_minimize

    with it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)

        def vag(theta, f_carry):
            value, grad, f_new = batched_neg_logz(
                kernel, tol, theta, data, f_carry, cache
            )
            return value, grad, f_new

        theta, f_final, f, n_iter, n_fev, stalled, f_all, best = (
            multistart_minimize(
                vag, log_space, theta0_batch, lower, upper,
                jnp.zeros_like(y), max_iter, tol,
            )
        )
        return theta, f_final, f, n_iter, n_fev, stalled, f_all, best
