"""Gaussian Process Classification (binary, sigmoid link) via Laplace
approximation — counterpart of classification/GaussianProcessClassifier.scala.

Fit pipeline (GPClf.scala:48-66): assert labels are {0,1}; group experts with
a zero-initialized latent vector f per expert; L-BFGS-B the hyperparameters
against the summed Laplace -log Z (f warm-started across evaluations); run one
final evaluation at theta* to settle f; then build the Projected Process model
treating the latent modes f as regression targets.

Prediction (GPClf.scala:137-162): latent mean f* from the shared raw
predictor; ``predict_raw = (-f*, f*)``; probability = sigmoid(f*).  The
reference computes the latent variance and then discards it; here
``predict_proba(..., averaged=True)`` optionally integrates the sigmoid over
the latent Gaussian with Gauss–Hermite quadrature (the ``Integrator`` the
reference ships but never wires in — util/Integrator.scala).

Binary only, like the reference (GPClf.scala:151); multiclass goes through
``utils.validation.OneVsRest``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.models.common import GaussianProcessCommons
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.models.laplace import (
    make_laplace_objective,
    make_sharded_laplace_objective,
)
from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.utils.instrumentation import Instrumentation, phase_sync


@jax.jit
def _labels_are_01(y, mask):
    # module-level jit: single compilation across fits, and the reduction
    # runs as a program (required for non-fully-addressable global arrays
    # in multi-host runs — eager ops can't touch those)
    ym = y * mask
    return jnp.all(ym * (ym - 1.0) == 0.0)


class GaussianProcessClassifier(GaussianProcessCommons):
    """Binary GP classifier with the reference's fluent parameter API."""

    def fit(self, x: np.ndarray, y: np.ndarray) -> "GaussianProcessClassificationModel":
        # subclasses (the EP engine) must log and report under their own
        # estimator name, mirroring gp_poisson.py's NB convention
        instr = Instrumentation(name=type(self).__name__)
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        if x.ndim != 2:
            raise ValueError(f"x must be [N, p], got shape {x.shape}")
        if y.shape != (x.shape[0],):
            raise ValueError(f"y must be [N], got shape {y.shape}")
        if not np.all(np.isin(y, (0.0, 1.0))):
            # GPClf.scala:68-72
            raise ValueError("Only 0 and 1 labels are supported.")
        # the observation shell wraps the WHOLE post-validation body (the
        # gpr.py convention): grouping/screen phases — and any screen-time
        # quarantine events — land inside the fit's root span
        return self._observed_fit(
            instr, lambda: self._fit_body(instr, x, y)
        )

    def _fit_body(self, instr, x, y) -> "GaussianProcessClassificationModel":
        with instr.phase("group_experts"):
            data = self._group_screened(instr, x, y)
        instr.log_metric("num_experts", data.num_experts)

        # PPA runs over the latent modes as targets (GPClf.scala:62-65), and
        # the active-set provider also sees the latents, not the 0/1 labels —
        # the reference substitutes f for y before produceModel.  targets_fn
        # defers flattening (a device sync on the device path) until a
        # provider actually reads the targets.
        from spark_gp_tpu.parallel.experts import num_experts_for, ungroup

        # providers sample raw host rows; hand them only finite ones, and
        # filter the ungrouped latent targets by the SAME mask so rows and
        # targets stay aligned (common._provider_rows_filter)
        x, n_orig, row_filter = self._provider_rows_filter(x)

        def make_targets_fn(latent_y):
            def targets_fn():
                e_real = num_experts_for(n_orig, self._dataset_size_for_expert)
                return row_filter(
                    ungroup(np.asarray(latent_y)[:e_real], n_orig)
                )

            return targets_fn

        # the theta-invariant gram cache, built once and shared by every
        # restart (all restarts wrap ONE kernel spec — common._gram_cache)
        cache = self._gram_cache(instr, data)

        def fit_once(kernel, instr_r):
            raw = self._fit_from_stack(
                instr_r, kernel, data, x, make_targets_fn, cache=cache
            )
            instr_r.log_success()
            model = GaussianProcessClassificationModel(raw)
            model.instr = instr_r
            return model

        def attempt():
            if self._use_batched_multistart():
                return self._fit_device_multistart(
                    instr, data, x, make_targets_fn, cache
                )
            return self._fit_with_restarts(instr, fit_once)

        from spark_gp_tpu.resilience import fallback

        # degradation ladder around the complete attempt (the same wrap as
        # gpr._fit_body): classified execution failures re-execute one
        # rung down; GP_FALLBACK=0 restores raw propagation
        return fallback.run_fit_ladder(self, instr, attempt, data=data)

    # human-readable engine tag for the multistart log line; the EP
    # subclass overrides both this and _multistart_device_call
    _engine_log_tag = ""

    def _multistart_device_call(
        self, kernel, log_space, theta_batch, lower, upper, data, max_iter,
        cache=None,
    ):
        """Engine hook for the shared multistart skeleton: run the vmapped
        R-restart device fit and return ``(theta, latent_y, nll, n_iter,
        n_fev, stalled, f_all, best)`` with ``latent_y`` the winner's PPA
        targets (masked latent stack).  ``cache`` is the theta-invariant
        gram cache, broadcast across the restart lanes (EP ignores it —
        its site-update engine has no cached-gram path yet)."""
        from spark_gp_tpu.models.laplace import fit_gpc_device_multistart

        theta, f_final, nll, n_iter, n_fev, stalled, f_all, best = (
            fit_gpc_device_multistart(
                kernel, float(self._tol), log_space, theta_batch,
                lower, upper, data.x, data.y, data.mask, max_iter, cache,
                solver=it_ops.solver_jit_key(),
            )
        )
        return (
            theta, f_final * data.mask, nll, n_iter, n_fev, stalled, f_all,
            best,
        )

    def _fit_device_multistart(
        self, instr, data, x, make_targets_fn, cache=None
    ) -> "GaussianProcessClassificationModel":
        """Batched on-device multi-start (single chip): R starting points
        run in one vmapped inference + L-BFGS dispatch (the engine hook
        ``_multistart_device_call``); the winner's latent targets feed one
        PPA build.  ONE skeleton for both inference engines (Laplace here,
        EP in gpc_ep.py)."""
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            kernel = self._get_kernel()
            dtype = data.x.dtype
            theta_batch = jnp.asarray(
                self._restart_theta_batch(kernel), dtype=dtype
            )
            lower, upper = kernel.bounds()
            log_space = self._use_log_space(kernel)
            instr.log_info(
                "Optimising the kernel hyperparameters "
                f"(on-device{self._engine_log_tag}, "
                f"{self._num_restarts} batched restarts)"
            )
            with instr.phase("optimize_hypers"):
                theta, latent_y, nll, n_iter, n_fev, stalled, f_all, best = (
                    self._multistart_device_call(
                        kernel, log_space, theta_batch,
                        jnp.asarray(lower, dtype=dtype),
                        jnp.asarray(upper, dtype=dtype),
                        data,
                        jnp.asarray(self._max_iter, dtype=jnp.int32),
                        cache,
                    )
                )
                phase_sync(theta, nll)
            latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)
            pending = {
                "lbfgs_iters": n_iter,
                "lbfgs_nfev": n_fev,
                "final_nll": nll,
                "lbfgs_stalled": stalled,
                "best_restart": best,
                "restart_nlls": f_all,
            }
            raw, fetched = self._finalize_device_fit(
                instr, kernel, theta, pending, x,
                make_targets_fn(latent_y), latent_data,
            )
            self._report_multistart_nlls(instr, fetched)
        instr.log_success()
        model = GaussianProcessClassificationModel(raw)
        model.instr = instr
        return model

    def fit_distributed(
        self, data, active_set: Optional[np.ndarray] = None
    ) -> "GaussianProcessClassificationModel":
        """Multi-host classifier fit from a pre-sharded expert stack.

        The classifier counterpart of
        :meth:`GaussianProcessRegression.fit_distributed`, closing the
        asymmetry the reference never had (its train skeleton is shared,
        GaussianProcessCommons.scala:15-115 / GPClf.scala:48-66): ``data``
        is a globally-sharded ``ExpertData`` of {0,1} labels
        (:func:`...distributed.distribute_global_experts`); the sharded
        Laplace + L-BFGS loop keeps the latent stacks device-resident, and
        the active-set provider selects over the *latent* targets from the
        sharded stack (``ActiveSetProvider.from_stack``) — GPClf.scala:62-65
        substitutes f for y before produceModel, so providers must see f.
        """
        def prepare(instr, active64, data):
            # Label-domain check on the sharded stack (GPClf.scala:68-72):
            # one reduction on device, no host gather of the labels.
            if not bool(_labels_are_01(data.y, data.mask)):
                raise ValueError("Only 0 and 1 labels are supported.")

            cache = self._gram_cache(instr, data)

            def fit_once(kernel, instr_r):
                raw = self._fit_from_stack(
                    instr_r, kernel, data, None, None, active64, cache=cache
                )
                instr_r.log_success()
                model = GaussianProcessClassificationModel(raw)
                model.instr = instr_r
                return model

            return fit_once

        return self._run_fit_distributed(
            type(self).__name__, data, active_set, prepare
        )

    def _fit_from_stack(
        self, instr, kernel, data, x, make_targets_fn, active_override=None,
        cache=None,
    ) -> ProjectedProcessRawPredictor:
        """Shared optimize → settle latents → active set → PPA tail of
        ``fit`` and ``fit_distributed``.  ``make_targets_fn(latent_y)`` must
        return a zero-arg callable producing the provider's flat targets
        (deferred: fetching latents is a device sync the random/kmeans
        providers never need).  ``cache`` is the per-fit theta-invariant
        gram cache (common._gram_cache)."""
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            return self._fit_from_stack_profiled(
                instr, kernel, data, x, make_targets_fn, active_override,
                cache,
            )

    def _fit_from_stack_profiled(
        self, instr, kernel, data, x, make_targets_fn, active_override=None,
        cache=None,
    ) -> ProjectedProcessRawPredictor:
        if self._resolved_optimizer() == "device":
            # Fully async pipeline: on-device Laplace + L-BFGS, the latent
            # modes stay on device as the PPA targets, and the host syncs
            # exactly once inside _finalize_device_fit.
            theta_dev, f_final, pending = self._fit_device(
                instr, kernel, data, cache
            )
            latent_y = f_final * data.mask
            latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)
            raw, _ = self._finalize_device_fit(
                instr, kernel, theta_dev, pending, x,
                None if make_targets_fn is None else make_targets_fn(latent_y),
                latent_data,
                active_override=active_override,
            )
        else:
            # ladder host_f64 rung: f64 stack, cache dropped (no-op on
            # every other path — common._host_f64_operands gates itself)
            data, _, cache = self._host_f64_operands(data, cache=cache)
            if self._mesh is not None:
                objective = make_sharded_laplace_objective(
                    kernel, data, self._tol, self._mesh, cache
                )
            else:
                objective = make_laplace_objective(
                    kernel, data, self._tol, cache
                )

            theta_opt, f_final = self._optimize_latent_host(
                instr, kernel, objective, jnp.zeros_like(data.y)
            )

            latent_y = f_final * data.mask
            latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)
            raw = self._projected_process(
                instr, kernel, theta_opt, x,
                # a callable: resolved only if the provider reads targets
                None if make_targets_fn is None
                else make_targets_fn(latent_y),
                latent_data,
                active_override=active_override,
            )
        return raw

    def _fit_device(self, instr: Instrumentation, kernel, data, cache=None):
        """Dispatch the one-program on-device Laplace optimization without
        blocking: returns device (theta, latent modes) plus pending scalars."""
        from spark_gp_tpu.models.laplace import (
            fit_gpc_device,
            fit_gpc_device_sharded,
        )

        dtype = data.x.dtype
        theta0 = jnp.asarray(kernel.init_theta(), dtype=dtype)
        lower, upper = kernel.bounds()
        lower = jnp.asarray(lower, dtype=dtype)
        upper = jnp.asarray(upper, dtype=dtype)
        max_iter = jnp.asarray(self._max_iter, dtype=jnp.int32)

        log_space = self._use_log_space(kernel)
        instr.log_info("Optimising the kernel hyperparameters (on-device)")
        from spark_gp_tpu.resilience import chaos

        # chaos choke point for staged execution faults (fallback ladder)
        # + the memory-budget allocator model (memplan/chaos)
        chaos.maybe_injected_failure(
            self._device_fit_op(), nbytes=self._dispatch_raw_bytes(data)
        )
        with instr.phase("optimize_hypers"):
            if self._checkpoint_dir is not None or self._fallback_segmented():
                from spark_gp_tpu.models.laplace import (
                    fit_gpc_device_checkpointed,
                )

                saver, chunk = self._segment_saver_and_chunk("gpc", data)
                theta, f_final, f, n_iter, n_fev, stalled = (
                    fit_gpc_device_checkpointed(
                        kernel, float(self._tol), self._mesh, log_space,
                        theta0, lower, upper, data, self._max_iter,
                        chunk, saver, cache,
                    )
                )
            elif self._mesh is not None:
                theta, f_final, f, n_iter, n_fev, stalled = (
                    fit_gpc_device_sharded(
                        kernel, float(self._tol), self._mesh, log_space,
                        theta0, lower, upper, data.x, data.y, data.mask,
                        max_iter, cache,
                        solver=it_ops.solver_jit_key(),
                    )
                )
            else:
                from spark_gp_tpu.obs import cost as obs_cost

                # measured cost of the one-dispatch program (obs/cost.py)
                theta, f_final, f, n_iter, n_fev, stalled = (
                    obs_cost.observed_call(
                        "fit.device", fit_gpc_device,
                        kernel, float(self._tol), log_space, theta0, lower,
                        upper, data.x, data.y, data.mask, max_iter, cache,
                        solver=it_ops.solver_jit_key(),
                    )
                )
            phase_sync(theta, f)
        pending = {
            "lbfgs_iters": n_iter,
            "lbfgs_nfev": n_fev,
            "final_nll": f,
            "lbfgs_stalled": stalled,
        }
        return theta, f_final, pending


class GaussianProcessClassificationModel:
    """Sigmoid link on the PPA latent mean (GPClf.scala:137-162)."""

    num_classes = 2

    def __init__(self, raw_predictor: ProjectedProcessRawPredictor):
        self.raw_predictor = raw_predictor
        self.instr: Optional[Instrumentation] = None
        self._integrator = None

    def predict_raw(self, x_test: np.ndarray) -> np.ndarray:
        """``[t, 2]`` of (-f, f) — GPClf.scala:153-156."""
        f = np.asarray(self.raw_predictor.predict_mean(np.asarray(x_test)))
        return np.stack([-f, f], axis=1)

    def predict_proba(self, x_test: np.ndarray, averaged: bool = False) -> np.ndarray:
        """``[t, 2]`` class probabilities.

        ``averaged=False`` (default) applies the sigmoid to the MAP latent,
        matching the reference (GPClf.scala:141-149).  ``averaged=True``
        computes E[sigmoid(f)] under the latent Gaussian via 32-point
        Gauss–Hermite quadrature using the predictive variance the reference
        discards.
        """
        if not averaged:
            # MAP path discards the variance — skip its O(t m^2) einsum
            f = self.raw_predictor.predict_mean(np.asarray(x_test))
            p1 = 1.0 / (1.0 + np.exp(-np.asarray(f)))
            return np.stack([1.0 - p1, p1], axis=1)
        f, var = self.raw_predictor(np.asarray(x_test))
        if var is None:
            raise ValueError(
                "model was fitted with setPredictiveVariance(False); "
                "averaged probabilities need the latent variance — use "
                "averaged=False or refit with variances enabled"
            )
        from spark_gp_tpu.ops.integrator import Integrator

        if self._integrator is None:
            self._integrator = Integrator(32)
        import jax.nn

        p1 = np.asarray(
            self._integrator.expected_of_function_of_normal(
                f, jnp.maximum(jnp.asarray(var), 0.0), jax.nn.sigmoid
            )
        )
        return np.stack([1.0 - p1, p1], axis=1)

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        """Class labels {0, 1} from the MAP latent sign."""
        f = self.raw_predictor.predict_mean(np.asarray(x_test))
        return (np.asarray(f) > 0.0).astype(np.float64)

    def save(self, path: str) -> None:
        from spark_gp_tpu.utils.serialization import save_model

        save_model(path, self, kind="classification")

    @staticmethod
    def load(path: str) -> "GaussianProcessClassificationModel":
        from spark_gp_tpu.utils.serialization import load_model

        model = load_model(path)
        if not isinstance(model, GaussianProcessClassificationModel):
            raise TypeError("not a classification model checkpoint")
        return model
