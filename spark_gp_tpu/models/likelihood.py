"""BCM (product-of-experts) marginal likelihood for GP regression.

Semantics of GaussianProcessRegression.likelihoodAndGradient
(GPR.scala:55-68): per expert, with noise-augmented kernel K,

    NLL_e  = 1/2 y^T K^-1 y + 1/2 log|K|          (constant term dropped,
                                                   as in the reference)

and the BCM objective is the sum over experts
(GaussianProcessCommons.scala:73-78).  Differences by design:

* one Cholesky replaces the LU + dgetri of util/logDetAndInv.scala — alpha
  comes from triangular solves, never an explicit inverse;
* the gradient is ``jax.value_and_grad`` through the Cholesky, replacing the
  hand-derived trace formula (GPR.scala:63-67) *and* the memoization cache
  (util/DiffFunctionMemoized.scala) — value and gradient are one fused XLA
  program, so a line-search re-evaluation costs one call, not two cluster
  round-trips;
* experts are a vmapped leading axis; across chips the sum is a ``psum``
  over ICI inside ``shard_map`` (see :func:`make_sharded_value_and_grad`),
  replacing Spark ``treeAggregate``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_gp_tpu.kernels.base import (
    Kernel,
    masked_gram_stack,
    supports_matfree,
)
from spark_gp_tpu.obs import cost as obs_cost
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.ops.linalg import chol_logdet, chol_solve, cholesky
from spark_gp_tpu.ops.precision import active_lane, precision_lane_scope
from spark_gp_tpu.optimize.lbfgs_device import lbfgs_state_donation
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.parallel.mesh import (
    EXPERT_AXIS,
    sharded_cache_operand,
    sharded_weights_operand,
)

# Every jitted fit entry point below carries the resolved precision lane
# (ops/precision.py) as a STATIC argument and re-pins it with
# precision_lane_scope during its trace: the lane is thereby part of the
# jit cache key, so set_precision_lane / GP_PRECISION_LANE switches
# between fits compile fresh executables instead of silently reusing the
# old lane's programs.  Public wrappers resolve lane=None to the ambient
# lane at CALL time.  The SOLVER lane (ops/iterative.py: exact batched
# Cholesky vs the CG/Lanczos lane) rides the same contract as a second
# static argument, so GP_SOLVER_LANE / set_solver_lane switches between
# fits recompile too.


def masked_matfree_operator(kernel: Kernel, theta, x, mask, jitter=None):
    """The masked + jittered NLL operator as INJECTED closures — the
    matfree lane's stand-in for ``masked_gram_stack`` + the jitter add.

    With M the 0/1 mask, the materialized operator is
    ``M K M^T + diag(1 - m) + c I`` (``ops/linalg.masked_kernel_matrix``
    plus the trace-relative jitter ridge ``c = boost * trace/s``), so
    its matvec is ``m ⊙ K(m ⊙ v) + (1 - m) ⊙ v + c v`` — the raw
    kernel matvec streams (``kernels/base.py`` matvec protocol), masking
    and jitter are O(s) elementwise dressing applied here, ONCE, for
    every consumer (CG loop, value legs, post-fit report).

    Returns ``(matvec, matvec_sg, diag_sg, col_fn_sg)`` for
    :func:`ops.iterative.inv_quad_logdet_matfree`: ``matvec`` is
    differentiable in ``theta`` (checkpointed streaming path),
    ``matvec_sg`` the stop-gradient twin the CG loop runs on (free to
    take the fused Pallas path), ``diag_sg`` the ``[E, s]`` masked +
    jittered diagonal and ``col_fn_sg(piv)`` the pivot-column oracle
    feeding the streamed pivoted-Cholesky preconditioner.  The column
    comes from ``kernel.cross`` against the single pivot row with its
    own diagonal entry pinned from ``diag_sg`` — correct even for
    kernels whose ``cross`` carries no diagonal term (the EyeKernel
    ridge's cross is identically zero)."""
    s = x.shape[-2]
    mcache = jax.vmap(kernel.prepare_matvec)(x)
    diag_k = jax.vmap(lambda xe: kernel.diag(theta, xe))(x)  # [E, s]
    mdiag = mask * diag_k + (1.0 - mask)
    if jitter is not None:
        trace = jnp.sum(mdiag, axis=-1)
        scale = jnp.where(jnp.isfinite(trace) & (trace > 0), trace / s, 1.0)
        boost = jnp.broadcast_to(jnp.asarray(jitter, x.dtype), trace.shape)
        c = boost * scale  # [E], differentiable through trace like the
        # materialized path's jnp.trace(kmat)
    else:
        c = jnp.zeros(mask.shape[:-1], dtype=x.dtype)
    diag_total = mdiag + c[..., None]

    theta_sg = jax.lax.stop_gradient(theta)
    mcache_sg = jax.lax.stop_gradient(mcache)
    mask_sg = jax.lax.stop_gradient(mask)
    c_sg = jax.lax.stop_gradient(c)
    diag_sg = jax.lax.stop_gradient(diag_total)
    x_sg = jax.lax.stop_gradient(x)

    def _apply(th, mc, msk, cj, v, **kw):
        mv = msk[..., None] * kernel.matvec_from_prepared(
            th, mc, msk[..., None] * v, **kw
        )
        return mv + ((1.0 - msk) + cj[..., None])[..., None] * v

    def matvec(v):
        return _apply(theta, mcache, mask, c, v, differentiable=True)

    def matvec_sg(v):
        return _apply(theta_sg, mcache_sg, mask_sg, c_sg, v)

    iota = jnp.arange(s)

    def col_fn_sg(piv):
        x_piv = jnp.take_along_axis(
            x_sg, piv[..., None, None], axis=-2
        )  # [E, 1, p]
        kcol = jax.vmap(
            lambda xp, xe: kernel.cross(theta_sg, xp, xe)
        )(x_piv, x_sg)[..., 0, :]  # K[piv, :] = K[:, piv]  [E, s]
        m_piv = jnp.take_along_axis(mask_sg, piv[..., None], axis=-1)
        d_piv = jnp.take_along_axis(diag_sg, piv[..., None], axis=-1)
        col = mask_sg * kcol * m_piv
        return jnp.where(iota == piv[..., None], d_piv, col)

    return matvec, matvec_sg, diag_sg, col_fn_sg


def batched_nll(kernel: Kernel, theta, data: ExpertData, jitter=None,
                weights=None, cache=None):
    """Sum of per-expert NLLs over the local ``[E, s, ...]`` stack.

    On TPU the factor/solve/invert chain for the whole Gram stack runs as
    ONE batched Pallas pass (``ops.pallas_linalg.spd_inv_logdet``) — XLA's
    per-matrix Cholesky lowering leaves the TPU ~10x underutilized at
    s ~ 100, and the kernel's explicit inverse also makes the backward pass
    two batched matmuls instead of batched triangular solves
    (dNLL/dK = 0.5*(K^-1 - alpha alpha^T), GPR.scala:63-67).

    Elsewhere (CPU tests, f64, s > 512) the classic formulation — one
    Cholesky, one vector solve, logdet from the diagonal — is cheaper than
    materializing inverses, so the two paths split here rather than inside
    ``spd_inv_logdet``.

    ``jitter`` (scalar or per-expert [E], trace-relative) is the adaptive
    escalation operand (``resilience/quarantine.py``): a *traced* value,
    so recovery retries reuse the compiled program, and the default
    ``None`` path — the clean hot loop — carries zero extra work.

    ``weights`` ([E], traced) is the expert aggregation plane's
    per-expert weight operand (``models/aggregation.py``): the objective
    becomes ``sum_e w_e NLL_e`` — ONE weighted sum shared by resilience
    (a quarantined expert's inert identity block contributes NLL_e = 0,
    so its w_e is irrelevant and masking IS w_e = 0) and fit-time
    selection (``downweight`` mode's fractional w_e).  ``None`` — every
    clean fit and the ``GP_AGG_POLICY=poe`` kill switch — keeps today's
    unweighted reduction bit-for-bit (a Python-level branch: the
    unweighted program is a distinct, unchanged trace).

    ``cache`` (a :func:`kernels.base.prepare_gram_cache` pytree, traced)
    is the theta-invariant precompute plane: when present, the Gram stack
    is rebuilt per evaluation from the cached distance structure
    (elementwise theta-map only — no MXU distance contraction, nothing
    for autodiff to traverse there), and the fit drivers build it once
    per fit.  ``None`` keeps the recompute path bit-for-bit.
    """
    from spark_gp_tpu.ops.pallas_linalg import _use_pallas, spd_inv_logdet

    resolved = it_ops.resolve_solver(
        data.x.shape[-2],
        num_experts=int(data.x.shape[0]),
        n_features=int(data.x.shape[-1]),
        itemsize=int(jnp.dtype(data.x.dtype).itemsize),
    )
    if resolved == "matfree" and supports_matfree(kernel):
        # the matrix-free lane (ops/pallas_matvec.py): the [E, s, s] gram
        # stack is NEVER materialized — this branch runs BEFORE
        # masked_gram_stack, CG matvecs stream row tiles of the distance
        # computation + kernel transform, and the preconditioner builds
        # from streamed pivot columns.  Masking and the trace-relative
        # jitter live in the injected operator (masked_matfree_operator),
        # so quarantine escalation rides this lane too.  The theta-
        # invariant gram cache is irrelevant here by design: that cache
        # IS the O(s^2) distance block this lane refuses to build.
        # Kernels without matvec capability fall through to the
        # materialized iterative path below, bit-for-bit.
        matvec, matvec_sg, diag_sg, col_fn_sg = masked_matfree_operator(
            kernel, theta, data.x, data.mask, jitter
        )
        ym = data.y * data.mask
        quad, logdet = it_ops.inv_quad_logdet_matfree(
            matvec, matvec_sg, diag_sg, col_fn_sg, ym
        )
        if weights is None:
            return 0.5 * jnp.sum(quad) + 0.5 * jnp.sum(logdet)
        w = jnp.asarray(weights, data.x.dtype)
        return 0.5 * jnp.sum(w * quad) + 0.5 * jnp.sum(w * logdet)

    kmat = masked_gram_stack(kernel, theta, data.x, data.mask, cache)
    if jitter is not None:
        s = kmat.shape[-1]
        trace = jnp.trace(kmat, axis1=-2, axis2=-1)
        scale = jnp.where(jnp.isfinite(trace) & (trace > 0), trace / s, 1.0)
        boost = jnp.broadcast_to(jnp.asarray(jitter, kmat.dtype), trace.shape)
        kmat = kmat + (boost * scale)[..., None, None] * jnp.eye(
            s, dtype=kmat.dtype
        )
    ym = data.y * data.mask
    if resolved in ("iterative", "matfree"):
        # the iterative solver lane (ops/iterative.py): one multi-RHS
        # preconditioned-CG stream replaces the batched factorization —
        # O(t s^2) matmul work instead of O(s^3), selected by
        # GP_SOLVER_LANE / setSolverLane (auto: s past the threshold).
        # The jittered, cache-fed kmat above is shared verbatim, so
        # jitter escalation and the gram cache ride both lanes.
        quad, logdet = it_ops.inv_quad_logdet(kmat, ym)
        if weights is None:
            return 0.5 * jnp.sum(quad) + 0.5 * jnp.sum(logdet)
        w = jnp.asarray(weights, kmat.dtype)
        return 0.5 * jnp.sum(w * quad) + 0.5 * jnp.sum(w * logdet)
    if _use_pallas(kmat):
        kinv, logdet = spd_inv_logdet(kmat)
        alpha = jnp.einsum("eij,ej->ei", kinv, ym)
        if weights is None:
            return 0.5 * jnp.einsum("ei,ei->", ym, alpha) + 0.5 * jnp.sum(
                logdet
            )
        w = jnp.asarray(weights, kmat.dtype)
        return 0.5 * jnp.einsum("ei,ei,e->", ym, alpha, w) + 0.5 * jnp.sum(
            w * logdet
        )
    chol_l = cholesky(kmat)
    alpha = chol_solve(chol_l, ym)
    if weights is None:
        return 0.5 * jnp.einsum("ei,ei->", ym, alpha) + 0.5 * jnp.sum(
            chol_logdet(chol_l)
        )
    w = jnp.asarray(weights, kmat.dtype)
    return 0.5 * jnp.einsum("ei,ei,e->", ym, alpha, w) + 0.5 * jnp.sum(
        w * chol_logdet(chol_l)
    )


def objective_supports_shard_map(objective: str) -> bool:
    """ONE home for the dispatch invariant: per-expert-sum objectives
    (psum of local scalars) ride the hand-written shard_map paths; the
    ELBO — a nonlinear function of global sums — rides jit/GSPMD instead
    (models/sgpr.py distribution note).  Consulted by every sharded entry
    point and by the estimator's mesh dispatch."""
    return objective != "elbo"


def _require_shard_map_support(objective: str) -> None:
    if not objective_supports_shard_map(objective):
        raise ValueError(
            f"the {objective!r} objective rides jit/GSPMD over sharded "
            "arrays, not the shard_map paths (models/sgpr.py "
            "distribution note)"
        )


def objective_fn(objective: str):
    """The per-expert-stack objective ``setObjective`` selects: the BCM
    marginal NLL (default, the reference's objective), the negative LOO
    log pseudo-likelihood (R&W eq. 5.13, ``models/loo.py``), or the
    negative Titsias collapsed ELBO (``models/sgpr.py``).  Uniform
    signature ``(kernel, theta, data, *extra, cache=None) -> scalar`` —
    ``extra`` is empty for the first two and ``(active, sigma2)`` for the
    ELBO — so every fit entry point swaps them via one static argument
    plus one traced operand tuple.  ``cache`` is the theta-invariant gram
    cache (``kernels/base.py``); the ELBO ignores it (its gram work is
    dominated by cross-kernel terms against the inducing set, which the
    self-distance cache does not cover)."""
    if objective == "marginal":
        # extra, when present, is (jitter,) — the resilience layer's
        # escalation operand — or (jitter, weights) when the aggregation
        # plane's per-expert weights ride along (jitter None when only
        # weights engaged; None is a valid empty-pytree operand) — absent
        # on every clean fit
        return lambda kernel, theta, data, *extra, cache=None: batched_nll(
            kernel, theta, data, *extra, cache=cache
        )
    if objective == "loo":
        from spark_gp_tpu.models.loo import batched_loo_nll

        return lambda kernel, theta, data, *extra, cache=None: (
            batched_loo_nll(kernel, theta, data, cache=cache)
        )
    if objective == "elbo":
        from spark_gp_tpu.models.sgpr import batched_elbo_nll

        return lambda kernel, theta, data, *extra, cache=None: (
            batched_elbo_nll(kernel, theta, data, *extra)
        )
    raise ValueError(
        f"unknown objective {objective!r}; "
        "expected 'marginal', 'loo' or 'elbo'"
    )


@partial(
    jax.jit, static_argnums=0, static_argnames=("objective", "lane", "solver")
)
def _vag_impl(
    kernel: Kernel, theta, x, y, mask, extra=(), cache=None, *,
    objective="marginal", lane=None, solver=None,
):
    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        obj = objective_fn(objective)
        return jax.value_and_grad(
            lambda t: obj(kernel, t, data, *extra, cache=cache)
        )(theta)


def make_value_and_grad(
    kernel: Kernel, data: ExpertData, objective: str = "marginal", extra=(),
    cache=None,
):
    """Single-device jitted ``theta -> (nll, grad)``.

    The kernel spec is a static (hashable) argument of a module-level jit, so
    the compiled executable is reused across estimator instances and fits —
    this matters on runtimes with high per-dispatch/retrace latency.

    ``cache`` is the per-expert theta-invariant gram cache
    (:func:`kernels.base.prepare_gram_cache`) — a traced operand that
    stays resident on device across the host optimizer's evaluations, so
    each of the ~40+ dispatches per fit skips the distance contraction.
    ``None`` (unsupported kernel / plane disabled) traces the exact
    pre-cache program.
    """

    def vag(theta):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        # measured flops/bytes per evaluation (obs/cost.py, GP_XLA_COST):
        # signature-cached, so the host optimizer's ~40 calls pay one
        # lowering and the counters accumulate true executed totals
        return obs_cost.observed_call(
            "fit.host_objective", _vag_impl,
            kernel, theta, data.x, data.y, data.mask, extra, cache,
            objective=objective, lane=active_lane(),
            solver=it_ops.solver_jit_key(),
        )

    return vag


@partial(jax.jit, static_argnums=0, static_argnames=("lane", "solver"))
def guard_probe_value_and_grad(
    kernel: Kernel, theta, x, y, mask, *, lane, solver=None
):
    """(NLL, grad) of one probe expert stack at an EXPLICIT lane — the
    fit-time mixed_precision_guard's objective probe (models/common.py).
    ``lane`` is static, so the strict and non-strict evaluations compile
    as separate executables and can be compared within one process.
    ``solver`` pins the solver lane the fit actually ran (ops/iterative)
    so the guard compares the very programs the fit dispatched.

    Probes the path the fit ACTUALLY runs: when the kernel carries a
    theta-invariant cache, the probe builds it (inside this program, under
    the probed lane — so the lane's compensated cache build is part of
    what the guard compares) and evaluates the cached objective."""
    from spark_gp_tpu.kernels.base import supports_gram_cache

    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        cache = (
            jax.vmap(kernel.prepare)(x) if supports_gram_cache(kernel)
            else None
        )
        return jax.value_and_grad(
            lambda t: batched_nll(kernel, t, data, cache=cache)
        )(theta)


def _make_sharded_vag(
    kernel: Kernel, mesh, objective: str = "marginal", cache_specs=(),
    cache_of=lambda maybe_cache: None, weight_specs=(),
    weight_of=lambda maybe_w: None,
):
    """shard_map'd ``(theta, x, y, mask[, cache][, weights]) ->
    (nll, grad)`` core, reusable inside larger jitted programs (the
    one-dispatch fits, the segmented checkpointing loop).
    ``(cache_specs, cache_of)`` come from
    :func:`parallel.mesh.sharded_cache_operand` and ``(weight_specs,
    weight_of)`` from :func:`parallel.mesh.sharded_weights_operand` —
    the two homes of the optional expert-sharded operand conventions.
    The weights shard exactly like the stack, so each device's local
    weighted partial sum psums to the global ``sum_e w_e NLL_e``."""
    _require_shard_map_support(objective)

    n_cache = len(tuple(cache_specs))
    in_specs = (
        P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS)
    ) + tuple(cache_specs) + tuple(weight_specs)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P()),
    )
    def sharded(theta_, x_, y_, mask_, *trailing):
        local = ExpertData(x=x_, y=y_, mask=mask_)
        cache = cache_of(trailing[:n_cache])
        weights = weight_of(trailing[n_cache:])
        obj = objective_fn(objective)
        # the marginal objective's positional extras are (jitter, weights)
        # — jitter cannot ride the sharded signature (quarantine docs), so
        # its slot pins to None when only weights are aboard.  The fit
        # drivers engage weights for the marginal objective only.
        obj_extra = (
            (None, weights)
            if weights is not None and objective == "marginal" else ()
        )
        value, grad = jax.value_and_grad(
            lambda t: obj(kernel, t, local, *obj_extra, cache=cache)
        )(theta_)
        # theta is replicated (P()): shard_map's transpose already inserts
        # the cross-device psum for its gradient, so only the value needs an
        # explicit all-reduce here (psum-ing grad too would multiply it by
        # the device count).  EXCEPT under the old-jax compat wrapper
        # (check_rep disabled — utils/compat.py): no replication machinery
        # runs, the local gradient would leak through the P() out_spec
        # unsummed, and the all-reduce must be explicit.
        from spark_gp_tpu.utils.compat import (
            shard_map_needs_explicit_grad_psum,
        )

        if shard_map_needs_explicit_grad_psum():
            grad = jax.lax.psum(grad, EXPERT_AXIS)
        return jax.lax.psum(value, EXPERT_AXIS), grad

    return sharded


@partial(
    jax.jit, static_argnums=(0, 1),
    static_argnames=("objective", "lane", "solver"),
)
def _sharded_vag_impl(
    kernel: Kernel, mesh, theta, x, y, mask, cache=None, weights=None, *,
    objective="marginal", lane=None, solver=None,
):
    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        weight_specs, weight_args, weight_of = sharded_weights_operand(
            weights
        )
        core = _make_sharded_vag(
            kernel, mesh, objective, cache_specs, cache_of, weight_specs,
            weight_of,
        )
        return core(theta, x, y, mask, *cache_args, *weight_args)


def make_sharded_value_and_grad(
    kernel: Kernel, data: ExpertData, mesh, objective: str = "marginal",
    cache=None, weights=None,
):
    """Multi-chip ``theta -> (nll, grad)`` via ``shard_map`` + ``psum``.

    ``theta`` is replicated; the expert stack is sharded on its leading axis;
    each device reduces its local experts and one ``psum`` over ICI yields the
    replicated global (scalar, gradient) — the exact communication pattern of
    the reference's ``treeAggregate`` of ``(Double, BDV)``
    (GaussianProcessCommons.scala:73-78), minus the driver round-trip.
    ``cache`` (expert-sharded like the stack) rides into the local programs
    so each evaluation skips the distance contraction.  ``weights``
    ([E], expert-sharded) turns the psum'd objective into the
    aggregation plane's ``sum_e w_e NLL_e`` (``models/aggregation.py``);
    ``None`` keeps today's unweighted reduction bit-for-bit.
    """

    def vag(theta):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        return obs_cost.observed_call(
            "fit.sharded_objective", _sharded_vag_impl,
            kernel, mesh, theta, data.x, data.y, data.mask, cache, weights,
            objective=objective, lane=active_lane(),
            solver=it_ops.solver_jit_key(),
        )

    return vag


# --- fully on-device fits: the entire L-BFGS loop is ONE dispatch ---------


@partial(
    jax.jit, static_argnums=(0, 1),
    static_argnames=("objective", "lane", "solver"),
)
def _fit_gpr_device_impl(
    kernel: Kernel, log_space, theta0, lower, upper, x, y, mask, max_iter,
    tol, extra=(), cache=None, *, objective="marginal", lane=None,
    solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )

    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        obj = objective_fn(objective)

        def vag(theta, aux):
            value, grad = jax.value_and_grad(
                lambda t: obj(kernel, t, data, *extra, cache=cache)
            )(theta)
            return value, grad, aux

        if log_space:
            vag, theta0, lower, upper, from_u = log_reparam(
                vag, theta0, lower, upper
            )
        else:
            from_u = lambda t: t

        theta, f, _, n_iter, n_fev, stalled = lbfgs_minimize_device(
            vag, theta0, lower, upper, jnp.zeros(()), max_iter=max_iter,
            tol=tol,
        )
        return from_u(theta), f, n_iter, n_fev, stalled


def fit_gpr_device(
    kernel: Kernel, log_space, theta0, lower, upper, x, y, mask, max_iter,
    tol, extra=(), cache=None, *, objective="marginal", lane=None,
    solver=None,
):
    """Single-chip on-device fit: objective + projected L-BFGS in one XLA
    program.  Returns (theta_opt, final_nll, n_iter, n_fev, stalled).
    ``lane=None`` / ``solver=None`` resolve the ambient precision/solver
    lanes at call time into the jit key (module note above).  ``cache``
    (the theta-invariant gram cache) enters the program as a constant
    operand OUTSIDE the L-BFGS while_loop, so every iteration's
    evaluation reuses it."""
    # measured cost of the whole one-dispatch program (the while body is
    # counted once by XLA's cost model — per-dispatch semantics, like the
    # compile counters)
    return obs_cost.observed_call(
        "fit.device", _fit_gpr_device_impl,
        kernel, log_space, theta0, lower, upper, x, y, mask, max_iter, tol,
        extra, cache, objective=objective,
        lane=active_lane() if lane is None else lane,
        solver=it_ops.solver_jit_key() if solver is None else solver,
    )


@partial(
    jax.jit, static_argnums=(0, 1),
    static_argnames=("objective", "lane", "solver"),
)
def _fit_gpr_device_multistart_impl(
    kernel: Kernel, log_space, theta0_batch, lower, upper, x, y, mask,
    max_iter, tol, extra=(), cache=None, *, objective="marginal", lane=None,
    solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import multistart_minimize

    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        obj = objective_fn(objective)

        # the cache is closed over, NOT vmapped: the R restart lanes map
        # over theta only, so one cache broadcasts to every lane
        def vag(theta, aux):
            value, grad = jax.value_and_grad(
                lambda t: obj(kernel, t, data, *extra, cache=cache)
            )(theta)
            return value, grad, aux

        theta, _, f, n_iter, n_fev, stalled, f_all, best = (
            multistart_minimize(
                vag, log_space, theta0_batch, lower, upper, jnp.zeros(()),
                max_iter, tol,
            )
        )
        return theta, f, n_iter, n_fev, stalled, f_all, best


def fit_gpr_device_multistart(
    kernel: Kernel, log_space, theta0_batch, lower, upper, x, y, mask,
    max_iter, tol, extra=(), cache=None, *, objective="marginal", lane=None,
):
    """Multi-start single-chip fit: the R restarts run as ONE vmapped
    on-device L-BFGS program (optimize/lbfgs_device.py multistart docs) and
    only the winning iterate is returned — the PPA model is then built
    once, for the winner.  ONE gram cache is shared (broadcast) across all
    R lanes — the cache is theta-invariant, so per-lane copies would be
    pure waste.  Returns ``(theta_best, f_best, n_iter, n_fev,
    stalled, f_all [R], best)``."""
    return _fit_gpr_device_multistart_impl(
        kernel, log_space, theta0_batch, lower, upper, x, y, mask,
        max_iter, tol, extra, cache, objective=objective,
        lane=active_lane() if lane is None else lane,
        solver=it_ops.solver_jit_key(),
    )


# --- segmented device fit: checkpoint/resume for long runs ----------------


def _gpr_segment_vag(
    kernel: Kernel, mesh, log_space, data: ExpertData, objective="marginal",
    extra=(), cache=None,
):
    """The (possibly sharded, possibly log-space) objective used by the
    segmented fit — identical math to the one-dispatch fits above.  The
    ELBO rides jit/GSPMD rather than shard_map (see models/sgpr.py), so
    its mesh variant is the mesh=None build over sharded arrays."""
    from spark_gp_tpu.optimize.lbfgs_device import log_transform_vag

    if mesh is None or objective == "elbo":
        obj = objective_fn(objective)

        def base(theta, aux):
            value, grad = jax.value_and_grad(
                lambda t: obj(kernel, t, data, *extra, cache=cache)
            )(theta)
            return value, grad, aux

    else:
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        # the sharded signature cannot carry the jitter extra (quarantine
        # docs) but DOES carry the aggregation plane's weights — slot 1 of
        # the marginal extras convention (jitter, weights)
        weights = extra[1] if len(extra) > 1 else None
        weight_specs, weight_args, weight_of = sharded_weights_operand(
            weights
        )
        core = _make_sharded_vag(
            kernel, mesh, objective, cache_specs, cache_of, weight_specs,
            weight_of,
        )

        def base(theta, aux):
            value, grad = core(
                theta, data.x, data.y, data.mask, *cache_args, *weight_args
            )
            return value, grad, aux

    return log_transform_vag(base) if log_space else base


@partial(
    jax.jit, static_argnums=(0, 1, 2),
    static_argnames=("objective", "lane", "solver"),
)
def gpr_device_segment_init(
    kernel: Kernel, mesh, log_space, theta0, lower, upper, x, y, mask,
    extra=(), cache=None, *, objective="marginal", lane=None, solver=None,
):
    """One objective evaluation -> the optimizer's carried state (the
    checkpoint unit)."""
    from spark_gp_tpu.optimize.lbfgs_device import lbfgs_init_state

    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        vag = _gpr_segment_vag(
            kernel, mesh, log_space, data, objective, extra, cache
        )
        t0 = jnp.log(theta0) if log_space else theta0
        return lbfgs_init_state(vag, t0, jnp.zeros((), theta0.dtype))


def _gpr_segment_run_impl(
    kernel: Kernel, mesh, log_space, state, lower, upper, x, y, mask,
    iter_limit, tol, extra=(), cache=None, *, objective="marginal", lane=None,
    solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_run_segment,
        log_transform_bounds,
    )

    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        data = ExpertData(x=x, y=y, mask=mask)
        vag = _gpr_segment_vag(
            kernel, mesh, log_space, data, objective, extra, cache
        )
        lo, hi = (
            log_transform_bounds(lower, upper) if log_space else (lower, upper)
        )
        return lbfgs_run_segment(vag, state, lo, hi, iter_limit, tol)


# The state carry (iterate + [m_hist, h] curvature history + aux) is
# consumed exactly once per segment and replaced by the returned state:
# donating it lets XLA write the new state into the old buffers instead
# of double-buffering the carry in HBM every chunk.  run_segmented
# (utils/checkpoint.py) persists the RETURNED state before the next
# dispatch, so the donated input is never read again.
gpr_device_segment_run = jax.jit(
    _gpr_segment_run_impl,
    static_argnums=(0, 1, 2),
    static_argnames=("objective", "lane", "solver"),
    donate_argnums=lbfgs_state_donation(3),
)


def fit_gpr_device_checkpointed(
    kernel: Kernel, mesh, log_space, theta0, lower, upper, data: ExpertData,
    max_iter: int, tol, chunk: int, saver, objective: str = "marginal",
    extra=(), cache=None,
):
    """On-device fit in K-iteration segments with state persistence.

    The single-program fits above have no host boundary to checkpoint at;
    this driver trades one host sync per ``chunk`` iterations for
    kill-and-resume durability: each segment is one dispatch of the same
    compiled program, and the full optimizer state (theta, gradient,
    curvature history, aux) round-trips through ``saver`` between segments.
    A valid prior checkpoint resumes the fit mid-run (same kernel/config,
    enforced via the saver's meta).  Returns (theta, nll, n_iter, n_fev).
    """
    from spark_gp_tpu.utils.checkpoint import run_segmented, segment_meta

    # the objective participates in the resume fingerprint: a checkpoint
    # from a marginal-NLL fit must never silently seed a LOO fit — and
    # for the ELBO the whole objective SURFACE (inducing set + sigma2)
    # must match, or a state optimal for a different bound resumes
    family = "gpr" if objective == "marginal" else f"gpr-{objective}"
    import numpy as np

    # a None slot (the unjittered (None, weights) extras of the
    # aggregation plane) fingerprints as an empty list — present in the
    # meta so slot positions stay distinguishable, nothing to hash
    extra_meta = {
        f"objective_extra_{i}": (
            [] if e is None else [float(v) for v in np.asarray(e).ravel()]
        )
        for i, e in enumerate(extra)
    }
    meta = segment_meta(
        family, kernel, tol, log_space, theta0, data.x, data.y, data.mask,
        **extra_meta,
    )
    lane = active_lane()
    solver = it_ops.solver_jit_key()

    def init(theta0_, lower_, upper_, x_, y_, mask_):
        return gpr_device_segment_init(
            kernel, mesh, log_space, theta0_, lower_, upper_, x_, y_, mask_,
            extra, cache, objective=objective, lane=lane, solver=solver,
        )

    tol_arr = jnp.asarray(tol, theta0.dtype)

    def run(state, limit):
        return gpr_device_segment_run(
            kernel, mesh, log_space, state, lower, upper,
            data.x, data.y, data.mask, limit, tol_arr, extra, cache,
            objective=objective, lane=lane, solver=solver,
        )

    theta, state = run_segmented(
        init, run, saver, meta,
        (theta0, lower, upper, data.x, data.y, data.mask),
        max_iter, chunk, log_space,
    )
    return theta, state.f, state.n_iter, state.n_fev, state.stalled


@partial(
    jax.jit, static_argnums=(0, 1, 2),
    static_argnames=("objective", "lane", "solver"),
)
def _fit_gpr_device_sharded_impl(
    kernel: Kernel, mesh, log_space, theta0, lower, upper, x, y, mask,
    max_iter, tol, cache=None, weights=None, *, objective="marginal",
    lane=None, solver=None,
):
    with precision_lane_scope(lane), it_ops.solver_lane_scope(solver):
        return _fit_gpr_device_sharded_body(
            kernel, mesh, log_space, theta0, lower, upper, x, y, mask,
            max_iter, tol, cache, objective, lane, solver, weights,
        )


def _fit_gpr_device_sharded_body(
    kernel, mesh, log_space, theta0, lower, upper, x, y, mask,
    max_iter, tol, cache, objective, lane, solver=None, weights=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )
    from spark_gp_tpu.utils.compat import whole_loop_shard_map_supported

    _require_shard_map_support(objective)

    if not whole_loop_shard_map_supported():
        # old-jax compat (utils/compat.py): the L-BFGS while_loop inside
        # shard_map wedges the compile; the plain jitted fit partitions
        # the same sharded stack via GSPMD instead (the weights ride as
        # the marginal extras' slot-1 operand)
        extra = () if weights is None else (None, weights)
        return fit_gpr_device(
            kernel, log_space, theta0, lower, upper, x, y, mask,
            max_iter, tol, extra, cache, objective=objective, lane=lane,
            solver=solver,
        )

    cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
    weight_specs, weight_args, weight_of = sharded_weights_operand(weights)
    n_cache = len(cache_specs)
    in_specs = (
        P(), P(), P(),
        P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
        P(), P(),
    ) + cache_specs + weight_specs

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P(), P(), P(), P()),
    )
    def run(theta0_, lower_, upper_, x_, y_, mask_, max_iter_, tol_,
            *trailing):
        local = ExpertData(x=x_, y=y_, mask=mask_)
        local_cache = cache_of(trailing[:n_cache])
        local_w = weight_of(trailing[n_cache:])
        obj = objective_fn(objective)
        obj_extra = (
            (None, local_w)
            if local_w is not None and objective == "marginal" else ()
        )

        def vag(theta, aux):
            value, grad = jax.value_and_grad(
                lambda t: obj(kernel, t, local, *obj_extra, cache=local_cache)
            )(theta)
            # value is the local shard's partial sum -> explicit psum;
            # grad w.r.t. replicated theta is already globally reduced by
            # shard_map's transpose rule.
            return jax.lax.psum(value, EXPERT_AXIS), grad, aux

        if log_space:
            vag, t0, lo, hi, from_u = log_reparam(vag, theta0_, lower_, upper_)
        else:
            vag, t0, lo, hi, from_u = vag, theta0_, lower_, upper_, (lambda t: t)

        theta, f, _, n_iter, n_fev, stalled = lbfgs_minimize_device(
            vag, t0, lo, hi, jnp.zeros(()), max_iter=max_iter_, tol=tol_,
        )
        return from_u(theta), f, n_iter, n_fev, stalled

    return run(
        theta0, lower, upper, x, y, mask, max_iter, tol,
        *cache_args, *weight_args,
    )


def fit_gpr_device_sharded(
    kernel: Kernel, mesh, log_space, theta0, lower, upper, x, y, mask,
    max_iter, tol, cache=None, *, objective="marginal", lane=None,
    solver=None, weights=None,
):
    """Multi-chip on-device fit: the WHOLE optimizer runs inside shard_map —
    per-iteration communication is exactly one psum of the scalar NLL plus
    the implicit gradient all-reduce, all over ICI, with zero host syncs.
    ``lane=None`` / ``solver=None`` resolve the ambient precision/solver
    lanes at call time into the jit key (module note above); ``cache``
    (expert-sharded) rides into each device's local program and is reused
    every iteration.  ``weights`` ([E], expert-sharded like the stack) is
    the aggregation plane's per-expert weight operand
    (``models/aggregation.py``) — ``None`` keeps today's reduction
    bit-for-bit."""
    return _fit_gpr_device_sharded_impl(
        kernel, mesh, log_space, theta0, lower, upper, x, y, mask,
        max_iter, tol, cache, weights, objective=objective,
        lane=active_lane() if lane is None else lane,
        solver=it_ops.solver_jit_key() if solver is None else solver,
    )
