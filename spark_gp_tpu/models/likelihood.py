"""BCM (product-of-experts) marginal likelihood for GP regression.

Semantics of GaussianProcessRegression.likelihoodAndGradient
(GPR.scala:55-68): per expert, with noise-augmented kernel K,

    NLL_e  = 1/2 y^T K^-1 y + 1/2 log|K|          (constant term dropped,
                                                   as in the reference)

and the BCM objective is the sum over experts
(GaussianProcessCommons.scala:73-78).  Differences by design:

* one Cholesky replaces the LU + dgetri of util/logDetAndInv.scala — alpha
  comes from triangular solves, never an explicit inverse;
* the gradient is ``jax.value_and_grad`` through the Cholesky, replacing the
  hand-derived trace formula (GPR.scala:63-67) *and* the memoization cache
  (util/DiffFunctionMemoized.scala) — value and gradient are one fused XLA
  program, so a line-search re-evaluation costs one call, not two cluster
  round-trips;
* experts are a vmapped leading axis; across chips the sum is a ``psum``
  over ICI inside ``shard_map`` (see :func:`make_sharded_value_and_grad`),
  replacing Spark ``treeAggregate``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.linalg import (
    chol_logdet,
    chol_solve,
    cholesky,
    masked_kernel_matrix,
)
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS


def expert_nll(kernel: Kernel, theta, x, y, mask):
    """NLL of a single (padded) expert: ``[s, p], [s], [s] -> scalar``."""
    kmat = masked_kernel_matrix(kernel.gram(theta, x), mask)
    chol_l = cholesky(kmat)
    ym = y * mask
    alpha = chol_solve(chol_l, ym)
    return 0.5 * jnp.dot(ym, alpha) + 0.5 * chol_logdet(chol_l)


def batched_nll(kernel: Kernel, theta, data: ExpertData):
    """Sum of per-expert NLLs over the local ``[E, s, ...]`` stack (vmap)."""
    per_expert = jax.vmap(expert_nll, in_axes=(None, None, 0, 0, 0))(
        kernel, theta, data.x, data.y, data.mask
    )
    return jnp.sum(per_expert)


@partial(jax.jit, static_argnums=0)
def _vag_impl(kernel: Kernel, theta, x, y, mask):
    data = ExpertData(x=x, y=y, mask=mask)
    return jax.value_and_grad(lambda t: batched_nll(kernel, t, data))(theta)


def make_value_and_grad(kernel: Kernel, data: ExpertData):
    """Single-device jitted ``theta -> (nll, grad)``.

    The kernel spec is a static (hashable) argument of a module-level jit, so
    the compiled executable is reused across estimator instances and fits —
    this matters on runtimes with high per-dispatch/retrace latency.
    """

    def vag(theta):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        return _vag_impl(kernel, theta, data.x, data.y, data.mask)

    return vag


@partial(jax.jit, static_argnums=(0, 1))
def _sharded_vag_impl(kernel: Kernel, mesh, theta, x, y, mask):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS)),
        out_specs=(P(), P()),
    )
    def sharded(theta_, x_, y_, mask_):
        local = ExpertData(x=x_, y=y_, mask=mask_)
        value, grad = jax.value_and_grad(
            lambda t: batched_nll(kernel, t, local)
        )(theta_)
        # theta is replicated (P()): shard_map's transpose already inserts
        # the cross-device psum for its gradient, so only the value needs an
        # explicit all-reduce here (psum-ing grad too would multiply it by
        # the device count).
        return jax.lax.psum(value, EXPERT_AXIS), grad

    return sharded(theta, x, y, mask)


def make_sharded_value_and_grad(kernel: Kernel, data: ExpertData, mesh):
    """Multi-chip ``theta -> (nll, grad)`` via ``shard_map`` + ``psum``.

    ``theta`` is replicated; the expert stack is sharded on its leading axis;
    each device reduces its local experts and one ``psum`` over ICI yields the
    replicated global (scalar, gradient) — the exact communication pattern of
    the reference's ``treeAggregate`` of ``(Double, BDV)``
    (GaussianProcessCommons.scala:73-78), minus the driver round-trip.
    """

    def vag(theta):
        theta = jnp.asarray(theta, dtype=data.x.dtype)
        return _sharded_vag_impl(kernel, mesh, theta, data.x, data.y, data.mask)

    return vag
