"""Greedy (Seeger et al. 2003) active-set forward selection.

Counterpart of GreedilyOptimizingActiveSetProvider (ASP.scala:59-136): grow
the active set one point at a time, scoring every candidate with the
information-gain delta of *Fast Forward Selection to Speed Up Sparse Gaussian
Process Regression*.

Re-design vs the reference (and vs the round-1 version of this file):

* the reference broadcasts ``inv(Kmm)`` and ``inv(sigma2 Kmm + Kmn Knm)`` and
  loops per-candidate per-expert on executors (ASP.scala:84-136), refactoring
  both matrices from scratch every round — O(k^2 N) solves per round;
* here NOTHING is refactored: appending a point only *extends* ``Kmm`` and
  ``sigma2 Kmm + Kmn Knm`` by one row/column (existing entries never change),
  so each round extends the two Cholesky factors by one row (a triangular
  solve), and the candidate statistics update incrementally from the new
  factor rows:

      W = L_mm^-1 K_mn   (row append:  W_k = (c_new - w . W) / d)
      p = sum_rows W^2   (p += W_k^2)
      V = L_pd^-1 K_mn,  q = sum_rows V^2,  z = L_pd^-1 K_mn y,
      mu = V^T z         (mu += V_k z_k)

  — O(m N) MXU work per round instead of O(k^2 N), a ~m/3-fold total FLOP
  reduction (three orders of magnitude at the reference's m=1000), and the
  entire m-round loop is ONE jitted ``lax.fori_loop``: state stays
  device-resident, zero host syncs until the final index fetch.

Memory: three [m, N] buffers (K_mn rows, W, V) — ~280 MB at the Protein
config (m=512, N=46k, f32), ~6 GB at m=1000, N=515k; chunk N if a config
ever exceeds HBM.

NaN candidate scores (li^2 <= 0 under float error) are excluded, matching the
reference's NaN filter (ASP.scala:130-132).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel


@partial(jax.jit, static_argnums=(0, 1))
def _greedy_select(kernel: Kernel, m: int, theta, xj, yj, first_idx):
    """Device-resident forward selection; returns the m chosen indices."""
    n = xj.shape[0]
    dtype = xj.dtype
    sigma2 = jnp.asarray(kernel.white_noise_var(theta), dtype)
    k_diag = kernel.diag(theta, xj)  # includes the +sigma2 noise diagonal
    solve = partial(
        jax.lax.linalg.triangular_solve,
        left_side=True, lower=True, transpose_a=False,
    )

    def cross_row(idx):
        # K(x_idx, .) against every candidate; the Eye/noise component of
        # the model kernel contributes 0 off its own training set, matching
        # the reference's crossKernel (kernel/Kernel.scala:151-161)
        return kernel.cross(theta, xj[idx][None, :], xj)[0]

    def append(k, idx, state):
        (cross, w_buf, v_buf, l_mm, l_pd, z, p_vec, q_vec, mu_vec,
         mask, chosen) = state
        c_new = cross_row(idx)

        # Kmm gains column [K(a_j, x_idx)]_j — already present in the stored
        # cross rows; unfilled rows are zero, which the identity-padded
        # factors forward-solve to zero (no masking needed).
        kmm_col = cross[:, idx]
        kmm_nn = k_diag[idx]
        w = solve(l_mm, kmm_col[:, None])[:, 0]
        d = jnp.sqrt(kmm_nn - w @ w)
        l_mm = l_mm.at[k].set(w.at[k].set(d))
        w_k = (c_new - w @ w_buf) / d
        p_vec = p_vec + w_k * w_k

        pd_col = sigma2 * kmm_col + cross @ c_new
        pd_nn = sigma2 * kmm_nn + c_new @ c_new
        v = solve(l_pd, pd_col[:, None])[:, 0]
        e = jnp.sqrt(pd_nn - v @ v)
        l_pd = l_pd.at[k].set(v.at[k].set(e))
        v_k = (c_new - v @ v_buf) / e
        q_vec = q_vec + v_k * v_k

        z_k = (c_new @ yj - v @ z) / e
        z = z.at[k].set(z_k)
        mu_vec = mu_vec + v_k * z_k

        return (
            cross.at[k].set(c_new),
            w_buf.at[k].set(w_k),
            v_buf.at[k].set(v_k),
            l_mm, l_pd, z, p_vec, q_vec, mu_vec,
            mask.at[idx].set(True),
            chosen.at[k].set(idx),
        )

    state = (
        jnp.zeros((m, n), dtype),  # cross (K_mn rows)
        jnp.zeros((m, n), dtype),  # W = L_mm^-1 K_mn
        jnp.zeros((m, n), dtype),  # V = L_pd^-1 K_mn
        jnp.eye(m, dtype=dtype),   # L_mm (unit diag on unfilled rows)
        jnp.eye(m, dtype=dtype),   # L_pd
        jnp.zeros((m,), dtype),    # z = L_pd^-1 K_mn y
        jnp.zeros((n,), dtype),    # p
        jnp.zeros((n,), dtype),    # q
        jnp.zeros((n,), dtype),    # mu
        jnp.zeros((n,), bool),     # chosen mask
        jnp.zeros((m,), jnp.int32),
    )
    state = append(0, first_idx, state)

    def body(k, state):
        p_vec, q_vec, mu_vec, mask = state[6], state[7], state[8], state[9]
        # Seeger information-gain delta (ASP.scala:106-128)
        li2 = k_diag - p_vec
        ratio2 = sigma2 / li2  # (sigma / li)^2
        ksi = 1.0 / (ratio2 + 1.0 - q_vec)
        kappa = ksi * (1.0 + 2.0 * ratio2)
        delta = -0.5 * jnp.log(ratio2) - 0.5 * (
            jnp.log(ksi)
            + ksi * (1.0 - kappa) / sigma2 * (yj - mu_vec) ** 2
            - kappa
            + 2.0
        )
        delta = jnp.where(jnp.isnan(delta) | mask, -jnp.inf, delta)
        return append(k, jnp.argmax(delta), state)

    state = jax.lax.fori_loop(1, m, body, state)
    return state[-1]


def greedy_active_set(
    active_set_size: int,
    x: np.ndarray,
    y: np.ndarray,
    kernel: Kernel,
    theta_opt: np.ndarray,
    seed: int,
) -> np.ndarray:
    """Select ``m`` active points greedily.  ``kernel`` must be the
    noise-augmented model kernel (the reference passes ``getKernel``,
    GaussianProcessCommons.scala:43)."""
    x = np.asarray(x)
    y = np.asarray(y)
    n = x.shape[0]
    m = min(active_set_size, n)
    rng = np.random.default_rng(seed)

    xj = jnp.asarray(x)
    theta = jnp.asarray(np.asarray(theta_opt, dtype=np.float64), dtype=xj.dtype)
    yj = jnp.asarray(y, dtype=xj.dtype)

    chosen = _greedy_select(
        kernel, m, theta, xj, yj, jnp.asarray(int(rng.integers(n)), jnp.int32)
    )
    return x[np.asarray(chosen)]
