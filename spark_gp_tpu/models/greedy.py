"""Greedy (Seeger et al. 2003) active-set forward selection.

Counterpart of GreedilyOptimizingActiveSetProvider (ASP.scala:59-136): grow
the active set one point at a time, scoring every candidate with the
information-gain delta of *Fast Forward Selection to Speed Up Sparse Gaussian
Process Regression*.

Re-design vs the reference:

* the reference broadcasts ``inv(Kmm)`` and ``inv(sigma2 Kmm + Kmn Knm)`` and
  loops per-candidate per-expert on executors (ASP.scala:84-136); here each
  round is dense linear algebra over *all* candidates at once — the expert
  partition is irrelevant to the math (experts partition the points), so the
  scores are three batched quadratic forms on the MXU;
* no explicit inverses: both quadratic forms go through Cholesky solves of
  the two m x m systems (factor reuse, SURVEY.md §7 hard-part 7).

NaN candidate scores (li^2 <= 0 under float error) are excluded, matching the
reference's NaN filter (ASP.scala:130-132).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.linalg import chol_solve


def greedy_active_set(
    active_set_size: int,
    x: np.ndarray,
    y: np.ndarray,
    kernel: Kernel,
    theta_opt: np.ndarray,
    seed: int,
) -> np.ndarray:
    """Select ``m`` active points greedily.  ``kernel`` must be the
    noise-augmented model kernel (the reference passes ``getKernel``,
    GaussianProcessCommons.scala:43)."""
    x = np.asarray(x)
    y = np.asarray(y)
    n = x.shape[0]
    m = min(active_set_size, n)
    rng = np.random.default_rng(seed)

    theta = jnp.asarray(np.asarray(theta_opt, dtype=np.float64))
    xj = jnp.asarray(x)
    yj = jnp.asarray(y)

    sigma2 = float(np.asarray(kernel.white_noise_var(theta)))
    sigma = np.sqrt(sigma2)
    k_diag_all = kernel.diag(theta, xj)  # includes the +sigma2 noise diagonal

    chosen = [int(rng.integers(n))]

    while len(chosen) < m:
        active = xj[jnp.asarray(chosen)]
        kmm = kernel.gram(theta, active)  # [k, k], noise-augmented diagonal
        cross = kernel.cross(theta, active, xj)  # [k, N]

        kmn_knm = cross @ cross.T
        kmn_y = cross @ yj
        pd_mat = sigma2 * kmm + kmn_knm

        l_mm = jnp.linalg.cholesky(kmm)
        l_pd = jnp.linalg.cholesky(pd_mat)

        kinv_cross = chol_solve(l_mm, cross)  # [k, N]
        pdinv_cross = chol_solve(l_pd, cross)  # [k, N]
        magic_vector = chol_solve(l_pd, kmn_y)

        p_i = jnp.sum(cross * kinv_cross, axis=0)
        q_i = jnp.sum(cross * pdinv_cross, axis=0)
        mu_i = cross.T @ magic_vector

        li2 = k_diag_all - p_i
        li = jnp.sqrt(li2)
        ratio2 = sigma2 / li2  # (sigma / li)^2
        ksi = 1.0 / (ratio2 + 1.0 - q_i)
        kappa = ksi * (1.0 + 2.0 * ratio2)
        delta = -jnp.log(sigma / li) - 0.5 * (
            jnp.log(ksi) + ksi * (1.0 - kappa) / sigma2 * (yj - mu_i) ** 2 - kappa + 2.0
        )

        delta = jnp.where(jnp.isnan(delta), -jnp.inf, delta)
        # exclude already-chosen points (their li^2 ~ 0 usually NaNs anyway)
        delta = delta.at[jnp.asarray(chosen)].set(-jnp.inf)
        chosen.append(int(jnp.argmax(delta)))

    return x[np.asarray(chosen)]
