"""Greedy (Seeger et al. 2003) active-set forward selection.

Counterpart of GreedilyOptimizingActiveSetProvider (ASP.scala:59-136): grow
the active set one point at a time, scoring every candidate with the
information-gain delta of *Fast Forward Selection to Speed Up Sparse Gaussian
Process Regression* (ASP.scala:106-128):

    li^2  = K_ii - k_i^T Kmm^-1 k_i
    q_i   = k_i^T (sigma2 Kmm + Kmn Knm)^-1 k_i
    mu_i  = k_i^T magicVector
    delta = -log(sigma/li) - (log ksi + ksi (1-kappa)/sigma2 (y_i-mu_i)^2
                              - kappa + 2) / 2

Re-design vs the reference (third iteration of this file):

* the reference broadcasts ``inv(Kmm)`` and ``inv(sigma2 Kmm + Kmn Knm)`` and
  loops per-candidate per-expert on executors (ASP.scala:84-136), refactoring
  both matrices from scratch every round — O(k^2 N) solves per round;
* here NOTHING is refactored: appending a point only *extends* ``Kmm`` and
  ``sigma2 Kmm + Kmn Knm`` by one row/column, so each round extends the two
  Cholesky factors by one row (a triangular solve).  The candidate statistics
  p = rowsum(W^2), q = rowsum(V^2), mu = V^T z (with W = L_mm^-1 K_sel,
  V = L_pd^-1 K_sel, z = L_pd^-1 Kmn y) update from the new factor rows, and
  the new rows themselves need only the STORED cross rows K_sel [m, N]:

      w_row = (c_new - (L_mm^-T w)^T K_sel) / d
      v_row = (c_new - (L_pd^-T v)^T K_sel) / e

  — the transpose-solve identity w^T (L^-1 K_sel) = (L^-T w)^T K_sel means
  the W and V buffers of the previous design never need materializing: ONE
  [m, N] buffer (the cross rows) instead of three, ~2 GB at the Year-MSD
  config (m=1000, N=515k, f32) vs ~6 GB before.  O(mN) MXU work per round,
  and the entire m-round loop is ONE jitted ``lax.fori_loop``.

* the candidate axis N shards over the device mesh: every buffer and
  candidate statistic is [m, N/D] or [N/D] per device, the small factor
  state (L_mm, L_pd, z) is replicated, and each round's cross-device
  traffic is two scalar all-reduces (the argmax) plus four psums of [m]/
  scalar statistics — the TPU counterpart of the reference's
  broadcast-inverses + distributed-argmax round (ASP.scala:88-132).  The
  same core runs unsharded when ``axis`` is None.

NaN candidate scores (li^2 <= 0 under float error) are excluded, matching
the reference's NaN filter (ASP.scala:130-132); padded stack slots and
already-chosen points are masked out the same way.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

_INT_MAX = np.int32(np.iinfo(np.int32).max)


def _greedy_core(kernel: Kernel, m: int, axis, theta, xf, yf, maskf, first_gidx):
    """Device-resident forward selection over a (possibly sharded) candidate
    axis; returns the m chosen points ``[m, p]`` (replicated under shard_map).

    ``xf [nl, p]``, ``yf [nl]``, ``maskf [nl]`` are the local candidate
    shard; ``first_gidx`` is the GLOBAL flat index of the seed point (the
    reference seeds with one uniform sample, ASP.scala:70).  ``axis`` is the
    shard_map axis name, or None when running unsharded.
    """
    nl = xf.shape[0]
    dtype = xf.dtype

    def psum(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    def pmax(v):
        return jax.lax.pmax(v, axis) if axis is not None else v

    def pmin(v):
        return jax.lax.pmin(v, axis) if axis is not None else v

    base = (
        jax.lax.axis_index(axis) * nl if axis is not None else jnp.int32(0)
    )
    gids = jnp.arange(nl, dtype=jnp.int32) + base

    sigma2 = jnp.asarray(kernel.white_noise_var(theta), dtype)
    k_diag = kernel.diag(theta, xf)  # includes the +sigma2 noise diagonal
    solve = partial(
        jax.lax.linalg.triangular_solve,
        left_side=True, lower=True, transpose_a=False,
    )
    solve_t = partial(
        jax.lax.linalg.triangular_solve,
        left_side=True, lower=True, transpose_a=True,
    )

    def append(k, gidx, state):
        (ksel, l_mm, l_pd, z, p_vec, q_vec, mu_vec, sel, chosen_x,
         chosen_gidx) = state
        onehot = (gids == gidx).astype(dtype)
        # Fused collective 1 — every onehot-derived statistic in ONE psum
        # (the per-round loop is ICI-latency-bound at m ~ 1000; separate
        # small all-reduces would dominate it): the selected point's row
        # [p], the new Kmm column [m], and its diagonal entry [1].
        fused_a = psum(
            jnp.concatenate(
                [onehot @ xf, ksel @ onehot, (k_diag * maskf) @ onehot[:, None]]
            )
        )
        p_dim = xf.shape[1]
        x_sel = fused_a[:p_dim]
        kmm_col = fused_a[p_dim:p_dim + m]  # zeros past k: identity-padded
        kmm_nn = fused_a[p_dim + m]         # factors forward-solve to zero
        # K(x_sel, .) against the local candidates; the Eye/noise component
        # of the model kernel contributes 0 off its own training set
        # (kernel/Kernel.scala:151-161).  Masked so padded slots never feed
        # the factor statistics.
        c_new = kernel.cross(theta, x_sel[None, :], xf)[0] * maskf
        # Fused collective 2 — every c_new-derived statistic.
        fused_b = psum(
            jnp.concatenate(
                [ksel @ c_new, (c_new @ c_new)[None], (c_new @ yf)[None]]
            )
        )

        w = solve(l_mm, kmm_col[:, None])[:, 0]
        d = jnp.sqrt(kmm_nn - w @ w)
        # row k of W = L_mm^-1 K_sel via the transpose-solve identity; uses
        # the PRE-update factor (prefix rows only — w is zero past k)
        a = solve_t(l_mm, w[:, None])[:, 0]
        w_row = (c_new - a @ ksel) / d
        l_mm = l_mm.at[k].set(w.at[k].set(d))
        p_vec = p_vec + w_row * w_row

        pd_col = sigma2 * kmm_col + fused_b[:m]
        pd_nn = sigma2 * kmm_nn + fused_b[m]
        v = solve(l_pd, pd_col[:, None])[:, 0]
        e = jnp.sqrt(pd_nn - v @ v)
        b = solve_t(l_pd, v[:, None])[:, 0]
        v_row = (c_new - b @ ksel) / e
        l_pd = l_pd.at[k].set(v.at[k].set(e))
        q_vec = q_vec + v_row * v_row

        z_k = (fused_b[m + 1] - v @ z) / e
        z = z.at[k].set(z_k)
        mu_vec = mu_vec + v_row * z_k

        return (
            ksel.at[k].set(c_new),
            l_mm, l_pd, z, p_vec, q_vec, mu_vec,
            sel | (onehot > 0),
            chosen_x.at[k].set(x_sel),
            chosen_gidx.at[k].set(jnp.asarray(gidx, jnp.int32)),
        )

    p_dim = xf.shape[1]
    state = (
        jnp.zeros((m, nl), dtype),  # ksel: cross rows of the chosen points
        jnp.eye(m, dtype=dtype),    # L_mm (unit diag on unfilled rows)
        jnp.eye(m, dtype=dtype),    # L_pd
        jnp.zeros((m,), dtype),     # z = L_pd^-1 K_mn y
        jnp.zeros((nl,), dtype),    # p
        jnp.zeros((nl,), dtype),    # q
        jnp.zeros((nl,), dtype),    # mu
        jnp.zeros((nl,), bool),     # chosen mask (local)
        jnp.zeros((m, p_dim), dtype),  # the selected points
        jnp.zeros((m,), jnp.int32),    # their global flat indices
    )
    state = append(0, jnp.asarray(first_gidx, jnp.int32), state)
    # per-round winning delta, appended AFTER the seed round (the seed is a
    # uniform draw, ASP.scala:70 — it has no score): the Δ-profile is the
    # flat-decay diagnostic surfaced by the host wrappers
    state = state + (jnp.full((m,), jnp.nan, dtype),)

    def body(k, state):
        state, deltas = state[:-1], state[-1]
        p_vec, q_vec, mu_vec, sel = state[4], state[5], state[6], state[7]
        # Seeger information-gain delta (ASP.scala:106-128)
        li2 = k_diag - p_vec
        ratio2 = sigma2 / li2  # (sigma / li)^2
        ksi = 1.0 / (ratio2 + 1.0 - q_vec)
        kappa = ksi * (1.0 + 2.0 * ratio2)
        delta = -0.5 * jnp.log(ratio2) - 0.5 * (
            jnp.log(ksi)
            + ksi * (1.0 - kappa) / sigma2 * (yf - mu_vec) ** 2
            - kappa
            + 2.0
        )
        delta = jnp.where(
            jnp.isnan(delta) | sel | (maskf == 0), -jnp.inf, delta
        )
        # distributed NaN-filtered argmax (ASP.scala:130-132): max value
        # across shards, lowest global index on ties
        loc = jnp.argmax(delta).astype(jnp.int32)
        lval = delta[loc]
        gmax = pmax(lval)
        gidx = pmin(jnp.where(lval == gmax, gids[loc], _INT_MAX))
        return append(k, gidx, state) + (deltas.at[k].set(gmax),)

    state = jax.lax.fori_loop(1, m, body, state)
    # (points [m, p], global indices [m], winning deltas [m])
    return state[-3], state[-2], state[-1]


@partial(jax.jit, static_argnums=(0, 1))
def _greedy_select(kernel: Kernel, m: int, theta, xj, yj, maskj, first_idx):
    return _greedy_core(kernel, m, None, theta, xj, yj, maskj, first_idx)


@partial(jax.jit, static_argnums=(0, 1, 2))
def _greedy_select_sharded(kernel: Kernel, m: int, mesh, theta, x, y, mask, first_gidx):
    p = x.shape[-1]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS), P(),
        ),
        out_specs=(P(), P(), P()),
    )
    def run(theta_, x_, y_, mask_, first_):
        return _greedy_core(
            kernel, m, EXPERT_AXIS, theta_,
            x_.reshape(-1, p), y_.reshape(-1), mask_.reshape(-1), first_,
        )

    return run(theta, x, y, mask, first_gidx)


def warn_on_flat_delta_profile(deltas: np.ndarray) -> float | None:
    """Fail-loud diagnostic for the regime where Seeger selection HURTS
    (VERDICT r4 #8; characterized in PARITY.md): on airfoil-like data the
    information-gain criterion chases high-variance boundary/outlier points
    that are remote in kernel space, so each pick reduces nobody else's
    score and the winning-Δ profile never decays — greedy RMSE 3-8x worse
    than random at m in {16, 32, 64}.

    Detector: tail-third median of the per-round winning deltas vs the
    head-third median.  Measured calibration (r5, both quality.py regimes,
    3 seeds x {m=24,48} and 2 seeds x {m=16,32,64}): density-skewed payoff
    regime decays to ratio 0.22-0.84; the airfoil pathology sits at
    1.05-5.7.  Threshold 0.95 splits them with margin.  Returns the ratio
    (None when the profile is too short to judge), logging the warning
    through the package logger so it lands in user logs and captured
    instrumentation alike.
    """
    from spark_gp_tpu.utils.instrumentation import logger

    d = np.asarray(deltas, dtype=np.float64)
    d = d[np.isfinite(d)]
    if d.size < 9:  # < 3 per third: medians too noisy to accuse anyone
        return None
    third = d.size // 3
    head = float(np.median(d[:third]))
    tail = float(np.median(d[-third:]))
    if head <= 0.0:  # degenerate scores; the NaN filter already handled worse
        return None
    ratio = tail / head
    if ratio >= 0.95:
        logger.warning(
            "greedy active-set selection: winning information-gain deltas "
            "are not decaying (tail/head median ratio %.2f over %d rounds) "
            "— late picks look remote in kernel space and likely contribute "
            "nothing (the airfoil-at-small-m pathology, PARITY.md). "
            "RandomActiveSetProvider (the reference default) or "
            "KMeansActiveSetProvider will likely fit better here.",
            ratio, d.size,
        )
    return ratio


def greedy_active_set(
    active_set_size: int,
    x: np.ndarray,
    y: np.ndarray,
    kernel: Kernel,
    theta_opt: np.ndarray,
    seed: int,
) -> np.ndarray:
    """Select ``m`` active points greedily from host-resident rows.
    ``kernel`` must be the noise-augmented model kernel (the reference passes
    ``getKernel``, GaussianProcessCommons.scala:43)."""
    x = np.asarray(x)
    y = np.asarray(y)
    n = x.shape[0]
    m = min(active_set_size, n)
    rng = np.random.default_rng(seed)

    xj = jnp.asarray(x)
    theta = jnp.asarray(np.asarray(theta_opt, dtype=np.float64), dtype=xj.dtype)
    yj = jnp.asarray(y, dtype=xj.dtype)
    maskj = jnp.ones((n,), dtype=xj.dtype)

    _, idx, deltas = _greedy_select(
        kernel, m, theta, xj, yj, maskj,
        jnp.asarray(int(rng.integers(n)), jnp.int32),
    )
    warn_on_flat_delta_profile(np.asarray(deltas))
    # return the exact host rows (the device points would be rounded to the
    # device dtype, perturbing the f64 magic solve downstream)
    return x[np.asarray(idx)]


def greedy_active_set_from_stack(
    active_set_size: int,
    data,
    kernel: Kernel,
    theta,
    seed: int,
    mesh,
) -> np.ndarray:
    """Greedy selection straight off a (possibly multi-host) sharded expert
    stack: candidate statistics stay sharded on the mesh for the whole
    m-round loop; only the m selected rows ever replicate.

    The targets are whatever the stack's ``y`` carries — labels for
    regression, latent modes for the classifier (GPClf.scala:62-65
    substitutes f for y before produceModel).
    """
    from spark_gp_tpu.parallel.distributed import replicated_valid_indices

    # Host-side seed draw over the valid (unpadded) slots — the counterpart
    # of the reference's 1-sample takeSample (ASP.scala:70).
    valid = replicated_valid_indices(data, mesh)
    m = min(active_set_size, valid.size)
    rng = np.random.default_rng(seed)
    first = int(rng.choice(valid))

    theta_dev = jnp.asarray(theta, dtype=data.x.dtype)
    chosen, _, deltas = _greedy_select_sharded(
        kernel, m, mesh, theta_dev, data.x, data.y, data.mask,
        jnp.asarray(first, jnp.int32),
    )
    warn_on_flat_delta_profile(np.asarray(deltas))
    return np.asarray(chosen, dtype=np.float64)
