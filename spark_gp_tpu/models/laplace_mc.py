"""Laplace approximation for MULTICLASS GP classification (softmax link).

Capability beyond the reference: akopich/spark-gp is binary-only
(GaussianProcessClassifier.scala:32, numClasses = 2 at :151) and handles
multiclass through Spark's OneVsRest meta-estimator (Iris.scala:26-27).
This module implements the native C-class Laplace approximation of
Rasmussen & Williams ch. 3.5 — one latent function per class under a
shared GP prior, coupled through the softmax likelihood — so probabilities
are jointly calibrated instead of C independent sigmoids.

Mode finding is R&W Algorithm 3.3 re-derived for the expert stack: with
``pi = softmax(f)``, ``D_c = diag(pi_c)`` and ``W = D - Pi Pi^T`` (the
softmax Hessian), each Newton step solves ``(I + W K_blk)^-1 b`` using only
per-class ``s x s`` factorizations:

    E_c = sqrt(D_c) (I + sqrt(D_c) K sqrt(D_c))^-1 sqrt(D_c)
    M   = chol(sum_c E_c)
    a_c = b_c - E_c K b_c + E_c M^-T M^-1 sum_c' (E_c' K b_c')
    f'  = K a

and the log-determinant splits the same way (Sylvester + the push-through
identity; ``sum_c D_c = I`` because softmax rows sum to one):

    log det(I + K_blk W) = sum_c log det(B_c) + 2 sum log diag chol(sum_c E_c)

Every ``B_c`` factorization is one batched pass over the ``[E * C, s, s]``
stack (the Pallas fused kernel on TPU, batched Cholesky elsewhere — the
same split as the binary path, laplace.py).

**The hyperparameter gradient needs no hand algebra.**  The binary path
implements R&W Algorithm 5.1's implicit-correction terms (s2/s3) manually;
here the same mathematics falls out of autodiff via the Newton fixed point:
the mode ``f_hat(theta)`` is found under ``stop_gradient``, then ONE
differentiable Newton step is taken from it.  Because the Newton map ``Phi``
has ``dPhi/df = 0`` at the mode (gradient of the inner objective vanishes),
the step's output carries exactly the implicit derivative
``df_hat/dtheta`` — so ``jax.value_and_grad`` of log Z evaluated at the
stepped iterate reproduces the full Algorithm-5.1-style gradient, including
the determinant's dependence on the mode, with machine accuracy (FD-checked
in tests/test_multiclass.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_gp_tpu.kernels.base import Kernel, masked_gram_stack
from spark_gp_tpu.obs import cost as obs_cost
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.optimize.lbfgs_device import lbfgs_state_donation


def _batched_spd_inv_logdet(mats):
    """Explicit inverse + logdet for a ``[..., s, s]`` SPD stack — the
    Pallas/Cholesky backend split of the binary path (laplace.py:58-88),
    except the multiclass formulas genuinely consume full inverses (E_c
    enters sums and products as a matrix), so both branches materialize
    them."""
    from spark_gp_tpu.ops.linalg import chol_logdet, chol_solve, cholesky
    from spark_gp_tpu.ops.pallas_linalg import _use_pallas, spd_inv_logdet

    shape = mats.shape
    flat = mats.reshape((-1,) + shape[-2:])
    if _use_pallas(flat):
        inv, logdet = spd_inv_logdet(flat)
    else:
        chol_l = cholesky(flat)
        eye = jnp.broadcast_to(
            jnp.eye(shape[-1], dtype=mats.dtype), flat.shape
        )
        inv = chol_solve(chol_l, eye)
        logdet = chol_logdet(chol_l)
    return inv.reshape(shape), logdet.reshape(shape[:-2])


class _McStep(NamedTuple):
    a: jax.Array  # [E, s, C]
    f_new: jax.Array  # [E, s, C]
    half_logdet_b: jax.Array  # [E]  = sum_c log diag chol(B_c)
    half_logdet_m: jax.Array  # [E]  = sum log diag chol(sum_c E_c)


def _mc_newton_quantities(kmat, y1h, mask, f) -> _McStep:
    """One Algorithm-3.3 Newton step from latent ``f`` for the whole
    ``[E, s, C]`` stack; also returns the two half-log-determinants of the
    Laplace normalizer evaluated at ``f``.

    Fully differentiable w.r.t. ``kmat`` and ``f`` (cholesky + solves);
    padding (mask 0) contributes exactly nothing: sqrt(D_c) is masked so
    B_c has unit padded rows, and sum_c E_c gets an identity pad block.
    """
    pi = jax.nn.softmax(f, axis=-1) * mask[..., None]  # [E, s, C]
    # double-where sqrt guard: at padded rows (and underflowed softmax
    # entries) pi is exactly 0, where sqrt's derivative is infinite and the
    # autodiff gradient path (unlike the binary module's hand-assembled
    # Alg 5.1) would turn 0 * inf into NaN
    pi_pos = pi > 0.0
    sqd = jnp.where(pi_pos, jnp.sqrt(jnp.where(pi_pos, pi, 1.0)), 0.0)

    if it_ops.resolve_solver(kmat.shape[-1]) in ("iterative", "matfree"):
        # (matfree resolves here too: the Laplace B systems are
        # materialized-operator solves — the matrix-free memory win is
        # marginal-NLL-scoped, and regressing to the batched Cholesky
        # under GP_SOLVER_LANE=matfree would be strictly worse)
        return _mc_newton_quantities_iter(kmat, y1h, mask, f, pi, sqd)

    # B_c = I + sqrt(D_c) K sqrt(D_c), batched over (expert, class)
    eye = jnp.eye(kmat.shape[-1], dtype=kmat.dtype)
    sq_ec = jnp.moveaxis(sqd, -1, 1)  # [E, C, s]
    b_mats = eye[None, None] + sq_ec[..., :, None] * kmat[:, None] * sq_ec[..., None, :]
    binv, logdet_b = _batched_spd_inv_logdet(b_mats)  # [E, C, s, s], [E, C]

    # E_c = sqrt(D_c) B_c^-1 sqrt(D_c)  (explicit — consumed as a matrix)
    e_mats = sq_ec[..., :, None] * binv * sq_ec[..., None, :]

    # M = chol(sum_c E_c + pad identity); padded rows of every E_c are zero
    pad_eye = eye[None] * (1.0 - mask[:, :, None])
    sum_e = jnp.sum(e_mats, axis=1) + pad_eye
    from spark_gp_tpu.ops.linalg import chol_solve, cholesky

    m_chol = cholesky(sum_e)
    half_logdet_m = jnp.sum(
        jnp.log(jnp.diagonal(m_chol, axis1=-2, axis2=-1)) * mask, axis=-1
    )

    # b = W f + (y - pi), W f = pi*f - pi * sum_c' pi_c' f_c'   (rowwise)
    pif_sum = jnp.sum(pi * f, axis=-1, keepdims=True)
    b_vec = (pi * f - pi * pif_sum + (y1h - pi)) * mask[..., None]

    kb = jnp.einsum("est,etc->esc", kmat, b_vec)  # [E, s, C]
    kb_ec = jnp.moveaxis(kb, -1, 1)  # [E, C, s]
    c_ec = sq_ec * jnp.einsum(
        "ecst,ect->ecs", binv, sq_ec * kb_ec
    )  # E_c K b_c, [E, C, s]
    c_sum = jnp.sum(c_ec, axis=1)  # [E, s]
    u = chol_solve(m_chol, c_sum)  # (sum_c E_c)^-1 sum_c c_c
    eu_ec = sq_ec * jnp.einsum("ecst,ect->ecs", binv, sq_ec * u[:, None, :])
    a = jnp.moveaxis(jnp.moveaxis(b_vec, -1, 1) - c_ec + eu_ec, 1, -1)
    f_new = jnp.einsum("est,etc->esc", kmat, a)
    return _McStep(
        a=a,
        f_new=f_new,
        half_logdet_b=0.5 * jnp.sum(logdet_b, axis=1),
        half_logdet_m=half_logdet_m,
    )


def _mc_newton_quantities_iter(kmat, y1h, mask, f, pi, sqd) -> _McStep:
    """The CG/Lanczos solver lane's Newton step (ops/iterative.py): no
    per-class factorizations, no explicit inverses — ONE factored system.

    The softmax Hessian admits a closed-form root ``W = S S^T`` with
    ``S = D^{1/2} (I - q q^T)``, ``q = sqrt(pi)`` (unit per unmasked row,
    since softmax rows sum to one; masked rows give ``S = 0``), i.e.
    elementwise ``S_cd = sqrt(pi_c) delta_cd - pi_c sqrt(pi_d)``.  Then by
    push-through the Newton step ``a = (I + W K_blk)^{-1} b`` becomes

        a = b - S (I + S^T K_blk S)^{-1} S^T K_blk b

    solved by multi-RHS CG on the FACTORED operator (never materializing
    the ``[sC, sC]`` block system — :func:`ops.iterative.factored_solve`,
    differentiable via ``custom_linear_solve``), and by Sylvester the
    whole normalizer determinant collapses to one term,

        log det(I + K_blk W) = log det(I + S^T K_blk S)

    (:func:`ops.iterative.factored_logdet`, SLQ value + Hutchinson
    surrogate gradient) — returned in the ``half_logdet_b`` slot with
    ``half_logdet_m = 0``, which the exact path's two-term split sums to.
    """
    eye_c = jnp.eye(pi.shape[-1], dtype=kmat.dtype)
    smat = sqd[..., :, None] * eye_c - pi[..., :, None] * sqd[..., None, :]

    # b = W f + (y - pi)  (rowwise, same as the exact path)
    pif_sum = jnp.sum(pi * f, axis=-1, keepdims=True)
    b_vec = (pi * f - pi * pif_sum + (y1h - pi)) * mask[..., None]

    kb = jnp.einsum("est,etc->esc", kmat, b_vec)         # K_blk b
    skb = jnp.einsum("esdc,esd->esc", smat, kb)          # S^T K_blk b
    v = it_ops.factored_solve(kmat, smat, skb)           # B'^-1 S^T K b
    a = b_vec - jnp.einsum("escd,esd->esc", smat, v)     # (I + W K)^-1 b
    f_new = jnp.einsum("est,etc->esc", kmat, a)
    half_logdet = 0.5 * it_ops.factored_logdet(kmat, smat)
    return _McStep(
        a=a,
        f_new=f_new,
        half_logdet_b=half_logdet,
        half_logdet_m=jnp.zeros_like(half_logdet),
    )


def _mc_log_lik(f, y1h, mask):
    """``sum_i mask_i (y_i . f_i - logsumexp_c f_ic)`` per expert."""
    return jnp.sum(
        (jnp.sum(y1h * f, axis=-1) - jax.scipy.special.logsumexp(f, axis=-1))
        * mask,
        axis=-1,
    )


def _mc_objective(a, f_new, y1h, mask):
    """Inner (penalized) objective ``-a^T f / 2 + log p(y|f)`` per expert —
    the multiclass analogue of the binary acceptance objective
    (GPClf.scala:102 semantics)."""
    return -0.5 * jnp.sum(a * f_new, axis=(-2, -1)) + _mc_log_lik(
        f_new, y1h, mask
    )


class _McNewtonState(NamedTuple):
    f: jax.Array  # [E, s, C]
    old_obj: jax.Array  # [E]
    new_obj: jax.Array  # [E]
    step: jax.Array  # [E]


def laplace_mc_mode(kmat, y1h, mask, f0, tol):
    """Softmax-Laplace mode Newton loop with per-expert step halving —
    the multiclass counterpart of ``laplace_mode_batch`` (same batched
    while_loop shape, same termination semantics).  Returns
    ``(f_modes [E, s, C], final objective [E])``; NOT differentiated (the
    gradient path takes one differentiable step from the result)."""
    dtype = kmat.dtype
    zero = jnp.zeros((), dtype=dtype) + 0.0 * jnp.sum(f0, axis=(-2, -1))
    init = _McNewtonState(
        f=f0,
        old_obj=zero - jnp.inf,
        new_obj=zero + jnp.finfo(dtype).min,
        step=zero + 1.0,
    )

    def running(state: _McNewtonState):
        return jnp.logical_and(
            jnp.abs(state.old_obj - state.new_obj) > tol, state.step > tol
        )

    def cond(state: _McNewtonState):
        return jnp.any(running(state))

    def body(state: _McNewtonState):
        stp = _mc_newton_quantities(kmat, y1h, mask, state.f)
        f_cand = (1.0 - state.step)[:, None, None] * state.f + state.step[
            :, None, None
        ] * stp.f_new
        obj_cand = _mc_objective(stp.a, f_cand, y1h, mask)
        accept = obj_cand > state.old_obj
        run = running(state)
        upd = run & accept
        return _McNewtonState(
            f=jnp.where(upd[:, None, None], f_cand, state.f),
            old_obj=jnp.where(upd, state.new_obj, state.old_obj),
            new_obj=jnp.where(upd, obj_cand, state.new_obj),
            step=jnp.where(run & ~accept, state.step / 2.0, state.step),
        )

    final = jax.lax.while_loop(cond, body, init)
    return final.f, final.new_obj


def _gram_stack(kernel: Kernel, theta, x, mask, cache=None):
    """Thin alias of :func:`kernels.base.masked_gram_stack` kept for the
    test oracles that build expert gram stacks directly."""
    return masked_gram_stack(kernel, theta, x, mask, cache)


def batched_neg_logz_mc(
    kernel: Kernel, tol, theta, x, y1h, mask, f0, cache=None, weights=None
):
    """Summed multiclass ``-log Z`` with gradient, over the local stack.

    Returns ``(nll, grad, f_modes)``.  The gradient comes from autodiff
    through ONE Newton step at the (stop-gradient) converged mode — exact
    by the implicit function theorem (module docstring); the determinant
    terms are re-evaluated at the differentiable iterate so their implicit
    f-dependence (the binary path's s2/s3 correction) is carried too.
    ``cache`` is the theta-invariant gram cache (kernels/base.py): the
    differentiated gram build then skips the distance contraction.
    ``weights`` is the aggregation plane's ``[E]`` per-expert vector
    (``models/aggregation.py``); ``None`` keeps the sum bit-for-bit.
    """
    from spark_gp_tpu.models.aggregation import weighted_expert_sum

    def nll(theta_):
        kmat = masked_gram_stack(kernel, theta_, x, mask, cache)
        f_hat = jax.lax.stop_gradient(
            laplace_mc_mode(
                jax.lax.stop_gradient(kmat), y1h, mask, f0, tol
            )[0]
        )
        stp = _mc_newton_quantities(kmat, y1h, mask, f_hat)
        # Determinants at the DIFFERENTIABLE iterate: f_new == f_hat in
        # value (converged), but carries df_hat/dtheta tangents.
        det = _mc_newton_quantities(kmat, y1h, mask, stp.f_new)
        log_z = (
            _mc_objective(stp.a, stp.f_new, y1h, mask)
            - det.half_logdet_b
            - det.half_logdet_m
        )
        return -weighted_expert_sum(log_z, weights), f_hat

    (value, f_hat), grad = jax.value_and_grad(nll, has_aux=True)(theta)
    return value, grad, f_hat


@partial(jax.jit, static_argnums=(0, 1), static_argnames=("solver",))
def _mc_vag_impl(
    kernel: Kernel, tol, theta, x, y1h, mask, f0, cache=None, *, solver=None
):
    with it_ops.solver_lane_scope(solver):
        return batched_neg_logz_mc(
            kernel, tol, theta, x, y1h, mask, f0, cache
        )


def make_mc_objective(kernel: Kernel, x, y1h, mask, tol, cache=None):
    """Single-device jitted ``(theta, f0) -> (nll, grad, f_modes)``.
    ``cache`` is the theta-invariant gram cache (kernels/base.py),
    device-resident across the host optimizer's evaluations."""

    def obj(theta, f0):
        theta = jnp.asarray(theta, dtype=x.dtype)
        # measured flops/bytes per evaluation (obs/cost.py, GP_XLA_COST)
        return obs_cost.observed_call(
            "fit.host_objective", _mc_vag_impl,
            kernel, float(tol), theta, x, y1h, mask, f0, cache,
            solver=it_ops.solver_jit_key(),
        )

    return obj


def _make_sharded_mc_logz(
    kernel: Kernel, tol, mesh, cache_specs=(),
    cache_of=lambda maybe_cache: None,
):
    """shard_map'd multiclass objective core: experts and latents sharded,
    (value, grad) psum-reduced over ICI — the exact communication pattern
    of the binary classifier's sharded objective (laplace.py)."""
    from jax.sharding import PartitionSpec as P

    from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

    in_specs = (
        P(), P(EXPERT_AXIS),
        P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
    ) + tuple(cache_specs)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P(), P(EXPERT_AXIS)),
    )
    def core(theta, f_carry, x_, y1h_, mask_, *maybe_cache):
        cache = cache_of(maybe_cache)
        value, grad, f_new = batched_neg_logz_mc(
            kernel, tol, theta, x_, y1h_, mask_, f_carry, cache
        )
        return (
            jax.lax.psum(value, EXPERT_AXIS),
            jax.lax.psum(grad, EXPERT_AXIS),
            f_new,
        )

    return core


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def _sharded_mc_vag_impl(
    kernel: Kernel, tol, mesh, theta, x, y1h, mask, f0, cache=None, *,
    solver=None,
):
    from spark_gp_tpu.parallel.mesh import sharded_cache_operand

    with it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        core = _make_sharded_mc_logz(kernel, tol, mesh, cache_specs, cache_of)
        return core(theta, f0, x, y1h, mask, *cache_args)


def make_sharded_mc_objective(
    kernel: Kernel, x, y1h, mask, tol, mesh, cache=None
):
    def obj(theta, f0):
        theta = jnp.asarray(theta, dtype=x.dtype)
        return _sharded_mc_vag_impl(
            kernel, float(tol), mesh, theta, x, y1h, mask, f0, cache,
            solver=it_ops.solver_jit_key(),
        )

    return obj


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def fit_gpc_mc_device(
    kernel: Kernel, tol, log_space, theta0, lower, upper, x, y1h, mask,
    max_iter, cache=None, *, solver=None,
):
    """Single-chip on-device multiclass fit: the latent ``[E, s, C]``
    warm-start stack rides as the optimizer's auxiliary carry, exactly like
    the binary path (laplace.py fit_gpc_device).  Returns
    ``(theta, f_latents, nll, n_iter, n_fev, stalled)``.  ``cache`` sits
    outside the L-BFGS while_loop and serves every evaluation."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )

    with it_ops.solver_lane_scope(solver):
        def vag(theta, f_carry):
            value, grad, f_new = batched_neg_logz_mc(
                kernel, tol, theta, x, y1h, mask, f_carry, cache
            )
            return value, grad, f_new

        if log_space:
            vag, theta0, lower, upper, from_u = log_reparam(
                vag, theta0, lower, upper
            )
        else:
            from_u = lambda t: t

        f0 = jnp.zeros_like(y1h)
        theta, f, f_final, n_iter, n_fev, stalled = lbfgs_minimize_device(
            vag, theta0, lower, upper, f0, max_iter=max_iter, tol=tol
        )
        return from_u(theta), f_final, f, n_iter, n_fev, stalled


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",)
)
def fit_gpc_mc_device_sharded(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper, x, y1h, mask,
    max_iter, cache=None, *, solver=None,
):
    """Multi-chip on-device multiclass fit inside one shard_map — the
    counterpart of laplace.fit_gpc_device_sharded with the ``[E, s, C]``
    latent stacks sharded on the expert axis for the whole optimization."""
    from jax.sharding import PartitionSpec as P

    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )
    from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

    from spark_gp_tpu.parallel.mesh import sharded_cache_operand

    with it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        in_specs = (
            P(), P(), P(),
            P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
            P(),
        ) + cache_specs

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(), P(EXPERT_AXIS), P(), P(), P(), P()),
        )
        def run(theta0_, lower_, upper_, x_, y1h_, mask_, max_iter_,
                *maybe_cache):
            local_cache = cache_of(maybe_cache)

            def vag(theta, f_carry):
                value, grad, f_new = batched_neg_logz_mc(
                    kernel, tol, theta, x_, y1h_, mask_, f_carry, local_cache
                )
                return (
                    jax.lax.psum(value, EXPERT_AXIS),
                    jax.lax.psum(grad, EXPERT_AXIS),
                    f_new,
                )

            if log_space:
                vag, t0, lo, hi, from_u = log_reparam(
                    vag, theta0_, lower_, upper_
                )
            else:
                vag, t0, lo, hi, from_u = (
                    vag, theta0_, lower_, upper_, (lambda t: t)
                )

            f0 = jnp.zeros_like(y1h_)
            theta, f, f_final, n_iter, n_fev, stalled = lbfgs_minimize_device(
                vag, t0, lo, hi, f0, max_iter=max_iter_, tol=tol
            )
            return from_u(theta), f_final, f, n_iter, n_fev, stalled

        return run(theta0, lower, upper, x, y1h, mask, max_iter, *cache_args)


# --- segmented device fit: checkpoint/resume (laplace.py counterpart) ------


def _mc_segment_vag(kernel: Kernel, tol, mesh, log_space, x, y1h, mask,
                    cache=None):
    from spark_gp_tpu.optimize.lbfgs_device import log_transform_vag

    if mesh is None:

        def base(theta, f_carry):
            value, grad, f_new = batched_neg_logz_mc(
                kernel, tol, theta, x, y1h, mask, f_carry, cache
            )
            return value, grad, f_new

    else:
        from spark_gp_tpu.parallel.mesh import sharded_cache_operand

        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        core = _make_sharded_mc_logz(kernel, tol, mesh, cache_specs, cache_of)

        def base(theta, f_carry):
            return core(theta, f_carry, x, y1h, mask, *cache_args)

    return log_transform_vag(base) if log_space else base


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",)
)
def gpc_mc_device_segment_init(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper, x, y1h, mask,
    cache=None, *, solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import lbfgs_init_state

    with it_ops.solver_lane_scope(solver):
        vag = _mc_segment_vag(
            kernel, tol, mesh, log_space, x, y1h, mask, cache
        )
        t0 = jnp.log(theta0) if log_space else theta0
        return lbfgs_init_state(vag, t0, jnp.zeros_like(y1h))


# the L-BFGS state carry is donated — consumed once per segment and
# replaced by the return value (optimize/lbfgs_device.lbfgs_state_donation)
@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",),
    donate_argnums=lbfgs_state_donation(4),
)
def gpc_mc_device_segment_run(
    kernel: Kernel, tol, mesh, log_space, state, lower, upper, x, y1h, mask,
    iter_limit, cache=None, *, solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_run_segment,
        log_transform_bounds,
    )

    with it_ops.solver_lane_scope(solver):
        vag = _mc_segment_vag(
            kernel, tol, mesh, log_space, x, y1h, mask, cache
        )
        lo, hi = (
            log_transform_bounds(lower, upper) if log_space
            else (lower, upper)
        )
        return lbfgs_run_segment(vag, state, lo, hi, iter_limit, tol)


def fit_gpc_mc_device_checkpointed(
    kernel: Kernel, tol, mesh, log_space, theta0, lower, upper,
    x, y1h, mask, max_iter: int, chunk: int, saver, cache=None,
):
    """Segmented on-device multiclass fit with kill-and-resume persistence
    — see laplace.fit_gpc_device_checkpointed; the aux carry here is the
    ``[E, s, C]`` latent warm-start stack.  Returns
    ``(theta, f_latents, nll, n_iter, n_fev, stalled)``.  The gram cache
    rides every segment dispatch (derived state — never checkpointed)."""
    from spark_gp_tpu.utils.checkpoint import run_segmented, segment_meta

    meta = segment_meta(
        "gpc_mc", kernel, tol, log_space, theta0, x, y1h, mask,
        num_classes=int(y1h.shape[-1]),
    )
    solver = it_ops.solver_jit_key()

    def init(theta0_, lower_, upper_, x_, y1h_, mask_):
        return gpc_mc_device_segment_init(
            kernel, float(tol), mesh, log_space, theta0_, lower_, upper_,
            x_, y1h_, mask_, cache, solver=solver,
        )

    def run(state, limit):
        return gpc_mc_device_segment_run(
            kernel, float(tol), mesh, log_space, state, lower, upper,
            x, y1h, mask, limit, cache, solver=solver,
        )

    theta, state = run_segmented(
        init, run, saver, meta, (theta0, lower, upper, x, y1h, mask),
        max_iter, chunk, log_space,
    )
    return theta, state.aux, state.f, state.n_iter, state.n_fev, state.stalled


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def fit_gpc_mc_device_multistart(
    kernel: Kernel, tol, log_space, theta0_batch, lower, upper, x, y1h, mask,
    max_iter, cache=None, *, solver=None,
):
    """Multi-start single-chip multiclass fit: R restarts as ONE vmapped
    device program; the ``[E, s, C]`` latent stacks ride per lane while one
    gram cache broadcasts to every lane.  Returns
    ``(theta_best, f_latents_best, nll_best, n_iter, n_fev, stalled,
    f_all [R], best)``."""
    from spark_gp_tpu.optimize.lbfgs_device import multistart_minimize

    with it_ops.solver_lane_scope(solver):
        def vag(theta, f_carry):
            value, grad, f_new = batched_neg_logz_mc(
                kernel, tol, theta, x, y1h, mask, f_carry, cache
            )
            return value, grad, f_new

        theta, f_final, f, n_iter, n_fev, stalled, f_all, best = (
            multistart_minimize(
                vag, log_space, theta0_batch, lower, upper,
                jnp.zeros_like(y1h), max_iter, tol,
            )
        )
        return theta, f_final, f, n_iter, n_fev, stalled, f_all, best
