"""Laplace approximation for ARBITRARY pointwise log-concave likelihoods.

Capability beyond the reference: akopich/spark-gp hard-codes the Bernoulli
/ sigmoid likelihood into its classifier (GaussianProcessClassifier.scala:
74-129, Algorithms 3.1/5.1 hand-derived for that one case).  This module is
the "bring your own likelihood" core: a likelihood is ONE pure function
``log_lik(f, y) -> per-point log p(y | f)``; everything else — the Newton
direction, the step-halving mode loop, the log Z normalizer, and the
hyperparameter gradient — is derived from it by autodiff:

* ``grad log p`` and the negative Hessian diagonal ``W`` come from
  elementwise ``jax.grad`` (no hand algebra per likelihood);
* the mode loop is the binary module's batched while_loop shape
  (laplace.py): one fused ``[E, s, s]`` factorization per Newton
  iteration, per-expert step halving, masked updates;
* the hyperparameter gradient uses the Newton-fixed-point trick proven
  out in :mod:`spark_gp_tpu.models.laplace_mc`: find the mode under
  ``stop_gradient``, take ONE differentiable Newton step (exact implicit
  derivative, since the Newton map's f-Jacobian vanishes at the mode),
  and re-evaluate the determinant at the differentiable iterate —
  ``jax.value_and_grad`` then reproduces the full Algorithm-5.1-style
  gradient including the implicit (s2/s3) terms, for ANY likelihood.

W must be positive (log-concave likelihood) for the ``B = I + sqrt(W) K
sqrt(W)`` form used here — true for Bernoulli, Poisson (log link), and
the other standard GLM links.

:class:`PoissonLikelihood` (counts, log link) ships as the first consumer
— see :mod:`spark_gp_tpu.models.gp_poisson`.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_gp_tpu.kernels.base import Kernel, masked_gram_stack
from spark_gp_tpu.obs import cost as obs_cost
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.optimize.lbfgs_device import lbfgs_state_donation


class Likelihood:
    """Pointwise likelihood spec: immutable, hashable (jit-static).

    Subclasses implement ``log_lik(f, y)`` mapping scalar latent ``f`` and
    target ``y`` to ``log p(y | f)``.  Derivatives are taken by autodiff;
    override ``grad_hess`` only if the likelihood needs a numerically
    special form.
    """

    def _spec(self) -> tuple:
        return ()

    def __hash__(self) -> int:
        return hash((type(self), self._spec()))

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._spec() == other._spec()

    def log_lik(self, f, y):
        raise NotImplementedError

    def grad_hess(self, f, y):
        """``(d log p / df, -d^2 log p / df^2)`` elementwise, by autodiff."""
        g = jax.grad(self.log_lik, argnums=0)
        h = jax.grad(g, argnums=0)
        flat_f = f.reshape(-1)
        flat_y = y.reshape(-1)
        grad = jax.vmap(g)(flat_f, flat_y).reshape(f.shape)
        hess = jax.vmap(h)(flat_f, flat_y).reshape(f.shape)
        return grad, -hess


class PoissonLikelihood(Likelihood):
    """Counts with the log link: ``y | f ~ Poisson(exp(f))``.

    ``log p = y f - exp(f) - log y!`` — the ``log y!`` term is constant in
    ``f`` and is dropped (it cancels in every gradient and in model
    comparison across hyperparameters, exactly like the reference drops
    its constant, GPR.scala:60-61).  ``W = exp(f) > 0``: log-concave.
    """

    def log_lik(self, f, y):
        return y * f - jnp.exp(f)

    def grad_hess(self, f, y):
        # closed forms (cheaper than vmapped autodiff, same values)
        ef = jnp.exp(f)
        return y - ef, ef


class BernoulliLikelihood(Likelihood):
    """{0,1} labels with the sigmoid link — the reference classifier's
    likelihood (GPClf.scala:92-97), expressed as one ``log_lik`` for the
    generic core.  Exists primarily as a cross-validation oracle: the
    generic autodiff path and the hand-assembled Algorithm-5.1 path of
    :mod:`spark_gp_tpu.models.laplace` must produce identical objectives
    and gradients (tests/test_poisson.py), each certifying the other.
    """

    def log_lik(self, f, y):
        # log sigmoid((2y - 1) f): the stable joint form for y in {0, 1}
        return jax.nn.log_sigmoid((2.0 * y - 1.0) * f)

    def grad_hess(self, f, y):
        pi = jax.nn.sigmoid(f)
        return y - pi, pi * (1.0 - pi)


class BinomialLikelihood(Likelihood):
    """``y`` successes out of ``trials`` attempts, logit link:
    ``y | f ~ Binomial(trials, sigmoid(f))``.

    ``log p = y f - trials * log(1 + exp(f))`` (the ``log C(trials, y)``
    term is constant in ``f`` and dropped).  ``W = trials * pi (1 - pi)``:
    log-concave.  ``trials`` is a spec constant (aggregated binary data
    with a common group size); per-point trial counts would need a
    two-channel target and are out of scope.
    """

    def __init__(self, trials: int) -> None:
        trials = int(trials)
        if trials < 1:
            raise ValueError("trials must be a positive integer")
        self.trials = trials

    def _spec(self) -> tuple:
        return (self.trials,)

    def log_lik(self, f, y):
        # -trials * log(1 + e^f) = trials * log_sigmoid(-f), the stable form
        return y * f + self.trials * jax.nn.log_sigmoid(-f)

    def grad_hess(self, f, y):
        pi = jax.nn.sigmoid(f)
        return y - self.trials * pi, self.trials * pi * (1.0 - pi)


class NegativeBinomialLikelihood(Likelihood):
    """Overdispersed counts, log link: ``y | f ~ NB(mean = exp(f),
    dispersion = r)`` (NB2: ``Var = mean + mean^2 / r``).

    ``log p = y f - (y + r) log(r + e^f) + const(y, r)`` — every term
    constant in ``f`` is dropped (same convention as the other
    likelihoods).  Stable form via ``sigmoid``/``softplus`` shifted by
    ``log r``: ``log p = y f - (y + r) softplus(f - log r)``,
    ``W = (y + r) s (1 - s)`` with ``s = sigmoid(f - log r)`` — strictly
    positive, so the likelihood is log-concave and the ``B = I + sqrt(W) K
    sqrt(W)`` Laplace form applies.  As ``r -> inf`` this converges to
    :class:`PoissonLikelihood` (tested).
    """

    def __init__(self, dispersion: float) -> None:
        dispersion = float(dispersion)
        if not dispersion > 0:
            raise ValueError("dispersion must be positive")
        self.dispersion = dispersion

    def _spec(self) -> tuple:
        return (self.dispersion,)

    def log_lik(self, f, y):
        r = self.dispersion
        return y * f - (y + r) * jax.nn.softplus(f - jnp.log(r))

    def grad_hess(self, f, y):
        r = self.dispersion
        s = jax.nn.sigmoid(f - jnp.log(r))
        return y - (y + r) * s, (y + r) * s * (1.0 - s)


class _GenNewtonState(NamedTuple):
    f: jax.Array  # [E, s]
    old_obj: jax.Array  # [E]
    new_obj: jax.Array  # [E]
    step: jax.Array  # [E]


class _GenStep(NamedTuple):
    a: jax.Array  # [E, s]
    f_new: jax.Array  # [E, s]
    half_logdet_b: jax.Array  # [E]


def _gen_newton_quantities(lik: Likelihood, kmat, y, mask, f) -> _GenStep:
    """One Newton step from latent ``f`` for the ``[E, s]`` stack, plus the
    half-log-determinant of ``B = I + sqrt(W) K sqrt(W)`` at ``f``.

    Same stable form as the binary path (laplace.py:117-122):
    ``a = b - sqrt(W) B^-1 sqrt(W) K b`` with ``b = W f + grad log p``,
    ``f' = K a``.  Fully differentiable; masked rows are inert (sqrt(W)
    is masked, so B has unit padded rows).
    """
    from spark_gp_tpu.ops.linalg import chol_logdet, chol_solve, cholesky

    grad_log_p, w = lik.grad_hess(f, y)
    w = w * mask
    grad_log_p = grad_log_p * mask
    # double-where sqrt guard (see laplace_mc.py): W can underflow to 0
    # where the likelihood saturates, and sqrt has an infinite derivative
    # at 0 on this autodiff gradient path
    w_pos = w > 0.0
    sqw = jnp.where(w_pos, jnp.sqrt(jnp.where(w_pos, w, 1.0)), 0.0)

    eye = jnp.eye(kmat.shape[-1], dtype=kmat.dtype)
    b_mats = eye[None] + sqw[:, :, None] * kmat * sqw[:, None, :]
    b_vec = w * f + grad_log_p
    kb = jnp.einsum("eij,ej->ei", kmat, b_vec)
    if it_ops.resolve_solver(kmat.shape[-1]) in ("iterative", "matfree"):
        # (matfree resolves here too: the Laplace B systems are
        # materialized-operator solves — the matrix-free memory win is
        # marginal-NLL-scoped, and regressing to the batched Cholesky
        # under GP_SOLVER_LANE=matfree would be strictly worse)
        # the CG/Lanczos solver lane (ops/iterative.py): the B solve rides
        # preconditioned multi-RHS CG under custom_linear_solve (implicit
        # differentiation — this function is autodiffed by the
        # Newton-fixed-point gradient) and log|B| the preconditioned SLQ
        # estimate with the Hutchinson surrogate gradient.  O(t s^2)
        # matmul work, no full factorization anywhere; the one rank-k
        # preconditioner build is shared by both consumers.
        precond = it_ops.build_spd_preconditioner(b_mats)
        half_logdet_b = 0.5 * it_ops.spd_logdet(b_mats, precond=precond)
        a = b_vec - sqw * it_ops.spd_solve(
            b_mats, sqw * kb, precond=precond
        )
    else:
        chol_l = cholesky(b_mats)
        half_logdet_b = 0.5 * chol_logdet(chol_l)
        a = b_vec - sqw * chol_solve(chol_l, sqw * kb)
    f_new = jnp.einsum("eij,ej->ei", kmat, a)
    return _GenStep(a=a, f_new=f_new, half_logdet_b=half_logdet_b)


def _gen_objective(lik: Likelihood, a, f_new, y, mask):
    """``-a^T f / 2 + sum_i mask_i log p(y_i | f_i)`` per expert."""
    flat_f = f_new.reshape(-1)
    flat_y = y.reshape(-1)
    ll = jax.vmap(lik.log_lik)(flat_f, flat_y).reshape(f_new.shape)
    return -0.5 * jnp.sum(a * f_new, axis=-1) + jnp.sum(ll * mask, axis=-1)


def laplace_generic_mode(lik: Likelihood, kmat, y, mask, f0, tol):
    """Mode Newton loop with per-expert step halving — the binary module's
    termination/acceptance semantics (laplace.py:133-185) for any
    likelihood.  Returns ``(f_modes [E, s], final objective [E])``; not
    differentiated."""
    dtype = kmat.dtype
    zero = jnp.zeros((), dtype=dtype) + 0.0 * jnp.sum(f0, axis=-1)
    init = _GenNewtonState(
        f=f0,
        old_obj=zero - jnp.inf,
        new_obj=zero + jnp.finfo(dtype).min,
        step=zero + 1.0,
    )

    def running(state):
        return jnp.logical_and(
            jnp.abs(state.old_obj - state.new_obj) > tol, state.step > tol
        )

    def cond(state):
        return jnp.any(running(state))

    def body(state):
        stp = _gen_newton_quantities(lik, kmat, y, mask, state.f)
        f_cand = (1.0 - state.step)[:, None] * state.f + state.step[
            :, None
        ] * stp.f_new
        obj_cand = _gen_objective(lik, stp.a, f_cand, y, mask)
        accept = obj_cand > state.old_obj
        run = running(state)
        upd = run & accept
        return _GenNewtonState(
            f=jnp.where(upd[:, None], f_cand, state.f),
            old_obj=jnp.where(upd, state.new_obj, state.old_obj),
            new_obj=jnp.where(upd, obj_cand, state.new_obj),
            step=jnp.where(run & ~accept, state.step / 2.0, state.step),
        )

    final = jax.lax.while_loop(cond, body, init)
    return final.f, final.new_obj


def _gram_stack(kernel: Kernel, theta, x, mask, cache=None):
    """Thin alias of :func:`kernels.base.masked_gram_stack` kept for the
    test oracles that build expert gram stacks directly."""
    return masked_gram_stack(kernel, theta, x, mask, cache)


def batched_neg_logz_generic(
    lik: Likelihood, kernel: Kernel, tol, theta, x, y, mask, f0,
    cache=None, weights=None,
):
    """Summed ``-log Z`` with gradient over the local stack for any
    likelihood; returns ``(nll, grad, f_modes)``.  Newton-fixed-point
    gradient (module docstring): stop-gradient mode, one differentiable
    step, determinant re-evaluated at the differentiable iterate.
    ``cache`` is the theta-invariant gram cache (kernels/base.py): the
    differentiated gram build then runs through ``gram_from_cache`` and
    autodiff never traverses the distance contraction.  ``weights`` is
    the aggregation plane's per-expert ``[E]`` vector
    (``models/aggregation.py``); ``None`` keeps the unweighted sum
    bit-for-bit."""
    from spark_gp_tpu.models.aggregation import weighted_expert_sum

    def nll(theta_):
        kmat = masked_gram_stack(kernel, theta_, x, mask, cache)
        f_hat = jax.lax.stop_gradient(
            laplace_generic_mode(
                lik, jax.lax.stop_gradient(kmat), y, mask, f0, tol
            )[0]
        )
        stp = _gen_newton_quantities(lik, kmat, y, mask, f_hat)
        det = _gen_newton_quantities(lik, kmat, y, mask, stp.f_new)
        log_z = (
            _gen_objective(lik, stp.a, stp.f_new, y, mask)
            - det.half_logdet_b
        )
        return -weighted_expert_sum(log_z, weights), f_hat

    (value, f_hat), grad = jax.value_and_grad(nll, has_aux=True)(theta)
    return value, grad, f_hat


@partial(jax.jit, static_argnums=(0, 1, 2), static_argnames=("solver",))
def _generic_vag_impl(
    lik, kernel, tol, theta, x, y, mask, f0, cache=None, *, solver=None
):
    with it_ops.solver_lane_scope(solver):
        return batched_neg_logz_generic(
            lik, kernel, tol, theta, x, y, mask, f0, cache
        )


def make_generic_objective(
    lik: Likelihood, kernel: Kernel, x, y, mask, tol, cache=None
):
    """Single-device jitted ``(theta, f0) -> (nll, grad, f_modes)``.
    ``cache`` is the theta-invariant gram cache (kernels/base.py),
    device-resident across the host optimizer's evaluations."""

    def obj(theta, f0):
        theta = jnp.asarray(theta, dtype=x.dtype)
        # measured flops/bytes per evaluation (obs/cost.py, GP_XLA_COST)
        return obs_cost.observed_call(
            "fit.host_objective", _generic_vag_impl,
            lik, kernel, float(tol), theta, x, y, mask, f0, cache,
            solver=it_ops.solver_jit_key(),
        )

    return obj


def _make_sharded_generic_logz(
    lik: Likelihood, kernel: Kernel, tol, mesh, cache_specs=(),
    cache_of=lambda maybe_cache: None,
):
    from jax.sharding import PartitionSpec as P

    from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

    in_specs = (
        P(), P(EXPERT_AXIS),
        P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
    ) + tuple(cache_specs)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P(), P(EXPERT_AXIS)),
    )
    def core(theta, f_carry, x_, y_, mask_, *maybe_cache):
        cache = cache_of(maybe_cache)
        value, grad, f_new = batched_neg_logz_generic(
            lik, kernel, tol, theta, x_, y_, mask_, f_carry, cache
        )
        return (
            jax.lax.psum(value, EXPERT_AXIS),
            jax.lax.psum(grad, EXPERT_AXIS),
            f_new,
        )

    return core


@partial(jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",))
def _sharded_generic_vag_impl(
    lik, kernel, tol, mesh, theta, x, y, mask, f0, cache=None, *, solver=None
):
    from spark_gp_tpu.parallel.mesh import sharded_cache_operand

    with it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        core = _make_sharded_generic_logz(
            lik, kernel, tol, mesh, cache_specs, cache_of
        )
        return core(theta, f0, x, y, mask, *cache_args)


def make_sharded_generic_objective(
    lik: Likelihood, kernel: Kernel, x, y, mask, tol, mesh, cache=None
):
    def obj(theta, f0):
        theta = jnp.asarray(theta, dtype=x.dtype)
        return _sharded_generic_vag_impl(
            lik, kernel, float(tol), mesh, theta, x, y, mask, f0, cache,
            solver=it_ops.solver_jit_key(),
        )

    return obj


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",)
)
def fit_generic_device(
    lik: Likelihood, kernel: Kernel, tol, log_space,
    theta0, lower, upper, x, y, mask, max_iter, cache=None, *, solver=None,
):
    """Single-chip on-device fit for any likelihood: the latent warm-start
    stack rides as the optimizer's auxiliary carry (laplace.py pattern).
    Returns ``(theta, f_latents, nll, n_iter, n_fev, stalled)``.  ``cache``
    sits outside the L-BFGS while_loop and serves every evaluation."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )

    with it_ops.solver_lane_scope(solver):
        def vag(theta, f_carry):
            value, grad, f_new = batched_neg_logz_generic(
                lik, kernel, tol, theta, x, y, mask, f_carry, cache
            )
            return value, grad, f_new

        if log_space:
            vag, theta0, lower, upper, from_u = log_reparam(
                vag, theta0, lower, upper
            )
        else:
            from_u = lambda t: t

        f0 = jnp.zeros_like(y)
        theta, f, f_final, n_iter, n_fev, stalled = lbfgs_minimize_device(
            vag, theta0, lower, upper, f0, max_iter=max_iter, tol=tol
        )
        return from_u(theta), f_final, f, n_iter, n_fev, stalled


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4), static_argnames=("solver",)
)
def fit_generic_device_sharded(
    lik: Likelihood, kernel: Kernel, tol, mesh, log_space,
    theta0, lower, upper, x, y, mask, max_iter, cache=None, *, solver=None,
):
    """Multi-chip on-device fit for any likelihood inside one shard_map:
    latent stacks stay device-resident and sharded for the entire
    optimization (the generic-likelihood counterpart of
    laplace.fit_gpc_device_sharded — one skeleton for every estimator,
    GaussianProcessCommons.scala:66-92)."""
    from jax.sharding import PartitionSpec as P

    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device,
        log_reparam,
    )
    from spark_gp_tpu.parallel.mesh import EXPERT_AXIS
    from spark_gp_tpu.utils.compat import whole_loop_shard_map_supported

    if not whole_loop_shard_map_supported():
        # old-jax compat (utils/compat.py): the L-BFGS while_loop inside
        # shard_map wedges the compile; GSPMD partitions the same stack
        return fit_generic_device(
            lik, kernel, tol, log_space, theta0, lower, upper, x, y, mask,
            max_iter, cache, solver=solver,
        )

    from spark_gp_tpu.parallel.mesh import sharded_cache_operand

    with it_ops.solver_lane_scope(solver):
        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        in_specs = (
            P(), P(), P(),
            P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
            P(),
        ) + cache_specs

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(), P(EXPERT_AXIS), P(), P(), P(), P()),
        )
        def run(theta0_, lower_, upper_, x_, y_, mask_, max_iter_,
                *maybe_cache):
            local_cache = cache_of(maybe_cache)

            def vag(theta, f_carry):
                value, grad, f_new = batched_neg_logz_generic(
                    lik, kernel, tol, theta, x_, y_, mask_, f_carry,
                    local_cache,
                )
                return (
                    jax.lax.psum(value, EXPERT_AXIS),
                    jax.lax.psum(grad, EXPERT_AXIS),
                    f_new,
                )

            if log_space:
                vag, t0, lo, hi, from_u = log_reparam(
                    vag, theta0_, lower_, upper_
                )
            else:
                vag, t0, lo, hi, from_u = (
                    vag, theta0_, lower_, upper_, (lambda t: t)
                )

            f0 = jnp.zeros_like(y_)
            theta, f, f_final, n_iter, n_fev, stalled = lbfgs_minimize_device(
                vag, t0, lo, hi, f0, max_iter=max_iter_, tol=tol
            )
            return from_u(theta), f_final, f, n_iter, n_fev, stalled

        return run(theta0, lower, upper, x, y, mask, max_iter, *cache_args)


# --- segmented device fit: checkpoint/resume (laplace.py counterpart) ------


def _generic_segment_vag(lik: Likelihood, kernel: Kernel, tol, mesh, log_space,
                         x, y, mask, cache=None):
    from spark_gp_tpu.optimize.lbfgs_device import log_transform_vag

    if mesh is None:

        def base(theta, f_carry):
            return batched_neg_logz_generic(
                lik, kernel, tol, theta, x, y, mask, f_carry, cache
            )

    else:
        from spark_gp_tpu.parallel.mesh import sharded_cache_operand

        cache_specs, cache_args, cache_of = sharded_cache_operand(cache)
        core = _make_sharded_generic_logz(
            lik, kernel, tol, mesh, cache_specs, cache_of
        )

        def base(theta, f_carry):
            return core(theta, f_carry, x, y, mask, *cache_args)

    return log_transform_vag(base) if log_space else base


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4), static_argnames=("solver",)
)
def generic_device_segment_init(
    lik: Likelihood, kernel: Kernel, tol, mesh, log_space,
    theta0, lower, upper, x, y, mask, cache=None, *, solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import lbfgs_init_state

    with it_ops.solver_lane_scope(solver):
        vag = _generic_segment_vag(
            lik, kernel, tol, mesh, log_space, x, y, mask, cache
        )
        t0 = jnp.log(theta0) if log_space else theta0
        return lbfgs_init_state(vag, t0, jnp.zeros_like(y))


# the L-BFGS state carry is donated — consumed once per segment and
# replaced by the return value (optimize/lbfgs_device.lbfgs_state_donation)
@partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4), static_argnames=("solver",),
    donate_argnums=lbfgs_state_donation(5),
)
def generic_device_segment_run(
    lik: Likelihood, kernel: Kernel, tol, mesh, log_space,
    state, lower, upper, x, y, mask, iter_limit, cache=None, *, solver=None,
):
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_run_segment,
        log_transform_bounds,
    )

    with it_ops.solver_lane_scope(solver):
        vag = _generic_segment_vag(
            lik, kernel, tol, mesh, log_space, x, y, mask, cache
        )
        lo, hi = (
            log_transform_bounds(lower, upper) if log_space
            else (lower, upper)
        )
        return lbfgs_run_segment(vag, state, lo, hi, iter_limit, tol)


def fit_generic_device_checkpointed(
    lik: Likelihood, kernel: Kernel, tol, mesh, log_space, theta0, lower,
    upper, x, y, mask, max_iter: int, chunk: int, saver, cache=None,
):
    """Segmented on-device generic-likelihood fit with state persistence —
    see laplace.fit_gpc_device_checkpointed.  The aux carry is the latent
    warm-start stack, so a resume continues from the settled modes.
    Returns ``(theta, f_latents, nll, n_iter, n_fev, stalled)``.  The
    gram cache rides every segment dispatch (derived state — never part
    of the persisted checkpoint)."""
    from spark_gp_tpu.utils.checkpoint import run_segmented, segment_meta

    meta = segment_meta(
        f"generic:{type(lik).__name__}{lik._spec()}", kernel, tol, log_space,
        theta0, x, y, mask,
    )
    solver = it_ops.solver_jit_key()

    def init(theta0_, lower_, upper_, x_, y_, mask_):
        return generic_device_segment_init(
            lik, kernel, float(tol), mesh, log_space, theta0_, lower_,
            upper_, x_, y_, mask_, cache, solver=solver,
        )

    def run(state, limit):
        return generic_device_segment_run(
            lik, kernel, float(tol), mesh, log_space, state, lower, upper,
            x, y, mask, limit, cache, solver=solver,
        )

    theta, state = run_segmented(
        init, run, saver, meta, (theta0, lower, upper, x, y, mask),
        max_iter, chunk, log_space,
    )
    return theta, state.aux, state.f, state.n_iter, state.n_fev, state.stalled


@partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("solver",)
)
def fit_generic_device_multistart(
    lik: Likelihood, kernel: Kernel, tol, log_space,
    theta0_batch, lower, upper, x, y, mask, max_iter, cache=None, *,
    solver=None,
):
    """Multi-start single-chip fit for any likelihood: R restarts as ONE
    vmapped device program; one gram cache broadcasts to every lane.
    Returns ``(theta_best, f_latents_best, nll_best, n_iter, n_fev,
    stalled, f_all [R], best)``."""
    from spark_gp_tpu.optimize.lbfgs_device import multistart_minimize

    with it_ops.solver_lane_scope(solver):
        def vag(theta, f_carry):
            value, grad, f_new = batched_neg_logz_generic(
                lik, kernel, tol, theta, x, y, mask, f_carry, cache
            )
            return value, grad, f_new

        theta, f_final, f, n_iter, n_fev, stalled, f_all, best = (
            multistart_minimize(
                vag, log_space, theta0_batch, lower, upper,
                jnp.zeros_like(y), max_iter, tol,
            )
        )
        return theta, f_final, f, n_iter, n_fev, stalled, f_all, best
