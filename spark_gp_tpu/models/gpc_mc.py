"""Native multiclass GP classification (softmax Laplace) — capability
beyond the reference.

akopich/spark-gp is binary-only (GaussianProcessClassifier.scala:32,
numClasses = 2 at :151); its own Iris example reaches 3 classes through
Spark's OneVsRest meta-estimator (Iris.scala:26-27), i.e. C independent
binary problems with uncalibrated score comparison.  This estimator fits
ONE model with C coupled latent functions under the softmax link
(R&W ch. 3.5, math in :mod:`spark_gp_tpu.models.laplace_mc`), so

* probabilities are jointly calibrated (they sum to 1 by construction,
  not by post-hoc normalization);
* training cost is one fit, not C — the per-class factorizations batch
  into the same fused ``[E * C, s, s]`` device pass;
* the PPA model shares one active set, one U1 statistic and one magic
  matrix across classes; only the per-class magic vectors differ (the
  rank-generic ``ppa.kmn_stats_jit`` / ``ppa.magic_solve`` with ``[m, C]``
  right-hand sides).

The training skeleton mirrors the binary classifier (gpc.py): group
experts, L-BFGS the shared-kernel hyperparameters against the summed
-log Z with the latent ``[E, s, C]`` stack warm-started across
evaluations, settle the latents at the optimum, then build the projected
process over the per-class latent targets.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.models import ppa
from spark_gp_tpu.models.common import GaussianProcessCommons
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.models.laplace_mc import (
    fit_gpc_mc_device,
    make_mc_objective,
    make_sharded_mc_objective,
)
from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor
from spark_gp_tpu.utils.instrumentation import Instrumentation, phase_sync


@jax.jit
def _max_label(y, mask):
    # module-level jit: runs as a program with a replicated scalar output
    # (multi-host global arrays reject eager reductions — gpc._labels_are_01
    # rationale)
    return jnp.max(y * mask)


@jax.jit
def _labels_valid(y, mask, n_classes):
    ym = y * mask
    return (
        jnp.all(jnp.floor(ym) == ym)
        & jnp.all(ym >= 0.0)
        & jnp.all(ym < n_classes)
    )


@partial(jax.jit, static_argnums=2)
def _one_hot_masked(y, mask, n_classes):
    """One-hot targets on the (possibly sharded) expert stack; padded rows
    all-zero.  A program, so the output inherits the stack's sharding."""
    return (
        jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=mask.dtype)
        * mask[..., None]
    )


@jax.jit
def _margin_targets(latents, mask):
    """Scalar per-point targets for stack-based active-set providers: the
    strongest class latent (a heuristic — the reference defines provider
    scoring only for scalar targets)."""
    return jnp.max(latents, axis=-1) * mask


class GaussianProcessMulticlassClassifier(GaussianProcessCommons):
    """C-class GP classifier (softmax Laplace) with the reference's fluent
    parameter API.  Labels are integers ``0 .. C-1``; C is inferred from
    the training labels."""

    def fit(self, x: np.ndarray, y: np.ndarray) -> "GaussianProcessMulticlassModel":
        instr = Instrumentation(name="GaussianProcessMulticlassClassifier")
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y)
        if x.ndim != 2:
            raise ValueError(f"x must be [N, p], got shape {x.shape}")
        if y.shape != (x.shape[0],):
            raise ValueError(f"y must be [N], got shape {y.shape}")
        y_int = np.asarray(y, dtype=np.int64)
        if not np.all(y_int == np.asarray(y, dtype=np.float64)):
            raise ValueError("labels must be integers 0 .. C-1")
        if y_int.min() < 0:
            raise ValueError("labels must be integers 0 .. C-1")
        n_classes = int(y_int.max()) + 1
        if n_classes < 2:
            raise ValueError("need at least 2 classes")
        # the observation shell wraps the WHOLE post-validation body (the
        # gpr.py convention): grouping/screen phases — and any screen-time
        # quarantine events — land inside the fit's root span
        return self._observed_fit(
            instr, lambda: self._fit_body(instr, x, y_int, n_classes)
        )

    def _fit_body(self, instr, x, y_int, n_classes) -> "GaussianProcessMulticlassModel":
        with instr.phase("group_experts"):
            data = self._group_screened(instr, x, y_int.astype(np.float64))
        instr.log_metric("num_experts", data.num_experts)
        instr.log_metric("num_classes", n_classes)

        y1h = _one_hot_masked(data.y, data.mask, n_classes)

        # theta-invariant gram cache, built once per fit and shared by
        # every restart (common._gram_cache)
        cache = self._gram_cache(instr, data)

        def fit_once(kernel, instr_r):
            return self._fit_from_stack(
                instr_r, kernel, data, y1h, x, cache=cache
            )

        def attempt():
            if self._use_batched_multistart():
                return self._fit_device_multistart(instr, data, y1h, x, cache)
            return self._fit_with_restarts(instr, fit_once)

        from spark_gp_tpu.resilience import fallback

        # degradation ladder around the complete attempt (gpr.py wrap)
        return fallback.run_fit_ladder(self, instr, attempt, data=data)

    def _fit_device_multistart(
        self, instr, data, y1h, x, cache=None
    ) -> "GaussianProcessMulticlassModel":
        """Batched on-device multi-start: R starting points in one vmapped
        softmax-Laplace + L-BFGS dispatch; one PPA build for the winner."""
        from spark_gp_tpu.models.laplace_mc import fit_gpc_mc_device_multistart
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            kernel = self._get_kernel()
            dtype = data.x.dtype
            theta_batch = jnp.asarray(
                self._restart_theta_batch(kernel), dtype=dtype
            )
            lower, upper = kernel.bounds()
            log_space = self._use_log_space(kernel)
            instr.log_info(
                "Optimising the kernel hyperparameters "
                f"(on-device, {self._num_restarts} batched restarts)"
            )
            with instr.phase("optimize_hypers"):
                theta, f_final, nll, n_iter, n_fev, stalled, f_all, best = (
                    fit_gpc_mc_device_multistart(
                        kernel, float(self._tol), log_space, theta_batch,
                        jnp.asarray(lower, dtype=dtype),
                        jnp.asarray(upper, dtype=dtype),
                        data.x, y1h, data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32),
                        cache, solver=it_ops.solver_jit_key(),
                    )
                )
                phase_sync(theta, nll)
            theta_host = np.asarray(theta, dtype=np.float64)
            self._log_device_optimizer_result(
                instr, kernel, theta_host, nll, n_iter, n_fev, stalled
            )
            instr.log_metric("best_restart", int(best))
            self._report_multistart_nlls(
                instr, {"restart_nlls": np.asarray(f_all)}
            )
            latents = f_final * data.mask[..., None]
            raw = self._projected_process_multi(
                instr, kernel, theta_host, x, data, latents
            )
        instr.log_success()
        model = GaussianProcessMulticlassModel(raw)
        model.instr = instr
        return model

    def fit_distributed(
        self,
        data,
        n_classes: Optional[int] = None,
        active_set: Optional[np.ndarray] = None,
    ) -> "GaussianProcessMulticlassModel":
        """Multi-host multiclass fit from a pre-sharded expert stack.

        The multiclass counterpart of
        :meth:`GaussianProcessClassifier.fit_distributed`: ``data`` is a
        globally-sharded ``ExpertData`` of integer labels ``0 .. C-1``
        (:func:`...distributed.distribute_global_experts`); the sharded
        softmax-Laplace + L-BFGS loop keeps the ``[E, s, C]`` latent
        stacks device-resident, and the active-set provider selects from
        the sharded stack over the max-class latent margin.  ``n_classes``
        may be passed explicitly (required when this process's shard might
        not contain every class); by default it is computed with one
        device reduction over the global labels.
        """
        def prepare(instr, active64, data):
            n_cls = n_classes
            if n_cls is None:
                n_cls = int(np.asarray(_max_label(data.y, data.mask))) + 1
                dcn = getattr(self, "_dcn_ctx", None)
                if dcn is not None:
                    # DCN-fallback: the reduction above only saw the LOCAL
                    # shard — agree on max(classes) across hosts, or a
                    # shard missing the top class trains a narrower head
                    parts = dcn.allgather_arrays(
                        "num_classes", np.asarray([n_cls], dtype=np.int64)
                    )
                    n_cls = max(int(p[0][0]) for p in parts)
            if n_cls < 2:
                raise ValueError("need at least 2 classes")
            if not bool(_labels_valid(data.y, data.mask, float(n_cls))):
                raise ValueError("labels must be integers 0 .. C-1")
            instr.log_metric("num_classes", n_cls)
            y1h = _one_hot_masked(data.y, data.mask, n_cls)

            cache = self._gram_cache(instr, data)

            def fit_once(kernel, instr_r):
                return self._fit_from_stack(
                    instr_r, kernel, data, y1h, None,
                    active_override=active64, cache=cache,
                )

            return fit_once

        return self._run_fit_distributed(
            "GaussianProcessMulticlassClassifier", data, active_set, prepare
        )

    def _fit_from_stack(
        self, instr, kernel, data, y1h, x, active_override=None, cache=None
    ) -> "GaussianProcessMulticlassModel":
        """Shared optimize → settle latents → PPA tail of ``fit`` and
        ``fit_distributed`` (the gpc.py:_fit_from_stack pattern; ``x is
        None`` means distributed mode)."""
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            if self._resolved_optimizer() == "device":
                theta_opt, f_final = self._fit_device(
                    instr, kernel, data, y1h, cache
                )
            else:
                theta_opt, f_final = self._fit_host(
                    instr, kernel, data, y1h, cache
                )

            latents = f_final * data.mask[..., None]  # [E, s, C]
            raw = self._projected_process_multi(
                instr, kernel, theta_opt, x, data, latents,
                active_override=active_override,
            )
        instr.log_success()
        model = GaussianProcessMulticlassModel(raw)
        model.instr = instr
        return model

    def _fit_host(self, instr, kernel, data, y1h, cache=None):
        """Host-driven L-BFGS-B over the jitted (possibly sharded)
        multiclass objective (shared driver: _optimize_latent_host)."""
        # ladder host_f64 rung: f64 stack + targets, cache dropped (no-op
        # on every other path — common._host_f64_operands gates itself)
        data, (y1h,), cache = self._host_f64_operands(data, (y1h,), cache)
        if self._mesh is not None:
            objective = make_sharded_mc_objective(
                kernel, data.x, y1h, data.mask, self._tol, self._mesh, cache
            )
        else:
            objective = make_mc_objective(
                kernel, data.x, y1h, data.mask, self._tol, cache
            )
        return self._optimize_latent_host(
            instr, kernel, objective, jnp.zeros_like(y1h)
        )

    def _fit_device(self, instr, kernel, data, y1h, cache=None):
        """On-device fit: one-dispatch single-chip / mesh-sharded, or the
        segmented checkpointable variant when ``setCheckpointDir`` is set
        (the same routing as the binary classifier, gpc.py:_fit_device)."""
        from spark_gp_tpu.models.laplace_mc import (
            fit_gpc_mc_device_checkpointed,
            fit_gpc_mc_device_sharded,
        )

        dtype = data.x.dtype
        theta0 = jnp.asarray(kernel.init_theta(), dtype=dtype)
        lower, upper = kernel.bounds()
        lower = jnp.asarray(lower, dtype=dtype)
        upper = jnp.asarray(upper, dtype=dtype)
        log_space = self._use_log_space(kernel)
        instr.log_info("Optimising the kernel hyperparameters (on-device)")
        from spark_gp_tpu.resilience import chaos

        # chaos choke point for staged execution faults (fallback ladder)
        # + the memory-budget allocator model (memplan/chaos)
        chaos.maybe_injected_failure(
            self._device_fit_op(), nbytes=self._dispatch_raw_bytes(data)
        )
        with instr.phase("optimize_hypers"):
            if self._checkpoint_dir is not None or self._fallback_segmented():
                saver, chunk = self._segment_saver_and_chunk("gpc_mc", data)
                theta, f_final, nll, n_iter, n_fev, stalled = (
                    fit_gpc_mc_device_checkpointed(
                        kernel, float(self._tol), self._mesh, log_space,
                        theta0, lower, upper, data.x, y1h, data.mask,
                        self._max_iter, chunk, saver, cache,
                    )
                )
            elif self._mesh is not None:
                theta, f_final, nll, n_iter, n_fev, stalled = (
                    fit_gpc_mc_device_sharded(
                        kernel, float(self._tol), self._mesh, log_space,
                        theta0, lower, upper, data.x, y1h, data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32),
                        cache, solver=it_ops.solver_jit_key(),
                    )
                )
            else:
                from spark_gp_tpu.obs import cost as obs_cost

                # measured cost of the one-dispatch program (obs/cost.py)
                theta, f_final, nll, n_iter, n_fev, stalled = (
                    obs_cost.observed_call(
                        "fit.device", fit_gpc_mc_device,
                        kernel, float(self._tol), log_space, theta0, lower,
                        upper, data.x, y1h, data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32), cache,
                        solver=it_ops.solver_jit_key(),
                    )
                )
            phase_sync(theta, nll)
        theta_host = np.asarray(theta, dtype=np.float64)
        self._log_device_optimizer_result(
            instr, kernel, theta_host, nll, n_iter, n_fev, stalled
        )
        return theta_host, f_final

    def _projected_process_multi(
        self, instr, kernel, theta_opt, x, data, latents,
        active_override: Optional[np.ndarray] = None,
    ) -> ProjectedProcessRawPredictor:
        """Active set → shared (U1, per-class U2) → multi-RHS magic solve
        (the multiclass tail of GaussianProcessCommons._projected_process;
        the per-class latent stacks substitute for y, GPClf.scala:62-65).
        Providers that score targets (greedy Seeger) see the strongest
        latent (max over classes) — a heuristic, since the reference
        defines greedy selection only for scalar targets.  ``x is None``
        means distributed mode: no host holds the rows, so the provider
        selects from the sharded stack (``from_stack``) over the margin
        targets."""
        from spark_gp_tpu.parallel.experts import (
            ExpertData,
            num_experts_for,
            ungroup,
        )

        with instr.phase("active_set"):
            provider = self._active_set_provider
            if active_override is not None:
                active = np.asarray(active_override, dtype=np.float64)
            elif x is None:
                sdata = ExpertData(
                    x=data.x,
                    y=_margin_targets(latents, data.mask),
                    mask=data.mask,
                )
                active = self._dcn_safe_provider(provider).from_stack(
                    self._active_set_size, sdata, kernel,
                    np.asarray(theta_opt, dtype=np.float64), self._seed,
                    self._mesh,
                )
            elif getattr(provider, "uses_fit_outputs", True):
                x_prov, n_orig, row_filter = self._provider_rows_filter(x)
                e_real = num_experts_for(n_orig, self._dataset_size_for_expert)
                margin = np.asarray(jnp.max(latents, axis=-1))[:e_real]
                targets = row_filter(ungroup(margin, n_orig))
                active = provider(
                    self._active_set_size, x_prov, targets, kernel,
                    np.asarray(theta_opt, dtype=np.float64), self._seed,
                )
            else:
                x_prov, _, _ = self._provider_rows_filter(x)
                active = provider(
                    self._active_set_size, x_prov, None, kernel, None,
                    self._seed,
                )
        active64 = np.asarray(active, dtype=np.float64)

        # f64 statistics for the same conditioning reasons as the
        # single-target path (common.py:_projected_process); sharded over
        # the mesh when one is set (experts sharded, one psum of
        # (U1, U2 [m, C]) over ICI)
        with instr.phase("kmn_stats"), jax.enable_x64():
            args = (
                jnp.asarray(np.asarray(theta_opt, np.float64)),
                jnp.asarray(active64),
                data.x.astype(jnp.float64),
                latents.astype(jnp.float64),
                data.mask.astype(jnp.float64),
            )
            if self._mesh is None:
                u1, u2 = ppa.kmn_stats_jit(kernel, *args)
            else:
                u1, u2 = ppa.kmn_stats_sharded(kernel, self._mesh, *args)
            u1 = np.asarray(u1)
            u2 = np.asarray(u2)
            dcn = getattr(self, "_dcn_ctx", None)
            if dcn is not None:
                # cross-host (U1, U2) sum over the KV store (the common.py
                # _projected_process convention)
                u1, u2 = dcn.allreduce_arrays("kmn_stats_mc", u1, u2)

        with instr.phase("magic_solve"):
            # the generic magic solve handles the [m, C] right-hand sides
            # on every dispatch branch (host / device / mesh-sharded)
            magic_vectors, magic_matrix = ppa.magic_solve(
                kernel, theta_opt, active64, u1, u2, mesh=self._mesh,
                with_variance=self._predictive_variance,
            )
        # the multiclass tail bypasses common._build_predictor, so the
        # solver-lane provenance stamp rides here (the other families
        # get it there)
        self._emit_solver_stats(instr, kernel, theta_opt, data)
        return ProjectedProcessRawPredictor(
            kernel=kernel,
            theta=np.asarray(theta_opt, dtype=np.float64),
            active=active64,
            magic_vector=magic_vectors,  # [m, C]
            magic_matrix=magic_matrix,
        )


class GaussianProcessMulticlassModel:
    """Softmax link over the C per-class PPA latent means.

    ``raw_predictor.magic_vector`` is ``[m, C]``; the predictive variance
    operator is shared across classes (same kernel, same active set), so
    each class latent has the same per-point variance.
    """

    def __init__(self, raw_predictor: ProjectedProcessRawPredictor):
        self.raw_predictor = raw_predictor
        self.instr: Optional[Instrumentation] = None

    @property
    def num_classes(self) -> int:
        return int(np.asarray(self.raw_predictor.magic_vector).shape[1])

    def predict_raw(self, x_test: np.ndarray) -> np.ndarray:
        """``[t, C]`` latent class scores (the softmax logits)."""
        return np.asarray(
            self.raw_predictor.predict_mean(np.asarray(x_test))
        )

    def predict_proba(
        self,
        x_test: np.ndarray,
        averaged: bool = False,
        mc_samples: int = 256,
        seed: int = 0,
    ) -> np.ndarray:
        """``[t, C]`` class probabilities.

        ``averaged=False`` (default): softmax of the MAP latents — the
        multiclass analogue of the reference's sigmoid-of-mean
        (GPClf.scala:141-149).  ``averaged=True``: Monte-Carlo expectation
        of the softmax under the latent Gaussian (softmax has no
        per-coordinate quadrature like the binary GH path; MC over the
        shared per-point variance is the standard estimator).
        """
        if not averaged:
            f = self.predict_raw(x_test)
            return np.asarray(jax.nn.softmax(jnp.asarray(f), axis=-1))
        f, var = self.raw_predictor(np.asarray(x_test))
        if var is None:
            raise ValueError(
                "model was fitted with setPredictiveVariance(False); "
                "averaged probabilities need the latent variance — use "
                "averaged=False or refit with variances enabled"
            )
        f = np.asarray(f)
        sd = np.sqrt(np.maximum(np.asarray(var), 0.0))[:, None]
        rng = np.random.default_rng(seed)
        # bounded memory at any test-set size: the [S, chunk, C] sample
        # tensor is capped like every other predict path (ppa._run)
        chunk = max(
            1,
            ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS
            // max(1, mc_samples * f.shape[1]),
        )
        out = np.empty_like(f)
        for start in range(0, f.shape[0], chunk):
            fb = f[start : start + chunk]
            sb = sd[start : start + chunk]
            eps = rng.standard_normal((mc_samples,) + fb.shape)
            probs = jax.nn.softmax(
                jnp.asarray(fb[None] + sb[None] * eps), axis=-1
            )
            out[start : start + chunk] = np.asarray(jnp.mean(probs, axis=0))
        return out

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        """Class labels ``0 .. C-1`` (argmax latent)."""
        return np.argmax(self.predict_raw(x_test), axis=-1).astype(np.float64)

    def save(self, path: str) -> None:
        from spark_gp_tpu.utils.serialization import save_model

        save_model(path, self, kind="multiclass")

    @staticmethod
    def load(path: str) -> "GaussianProcessMulticlassModel":
        from spark_gp_tpu.utils.serialization import load_model

        model = load_model(path)
        if not isinstance(model, GaussianProcessMulticlassModel):
            raise TypeError("not a multiclass model checkpoint")
        return model
