"""Gaussian Process Regression — BCM fit + PPA prediction.

Counterpart of regression/GaussianProcessRegression.scala:36-87:

* ``fit`` groups points into experts, optimizes the noise-augmented kernel's
  hyperparameters against the summed per-expert exact-GP NLL (autodiff
  gradients, L-BFGS-B with the kernel's box bounds), then builds the m-point
  Projected Process model.
* the fitted model predicts the posterior mean (``predict``) and also exposes
  the predictive variance (``predict_with_var``) which the reference computes
  and exposes via its raw predictor (GaussianProcessCommons.scala:118-126).

[1] Rasmussen & Williams, *Gaussian Processes for Machine Learning*, ch. 8.3.4.
[2] Deisenroth & Ng, *Distributed Gaussian Processes*, ICML'15.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from spark_gp_tpu.models.common import GaussianProcessCommons
from spark_gp_tpu.models.likelihood import (
    make_sharded_value_and_grad,
    make_value_and_grad,
)
from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor
from spark_gp_tpu.utils.instrumentation import Instrumentation, phase_sync


class GaussianProcessRegression(GaussianProcessCommons):
    """Estimator. Usage mirrors the reference's fluent API:

    >>> gp = (GaussianProcessRegression()
    ...       .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10))
    ...       .setDatasetSizeForExpert(100)
    ...       .setActiveSetSize(100)
    ...       .setSigma2(1e-3))
    >>> model = gp.fit(x, y)
    >>> mean = model.predict(x_test)
    """

    # keep U1/u2 on the fitted model: regression targets are the raw y, so
    # the additive statistics support model.update() (incremental data)
    _keeps_update_statistics = True

    # hyperparameter objective: the BCM marginal NLL (the reference's,
    # GPR.scala:55-68), the negative LOO log pseudo-likelihood
    # (R&W eq. 5.13 — setObjective("loo"), models/loo.py), or the Titsias
    # collapsed SGPR ELBO (setObjective("elbo"), models/sgpr.py)
    _objective = "marginal"

    def setObjective(self, objective: str) -> "GaussianProcessRegression":
        """``"marginal"`` (default), ``"loo"`` — the LOO log
        pseudo-likelihood, more robust under model misspecification
        (R&W §5.4.2) — or ``"elbo"`` — the Titsias collapsed SGPR bound
        (``models/sgpr.py``): the active set is selected up front and the
        hyperparameters train against a principled variational lower
        bound with sigma2 as the likelihood noise.  Every fit path (host,
        device, sharded, checkpointed, multi-start, distributed) honors
        the choice."""
        if objective not in ("marginal", "loo", "elbo"):
            raise ValueError(
                f"unknown objective {objective!r}; "
                "expected 'marginal', 'loo' or 'elbo'"
            )
        self._objective = objective
        return self

    def _elbo_extra(self, active, data):
        """The (active, sigma2) traced-operand tuple the ELBO objective
        consumes (likelihood.objective_fn signature note)."""
        import jax.numpy as jnp

        return (
            jnp.asarray(np.asarray(active), dtype=data.x.dtype),
            jnp.asarray(self._sigma2, dtype=data.x.dtype),
        )

    def _elbo_setup(self, instr, kernel, x, targets_fn, data, active_override):
        """ONE home for the ELBO pre-selection (used by the plain and the
        batched-multistart fit drivers): the inducing set must exist
        BEFORE training — select it at the initial theta unless supplied —
        and the (active, sigma2) operand tuple rides every evaluation.
        Returns ``(active_override, extra)``."""
        from spark_gp_tpu.models.active_set import (
            GreedilyOptimizingActiveSetProvider,
        )

        theta0 = kernel.init_theta()
        provider = self._active_set_provider
        is_greedy = provider is GreedilyOptimizingActiveSetProvider or (
            isinstance(provider, GreedilyOptimizingActiveSetProvider)
        )
        if (
            is_greedy
            and float(kernel.white_noise_var(np.asarray(theta0))) == 0.0
        ):
            # the model kernel is user kernel + sigma2*Eye, so this fires
            # only at setSigma2(0) with no kernel noise of its own
            raise ValueError(
                "setObjective('elbo') with the greedy provider needs "
                "nonzero white noise (the Seeger scores divide by it); "
                "set a nonzero sigma2, or use the random/k-means provider"
            )
        if active_override is None:
            with instr.phase("active_set"):
                active_override = self._select_active(
                    kernel, theta0, x, targets_fn, data
                )
        extra = self._elbo_extra(active_override, data)
        # the host checkpoint tag (common._checkpoint_tag) carries this:
        # two ELBO fits over different surfaces must not share state files
        self._objective_salt = self._elbo_checkpoint_salt(extra)
        return active_override, extra

    def _elbo_checkpoint_salt(self, extra) -> str:
        """Digest of the ELBO objective surface (inducing set + sigma2):
        checkpoint tags carry it so fits of DIFFERENT bounds sharing a dir
        neither resume from nor clobber each other."""
        import hashlib

        h = hashlib.sha1()
        for e in extra:
            if e is None:  # the aggregation plane's placeholder slot
                h.update(b"none")
                continue
            h.update(np.asarray(e, dtype=np.float64).tobytes())
        return h.hexdigest()[:10]

    set_objective = setObjective

    def fit(self, x: np.ndarray, y: np.ndarray) -> "GaussianProcessRegressionModel":
        instr = Instrumentation(name="GaussianProcessRegression")
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        if x.ndim != 2:
            raise ValueError(f"x must be [N, p], got shape {x.shape}")
        if y.shape != (x.shape[0],):
            raise ValueError(f"y must be [N], got shape {y.shape}")
        # the observation shell wraps the WHOLE post-validation body, so
        # the grouping/screen phases land inside the fit's root span
        return self._observed_fit(
            instr, lambda: self._fit_body(instr, x, y)
        )

    def _fit_body(self, instr, x, y) -> "GaussianProcessRegressionModel":
        with instr.phase("group_experts"):
            data = self._group_screened(instr, x, y)
        instr.log_metric("num_experts", data.num_experts)
        instr.log_metric("expert_size", data.expert_size)
        # providers sample raw host rows — hand them only finite ones
        x, y = self._screen_rows(x, y)

        def run_fit(data_r, rextra, cache):
            x_r, y_r = x, y
            if data_r is not data:
                # fit recovery rebuilt the stack: provider rows must come
                # from the QUARANTINED stack, not the raw inputs — a
                # poisoned row can be finite (catastrophic scaling) and a
                # single one in the active set re-poisons the PPA
                # statistics the quarantine just cleaned
                keep = np.asarray(data_r.mask) > 0
                x_r = np.asarray(data_r.x)[keep]
                y_r = np.asarray(data_r.y)[keep]
            if self._use_batched_multistart():
                # ALL restarts as one vmapped device program; the PPA
                # model is built once, for the winner (vs the sequential
                # driver's full-fit-per-restart)
                return self._fit_device_multistart(
                    instr, data_r, x_r, y_r, rextra, cache
                )

            # ELBO: ONE inducing set, selected at the base kernel's init
            # theta and shared by every sequential restart — matching the
            # batched path's semantics (each restart's ThetaOverrideKernel
            # has a different init theta, so per-restart selection would
            # both repeat the work and, for theta-dependent providers,
            # optimize each restart over a different surface).  Selected
            # INSIDE the attempt: a recovery retry must re-select from the
            # repaired rows, not reuse a poisoned inducing set.
            active_shared = None
            if self._objective == "elbo":
                base_kernel = self._get_kernel()
                with instr.phase("active_set"):
                    active_shared = self._select_active(
                        base_kernel, base_kernel.init_theta(), x_r,
                        lambda: y_r, data_r,
                    )

            def fit_once(kernel, instr_r):
                return self._fit_from_stack(
                    instr_r, kernel, data_r, x_r, lambda: y_r, active_shared,
                    resilience_extra=rextra, cache=cache,
                )

            return self._fit_with_restarts(instr, fit_once)

        from spark_gp_tpu.resilience import fallback

        # the degradation ladder wraps the COMPLETE attempt (expert
        # resilience included): a classified execution failure — OOM,
        # compile, exhausted numerics, guard breach — re-executes the fit
        # one rung down instead of propagating raw (GP_FALLBACK=0 restores
        # raw propagation).  ``data`` lets the memory planner pre-size
        # the starting rung against the device budget (memplan.py).
        return fallback.run_fit_ladder(
            self, instr,
            lambda: self._run_with_expert_resilience(instr, data, run_fit),
            data=data,
        )

    def loo(
        self,
        x: np.ndarray,
        y: np.ndarray,
        model: "Optional[GaussianProcessRegressionModel]" = None,
    ) -> dict:
        """Exact per-expert leave-one-out diagnostics (R&W §5.4.2).

        Evaluated at ``model``'s fitted hyperparameters when given (the
        usual post-fit model criticism: ``gp.loo(x, y, model)``), else at
        the kernel's initial theta.  Uses this estimator's expert grouping
        — the same conditioning structure the training objective sums
        over — at one batched factorization's cost; see
        :mod:`spark_gp_tpu.models.loo` for the formulas and summaries.
        """
        from spark_gp_tpu.models.loo import loo_diagnostics

        x, y, kernel, theta = self._resolve_eval_inputs(x, y, model)
        return loo_diagnostics(
            kernel, theta, x, y, self._dataset_size_for_expert
        )

    def _resolve_eval_inputs(self, x, y, model):
        """Shared validation + kernel/theta resolution for the post-fit
        evaluation entry points (``loo``, ``poe_predictor``): the model's
        fitted hyperparameters when given, else the kernel's initial
        theta."""
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        if x.ndim != 2:
            raise ValueError(f"x must be [N, p], got shape {x.shape}")
        if y.shape != (x.shape[0],):
            raise ValueError(f"y must be [N], got shape {y.shape}")
        if model is not None:
            return x, y, model.raw_predictor.kernel, model.raw_predictor.theta
        kernel = self._get_kernel()
        return x, y, kernel, kernel.init_theta()

    def poe_predictor(
        self,
        x: np.ndarray,
        y: np.ndarray,
        model: "Optional[GaussianProcessRegressionModel]" = None,
        mode: Optional[str] = None,
    ):
        """Product-of-experts predictor (Deisenroth & Ng ICML'15) over this
        estimator's expert split — the inducing-set-free alternative to the
        PPA model: each expert answers from its exact s-point posterior and
        the answers combine by precision weighting (``mode``: ``"rbcm"``
        [robust default] / ``"gpoe"`` / ``"bcm"`` / ``"poe"`` /
        ``"healed"``).  ``mode=None`` resolves through the aggregation
        plane (``models/aggregation.py``): the explicitly engaged policy
        (``setAggregationPolicy`` / ``GP_AGG_POLICY``) when one is set,
        else the documented ``"rbcm"`` robust default.  Evaluated at
        ``model``'s fitted hyperparameters when given, else at the
        kernel's initial theta.  See :mod:`spark_gp_tpu.models.poe`.
        """
        from spark_gp_tpu.models.poe import make_poe_predictor

        x, y, kernel, theta = self._resolve_eval_inputs(x, y, model)
        return make_poe_predictor(
            kernel, theta, x, y, self._dataset_size_for_expert, mode=mode,
            mesh=self._mesh,
        )

    def _fit_device_multistart(
        self, instr, data, x, y, resilience_extra=(), cache=None
    ) -> "GaussianProcessRegressionModel":
        """Batched on-device multi-start (single chip): R starting points
        run in one vmapped L-BFGS dispatch
        (likelihood.fit_gpr_device_multistart); identical exploration to the
        sequential driver (same ``_restart_theta_batch``)."""
        import jax.numpy as jnp

        from spark_gp_tpu.models.likelihood import fit_gpr_device_multistart
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            kernel = self._get_kernel()
            dtype = data.x.dtype
            theta_batch = jnp.asarray(
                self._restart_theta_batch(kernel), dtype=dtype
            )
            lower, upper = kernel.bounds()
            log_space = self._use_log_space(kernel)
            # the marginal objective's trailing operands are the resilience
            # layer's jitter escalation (empty on clean fits)
            extra = resilience_extra if self._objective == "marginal" else ()
            active_override = None
            if self._objective == "elbo":
                # one inducing set, shared by every restart lane and the
                # PPA build below (the gram cache is None for the ELBO —
                # common._gram_cache)
                active_override, extra = self._elbo_setup(
                    instr, kernel, x, lambda: y, data, active_override
                )
            instr.log_info(
                "Optimising the kernel hyperparameters "
                f"(on-device, {self._num_restarts} batched restarts)"
            )
            with instr.phase("optimize_hypers"):
                theta, f, n_iter, n_fev, stalled, f_all, best = (
                    fit_gpr_device_multistart(
                        kernel, log_space, theta_batch,
                        jnp.asarray(lower, dtype=dtype),
                        jnp.asarray(upper, dtype=dtype),
                        data.x, data.y, data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32),
                        jnp.asarray(self._tol, dtype=dtype),
                        extra, cache,
                        objective=self._objective,
                    )
                )
                phase_sync(theta, f)
            # the per-restart vector and the device-chosen winner index ride
            # the existing single deferred fetch (no extra host sync here);
            # non-scalar entries are returned un-logged
            pending = {
                "lbfgs_iters": n_iter,
                "lbfgs_nfev": n_fev,
                "final_nll": f,
                "lbfgs_stalled": stalled,
                "best_restart": best,
                "restart_nlls": f_all,
            }
            raw, fetched = self._finalize_device_fit(
                instr, kernel, theta, pending, x, lambda: y, data,
                active_override=active_override,
            )
            self._report_multistart_nlls(instr, fetched)
        instr.log_success()
        model = GaussianProcessRegressionModel(raw)
        model.instr = instr
        return model

    def _fit_from_stack(
        self, instr, kernel, data, x, targets_fn, active_override,
        resilience_extra=(), cache=None,
    ) -> "GaussianProcessRegressionModel":
        """Shared optimize → active set → PPA tail of ``fit`` and
        ``fit_distributed``.  ``cache`` is the per-fit theta-invariant
        gram cache (common._gram_cache), threaded into whichever optimizer
        path runs."""
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            extra = resilience_extra if self._objective == "marginal" else ()
            if self._objective == "elbo":
                # selected once up front, reused for the PPA build below
                active_override, extra = self._elbo_setup(
                    instr, kernel, x, targets_fn, data, active_override
                )
            if self._resolved_optimizer() == "device":
                # Fully async pipeline: the on-device L-BFGS, the f64 PPA
                # statistics and the scalar diagnostics drain in one host
                # sync inside _finalize_device_fit.
                theta_dev, pending = self._fit_device(
                    instr, kernel, data, extra, cache
                )
                raw, fetched = self._finalize_device_fit(
                    instr, kernel, theta_dev, pending, x, targets_fn, data,
                    active_override=active_override,
                )
                if self._expert_quarantine and not np.isfinite(
                    float(np.asarray(fetched.get("final_nll", 0.0)))
                ):
                    # the one-dispatch device loop cannot raise mid-flight;
                    # surface the poisoned objective HERE so the resilience
                    # driver can diagnose/quarantine and re-dispatch
                    from spark_gp_tpu.resilience.quarantine import (
                        NonFiniteFitError,
                    )

                    raise NonFiniteFitError(
                        "device fit converged to a non-finite objective"
                    )
            else:
                # ladder host_f64 rung: f64 stack, cache dropped (no-op on
                # every other path — the gate lives in the helper)
                data, extra, cache = self._host_f64_operands(
                    data, extra, cache
                )
                if self._mesh is not None and self._objective != "elbo":
                    vag = make_sharded_value_and_grad(
                        kernel, data, self._mesh, self._objective,
                        cache=cache,
                        # extras slot 1 is the aggregation plane's weight
                        # vector (slot 0, jitter, cannot ride shard_map —
                        # common._run_with_expert_resilience gates it off)
                        weights=extra[1] if len(extra) > 1 else None,
                    )
                else:
                    # the ELBO (a nonlinear function of global sums) rides
                    # jit/GSPMD over the possibly-sharded stack instead of
                    # the shard_map path (models/sgpr.py)
                    vag = make_value_and_grad(
                        kernel, data, self._objective, extra, cache
                    )

                # arm the integrity plane's duplicate-dispatch spot
                # checks for a DCN-coordinated fit: the audit needs the
                # host-local stack to republish blocks of, which only
                # exists at this staging point
                dcn = getattr(self, "_dcn_ctx", None)
                if dcn is not None:
                    from spark_gp_tpu.resilience import integrity

                    integrity.stage_spot_check(
                        dcn, kernel, data, self._objective
                    )
                checkpointer = self._make_checkpointer(kernel)
                theta_opt = self._optimize_hypers(
                    instr, kernel, vag, callback=checkpointer
                )
                raw = self._projected_process(
                    instr, kernel, theta_opt, x, targets_fn, data,
                    active_override=active_override,
                )
        instr.log_success()
        model = GaussianProcessRegressionModel(raw)
        model.instr = instr
        return model

    def fit_distributed(
        self, data, active_set: Optional[np.ndarray] = None
    ) -> "GaussianProcessRegressionModel":
        """Multi-host fit from a pre-sharded expert stack.

        ``data`` is the output of
        :func:`spark_gp_tpu.parallel.distributed.distribute_global_experts`
        — a globally-sharded ``ExpertData`` whose expert axis spans every
        host's devices.  No process ever needs the full row set: the active
        set is either supplied explicitly (replicated ``[m, p]``) or selected
        by the configured provider's sharded-stack entry point
        (``ActiveSetProvider.from_stack`` — random sampling, sharded-Lloyd
        k-means and sharded greedy Seeger selection all run as mesh
        collectives).

        Single-process it is equivalent to ``fit`` with a pre-grouped stack.
        """
        def prepare(instr, active64, data):
            if active64 is None and self._objective == "elbo":
                # same shared-inducing-set semantics as fit(): select once
                # from the sharded stack at the base kernel's init theta,
                # not once per restart
                base_kernel = self._get_kernel()
                with instr.phase("active_set"):
                    active64 = self._select_active(
                        base_kernel, base_kernel.init_theta(), None, None,
                        data,
                    )
            # one cache per distributed fit too: sharded like the stack it
            # was built from, it rides the shard_map/DCN local programs
            cache = self._gram_cache(instr, data)

            def fit_once(kernel, instr_r):
                return self._fit_from_stack(
                    instr_r, kernel, data, None, None, active64, cache=cache
                )

            return fit_once

        return self._run_fit_distributed(
            "GaussianProcessRegression", data, active_set, prepare
        )

    def _fit_device(
        self, instr: Instrumentation, kernel, data, extra=(), cache=None
    ):
        """Dispatch the one-program on-device optimization
        (optimize/lbfgs_device.py) WITHOUT blocking: returns the device theta
        plus the pending diagnostic scalars for a single deferred fetch."""
        import jax.numpy as jnp

        from spark_gp_tpu.models.likelihood import (
            fit_gpr_device,
            fit_gpr_device_sharded,
        )

        dtype = data.x.dtype
        theta0 = jnp.asarray(kernel.init_theta(), dtype=dtype)
        lower, upper = kernel.bounds()
        lower = jnp.asarray(lower, dtype=dtype)
        upper = jnp.asarray(upper, dtype=dtype)
        max_iter = jnp.asarray(self._max_iter, dtype=jnp.int32)
        tol = jnp.asarray(self._tol, dtype=dtype)

        log_space = self._use_log_space(kernel)
        instr.log_info("Optimising the kernel hyperparameters (on-device)")
        from spark_gp_tpu.resilience import chaos

        # chaos choke point: a staged execution fault (injected OOM /
        # compile failure / memory-budget OOM) surfaces here, scoped to
        # this dispatch shape and its modeled byte cost
        chaos.maybe_injected_failure(
            self._device_fit_op(), nbytes=self._dispatch_raw_bytes(data)
        )
        with instr.phase("optimize_hypers"):
            if self._checkpoint_dir is not None or self._fallback_segmented():
                # segmented fit: one host sync per checkpointInterval
                # iterations, full state persisted between segments, resumes
                # from a matching prior checkpoint automatically.  The
                # degradation ladder's segmented rung rides the same driver
                # with an in-memory saver and a halved segment batch.
                from spark_gp_tpu.models.likelihood import (
                    fit_gpr_device_checkpointed,
                )

                # the objective is part of the FILE tag too (not only the
                # resume-meta family): a loo fit must not overwrite a
                # marginal fit's resumable state in the same dir; for the
                # elbo the tag also carries the objective-surface digest
                file_tag = (
                    "gpr" if self._objective == "marginal"
                    else f"gpr-{self._objective}"
                )
                if extra:
                    file_tag += "-" + self._elbo_checkpoint_salt(extra)
                saver, chunk = self._segment_saver_and_chunk(file_tag, data)
                theta, f, n_iter, n_fev, stalled = fit_gpr_device_checkpointed(
                    kernel, self._mesh, log_space, theta0, lower, upper,
                    data, self._max_iter, tol, chunk, saver,
                    objective=self._objective, extra=extra, cache=cache,
                )
            elif self._mesh is not None and self._objective != "elbo":
                theta, f, n_iter, n_fev, stalled = fit_gpr_device_sharded(
                    kernel, self._mesh, log_space, theta0, lower, upper,
                    data.x, data.y, data.mask, max_iter, tol, cache,
                    objective=self._objective,
                    weights=extra[1] if len(extra) > 1 else None,
                )
            else:
                # elbo + mesh lands here too: jit/GSPMD partitions the
                # sharded stack and replicates the [m, m] algebra
                theta, f, n_iter, n_fev, stalled = fit_gpr_device(
                    kernel, log_space, theta0, lower, upper,
                    data.x, data.y, data.mask, max_iter, tol, extra, cache,
                    objective=self._objective,
                )
            phase_sync(theta, f)
        pending = {
            "lbfgs_iters": n_iter,
            "lbfgs_nfev": n_fev,
            "final_nll": f,
            "lbfgs_stalled": stalled,
        }
        return theta, pending


class GaussianProcessRegressionModel:
    """Fitted model: posterior mean / variance against the m-point active set
    (GaussianProcessRegression.scala:75-87)."""

    def __init__(self, raw_predictor: ProjectedProcessRawPredictor):
        self.raw_predictor = raw_predictor
        self.instr: Optional[Instrumentation] = None

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        # mean-only path even on full models: the variance would be
        # computed (O(t m^2)) just to be discarded
        return np.asarray(self.raw_predictor.predict_mean(np.asarray(x_test)))

    def predict_with_var(self, x_test: np.ndarray):
        mean, var = self.raw_predictor(np.asarray(x_test))
        if var is None:
            raise ValueError(
                "model was fitted with setPredictiveVariance(False); "
                "use predict(), or refit with variances enabled"
            )
        return np.asarray(mean), np.asarray(var)

    def predict_with_cov(self, x_test: np.ndarray):
        """``(mean [t], cov [t, t])`` — joint predictive covariance between
        the test points (the reference exposes only the per-point variance,
        GaussianProcessCommons.scala:124).  ``diag(cov)`` agrees with
        ``predict_with_var`` to float rounding (the two paths evaluate the
        diagonal kernel term via ``self_diag`` vs ``diag(gram)``)."""
        mean, cov = self.raw_predictor.predict_with_cov(np.asarray(x_test))
        return np.asarray(mean), np.asarray(cov)

    def sample_posterior(
        self, x_test: np.ndarray, n_samples: int = 1, seed: int = 0
    ) -> np.ndarray:
        """``[n_samples, t]`` coherent draws from the joint posterior over
        the test points (mean + L eps with L the jitter-repaired Cholesky
        of the predictive covariance) — the Thompson-sampling primitive a
        per-point variance cannot provide."""
        from spark_gp_tpu.models.ppa import _psd_safe_cholesky

        mean, cov = self.predict_with_cov(x_test)
        chol = _psd_safe_cholesky(
            np.asarray(cov, dtype=np.float64), "predictive covariance"
        )
        eps = np.random.default_rng(seed).standard_normal(
            (n_samples, mean.shape[0])
        )
        return mean[None, :] + eps @ chol.T

    def update(self, x_new: np.ndarray, y_new: np.ndarray) -> "GaussianProcessRegressionModel":
        """New model with ``(x_new, y_new)`` folded in WITHOUT a refit.

        The PPA statistics are additive over observations, so an update is
        one [m, t] cross kernel plus one O(m^3) magic re-solve at the
        fitted hyperparameters and active set
        (:meth:`ProjectedProcessRawPredictor.with_additional_data`) —
        streaming/online data at prediction-grade cost, a capability the
        reference's frozen produceModel cannot offer.  Functional: the
        original model is untouched.  Re-fit instead when the new data
        plausibly shifts the hyperparameters or deserves active-set slots.

        Regression only: the classifier/count families would need a fresh
        Laplace mode solve over the new points to produce their latent
        targets — refit those.
        """
        model = GaussianProcessRegressionModel(
            self.raw_predictor.with_additional_data(x_new, y_new)
        )
        return model

    def save(self, path: str) -> None:
        from spark_gp_tpu.utils.serialization import save_model

        save_model(path, self, kind="regression")

    @staticmethod
    def load(path: str) -> "GaussianProcessRegressionModel":
        from spark_gp_tpu.utils.serialization import load_model

        model = load_model(path)
        if not isinstance(model, GaussianProcessRegressionModel):
            raise TypeError("not a regression model checkpoint")
        return model
