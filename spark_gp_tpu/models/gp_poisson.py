"""GP Poisson (count) regression — log link, Laplace approximation.

Model family beyond the reference (akopich/spark-gp ships Gaussian
regression and Bernoulli classification only): ``y_i | f_i ~
Poisson(exp(f_i))`` with a GP prior on the log-rate ``f``.  Fitting rides
the generic-likelihood Laplace core (:mod:`laplace_generic` — mode Newton,
autodiff hyperparameter gradients via the Newton-fixed-point trick) under
the same BCM expert split and PPA model production as every other
estimator: the fitted latent modes become the regression targets of the
projected process (the classifier's GPClf.scala:62-65 substitution,
applied to a different likelihood).

Prediction: the PPA latent mean/variance gives the log-rate posterior;
``predict_rate`` returns ``E[exp(f*)] = exp(mu + var / 2)`` (the lognormal
mean — using the latent variance the reference's classifier discards) or
plain ``exp(mu)`` (the MAP rate) when the model is mean-only.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

import jax

from spark_gp_tpu.models.common import GaussianProcessCommons
from spark_gp_tpu.ops import iterative as it_ops
from spark_gp_tpu.models.laplace_generic import (
    NegativeBinomialLikelihood,
    PoissonLikelihood,
    fit_generic_device,
    make_generic_objective,
    make_sharded_generic_objective,
)
from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor
from spark_gp_tpu.utils.instrumentation import Instrumentation, phase_sync


@jax.jit
def _counts_valid(y, mask):
    # module-level jit: one device reduction with a replicated scalar
    # output (multi-host global arrays reject eager reductions)
    ym = y * mask
    return jnp.all(ym >= 0.0) & jnp.all(jnp.floor(ym) == ym)


class GaussianProcessPoissonRegression(GaussianProcessCommons):
    """Count-data GP with the reference's fluent parameter API.  Targets
    are non-negative integer counts."""

    _likelihood = PoissonLikelihood()

    def fit(self, x: np.ndarray, y: np.ndarray) -> "GaussianProcessPoissonModel":
        # type(self).__name__, not a literal: subclasses (NegativeBinomial)
        # must log and report under their own estimator name
        instr = Instrumentation(name=type(self).__name__)
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y)
        if x.ndim != 2:
            raise ValueError(f"x must be [N, p], got shape {x.shape}")
        if y.shape != (x.shape[0],):
            raise ValueError(f"y must be [N], got shape {y.shape}")
        y_f = np.asarray(y, dtype=np.float64)
        if np.any(y_f < 0) or not np.all(y_f == np.floor(y_f)):
            raise ValueError("targets must be non-negative integer counts")
        # the observation shell wraps the WHOLE post-validation body (the
        # gpr.py convention): grouping/screen phases — and any screen-time
        # quarantine events — land inside the fit's root span
        return self._observed_fit(
            instr, lambda: self._fit_body(instr, x, y_f)
        )

    def _fit_body(self, instr, x, y_f) -> "GaussianProcessPoissonModel":
        with instr.phase("group_experts"):
            data = self._group_screened(instr, x, y_f)
        instr.log_metric("num_experts", data.num_experts)

        # theta-invariant gram cache, built once per fit and shared by
        # every restart (common._gram_cache)
        cache = self._gram_cache(instr, data)

        def fit_once(kernel, instr_r):
            return self._fit_from_stack(instr_r, kernel, data, x, cache=cache)

        def attempt():
            if self._use_batched_multistart():
                return self._fit_device_multistart(instr, data, x, cache)
            return self._fit_with_restarts(instr, fit_once)

        from spark_gp_tpu.resilience import fallback

        # degradation ladder around the complete attempt (gpr.py wrap)
        return fallback.run_fit_ladder(self, instr, attempt, data=data)

    def _fit_device_multistart(
        self, instr, data, x, cache=None
    ) -> "GaussianProcessPoissonModel":
        """Batched on-device multi-start: R starting points in one vmapped
        generic-Laplace + L-BFGS dispatch; one PPA build for the winner."""
        from spark_gp_tpu.models.laplace_generic import (
            fit_generic_device_multistart,
        )
        from spark_gp_tpu.parallel.experts import (
            ExpertData,
            num_experts_for,
            ungroup,
        )
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            kernel = self._get_kernel()
            dtype = data.x.dtype
            theta_batch = jnp.asarray(
                self._restart_theta_batch(kernel), dtype=dtype
            )
            lower, upper = kernel.bounds()
            log_space = self._use_log_space(kernel)
            instr.log_info(
                "Optimising the kernel hyperparameters "
                f"(on-device, {self._num_restarts} batched restarts)"
            )
            with instr.phase("optimize_hypers"):
                theta, f_final, nll, n_iter, n_fev, stalled, f_all, best = (
                    fit_generic_device_multistart(
                        self._likelihood, kernel, float(self._tol), log_space,
                        theta_batch,
                        jnp.asarray(lower, dtype=dtype),
                        jnp.asarray(upper, dtype=dtype),
                        data.x, data.y, data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32),
                        cache, solver=it_ops.solver_jit_key(),
                    )
                )
                phase_sync(theta, nll)
            theta_host = np.asarray(theta, dtype=np.float64)
            self._log_device_optimizer_result(
                instr, kernel, theta_host, nll, n_iter, n_fev, stalled
            )
            instr.log_metric("best_restart", int(best))
            self._report_multistart_nlls(
                instr, {"restart_nlls": np.asarray(f_all)}
            )

            latent_y = f_final * data.mask
            latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)

            x_prov, n_orig, row_filter = self._provider_rows_filter(x)

            def targets_fn():
                e_real = num_experts_for(
                    n_orig, self._dataset_size_for_expert
                )
                return row_filter(
                    ungroup(np.asarray(latent_y)[:e_real], n_orig)
                )

            raw = self._projected_process(
                instr, kernel, theta_host, x_prov, targets_fn, latent_data
            )
        instr.log_success()
        model = GaussianProcessPoissonModel(raw)
        model.instr = instr
        return model

    def fit_distributed(
        self, data, active_set: Optional[np.ndarray] = None
    ) -> "GaussianProcessPoissonModel":
        """Multi-host count-regression fit from a pre-sharded expert stack
        (the same entry point every other estimator has): ``data`` is a
        globally-sharded ``ExpertData`` of counts
        (:func:`...distributed.distribute_global_experts`); the sharded
        generic-Laplace objective keeps the latent stacks device-resident,
        and the provider selects over the latent log-rates from the stack.
        """
        def prepare(instr, active64, data):
            if not bool(_counts_valid(data.y, data.mask)):
                raise ValueError(
                    "targets must be non-negative integer counts"
                )

            cache = self._gram_cache(instr, data)

            def fit_once(kernel, instr_r):
                return self._fit_from_stack(
                    instr_r, kernel, data, None, active_override=active64,
                    cache=cache,
                )

            return fit_once

        return self._run_fit_distributed(
            type(self).__name__, data, active_set, prepare
        )

    def _fit_from_stack(
        self, instr, kernel, data, x, active_override=None, cache=None
    ) -> "GaussianProcessPoissonModel":
        from spark_gp_tpu.parallel.experts import (
            ExpertData,
            num_experts_for,
            ungroup,
        )
        from spark_gp_tpu.utils.instrumentation import maybe_profile

        with maybe_profile(self._profile_dir):
            if self._resolved_optimizer() == "device":
                theta_opt, f_final = self._fit_device(
                    instr, kernel, data, cache
                )
            else:
                theta_opt, f_final = self._fit_host(
                    instr, kernel, data, cache
                )

            latent_y = f_final * data.mask
            # latent log-rates substitute for y in the PPA build AND as the
            # stack providers' targets (the GPClf.scala:62-65 substitution)
            latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)

            if x is None:
                # distributed: provider selects from the sharded stack
                targets_fn = None
            else:
                x, n_orig, row_filter = self._provider_rows_filter(x)

                def targets_fn():
                    e_real = num_experts_for(
                        n_orig, self._dataset_size_for_expert
                    )
                    return row_filter(
                        ungroup(np.asarray(latent_y)[:e_real], n_orig)
                    )

            # targets stay a callable: materializing the latent stack is a
            # device sync the random/kmeans providers never need
            raw = self._projected_process(
                instr, kernel, theta_opt, x, targets_fn, latent_data,
                active_override=active_override,
            )
        instr.log_success()
        model = GaussianProcessPoissonModel(raw)
        model.instr = instr
        return model

    def _fit_host(self, instr, kernel, data, cache=None):
        lik = self._likelihood
        # ladder host_f64 rung: f64 stack, cache dropped (no-op on every
        # other path — common._host_f64_operands gates itself)
        data, _, cache = self._host_f64_operands(data, cache=cache)
        if self._mesh is not None:
            objective = make_sharded_generic_objective(
                lik, kernel, data.x, data.y, data.mask, self._tol,
                self._mesh, cache,
            )
        else:
            objective = make_generic_objective(
                lik, kernel, data.x, data.y, data.mask, self._tol, cache
            )
        return self._optimize_latent_host(
            instr, kernel, objective, jnp.zeros_like(data.y)
        )

    def _fit_device(self, instr, kernel, data, cache=None):
        """One-dispatch on-device fit — the same mesh/checkpoint dispatch as
        the other three families (GaussianProcessCommons.scala:66-92 is one
        skeleton for every estimator; so is this)."""
        dtype = data.x.dtype
        theta0 = jnp.asarray(kernel.init_theta(), dtype=dtype)
        lower, upper = kernel.bounds()
        lower = jnp.asarray(lower, dtype=dtype)
        upper = jnp.asarray(upper, dtype=dtype)
        log_space = self._use_log_space(kernel)
        instr.log_info("Optimising the kernel hyperparameters (on-device)")
        from spark_gp_tpu.resilience import chaos

        # chaos choke point for staged execution faults (fallback ladder)
        # + the memory-budget allocator model (memplan/chaos)
        chaos.maybe_injected_failure(
            self._device_fit_op(), nbytes=self._dispatch_raw_bytes(data)
        )
        with instr.phase("optimize_hypers"):
            if self._checkpoint_dir is not None or self._fallback_segmented():
                from spark_gp_tpu.models.laplace_generic import (
                    fit_generic_device_checkpointed,
                )
                import hashlib

                # likelihood-keyed FILE tag: NB and Poisson fits (or two NB
                # fits with different dispersions) sharing a dir must not
                # clobber each other's resumable state — the same hazard
                # gpr.py's objective-keyed file_tag closes for objectives
                lik = self._likelihood
                lik_digest = hashlib.sha1(
                    repr((type(lik).__name__, lik._spec())).encode()
                ).hexdigest()[:10]
                saver, chunk = self._segment_saver_and_chunk(
                    f"generic-{type(lik).__name__}-{lik_digest}", data
                )
                theta, f_final, nll, n_iter, n_fev, stalled = (
                    fit_generic_device_checkpointed(
                        self._likelihood, kernel, float(self._tol),
                        self._mesh, log_space, theta0, lower, upper,
                        data.x, data.y, data.mask, self._max_iter,
                        chunk, saver, cache,
                    )
                )
            elif self._mesh is not None:
                from spark_gp_tpu.models.laplace_generic import (
                    fit_generic_device_sharded,
                )

                theta, f_final, nll, n_iter, n_fev, stalled = (
                    fit_generic_device_sharded(
                        self._likelihood, kernel, float(self._tol),
                        self._mesh, log_space, theta0, lower, upper,
                        data.x, data.y, data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32),
                        cache, solver=it_ops.solver_jit_key(),
                    )
                )
            else:
                from spark_gp_tpu.obs import cost as obs_cost

                # measured cost of the one-dispatch program (obs/cost.py)
                theta, f_final, nll, n_iter, n_fev, stalled = (
                    obs_cost.observed_call(
                        "fit.device", fit_generic_device,
                        self._likelihood, kernel, float(self._tol),
                        log_space, theta0, lower, upper, data.x, data.y,
                        data.mask,
                        jnp.asarray(self._max_iter, dtype=jnp.int32), cache,
                        solver=it_ops.solver_jit_key(),
                    )
                )
            phase_sync(theta, nll)
        theta_host = np.asarray(theta, dtype=np.float64)
        self._log_device_optimizer_result(
            instr, kernel, theta_host, nll, n_iter, n_fev, stalled
        )
        return theta_host, f_final


class GaussianProcessNegativeBinomialRegression(GaussianProcessPoissonRegression):
    """Overdispersed count regression: ``y | f ~ NB(exp(f), r)`` with a GP
    prior on the log-mean — the same generic-Laplace pipeline as the
    Poisson estimator (one skeleton for every family) with the
    :class:`NegativeBinomialLikelihood` plugged in.  Use when the counts'
    variance exceeds their mean (Poisson forces Var = mean; NB2 models
    ``Var = mean + mean^2 / r``) — a Poisson fit on overdispersed data
    inflates the latent noise instead.  The fitted model is the shared
    log-link rate model (prediction depends only on the latent posterior,
    not the counting likelihood).
    """

    def __init__(self, dispersion: float = 10.0) -> None:
        super().__init__()
        self.setDispersion(dispersion)

    def setDispersion(self, dispersion: float):
        self._likelihood = NegativeBinomialLikelihood(dispersion)
        return self

    set_dispersion = setDispersion

    def getDispersion(self) -> float:
        return self._likelihood.dispersion


class GaussianProcessPoissonModel:
    """Log-link rate model over the PPA latent posterior."""

    def __init__(self, raw_predictor: ProjectedProcessRawPredictor):
        self.raw_predictor = raw_predictor
        self.instr: Optional[Instrumentation] = None

    def predict_latent(self, x_test: np.ndarray):
        """``(mean, var)`` of the log-rate posterior (``var`` is None on
        mean-only models)."""
        mean, var = self.raw_predictor(np.asarray(x_test))
        return np.asarray(mean), (None if var is None else np.asarray(var))

    def predict_rate(self, x_test: np.ndarray) -> np.ndarray:
        """Posterior-expected rate ``E[exp(f*)] = exp(mu + var / 2)``; falls
        back to the MAP rate ``exp(mu)`` on mean-only models."""
        mean, var = self.predict_latent(x_test)
        if var is None:
            return np.exp(mean)
        return np.exp(mean + 0.5 * np.maximum(var, 0.0))

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        """Alias for :meth:`predict_rate` (the natural point prediction)."""
        return self.predict_rate(x_test)

    def save(self, path: str) -> None:
        from spark_gp_tpu.utils.serialization import save_model

        save_model(path, self, kind="poisson")

    @staticmethod
    def load(path: str) -> "GaussianProcessPoissonModel":
        from spark_gp_tpu.utils.serialization import load_model

        model = load_model(path)
        if not isinstance(model, GaussianProcessPoissonModel):
            raise TypeError("not a poisson model checkpoint")
        return model
