"""Active-set (inducing point) providers.

Counterpart of commons/ActiveSetProvider.scala:13-139.  The SPI takes the
full training data (host numpy), the kernel spec and the optimal
hyperparameters, and returns the m active points ``[m, p]``.

* :class:`RandomActiveSetProvider` — uniform sample without replacement
  (ASP.scala:48-56; the default, GaussianProcessParams.scala:33).
* :class:`KMeansActiveSetProvider` — centroids of a jitted Lloyd iteration
  (ASP.scala:26-43 delegates to Spark ML KMeans, whose default init is the
  parallelized k-means++ variant; here true k-means++ D²-weighted seeding
  as one jitted ``fori_loop``, then ``lax.scan`` over Lloyd steps with
  distance matrices on the MXU; default maxIter 20 as the reference's).
* :class:`GreedilyOptimizingActiveSetProvider` — Seeger et al. 2003 fast
  forward selection (ASP.scala:59-136), implemented in ``greedy.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.distance import sq_dist


class ActiveSetProvider:
    """SPI: ``(active_set_size, x, y, kernel, theta_opt, seed) -> [m, p]``.

    ``uses_fit_outputs`` tells the training driver whether the provider reads
    the fitted hyperparameters / targets at all: providers that only look at
    ``x`` (random sampling, k-means) let the driver keep theta on device and
    defer every host sync to one final fetch.
    """

    uses_fit_outputs = True

    def __call__(
        self,
        active_set_size: int,
        x: np.ndarray,
        y: np.ndarray,
        kernel: Kernel,
        theta_opt: np.ndarray,
        seed: int,
    ) -> np.ndarray:
        raise NotImplementedError


class _RandomActiveSetProvider(ActiveSetProvider):
    """Uniform sample of m training points (ASP.scala:48-56)."""

    uses_fit_outputs = False

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        rng = np.random.default_rng(seed)
        n = x.shape[0]
        m = min(active_set_size, n)
        idx = rng.choice(n, size=m, replace=False)
        return np.asarray(x)[idx]


RandomActiveSetProvider = _RandomActiveSetProvider()


class KMeansActiveSetProvider(ActiveSetProvider):
    """K-means centroids as the active set (ASP.scala:26-43).

    Jitted Lloyd iterations: the point-to-centroid distance matrix is one
    MXU matmul per step; assignments via argmin; centroid update via
    one-hot matmul (segment mean without scatter — TPU-friendly).  Empty
    clusters keep their previous centroid.
    """

    uses_fit_outputs = False

    def __init__(self, max_iter: int = 20):
        self.max_iter = max_iter

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        x = np.asarray(x)
        n = x.shape[0]
        k = min(active_set_size, n)
        xj = jnp.asarray(x)

        key = jax.random.PRNGKey(seed)
        centroids = _kmeanspp_init(key, xj, k)
        centroids = _lloyd(xj, centroids, self.max_iter)
        return np.asarray(centroids)


@partial(jax.jit, static_argnums=2)
def _kmeanspp_init(key, x, k):
    """k-means++ D²-weighted seeding (Arthur & Vassilvitskii 2007), fully
    jitted: the running min-squared-distance vector is the categorical
    sampling weight for each next seed.  Duplicate points get weight 0 and
    are never re-selected while any spread remains."""
    n = x.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centroids = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])
    min_d2 = jnp.sum((x - x[first]) ** 2, axis=1)

    def body(i, carry):
        centroids, min_d2, key = carry
        key, sub = jax.random.split(key)
        # log-weights: zero-distance (already-chosen/duplicate) points get
        # -inf; if every point coincides with a centroid, fall back uniform
        weights = jnp.where(
            jnp.any(min_d2 > 0), jnp.log(min_d2), jnp.zeros_like(min_d2)
        )
        idx = jax.random.categorical(sub, weights)
        c = x[idx]
        centroids = centroids.at[i].set(c)
        min_d2 = jnp.minimum(min_d2, jnp.sum((x - c) ** 2, axis=1))
        return centroids, min_d2, key

    centroids, _, _ = jax.lax.fori_loop(1, k, body, (centroids, min_d2, key))
    return centroids


def _lloyd(x, centroids, max_iter, mask=None):
    """``max_iter`` Lloyd steps; ``mask`` (optional [n]) excludes padded
    points from assignments and centroid updates."""
    k = centroids.shape[0]

    def step(c, _):
        d = sq_dist(x, c)  # [n, k]
        assign = jnp.argmin(d, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)  # [n, k]
        if mask is not None:
            onehot = onehot * mask[:, None]
        counts = jnp.sum(onehot, axis=0)  # [k]
        sums = jax.lax.dot_general(
            onehot, x, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        )  # [k, p]
        new_c = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), c
        )
        return new_c, None

    out, _ = jax.lax.scan(jax.jit(step), centroids, None, length=max_iter)
    return out


class GreedilyOptimizingActiveSetProvider(ActiveSetProvider):
    """Seeger et al. 2003 fast forward selection (ASP.scala:59-136)."""

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        from spark_gp_tpu.models.greedy import greedy_active_set

        return greedy_active_set(active_set_size, x, y, kernel, theta_opt, seed)
