"""Active-set (inducing point) providers.

Counterpart of commons/ActiveSetProvider.scala:13-139.  The SPI takes the
full training data (host numpy), the kernel spec and the optimal
hyperparameters, and returns the m active points ``[m, p]``.

* :class:`RandomActiveSetProvider` — uniform sample without replacement
  (ASP.scala:48-56; the default, GaussianProcessParams.scala:33).
* :class:`KMeansActiveSetProvider` — centroids of a jitted Lloyd iteration
  (ASP.scala:26-43 delegates to Spark ML KMeans; here ``lax.scan`` over
  Lloyd steps, distance matrices on the MXU, k-means++-style seeding by
  random choice as Spark does by default maxIter 20).
* :class:`GreedilyOptimizingActiveSetProvider` — Seeger et al. 2003 fast
  forward selection (ASP.scala:59-136), implemented in ``greedy.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.distance import sq_dist


class ActiveSetProvider:
    """SPI: ``(active_set_size, x, y, kernel, theta_opt, seed) -> [m, p]``.

    ``uses_fit_outputs`` tells the training driver whether the provider reads
    the fitted hyperparameters / targets at all: providers that only look at
    ``x`` (random sampling, k-means) let the driver keep theta on device and
    defer every host sync to one final fetch.
    """

    uses_fit_outputs = True

    def __call__(
        self,
        active_set_size: int,
        x: np.ndarray,
        y: np.ndarray,
        kernel: Kernel,
        theta_opt: np.ndarray,
        seed: int,
    ) -> np.ndarray:
        raise NotImplementedError


class _RandomActiveSetProvider(ActiveSetProvider):
    """Uniform sample of m training points (ASP.scala:48-56)."""

    uses_fit_outputs = False

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        rng = np.random.default_rng(seed)
        n = x.shape[0]
        m = min(active_set_size, n)
        idx = rng.choice(n, size=m, replace=False)
        return np.asarray(x)[idx]


RandomActiveSetProvider = _RandomActiveSetProvider()


class KMeansActiveSetProvider(ActiveSetProvider):
    """K-means centroids as the active set (ASP.scala:26-43).

    Jitted Lloyd iterations: the point-to-centroid distance matrix is one
    MXU matmul per step; assignments via argmin; centroid update via
    one-hot matmul (segment mean without scatter — TPU-friendly).  Empty
    clusters keep their previous centroid.
    """

    uses_fit_outputs = False

    def __init__(self, max_iter: int = 20):
        self.max_iter = max_iter

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        x = np.asarray(x)
        n = x.shape[0]
        k = min(active_set_size, n)
        rng = np.random.default_rng(seed)
        init_idx = rng.choice(n, size=k, replace=False)
        centroids = jnp.asarray(x[init_idx])
        xj = jnp.asarray(x)

        centroids = _lloyd(xj, centroids, self.max_iter)
        return np.asarray(centroids)


def _lloyd(x, centroids, max_iter):
    k = centroids.shape[0]

    def step(c, _):
        d = sq_dist(x, c)  # [n, k]
        assign = jnp.argmin(d, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)  # [n, k]
        counts = jnp.sum(onehot, axis=0)  # [k]
        sums = jax.lax.dot_general(
            onehot, x, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        )  # [k, p]
        new_c = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), c
        )
        return new_c, None

    out, _ = jax.lax.scan(jax.jit(step), centroids, None, length=max_iter)
    return out


class GreedilyOptimizingActiveSetProvider(ActiveSetProvider):
    """Seeger et al. 2003 fast forward selection (ASP.scala:59-136)."""

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        from spark_gp_tpu.models.greedy import greedy_active_set

        return greedy_active_set(active_set_size, x, y, kernel, theta_opt, seed)
