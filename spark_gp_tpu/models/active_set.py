"""Active-set (inducing point) providers.

Counterpart of commons/ActiveSetProvider.scala:13-139.  The SPI takes the
full training data (host numpy), the kernel spec and the optimal
hyperparameters, and returns the m active points ``[m, p]``.

* :class:`RandomActiveSetProvider` — uniform sample without replacement
  (ASP.scala:48-56; the default, GaussianProcessParams.scala:33).
* :class:`KMeansActiveSetProvider` — centroids of a jitted Lloyd iteration
  (ASP.scala:26-43 delegates to Spark ML KMeans, whose default init is the
  parallelized k-means++ variant; here true k-means++ D²-weighted seeding
  as one jitted ``fori_loop``, then ``lax.scan`` over Lloyd steps with
  distance matrices on the MXU; default maxIter 20 as the reference's).
* :class:`GreedilyOptimizingActiveSetProvider` — Seeger et al. 2003 fast
  forward selection (ASP.scala:59-136), implemented in ``greedy.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.distance import sq_dist


class ActiveSetProvider:
    """SPI: ``(active_set_size, x, y, kernel, theta_opt, seed) -> [m, p]``.

    ``uses_fit_outputs`` tells the training driver whether the provider reads
    the fitted hyperparameters / targets at all: providers that only look at
    ``x`` (random sampling, k-means) let the driver keep theta on device and
    defer every host sync to one final fetch.

    ``from_stack`` is the sharded entry point used by ``fit_distributed``:
    no host ever holds the full row set, so selection runs against the
    globally-sharded ``ExpertData`` stack directly (the counterpart of the
    reference providers consuming RDDs, ASP.scala:13-20).  All three built-in
    providers implement it natively; third-party providers inherit a
    uniform-sampling fallback (with a warning) so ``fit_distributed`` still
    produces a model.
    """

    uses_fit_outputs = True

    def __call__(
        self,
        active_set_size: int,
        x: np.ndarray,
        y: np.ndarray,
        kernel: Kernel,
        theta_opt: np.ndarray,
        seed: int,
    ) -> np.ndarray:
        raise NotImplementedError

    def from_stack(
        self, active_set_size: int, data, kernel: Kernel, theta, seed: int,
        mesh,
    ) -> np.ndarray:
        """Select ``[m, p]`` active points from a sharded expert stack.

        ``data.y`` carries the provider's targets (labels for regression,
        latent modes for the classifier).  ``theta`` may be a device array.
        """
        import warnings

        from spark_gp_tpu.parallel.distributed import sample_active_from_stack

        warnings.warn(
            f"{type(self).__name__} has no sharded-stack implementation; "
            "falling back to uniform sampling for fit_distributed.  "
            "Implement from_stack(...) to override.",
            stacklevel=2,
        )
        return sample_active_from_stack(data, active_set_size, seed, mesh)


class _RandomActiveSetProvider(ActiveSetProvider):
    """Uniform sample of m training points (ASP.scala:48-56)."""

    uses_fit_outputs = False

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        rng = np.random.default_rng(seed)
        n = x.shape[0]
        m = min(active_set_size, n)
        idx = rng.choice(n, size=m, replace=False)
        return np.asarray(x)[idx]

    def from_stack(self, active_set_size, data, kernel, theta, seed, mesh):
        from spark_gp_tpu.parallel.distributed import sample_active_from_stack

        return sample_active_from_stack(data, active_set_size, seed, mesh)


RandomActiveSetProvider = _RandomActiveSetProvider()


class KMeansActiveSetProvider(ActiveSetProvider):
    """K-means centroids as the active set (ASP.scala:26-43).

    Jitted Lloyd iterations: the point-to-centroid distance matrix is one
    MXU matmul per step; assignments via argmin; centroid update via
    one-hot matmul (segment mean without scatter — TPU-friendly).  Empty
    clusters keep their previous centroid.
    """

    uses_fit_outputs = False

    def __init__(self, max_iter: int = 20):
        self.max_iter = max_iter

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        x = np.asarray(x)
        n = x.shape[0]
        k = min(active_set_size, n)
        xj = jnp.asarray(x)

        key = jax.random.PRNGKey(seed)
        centroids = _kmeanspp_init(key, xj, k)
        centroids = _lloyd(xj, centroids, self.max_iter)
        return np.asarray(centroids)

    def from_stack(self, active_set_size, data, kernel, theta, seed, mesh):
        """Sharded Lloyd over the expert stack: centroids replicated, points
        sharded, per-step communication = one psum of the [k, p] sums and
        [k] counts over ICI (the counterpart of Spark ML KMeans's
        treeAggregate, ASP.scala:36-41).

        Seeding: k-means++ over a replicated uniform subsample (≤ max(4k,
        4096) rows) — the same spirit as Spark's k-means|| oversampling
        init, which also avoids n sequential global D² passes.
        """
        from spark_gp_tpu.parallel.distributed import sample_active_from_stack

        n_sub = max(4 * active_set_size, 4096)
        sub = sample_active_from_stack(data, n_sub, seed, mesh)
        k = min(active_set_size, sub.shape[0])
        centroids = _kmeanspp_init(
            jax.random.PRNGKey(seed), jnp.asarray(sub, dtype=data.x.dtype), k
        )
        centroids = _lloyd_stack_jit(
            mesh, self.max_iter, data.x, data.mask, centroids
        )
        return np.asarray(centroids, dtype=np.float64)


@partial(jax.jit, static_argnums=2)
def _kmeanspp_init(key, x, k):
    """k-means++ D²-weighted seeding (Arthur & Vassilvitskii 2007), fully
    jitted: the running min-squared-distance vector is the categorical
    sampling weight for each next seed.  Duplicate points get weight 0 and
    are never re-selected while any spread remains."""
    n = x.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centroids = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])
    min_d2 = jnp.sum((x - x[first]) ** 2, axis=1)

    def body(i, carry):
        centroids, min_d2, key = carry
        key, sub = jax.random.split(key)
        # log-weights: zero-distance (already-chosen/duplicate) points get
        # -inf; if every point coincides with a centroid, fall back uniform
        weights = jnp.where(
            jnp.any(min_d2 > 0), jnp.log(min_d2), jnp.zeros_like(min_d2)
        )
        idx = jax.random.categorical(sub, weights)
        c = x[idx]
        centroids = centroids.at[i].set(c)
        min_d2 = jnp.minimum(min_d2, jnp.sum((x - c) ** 2, axis=1))
        return centroids, min_d2, key

    centroids, _, _ = jax.lax.fori_loop(1, k, body, (centroids, min_d2, key))
    return centroids


def _lloyd(x, centroids, max_iter, mask=None, psum=None):
    """``max_iter`` Lloyd steps.  ``mask`` (optional [n]) excludes padded
    points from assignments and centroid updates; ``psum`` (optional)
    all-reduces the per-shard counts/sums when the point axis is sharded
    (the single shared step for both the host and shard_map paths)."""
    k = centroids.shape[0]

    def step(c, _):
        d = sq_dist(x, c)  # [n, k]
        assign = jnp.argmin(d, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)  # [n, k]
        if mask is not None:
            onehot = onehot * mask[:, None]
        counts = jnp.sum(onehot, axis=0)  # [k]
        # linalg-stage precision from the policy (ops/precision.py), not a
        # raw HIGHEST pin: a one-hot scatter-sum has no cancellation, so
        # it rides the same lane as the other non-gram matmuls
        from spark_gp_tpu.ops.precision import matmul_precision

        sums = jax.lax.dot_general(
            onehot, x, (((0,), (0,)), ((), ())),
            precision=matmul_precision(),
        )  # [k, p]
        if psum is not None:
            # one fused all-reduce per Lloyd step (latency over ICI)
            fused = psum(jnp.concatenate([sums, counts[:, None]], axis=1))
            sums, counts = fused[:, :-1], fused[:, -1]
        new_c = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), c
        )
        return new_c, None

    out, _ = jax.lax.scan(step, centroids, None, length=max_iter)
    return out


@partial(jax.jit, static_argnums=(0, 1))
def _lloyd_stack_jit(mesh, max_iter, x, mask, centroids):
    """Lloyd iterations over a sharded ``[E, s, p]`` stack (masked)."""
    from jax.sharding import PartitionSpec as P

    from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

    p = x.shape[-1]
    k = centroids.shape[0]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(EXPERT_AXIS), P(EXPERT_AXIS), P()),
        out_specs=P(),
    )
    def run(x_, mask_, c0):
        return _lloyd(
            x_.reshape(-1, p), c0, max_iter, mask=mask_.reshape(-1),
            psum=lambda v: jax.lax.psum(v, EXPERT_AXIS),
        )

    return run(x, mask, centroids)


class GreedilyOptimizingActiveSetProvider(ActiveSetProvider):
    """Seeger et al. 2003 fast forward selection (ASP.scala:59-136)."""

    def __call__(self, active_set_size, x, y, kernel, theta_opt, seed):
        from spark_gp_tpu.models.greedy import greedy_active_set

        return greedy_active_set(active_set_size, x, y, kernel, theta_opt, seed)

    def from_stack(self, active_set_size, data, kernel, theta, seed, mesh):
        from spark_gp_tpu.models.greedy import greedy_active_set_from_stack

        return greedy_active_set_from_stack(
            active_set_size, data, kernel, theta, seed, mesh
        )
