"""Model layer: estimators, likelihoods, the PPA solver and active-set
providers — the TPU-native counterparts of the reference's L3-L5 layers
(SURVEY.md §1)."""
