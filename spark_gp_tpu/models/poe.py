"""Product-of-experts predictive aggregation (BCM family).

The PPA predictor (``models/ppa.py``) answers queries through an m-point
inducing set — the reference's design (GaussianProcessCommons.scala:118-126).
This module is the OTHER classic way to predict from the very expert split
the training objective already uses: each expert answers from its own
s-point exact posterior and the answers combine by precision weighting
(Deisenroth & Ng, *Distributed Gaussian Processes*, ICML'15 — citation [2]
of ``models/gpr.py``; cf. "Healing Products of Gaussian Processes",
arXiv:2102.07106, for the failure modes the robust variants patch):

    poe    prec = sum_e 1/s2_e                      (overconfident in voids)
    gpoe   prec = sum_e b_e/s2_e,  b_e = 1/E        (calibrated scale)
    bcm    poe + (1-E)/k**  prior correction        (valid posterior, can
                                                     still overcorrect)
    rbcm   b_e = 0.5(log k** - log s2_e) per point  (entropy-weighted;
           prec = sum_e b_e/s2_e + (1-sum_e b_e)/k**  the robust default)
    healed b_e = max(0, rbcm entropy weight), normalized:
           prec = sum_e b_e/s2_e / sum_e b_e — a CONVEX combination of
           expert precisions (never sharper than its most confident
           expert, never a negative precision; the "healed product"
           repair of arXiv 2102.07106's failure modes), falling back to
           the prior where no expert carries information

where ``k**`` is the prior variance ``kernel.self_diag`` — the same
(noise-inclusive) convention as the PPA variance, so the two predictors
are directly comparable.  Mode selection is the expert aggregation
plane's policy (``models/aggregation.py``): ``mode=None`` resolves
``GP_AGG_POLICY`` / ``setAggregationPolicy`` when engaged, and the mode
is a static argument of the jitted predict programs, so a policy switch
recompiles rather than reusing the old reduction.  Cost: O(E s²) per test point, embarrassingly
parallel over the expert axis — no O(m³) build, no inducing set; the
natural choice when the active-set budget, not the data, limits PPA
fidelity.

Everything is one batched/vmapped program: per-expert Cholesky factors
``[E, s, s]`` are precomputed once (the same masked-gram embedding as
training keeps padding inert), prediction is two batched triangular
solves + the aggregation reduction.  On a mesh the expert axis shards and
the three precision sums ride one ``psum`` each.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.kernels.base import Kernel
from spark_gp_tpu.ops.linalg import (
    JITTER_SCHEDULE,
    chol_solve,
    cholesky,
    cholesky_escalated,
    is_pd,
    masked_kernel_matrix,
)
from spark_gp_tpu.parallel.experts import ExpertData

_MODES = ("poe", "gpoe", "bcm", "rbcm", "healed")


@partial(jax.jit, static_argnums=0)
def _expert_grams(kernel: Kernel, theta, x, mask):
    """Masked per-expert Gram stack [E, s, s]."""
    return jax.vmap(
        lambda xe, me: masked_kernel_matrix(kernel.gram(theta, xe), me)
    )(x, mask)


@jax.jit
def _alpha_from_chol(chol_l, y, mask):
    ym = y * mask
    return chol_solve(chol_l, ym)


@partial(jax.jit, static_argnums=0)
def _factor_experts(kernel: Kernel, theta, x, y, mask):
    """One-time batched factorization: L [E,s,s], alpha [E,s]."""
    kmat = _expert_grams(kernel, theta, x, mask)
    chol_l = cholesky(kmat)
    return chol_l, _alpha_from_chol(chol_l, y, mask)


def _local_moments(kernel: Kernel, mode, theta, x, mask, chol_l, alpha,
                   x_test, k_ss, psum_axis=None):
    """The (possibly device-local) expert reduction behind both predict
    paths: three sums over the expert axis — sum(beta*prec),
    sum(beta*prec*mean), sum(beta) — each a ``psum`` when sharded."""

    def per_expert(xe, me, le, ae):
        k_cross = kernel.cross(theta, x_test, xe) * me[None, :]  # [t, s]
        mean_e = k_cross @ ae
        v = jax.scipy.linalg.solve_triangular(
            le, k_cross.T, lower=True
        )  # [s, t]
        var_e = k_ss - jnp.sum(v * v, axis=0)
        return mean_e, var_e

    mean_e, var_e = jax.vmap(per_expert)(x, mask, chol_l, alpha)  # [E, t]
    # fully-padded experts (mesh padding) must not vote: mask their
    # precision weight to zero
    alive = (jnp.sum(mask, axis=1) > 0).astype(k_ss.dtype)[:, None]  # [E,1]
    n_alive = jnp.sum(alive)
    prec_e = alive / var_e  # [E, t]

    if mode in ("rbcm", "healed"):
        beta = alive * 0.5 * (jnp.log(k_ss)[None, :] - jnp.log(var_e))
        if mode == "healed":
            # the healed convex combination admits only non-negative
            # weights: an expert LESS confident than the prior carries no
            # information about this test point and must not vote with a
            # negative coefficient (it would flip the sign of its
            # precision contribution under the normalization below)
            beta = jnp.maximum(beta, 0.0)
    else:  # poe / bcm / gpoe: unit weights here.  gpoe's 1/E_global weight
        # cannot be applied per shard (the local expert count is wrong under
        # sharding) — _aggregate divides by n_alive AFTER the reduction.
        beta = alive * jnp.ones_like(var_e)

    sums = (
        jnp.sum(beta * prec_e, axis=0),           # [t]
        jnp.sum(beta * prec_e * mean_e, axis=0),  # [t]
        jnp.sum(beta, axis=0),                    # [t] (== n_alive for
                                                  #  unit-weight modes)
        n_alive,
    )
    if psum_axis is not None:
        sums = jax.lax.psum(sums, psum_axis)
    return sums


def _aggregate(mode, sums, k_ss):
    prec_sum, wmean_sum, beta_sum, n_alive = sums
    if mode == "poe":
        prior_w = 0.0
    elif mode == "gpoe":
        # beta = 1/E_global: scale the unit-weight sums after the reduction
        prec_sum = prec_sum / n_alive
        wmean_sum = wmean_sum / n_alive
        prior_w = 0.0
    elif mode == "bcm":
        prior_w = 1.0 - n_alive
    elif mode == "healed":
        # normalize AFTER the (possibly psum'd) reduction — the weights
        # then form a global convex combination whatever the sharding.
        # Test points where every expert reverted to the prior
        # (beta_sum == 0) fall back to the prior moments exactly.
        safe = jnp.maximum(beta_sum, jnp.finfo(k_ss.dtype).tiny)
        informed = beta_sum > 0
        prec = jnp.where(informed, prec_sum / safe, 1.0 / k_ss)
        wmean = jnp.where(informed, wmean_sum / safe, 0.0)
        return wmean / prec, 1.0 / prec
    else:  # rbcm
        prior_w = 1.0 - beta_sum
    prec = prec_sum + prior_w / k_ss  # [t]
    return wmean_sum / prec, 1.0 / prec


@partial(jax.jit, static_argnums=(0, 1))
def _predict_impl(kernel: Kernel, mode, theta, x, mask, chol_l, alpha, x_test):
    """``[t]`` aggregated (mean, var) from every expert's exact posterior."""
    k_ss = kernel.self_diag(theta, x_test)  # [t] prior var (incl. noise)
    sums = _local_moments(
        kernel, mode, theta, x, mask, chol_l, alpha, x_test, k_ss
    )
    return _aggregate(mode, sums, k_ss)


@partial(jax.jit, static_argnums=(0, 1, 2))
def _predict_sharded_impl(
    kernel: Kernel, mode, mesh, theta, x, mask, chol_l, alpha, x_test
):
    """Mesh-sharded prediction: the expert axis (data AND factors) shards,
    the test block and the three reduction sums replicate via one psum."""
    from jax.sharding import PartitionSpec as P

    from spark_gp_tpu.parallel.mesh import EXPERT_AXIS

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P(EXPERT_AXIS),
            P(EXPERT_AXIS), P(),
        ),
        out_specs=(P(), P()),
    )
    def run(theta_, x_, mask_, chol_, alpha_, x_test_):
        k_ss = kernel.self_diag(theta_, x_test_)
        sums = _local_moments(
            kernel, mode, theta_, x_, mask_, chol_, alpha_, x_test_, k_ss,
            psum_axis=EXPERT_AXIS,
        )
        return _aggregate(mode, sums, k_ss)

    return run(theta, x, mask, chol_l, alpha, x_test)


class PoEPredictor:
    """Fitted product-of-experts predictor at fixed hyperparameters.

    Built by :meth:`GaussianProcessRegression.poe_predictor`; holds the
    expert stack and its per-expert factors (O(E s²) memory — the data
    itself, unlike the N-independent PPA model)."""

    def __init__(
        self,
        kernel: Kernel,
        theta,
        data: ExpertData,
        mode=None,
        mesh=None,
    ):
        if mode is None:
            # the aggregation plane's policy (models/aggregation.py) when
            # engaged; the predictor's documented robust default otherwise
            from spark_gp_tpu.models.aggregation import resolve_predictor_mode

            mode = resolve_predictor_mode(None, default="rbcm")
        if mode not in _MODES:
            raise ValueError(
                f"unknown PoE mode {mode!r}; expected one of {_MODES}"
            )
        self.kernel = kernel
        self.theta = jnp.asarray(theta, dtype=data.x.dtype)
        self.data = data
        self.mode = mode
        self.mesh = mesh
        self._chol, self._alpha = _factor_experts(
            kernel, self.theta, data.x, data.y, data.mask
        )
        if not bool(is_pd(self._chol)):
            # a borderline expert Gram gets the shared adaptive jitter
            # ladder (ops/linalg.py) before we give up: the unjittered
            # clean path above stays untouched, the escalation re-runs the
            # factorization host-driven, and only a stack the whole ladder
            # cannot repair raises NotPositiveDefiniteException (with the
            # reference's advice) — never NaN predictions later.
            kmat = _expert_grams(kernel, self.theta, data.x, data.mask)
            # full ladder (rung 0 included): the escalation is per matrix,
            # so healthy experts keep their unjittered factors bit-exact
            # and only the borderline Grams climb rungs
            self._chol, _tau = cholesky_escalated(
                kmat, "per-expert Gram (PoE)", schedule=JITTER_SCHEDULE
            )
            self._alpha = _alpha_from_chol(self._chol, data.y, data.mask)

    # per-chunk element budget for the [E*s, t_chunk] cross-kernel /
    # solve intermediates — bounds device memory at ANY test-set size
    # (the same streaming contract as the PPA predictor)
    _PREDICT_CHUNK_ELEMS = 4_000_000

    def predict(self, x_test: np.ndarray) -> np.ndarray:
        return self.predict_with_var(x_test)[0]

    def predict_with_var(self, x_test: np.ndarray):
        x_test = jnp.asarray(
            np.asarray(x_test), dtype=self.data.x.dtype
        )
        t = x_test.shape[0]
        rows = max(1, self.data.num_experts * self.data.expert_size)
        chunk = max(1, self._PREDICT_CHUNK_ELEMS // rows)
        if t <= chunk:
            mean, var = self._predict_block(x_test)
            return np.asarray(mean), np.asarray(var)
        # fixed chunk shape (last chunk padded) -> one compiled executable
        means, vars_ = [], []
        for start in range(0, t, chunk):
            part = x_test[start : start + chunk]
            pad = chunk - part.shape[0]
            if pad:
                part = jnp.concatenate(
                    [part, jnp.broadcast_to(part[:1], (pad, part.shape[1]))]
                )
            mean, var = self._predict_block(part)
            means.append(np.asarray(mean[: chunk - pad] if pad else mean))
            vars_.append(np.asarray(var[: chunk - pad] if pad else var))
        return np.concatenate(means), np.concatenate(vars_)

    def _predict_block(self, x_test):
        if self.mesh is not None:
            return _predict_sharded_impl(
                self.kernel, self.mode, self.mesh, self.theta, self.data.x,
                self.data.mask, self._chol, self._alpha, x_test,
            )
        return _predict_impl(
            self.kernel, self.mode, self.theta, self.data.x,
            self.data.mask, self._chol, self._alpha, x_test,
        )


def make_poe_predictor(
    kernel: Kernel,
    theta,
    x: np.ndarray,
    y: np.ndarray,
    dataset_size_for_expert: int,
    mode=None,
    dtype=None,
    mesh=None,
) -> PoEPredictor:
    """Group + factor + wrap.  ``mode=None`` resolves the engaged
    aggregation policy (``models/aggregation.py``), falling back to the
    documented robust default ``rbcm``."""
    from spark_gp_tpu.parallel.experts import group_for_experts

    data = group_for_experts(x, y, dataset_size_for_expert, dtype=dtype)
    if mesh is not None:
        from spark_gp_tpu.parallel.mesh import shard_experts

        data = shard_experts(data, mesh)
    return PoEPredictor(kernel, theta, data, mode=mode, mesh=mesh)
