"""Binary GP classification with Expectation Propagation inference.

Second inference engine for the binary classifier (R&W ch. 3.6) beside
the Laplace approximation of :mod:`models/gpc` — same estimator API, same
BCM expert split, same PPA model production (with EP's posterior latent
means as the regression targets), but Gaussian sites matched to the true
per-site MOMENTS (probit likelihood, closed forms) rather than the mode
curvature — generally better-calibrated probabilities (Kuss & Rasmussen
2005).  See :mod:`models/ep` for the parallel-EP TPU design.

Prediction: the probit posterior predictive is CLOSED FORM —
``p(y=1 | x*) = Phi(mu* / sqrt(1 + var*))`` — so ``predict_proba``'s
``averaged=True`` needs no quadrature here (the Laplace/logistic engine
integrates with Gauss–Hermite).

Engine differences from :class:`GaussianProcessClassifier`: the
checkpointed device variant is not wired (a checkpoint dir falls back to
the host driver, whose theta-per-iteration checkpointing works
unchanged).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_gp_tpu.models.ep import (
    ep_posterior_mean,
    fit_gpc_ep_device,
    fit_gpc_ep_device_sharded,
    make_ep_objective,
    make_sharded_ep_objective,
)
from spark_gp_tpu.models.gpc import (
    GaussianProcessClassificationModel,
    GaussianProcessClassifier,
)
from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor
from spark_gp_tpu.parallel.experts import ExpertData
from spark_gp_tpu.utils.instrumentation import Instrumentation, phase_sync


class GaussianProcessEPClassifier(GaussianProcessClassifier):
    """Binary classifier with the EP inference engine; the fluent API and
    every orchestration feature come from the shared skeleton."""

    _engine_log_tag = " EP"

    def _gram_cache(self, instr, data):
        """The EP engine's site sweeps have no cached-gram path yet: never
        BUILD a cache its fit paths would silently discard (the prepare
        pass is a full O(E s^2 p) contraction plus an [E, s, s] stack of
        HBM), and report ``gram_cache_engaged=0`` truthfully."""
        if instr is not None:
            instr.log_metric("gram_cache_engaged", 0.0)
        return None

    def _multistart_device_call(
        self, kernel, log_space, theta_batch, lower, upper, data, max_iter,
        cache=None,
    ):
        """Engine hook for the parent's multistart skeleton: the vmapped
        EP + L-BFGS dispatch, site pairs riding per lane; the winner's
        latent mean comes back from the same program.  ``cache`` (the
        theta-invariant gram cache) is accepted for hook-signature parity
        and ignored: the EP engine's site sweeps have no cached-gram path
        yet."""
        from spark_gp_tpu.models.ep import fit_gpc_ep_device_multistart

        return fit_gpc_ep_device_multistart(
            kernel, float(self._tol), log_space, theta_batch,
            lower, upper, data.x, data.y, data.mask, max_iter,
        )

    def _fit_from_stack_profiled(
        self, instr, kernel, data, x, make_targets_fn, active_override=None,
        cache=None,
    ) -> ProjectedProcessRawPredictor:
        if (
            self._resolved_optimizer() == "device"
            and self._checkpoint_dir is None
        ):
            theta_dev, latent_y, pending = self._fit_ep_device(
                instr, kernel, data
            )
            latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)
            raw, _ = self._finalize_device_fit(
                instr, kernel, theta_dev, pending, x,
                None if make_targets_fn is None else make_targets_fn(latent_y),
                latent_data,
                active_override=active_override,
            )
            return raw

        # host-driven (also the checkpoint-dir path: the host driver's
        # theta-per-iteration checkpointing works unchanged)
        if self._mesh is not None:
            objective = make_sharded_ep_objective(
                kernel, data, self._tol, self._mesh
            )
        else:
            objective = make_ep_objective(kernel, data, self._tol)

        sites0 = (jnp.zeros_like(data.y), jnp.zeros_like(data.y))
        theta_opt, sites = self._optimize_latent_host(
            instr, kernel, objective, sites0
        )
        latent_y = ep_posterior_mean(
            kernel, jnp.asarray(theta_opt, dtype=data.x.dtype),
            data.x, data.mask, *sites,
        )
        latent_data = ExpertData(x=data.x, y=latent_y, mask=data.mask)
        return self._projected_process(
            instr, kernel, theta_opt, x,
            None if make_targets_fn is None else make_targets_fn(latent_y),
            latent_data,
            active_override=active_override,
        )

    def _fit_ep_device(self, instr: Instrumentation, kernel, data):
        dtype = data.x.dtype
        theta0 = jnp.asarray(kernel.init_theta(), dtype=dtype)
        lower, upper = kernel.bounds()
        lower = jnp.asarray(lower, dtype=dtype)
        upper = jnp.asarray(upper, dtype=dtype)
        max_iter = jnp.asarray(self._max_iter, dtype=jnp.int32)
        log_space = self._use_log_space(kernel)
        instr.log_info(
            "Optimising the kernel hyperparameters (on-device, EP)"
        )
        with instr.phase("optimize_hypers"):
            if self._mesh is not None:
                theta, _sites, latent_mu, f, n_iter, n_fev, stalled = (
                    fit_gpc_ep_device_sharded(
                        kernel, float(self._tol), self._mesh, log_space,
                        theta0, lower, upper, data.x, data.y, data.mask,
                        max_iter,
                    )
                )
            else:
                theta, _sites, latent_mu, f, n_iter, n_fev, stalled = (
                    fit_gpc_ep_device(
                        kernel, float(self._tol), log_space, theta0, lower,
                        upper, data.x, data.y, data.mask, max_iter,
                    )
                )
            phase_sync(theta, f)
        pending = {
            "lbfgs_iters": n_iter,
            "lbfgs_nfev": n_fev,
            "final_nll": f,
            "lbfgs_stalled": stalled,
        }
        return theta, latent_mu, pending

    # fit()/fit_distributed() build the Laplace model class through the
    # parent; wrap to return the EP model (closed-form probit proba)
    def fit(self, x, y):
        model = super().fit(x, y)
        ep_model = GaussianProcessEPClassificationModel(model.raw_predictor)
        ep_model.instr = model.instr
        ep_model.run_journal = getattr(model, "run_journal", None)
        if getattr(model, "degradations", None):
            # the rewrap must not lose the ladder's provenance stamp
            ep_model.degradations = model.degradations
        return ep_model

    def fit_distributed(self, data, active_set=None):
        model = super().fit_distributed(data, active_set)
        ep_model = GaussianProcessEPClassificationModel(model.raw_predictor)
        ep_model.instr = model.instr
        ep_model.run_journal = getattr(model, "run_journal", None)
        if getattr(model, "degradations", None):
            ep_model.degradations = model.degradations
        return ep_model


class GaussianProcessEPClassificationModel(GaussianProcessClassificationModel):
    """Probit head over the PPA latent posterior.

    ``predict_proba`` keeps the shared-API default ``averaged=False``
    (MAP latent through the link, like every classifier model here — and
    the only mode available on variance-free models), but ``averaged=True``
    is CLOSED FORM for probit: the Gaussian CDF integrates analytically
    against the latent Gaussian, ``E[Phi(f)] = Phi(mu / sqrt(1 + var))``
    — no quadrature (the logistic/Laplace model needs Gauss–Hermite for
    the same quantity).
    """

    def predict_proba(self, x_test: np.ndarray, averaged: bool = False) -> np.ndarray:
        from scipy.stats import norm

        if averaged:
            f, var = self.raw_predictor(np.asarray(x_test))
            if var is None:
                raise ValueError(
                    "model was fitted with setPredictiveVariance(False); "
                    "averaged probabilities need the latent variance — use "
                    "averaged=False or refit with variances enabled"
                )
            p1 = norm.cdf(
                np.asarray(f) / np.sqrt(1.0 + np.maximum(np.asarray(var), 0.0))
            )
        else:
            f = self.raw_predictor.predict_mean(np.asarray(x_test))
            p1 = norm.cdf(np.asarray(f))
        return np.stack([1.0 - p1, p1], axis=1)

    def save(self, path: str) -> None:
        from spark_gp_tpu.utils.serialization import save_model

        # own kind: a round-trip must come back with the probit head, not
        # silently downgrade to the Laplace/sigmoid model class
        save_model(path, self, kind="ep_classification")

    @staticmethod
    def load(path: str) -> "GaussianProcessEPClassificationModel":
        from spark_gp_tpu.utils.serialization import load_model

        model = load_model(path)
        if not isinstance(model, GaussianProcessEPClassificationModel):
            raise TypeError("not an EP classification model checkpoint")
        return model
