"""Dataset loaders for the examples / acceptance tests."""

from spark_gp_tpu.data.datasets import (
    DATASET_FILES,
    dataset_provenance,
    find_dataset_file,
    load_airfoil,
    load_iris,
    load_mnist_binary,
    load_protein,
    load_year_msd,
    make_benchmark_data,
    make_clustered,
    make_heteroscedastic,
    make_synthetics,
)

__all__ = [
    "make_synthetics",
    "load_airfoil",
    "load_iris",
    "load_mnist_binary",
    "load_protein",
    "load_year_msd",
    "make_benchmark_data",
    "make_clustered",
    "make_heteroscedastic",
    "DATASET_FILES",
    "find_dataset_file",
    "dataset_provenance",
]
