"""Dataset generators/loaders matching the reference examples' data.

* :func:`make_synthetics` — 2000-point sin(x) + N(0, 0.01) on [0, 1]
  (regression/examples/Synthetics.scala:16-23).
* :func:`load_airfoil` — UCI airfoil self-noise CSV, 5 features, 1503 rows
  (regression/examples/Airfoil.scala:26-33; data/airfoil.csv).
* :func:`load_iris` — UCI iris, 3 classes as integer labels
  (classification/examples/Iris.scala:16-24).
* :func:`load_mnist_binary` — MNIST digits 6-vs-8 (the reference's blob is
  absent upstream; built from any MNIST csv path when available, else a
  synthetic stand-in shaped 784-d for pipeline/perf testing).
* :func:`make_benchmark_data` — sin(sum(x)/1000), 3 uniform features
  (regression/benchmark/PerformanceBenchmark.scala:19-36).
* :func:`load_protein` / :func:`load_year_msd` — the BASELINE.json UCI
  stress configs (46k CASP, 515k Year-Prediction-MSD); real CSV when a path
  is given, synthetic stand-ins of the same shape otherwise.
"""

from __future__ import annotations

import os

import numpy as np

_DATA_DIR = os.path.join(os.path.dirname(__file__), "files")

# Real-data snap-in (VERDICT r4 #5).  The zero-egress build environment
# cannot download the UCI stress datasets, so the stress/classification
# loaders fall back to synthetic stand-ins — but the moment ANY environment
# drops the real CSVs into ``$GP_DATA_DIR`` (or the bundled files dir),
# every consumer (examples, quality.py) flips to real data with zero code
# change.  Accepted filenames per dataset (first match wins; the UCI
# canonical names first):
DATASET_FILES = {
    "protein": ("CASP.csv", "protein.csv"),
    "year_msd": (
        "YearPredictionMSD.csv", "YearPredictionMSD.txt", "year_msd.csv",
    ),
    "mnist": ("mnist68.csv", "mnist_train.csv", "mnist.csv"),
}


def find_dataset_file(dataset: str) -> str | None:
    """Path of a real on-disk CSV for ``dataset`` (a :data:`DATASET_FILES`
    key), searching ``$GP_DATA_DIR`` then the bundled files dir — or None
    (callers then use their synthetic stand-in)."""
    names = DATASET_FILES[dataset]
    dirs = []
    env_dir = os.environ.get("GP_DATA_DIR")
    if env_dir:
        dirs.append(env_dir)
    dirs.append(_DATA_DIR)
    for d in dirs:
        for name in names:
            candidate = os.path.join(d, name)
            if os.path.isfile(candidate):
                return candidate
    return None


def dataset_provenance(dataset: str, path: str | None = None) -> str:
    """Human/JSON-readable record of which data a consumer used: the real
    file when one is (or was) discoverable, else the stand-in marker the
    round artifacts key on."""
    path = path or find_dataset_file(dataset)
    if path:
        return f"real ({os.path.basename(path)})"
    return "synthetic stand-in (zero-egress env; snap-in: GP_DATA_DIR)"


def _read_csv(path: str, skip_rows: int = 0) -> np.ndarray:
    """Numeric CSV -> float64 [rows, cols]: the native parallel parser
    (spark_gp_tpu.native, the counterpart of the reference's Spark CSV
    ingestion runtime) when it builds, ``np.loadtxt`` otherwise."""
    from spark_gp_tpu import native

    if native.available():
        return native.read_csv(path, skip_rows=skip_rows)
    return np.loadtxt(path, delimiter=",", skiprows=skip_rows, ndmin=2)


def _has_header(path: str) -> bool:
    """True when the file's first cell is not parseable as a number (e.g.
    Kaggle's ``label,pixel0,...`` MNIST header) — snap-in files arrive in
    both header and header-less flavors."""
    try:
        with open(path) as fh:
            first = fh.readline().split(",")[0].strip()
        float(first)
        return False
    except ValueError:
        return True
    except OSError:
        return False


def make_synthetics(n: int = 2000, noise_var: float = 0.01, seed: int = 13):
    x = np.linspace(0.0, 1.0, n).reshape(n, 1)
    rng = np.random.default_rng(seed)
    y = np.sin(x[:, 0]) + rng.normal(0.0, np.sqrt(noise_var), size=n)
    return x, y


def load_airfoil(path: str | None = None):
    """Returns (x [1503, 5], y [1503]) — frequency, angle of attack, chord
    length, free-stream velocity, displacement thickness -> sound pressure."""
    path = path or os.path.join(_DATA_DIR, "airfoil.csv")
    raw = _read_csv(path)
    return raw[:, :5], raw[:, 5]


def load_iris(path: str | None = None):
    """Returns (x [150, 4], y [150] in {0, 1, 2}) with the reference's class
    index mapping (Iris.scala:16): versicolor=0, setosa=1, virginica=2."""
    path = path or os.path.join(_DATA_DIR, "iris.csv")
    name2idx = {
        "Iris-versicolor": 0,
        "Iris-setosa": 1,
        "Iris-virginica": 2,
    }
    xs, ys = [], []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            *feats, name = line.split(",")
            xs.append([float(v) for v in feats])
            ys.append(name2idx[name])
    return np.asarray(xs), np.asarray(ys, dtype=np.float64)


#: planted Bayes accuracy of the synthetic MNIST stand-in: two unit-
#: covariance Gaussians at center separation d have Bayes accuracy
#: Phi(d/2); quality.py derives its falsifiable accuracy bar from this
MNIST_STANDIN_BAYES_ACCURACY = 0.970
_MNIST_STANDIN_SEPARATION = 3.76  # 2 * Phi^-1(0.970)


def load_mnist_binary(path: str | None = None, digits=(6, 8), seed: int = 0):
    """MNIST ``digits[0]``-vs-``digits[1]`` as (x [n, 784], y in {0,1}).

    Reads a label-first CSV when ``path`` is given (the reference's
    data/mnist68.csv format, MNIST.scala:22-26) or discoverable via
    :func:`find_dataset_file`.  The upstream blob is missing from the
    reference repo (.MISSING_LARGE_BLOBS); otherwise a deterministic
    synthetic 784-d two-class problem of the same shape is generated so the
    pipeline and benchmarks remain runnable.
    """
    path = path or find_dataset_file("mnist")
    if path is not None:
        raw = _read_csv(path, skip_rows=1 if _has_header(path) else 0)
        labels = raw[:, 0]
        keep = np.isin(labels, digits)
        x = raw[keep, 1:]
        y = (labels[keep] == digits[1]).astype(np.float64)
        return x, y
    rng = np.random.default_rng(seed)
    n_per = 1000
    # Calibrated class overlap (VERDICT next #5): two unit-covariance
    # Gaussians at |c1 - c2| = d have Bayes accuracy Phi(d/2); d = 3.76
    # plants it at ~0.970.  The old stand-in (independent N(0, 0.5^2)
    # centers per dim: d ~ 19.8) was separable by ANY projection —
    # r03's recorded 1.0 accuracy meant the bar could only catch total
    # breakage, never a subtly-regressed 784-d Laplace path.  Against a
    # planted 0.97 ceiling, quality.py's bar sits just under the healthy
    # classifier's margin and an accuracy regression actually trips it.
    d = _MNIST_STANDIN_SEPARATION
    direction = rng.normal(size=784)
    direction *= (d / 2.0) / np.linalg.norm(direction)
    centers = np.stack([-direction, direction])
    x = np.concatenate(
        [centers[i] + rng.normal(size=(n_per, 784)) for i in range(2)]
    )
    y = np.concatenate([np.zeros(n_per), np.ones(n_per)])
    perm = rng.permutation(2 * n_per)
    return x[perm], y[perm]


#: planted additive noise of the clustered stand-in — the calibrated side
#: of its aggregation-quality bars (clustered_noise_floor derives the rest)
CLUSTERED_NOISE = 0.05

#: noise ramp endpoints of the heteroscedastic stand-in: sigma(t) runs
#: linearly from LOW to HIGH across the input range, so the AVERAGE
#: predictive variance a stationary GP can honestly learn is the mean of
#: sigma^2(t) — the coverage bars in quality.py are stated against that
#: planted profile, not a free constant
HETERO_NOISE_LOW = 0.02
HETERO_NOISE_HIGH = 0.40


def make_clustered(
    n: int = 4096, p: int = 2, n_clusters: int = 8, seed: int = 3,
    noise: float = CLUSTERED_NOISE, spread: float = 0.15,
):
    """Disjoint-cluster regression — the aggregation plane's canary.

    ``n_clusters`` well-separated Gaussian blobs (centers ~ 4 sigma
    apart vs ``spread``), each carrying its own local response (a
    cluster-specific sinusoid plus offset) and the PLANTED additive
    noise.  Why this shape: with experts covering disjoint regions,
    every expert reverts to the prior far from its own data, and the
    plain product-of-experts multiplies E near-prior precisions into a
    variance ~k**/E — overconfident by construction (Healing PoGPs,
    PAPERS.md) — while rBCM/healed entropy weights zero the uninformed
    votes.  ``models/aggregation.py``'s policy bars and bench.py's
    ``aggregation`` section are measured on exactly this generator, so
    the planted noise/spread double as their calibration constants.
    Returns ``(x [n, p], y [n])``; row ``i`` belongs to cluster
    ``i % n_clusters``, so the round-robin expert grouping (expert ``j``
    takes rows ``j, j+E, ...`` — parallel/experts.py) pins every expert
    to a single cluster whenever ``n_clusters`` divides ``E``.
    """
    rng = np.random.default_rng(seed)
    centers = 4.0 * rng.normal(size=(n_clusters, p))
    assign = np.arange(n) % n_clusters
    x = centers[assign] + spread * rng.normal(size=(n, p))
    w = rng.normal(size=(n_clusters, p))
    offsets = 2.0 * rng.normal(size=n_clusters)
    y = (
        np.sin(np.einsum("np,np->n", x - centers[assign], w[assign]) * 3.0)
        + offsets[assign]
        + noise * rng.normal(size=n)
    )
    return x, y


def clustered_noise_floor(n: int = 4096) -> float:
    """Irreducible scaled RMSE of :func:`make_clustered` — planted noise
    over target std, the same derivation as :func:`standin_noise_floor`
    (quality.py states the aggregation bars against it)."""
    _, y = make_clustered(n)
    return CLUSTERED_NOISE / float(np.std(y))


def make_heteroscedastic(n: int = 4096, seed: int = 5):
    """1-d regression with input-dependent noise — the calibration canary.

    ``y = sin(6 t) + sigma(t) eps`` with ``sigma(t)`` ramping linearly
    from :data:`HETERO_NOISE_LOW` to :data:`HETERO_NOISE_HIGH` across
    ``t in [0, 1]``.  A stationary GP can only learn ONE noise level, so
    its predictive sigmas are honest on average but over-cover the quiet
    end and under-cover the loud end; the quality bars assert the
    AVERAGE 90% coverage stays inside a band derived from this planted
    profile (anything tighter would assert what the model class cannot
    deliver).  Returns ``(x [n, 1], y [n], sigma [n])`` — the true
    per-point noise rides along so calibration can be scored against
    ground truth, not just empirically.
    """
    rng = np.random.default_rng(seed)
    t = np.sort(rng.uniform(size=n))
    sigma = HETERO_NOISE_LOW + (HETERO_NOISE_HIGH - HETERO_NOISE_LOW) * t
    y = np.sin(6.0 * t) + sigma * rng.normal(size=n)
    return t[:, None], y, sigma


def make_benchmark_data(n: int, n_features: int = 3, seed: int = 13):
    """PerformanceBenchmark.scala:19-36: uniform features,
    y = sin(sum(x) / 1000)."""
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n, n_features))
    y = np.sin(x.sum(axis=1) / 1000.0)
    return x, y


#: additive noise level of the regression stand-ins — the PLANTED side of
#: their signal-to-noise ratio (standin_noise_floor derives the other)
STANDIN_NOISE = 0.1

#: (features, seed, effective_dim) of each regression stand-in — ONE home,
#: so the loaders and the noise-floor derivation can never disagree
_STANDIN_PARAMS = {
    "protein": (9, 7, None),
    "year_msd": (90, 11, 8),
}


def standin_noise_floor(dataset: str, n: int = 4000) -> float:
    """The stand-in's irreducible scaled RMSE: planted noise / target std.

    quality.py restates its stress-regression bars against this floor
    (``bar^2 = floor^2 + structural_budget^2``) instead of a free-floating
    constant: the bar then moves with the generator's planted
    signal-to-noise ratio by construction, and a quality regression in
    the fit path — which can only grow the structural term — trips it.
    Deterministic (the generator's own seed) and cheap (one n-row draw).
    """
    p, seed, eff = _STANDIN_PARAMS[dataset]
    _, y = _synthetic_regression(n, p, seed, effective_dim=eff)
    return STANDIN_NOISE / float(np.std(y))


def _synthetic_regression(
    n: int, p: int, seed: int, noise: float = STANDIN_NOISE,
    effective_dim: int | None = None,
):
    """Nonlinear multi-scale regression surface used as the stand-in for the
    UCI stress datasets when the real CSVs are unavailable (zero-egress
    environment): y = sin(w1.x) + 0.5 cos(w2.x) + quadratic + noise.

    ``effective_dim`` restricts the signal to the first k features (the
    remaining p-k are pure distractors).  A full-rank random surface over
    p ~ 90 dims is statistically unlearnable at any feasible sample size —
    every direction is signal — whereas real wide tabular data (Year-MSD's
    timbre features) concentrates relevance in a few directions; a
    low-effective-dimension stand-in both mimics that and actually
    exercises what ARD is for (pruning irrelevant dims).
    """
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, p))
    k = p if effective_dim is None else min(effective_dim, p)
    w1 = np.zeros(p)
    w2 = np.zeros(p)
    w1[:k] = rng.normal(size=k) / np.sqrt(k)
    w2[:k] = rng.normal(size=k) / np.sqrt(k)
    y = (
        np.sin(x @ w1)
        + 0.5 * np.cos(3.0 * (x @ w2))
        + 0.1 * (x @ w1) ** 2
        + noise * rng.normal(size=n)
    )
    return x, y


def _subsample(x, y, n, seed):
    """Subsample n rows, preserving row order (Year-MSD's canonical
    train/test split is positional)."""
    if n is None or n >= x.shape[0]:
        return x, y
    idx = np.random.default_rng(seed).choice(x.shape[0], size=n, replace=False)
    idx.sort()
    return x[idx], y[idx]


def load_protein(
    path: str | None = None, n: int | None = None,
    seed: int = _STANDIN_PARAMS["protein"][1],
):
    """UCI Physicochemical-Properties-of-Protein-Tertiary-Structure (CASP):
    45730 rows, 9 features, target RMSD — the BASELINE.json 46k stress
    config for the product-of-experts reduction.

    Reads the UCI ``RMSD,F1..F9`` CSV (one header row) when ``path`` is
    given or discoverable via :func:`find_dataset_file`; otherwise
    generates a synthetic stand-in of the same shape.  ``n`` subsamples
    either source.
    """
    path = path or find_dataset_file("protein")
    if path is not None:
        raw = _read_csv(path, skip_rows=1 if _has_header(path) else 0)
        return _subsample(raw[:, 1:], raw[:, 0], n, seed)
    p, _, eff = _STANDIN_PARAMS["protein"]
    return _synthetic_regression(n or 45730, p, seed, effective_dim=eff)


def load_year_msd(
    path: str | None = None, n: int | None = None,
    seed: int = _STANDIN_PARAMS["year_msd"][1],
):
    """Year-Prediction-MSD: 515345 rows, 90 timbre features, target year —
    the BASELINE.json pod-scale inducing-point stress config.

    Reads the UCI header-less ``year,F1..F90`` CSV when ``path`` is given
    or discoverable via :func:`find_dataset_file`; otherwise generates a
    synthetic stand-in of the same shape.  ``n`` subsamples either source.
    """
    path = path or find_dataset_file("year_msd")
    if path is not None:
        raw = _read_csv(path, skip_rows=1 if _has_header(path) else 0)
        return _subsample(raw[:, 1:], raw[:, 0], n, seed)
    p, _, eff = _STANDIN_PARAMS["year_msd"]
    return _synthetic_regression(n or 515345, p, seed, effective_dim=eff)
