"""Online inference: model registry, shape-bucketed micro-batching,
warm compiled predict paths.

The PPA predictor's cost depends only on the m-point active set
(models/ppa.py, R&W ch. 8.3.4) — exactly the shape of a low-latency
scorer.  What a request-driven workload adds on top of a correct
``predict`` is *shape discipline*: XLA compiles one executable per input
shape, so free-form request sizes would recompile on the hot path and a
p50 of microseconds would hide a p99 of seconds.  This package keeps the
compiled surface finite and warm:

* :class:`~spark_gp_tpu.serve.registry.ModelRegistry` — ``.npz`` models
  (utils/serialization.py) keyed by name+version, hot-swapped on reload;
* :class:`~spark_gp_tpu.serve.batcher.BucketedPredictor` — requests are
  padded to a small set of power-of-two batch buckets, one XLA compile
  per (model, bucket), with an explicit recompile guard after warmup;
* :class:`~spark_gp_tpu.serve.server.GPServeServer` — a bounded request
  queue with micro-batch coalescing (max-wait deadline), per-request
  timeouts, and load shedding instead of stalling;
* :class:`~spark_gp_tpu.serve.metrics.ServingMetrics` — counters and
  latency histograms (p50/p99, batch occupancy, queue depth) on top of
  utils/instrumentation.py;
* ``python -m spark_gp_tpu.serve`` — a JSON-lines (stdin or socket)
  entrypoint that warms every bucket before reporting ready.

Failures degrade per model / per request, never the process: per-model
circuit breakers, poisoned-request isolation, classed shed metrics and
a health verb (``resilience/``, docs/RESILIENCE.md).

The process lifecycle is hardened too
(:mod:`~spark_gp_tpu.serve.lifecycle`): graceful drain on
SIGTERM/SIGINT, canary rollouts shadow-scored against the incumbent
with auto-promote/auto-rollback, a hang watchdog over device
dispatches, and memory-pressure admission with hysteresis.

At fleet scale (:mod:`~spark_gp_tpu.serve.fleet` +
:mod:`~spark_gp_tpu.serve.router`): consistent-hash routing of
``(model, bucket)`` across N replicas, registration/heartbeat/
generation-stamped membership over the coord KV plane, per-request
failover with bounded jittered retry, hedged re-dispatch around
stragglers, drain-aware rebalancing, fleet-wide canary (promote only
when ALL replicas clear the guard bar), and aggregated scaling signals
on one OpenMetrics page (docs/SERVING.md "Fleet").

See docs/SERVING.md for architecture, tuning and the
"Deployment & lifecycle" section.
"""

from spark_gp_tpu.resilience.breaker import BreakerOpenError, CircuitBreaker
from spark_gp_tpu.serve.batcher import (
    BucketOverflowError,
    BucketedPredictor,
    RecompileGuardError,
    bucket_sizes,
)
from spark_gp_tpu.serve.lifecycle import (
    CanaryPolicy,
    DrainingError,
    ExecHungError,
    HangWatchdog,
    MemoryAdmissionGate,
    MemoryPressureError,
)
from spark_gp_tpu.serve.metrics import LatencyHistogram, ServingMetrics
from spark_gp_tpu.serve.queue import (
    DeadlineExpiredError,
    QueueFullError,
    RequestTimeoutError,
    ServeFuture,
)
from spark_gp_tpu.serve.fleet import (
    FleetCanary,
    FleetMembership,
    HashRing,
    LocalReplica,
)
from spark_gp_tpu.serve.registry import ModelRegistry, ServableModel
from spark_gp_tpu.serve.router import (
    FailoverExhaustedError,
    FleetRouter,
    LocalReplicaTransport,
    NoReplicasError,
    ReplicaUnreachableError,
    RouterDeadlineError,
    TcpReplicaTransport,
)
from spark_gp_tpu.serve.server import GPServeServer

__all__ = [
    "FailoverExhaustedError",
    "FleetCanary",
    "FleetMembership",
    "FleetRouter",
    "HashRing",
    "LocalReplica",
    "LocalReplicaTransport",
    "NoReplicasError",
    "ReplicaUnreachableError",
    "RouterDeadlineError",
    "TcpReplicaTransport",
    "BreakerOpenError",
    "BucketedPredictor",
    "BucketOverflowError",
    "CanaryPolicy",
    "CircuitBreaker",
    "DeadlineExpiredError",
    "DrainingError",
    "ExecHungError",
    "HangWatchdog",
    "MemoryAdmissionGate",
    "MemoryPressureError",
    "RecompileGuardError",
    "bucket_sizes",
    "ServingMetrics",
    "LatencyHistogram",
    "QueueFullError",
    "RequestTimeoutError",
    "ServeFuture",
    "ModelRegistry",
    "ServableModel",
    "GPServeServer",
]
